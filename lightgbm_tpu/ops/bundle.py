"""Device-side Exclusive Feature Bundling support.

The storage/bin matrix holds G bundled columns; the split layer sees F
original features.  Two primitives bridge them (reference counterpart:
FeatureGroup bin offsets + FeatureHistogram views into the group
histogram, include/LightGBM/feature_group.h:18):

- `expand_histogram`: [G, Bg, 3] bundle histogram -> [F, B, 3] per-feature
  views by static gathers; a feature's default (zero) bin takes the bundle
  remainder (rows where any OTHER member was non-default are rows where
  this member sat at its default).
- `decode_bin`: bundled storage value -> the original feature's bin, used
  by every routing site (partition predicates, traversal).

A dataset without bundling uses the identity map (f_group=arange,
identity=True) so every consumer runs one uniform code path.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class BundleMap(NamedTuple):
    f_group: jax.Array     # [F] i32 storage column of each feature
    f_offset: jax.Array    # [F] i32 bin offset inside the bundle
    f_identity: jax.Array  # [F] bool raw-bin passthrough


def identity_bundle_map(num_features: int) -> BundleMap:
    return BundleMap(
        f_group=jnp.arange(num_features, dtype=jnp.int32),
        f_offset=jnp.zeros(num_features, jnp.int32),
        f_identity=jnp.ones(num_features, bool))


def bundle_map_from_info(info) -> BundleMap:
    return BundleMap(f_group=jnp.asarray(info.f_group, jnp.int32),
                     f_offset=jnp.asarray(info.f_offset, jnp.int32),
                     f_identity=jnp.asarray(info.f_identity))


def decode_bin(value, identity, offset, num_bin, default_bin):
    """Original bin of one feature given its bundle's storage value.

    enc = offset + b - (b > d) for b != d; anything outside the feature's
    range means "this member at its default bin"."""
    v = value.astype(jnp.int32)
    e = v - offset
    in_range = (e >= 0) & (e < num_bin - 1)
    b = e + (e >= default_bin)
    return jnp.where(identity, v, jnp.where(in_range, b, default_bin))


def expand_histogram(hist_g: jax.Array, bmap: BundleMap, num_bin,
                     default_bin, num_bins_feature: int) -> jax.Array:
    """[G, Bg, 3] -> [F, B, 3] per-feature histogram views.

    num_bin/default_bin: [F] i32 (FeatureMeta columns)."""
    Bg = hist_g.shape[1]
    B = num_bins_feature
    b = jnp.arange(B, dtype=jnp.int32)[None, :]              # [1, B]
    d = default_bin[:, None]
    ident = bmap.f_identity[:, None]
    src = jnp.where(ident, b, bmap.f_offset[:, None] + b - (b > d))
    src = jnp.clip(src, 0, Bg - 1)
    out = hist_g[bmap.f_group[:, None], src]                 # [F, B, 3]
    valid = (b < num_bin[:, None])[:, :, None]
    out = jnp.where(valid, out, 0.0)
    # non-identity default bin = bundle total minus this member's own mass
    totals = jnp.sum(hist_g, axis=1)[bmap.f_group]           # [F, 3]
    own = jnp.sum(jnp.where((b == d)[:, :, None], 0.0, out), axis=1)
    fixed = (totals - own)[:, None, :]
    at_default = (b == d)[:, :, None] & ~ident[:, :, None]
    return jnp.where(at_default, fixed, out)
