"""Per-iteration gradient/hessian quantization (Shi et al., NeurIPS 2022,
"Quantized Training of Gradient Boosting Decision Trees").

The histogram loop is the bandwidth bottleneck of histogram GBDT; the paper
shows the per-row gradient/hessian can be quantized to a few bits with
STOCHASTIC rounding and integer histogram accumulation at negligible
accuracy cost.  Here that maps onto the payload engine (`ops.segment`):

- once per (iteration, class), AFTER the bagging mask is applied, the f32
  gradients/hessians are scaled into an integer grid and stochastically
  rounded (`quantize_pair`); the integer-VALUED results live in the payload
  grad/hess columns (f32 lanes — small integers are exact), so every
  partition/ride-along mechanism is unchanged;
- histograms accumulate the integers into an int32 [F, B, 3] state
  (`segment_histogram(..., quantized=True)`, or the staged int8 MXU kernel
  `pallas_segment.segment_histogram_quant`) — integer addition is exact and
  order-independent, so subtraction-trick siblings, cross-engine results
  and cross-shard `psum`s of the histogram are all bit-exact;
- the f32 view is recovered only at the split-search boundary
  (`ops.split.dequantize_hist`), so the gain arithmetic is unchanged.

Overflow safety: an int32 histogram cell accumulates at most
rows_per_leaf * qmax, so the grid half-range is derived AT TRACE TIME as
`qmax = min(dtype_max, (2^31 - 1) // n_rows)` (`derive_qmax`) — the same
adaptive-width argument as the paper's 2-5 bit gradients at scale.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

#: integer grid half-range per requested packing width (the sign bit is
#: spent on the gradient's sign; hessians are non-negative and use [0, qmax])
QUANT_DTYPE_MAX = {"int8": 127, "int16": 32767}

#: bytes of gradient+hessian information per row fed to a histogram
#: dispatch, per packing width (f32 reference: 4 + 4)
QUANT_GH_BYTES = {"int8": 2, "int16": 4}
F32_GH_BYTES = 8


def derive_qmax(n_rows: int, dtype: str) -> int:
    """Trace-time integer grid half-range for `dtype` at `n_rows`.

    Caps the requested width by the int32 accumulator overflow bound
    (rows-per-leaf * max|q| < 2^31; the root leaf holds every row, so
    n_rows is the bound).  Raises when the surviving grid is too coarse
    to carry any gradient signal (< 2 levels per sign)."""
    if dtype not in QUANT_DTYPE_MAX:
        raise ValueError(
            "gradient_quant_dtype must be one of %s, got %r"
            % (sorted(QUANT_DTYPE_MAX), dtype))
    qmax = min(QUANT_DTYPE_MAX[dtype], (2 ** 31 - 1) // max(int(n_rows), 1))
    if qmax < 2:
        raise ValueError(
            "gradient_quantization: %d rows leave no int32 headroom for "
            "an integer histogram (rows * qmax must stay below 2^31)"
            % n_rows)
    return qmax


def stochastic_round(x: jax.Array, key: jax.Array, lo: float,
                     hi: float) -> jax.Array:
    """floor(x + u), u ~ U[0, 1) — unbiased (E[floor(x+u)] = x), clipped to
    [lo, hi] (the clip only fires at the grid edge, where rounding up would
    leave the grid).  Exact zero stays exactly zero (u < 1), so masked-out
    rows keep contributing nothing."""
    u = jax.random.uniform(key, x.shape, jnp.float32)
    return jnp.clip(jnp.floor(x + u), lo, hi)


def quantize_pair(g: jax.Array, h: jax.Array, qseed: jax.Array, qmax: float):
    """Quantize one class's (already masked) gradient/hessian vectors.

    Returns (qg, qh, qscale): integer-VALUED f32 vectors ready for the
    payload grad/hess columns, and the [2] f32 per-class scale factors
    (gradient, hessian) the split boundary dequantizes with.  Scales are
    per-iteration per-class maxima over the masked rows (the paper's
    max-scaling); an all-zero vector gets scale 1 so the division is
    always finite."""
    key = jax.random.PRNGKey(jnp.asarray(qseed, jnp.int32))
    kg, kh = jax.random.split(key)
    gmax = jnp.max(jnp.abs(g))
    hmax = jnp.max(h)
    gscale = jnp.where(gmax > 0, gmax, jnp.float32(qmax)) / qmax
    hscale = jnp.where(hmax > 0, hmax, jnp.float32(qmax)) / qmax
    qg = stochastic_round(g / gscale, kg, -qmax, qmax)
    qh = stochastic_round(h / hscale, kh, 0.0, qmax)
    return qg, qh, jnp.stack([gscale, hscale])
