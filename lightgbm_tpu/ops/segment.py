"""Segment engine: O(rows-touched) histogram + partition over a row-payload.

The reference keeps rows of each leaf contiguous through DataPartition
(src/treelearner/data_partition.hpp) so ConstructHistogram only scans the
split leaf's rows (src/io/dense_bin.hpp:66-132, ordered gather
src/io/dataset.cpp:664-678).  TPUs have no fast random scatter/gather, so the
same idea is re-expressed in MXU-native primitives:

- training rows live in ONE row-major payload matrix [N_pad + C, P] (f32):
  bin columns, then value columns (grad/hess/count-mask/leaf-value/...);
  rows of every tree leaf are kept physically contiguous;
- a split's stable partition is three chunked passes (compact-left,
  compact-right, blended copy-back), each chunk compacted by a one-hot
  permutation matrix applied as a matmul — a scatter the MXU can run;
- a leaf's histogram is built by walking only that leaf's chunks and
  contracting a joint (feature, bin) one-hot with the value columns.

This module is the portable lax implementation used on CPU meshes and as
the semantic reference; `ops.pallas_histogram` / `ops.pallas_partition`
override the two hot kernels on TPU with VMEM-resident one-hots.

Chunks are fixed at C rows; `start`/`count` are dynamic scalars, so every
pass is a `lax.while_loop` with a data-dependent trip count — no
recompilation per segment size.  The payload carries a C-row guard at the
end: compact passes may write up to C garbage rows past a segment into the
scratch buffer, and the copy-back blends row-exactly, so no pass ever needs
a partial-chunk write.
"""
from __future__ import annotations

import os
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from .bundle import decode_bin
from .split import MISSING_NAN, MISSING_ZERO

# rows per chunk: small enough that the joint one-hot [C, F*B] and the
# permutation matrix [C, C] sit comfortably in VMEM on the Pallas path.
# LIGHTGBM_TPU_CHUNK lets a hardware session A/B larger chunks (fewer
# per-chunk DMA waits, more VMEM per buffer — every kernel's VMEM-fit
# plan recomputes from this constant); the tested/shipped default is 256.
# Exactness is chunk-size-independent up to 2^24 (f32-exact prefix
# counts); the sublane alignment story only needs CHUNK % 8 == 0.
# a ValueError (not assert): the sublane-alignment assumption is baked
# into every Pallas kernel and the GUARD sizing, and the check must
# survive python -O
_chunk_raw = os.environ.get("LIGHTGBM_TPU_CHUNK", "256")
try:
    CHUNK = int(_chunk_raw)
except ValueError:
    raise ValueError(
        "LIGHTGBM_TPU_CHUNK must be an integer multiple of 8 in "
        "[8, 2048], got %r" % _chunk_raw) from None
if CHUNK % 8 != 0 or not 8 <= CHUNK <= 2048:
    raise ValueError(
        "LIGHTGBM_TPU_CHUNK must be a multiple of 8 in [8, 2048], got %d"
        % CHUNK)

# guard rows past the last real row.  The portable passes write up to CHUNK
# garbage rows past a segment; the Pallas partition kernel additionally
# writes aligned CHUNK+8-row windows (HBM row slices must start at a
# multiple of the f32 sublane tiling of 8, so a write at an arbitrary
# cursor becomes a read-modify-write of the enclosing aligned window).
GUARD = CHUNK + 8


def resolve_impl(impl: str, num_features: int, num_bins: int,
                 payload_width: int = None) -> str:
    """Pick the segment-engine implementation at trace time.

    "auto" (Config.tpu_histogram_impl default) chooses the Pallas kernels on
    a TPU backend when the joint one-hot fits VMEM, otherwise the portable
    lax path.  "pallas" / "lax" force a choice (tests, debugging).

    payload_width: the REAL payload lane count, when the caller knows it —
    the kernel DMAs full payload rows, so the VMEM plan must budget the
    actual width.  Feature-parallel shards histogram only their owned
    leading columns (num_features = Gloc) but still stream full-width rows;
    the old num_features+32 estimate under-budgeted exactly there."""
    if impl not in ("auto", "pallas", "lax"):
        raise ValueError(
            "tpu_histogram_impl must be one of auto|pallas|lax, got %r" % impl)
    if impl == "auto":
        from . import pallas_segment
        if (jax.default_backend() == "tpu"
                and pallas_segment.fits_vmem(num_features, num_bins,
                                             payload_width)):
            return "pallas"
        return "lax"
    if impl == "pallas" and num_bins > 256:
        raise ValueError(
            "tpu_histogram_impl=pallas requires max_bin <= 256 (the kernel's "
            "exactness argument needs bf16-representable bin values, like "
            "the reference's 256-bin OpenCL kernel ceiling)")
    return impl


def payload_col_write(payload: jax.Array, col, vec, op: str = "set"):
    """payload[:, col] <op>= vec as a lane-masked elementwise select.

    A DUS column write (``payload.at[:, col].set(vec)``) on the lane-tiled
    [N, P] payload makes XLA materialize BOTH a payload-sized copy and the
    [N, 1] update operand re-tiled to the payload's T(8, 128) layout — a
    128x padding expansion.  At 10.5M rows that is 2 x 5 GB of HLO temp,
    which OOMs the 16 GB v5e (measured from the compiler's HBM breakdown,
    round 4).  The masked select instead fuses into ONE in-place
    elementwise pass over the donated buffer; consecutive writes fuse
    together.  `col` may be a traced scalar; `vec` a [N] vector or scalar.
    """
    mask = lax.broadcasted_iota(jnp.int32, (1, payload.shape[1]), 1) == col
    v = vec if jnp.ndim(vec) == 0 else vec[:, None]
    if op == "add":
        v = payload + v
    elif op == "mul":
        v = payload * v
    else:
        assert op == "set", op
    return jnp.where(mask, v, payload)


class SplitPredicate(NamedTuple):
    """Scalars describing one split's routing decision
    (Bin::Split semantics, src/io/dense_bin.hpp:190-283).  `col` is the
    STORAGE column (the feature's EFB bundle); offset/identity decode the
    stored value back to the feature's own bin."""
    col: jax.Array           # i32 storage-column index into the bin columns
    threshold: jax.Array     # i32 bin threshold (numerical)
    default_left: jax.Array  # bool — where missing rows go
    is_cat: jax.Array        # bool — categorical bitset split
    bitset: jax.Array        # [B] bool — bins routed left (categorical)
    missing_type: jax.Array  # i32 (of the split feature)
    num_bin: jax.Array       # i32
    default_bin: jax.Array   # i32
    offset: jax.Array        # i32 bin offset inside the bundle
    identity: jax.Array      # bool — raw-bin passthrough (no bundle)


def go_left_chunk(chunk: jax.Array, pred: SplitPredicate) -> jax.Array:
    """[C] bool routing for one payload chunk (bin cols at [:, :G])."""
    C = chunk.shape[0]
    fcol = lax.dynamic_slice(chunk, (0, pred.col), (C, 1))[:, 0]
    fbin = decode_bin(fcol, pred.identity, pred.offset, pred.num_bin,
                      pred.default_bin)
    miss = ((pred.missing_type == MISSING_NAN) & (fbin == pred.num_bin - 1)) | \
           ((pred.missing_type == MISSING_ZERO) & (fbin == pred.default_bin))
    gl_num = jnp.where(miss, pred.default_left, fbin <= pred.threshold)
    B = pred.bitset.shape[0]
    onehot = fbin[:, None] == jnp.arange(B, dtype=jnp.int32)[None, :]
    gl_cat = jnp.sum(onehot & pred.bitset[None, :], axis=1) > 0
    return jnp.where(pred.is_cat, gl_cat, gl_num)


def _compact_matmul(chunk: jax.Array, keep: jax.Array) -> jax.Array:
    """Stable-compact kept rows to the front via a one-hot permutation
    matmul — the TPU-native scatter.  HIGHEST precision: the TPU MXU's
    default one-bf16-pass f32 matmul would round every payload value it
    permutes (and corrupt >8-bit idx columns)."""
    C = chunk.shape[0]
    dest = jnp.cumsum(keep.astype(jnp.int32)) - keep.astype(jnp.int32)
    perm = ((dest[None, :] == jnp.arange(C, dtype=jnp.int32)[:, None])
            & keep[None, :]).astype(chunk.dtype)
    return jnp.matmul(perm, chunk, precision=jax.lax.Precision.HIGHEST)


def partition_segment_stage(payload: jax.Array, aux: jax.Array,
                            start: jax.Array, count: jax.Array,
                            pred: SplitPredicate):
    """Passes A+B of the stable partition: compact LEFT rows of
    [start, start+count) into aux[start..], then RIGHT rows after them.
    payload is only READ — the frontier-batched grower stages candidate
    splits here and copies back (`partition_segment_commit`) only for the
    splits that commit, so an evaluated-but-uncommitted leaf's rows keep
    their exact sequential-grower order.  Compact writes overrun up to one
    chunk past the segment end in aux; callers staging several segments
    must stage them in ASCENDING start order so an overrun only ever
    clobbers a region that is (re)staged afterwards.
    Returns (aux, num_left)."""
    C = CHUNK
    nch = (count + C - 1) // C

    def read(buf, k):
        return lax.dynamic_slice(buf, (start + k * C, 0),
                                 (C, buf.shape[1]))

    def valid_rows(k):
        return jnp.arange(C, dtype=jnp.int32) < (count - k * C)

    # pass A: compact LEFT rows of each chunk, append at aux[start + running)
    def body_a(carry):
        k, nl, aux = carry
        chunk = read(payload, k)
        keep = go_left_chunk(chunk, pred) & valid_rows(k)
        compact = _compact_matmul(chunk, keep)
        aux = lax.dynamic_update_slice(aux, compact, (start + nl, 0))
        return k + 1, nl + jnp.sum(keep.astype(jnp.int32)), aux

    _, num_left, aux = lax.while_loop(lambda c: c[0] < nch, body_a,
                                      (jnp.int32(0), jnp.int32(0), aux))

    # pass B: compact RIGHT rows, append at aux[start + num_left + running)
    def body_b(carry):
        k, nr, aux = carry
        chunk = read(payload, k)
        keep = (~go_left_chunk(chunk, pred)) & valid_rows(k)
        compact = _compact_matmul(chunk, keep)
        aux = lax.dynamic_update_slice(aux, compact,
                                       (start + num_left + nr, 0))
        return k + 1, nr + jnp.sum(keep.astype(jnp.int32)), aux

    _, _, aux = lax.while_loop(lambda c: c[0] < nch, body_b,
                               (jnp.int32(0), jnp.int32(0), aux))
    return aux, num_left


def partition_segment_commit(payload: jax.Array, aux: jax.Array,
                             start: jax.Array, count: jax.Array,
                             num_left: jax.Array, left_value: jax.Array,
                             right_value: jax.Array, value_col: int):
    """Pass C of the stable partition: blended copy-back aux -> payload
    over [start, start+count), writing the children's creation values
    (Tree::Split leaf_value_) into the value column on the way through.
    count = 0 is a no-op (uncommitted staged candidates)."""
    C = CHUNK
    nch = (count + C - 1) // C
    vcol_onehot = (jnp.arange(payload.shape[1]) == value_col)[None, :]

    def read(buf, k):
        return lax.dynamic_slice(buf, (start + k * C, 0),
                                 (C, buf.shape[1]))

    def body_c(carry):
        k, payload = carry
        src = read(aux, k)
        dst = read(payload, k)
        ok = (jnp.arange(C, dtype=jnp.int32) < (count - k * C))[:, None]
        pos = start + k * C + jnp.arange(C, dtype=jnp.int32)
        val = jnp.where(pos < start + num_left, left_value, right_value)
        src = jnp.where(vcol_onehot, val[:, None], src)
        blended = jnp.where(ok, src, dst)
        payload = lax.dynamic_update_slice(payload, blended,
                                           (start + k * C, 0))
        return k + 1, payload

    _, payload = lax.while_loop(lambda c: c[0] < nch, body_c,
                                (jnp.int32(0), payload))
    return payload


def partition_segment(payload: jax.Array, aux: jax.Array, start: jax.Array,
                      count: jax.Array, pred: SplitPredicate,
                      left_value: jax.Array, right_value: jax.Array,
                      value_col: int):
    """Stably partition payload rows [start, start+count) by the predicate:
    left rows first.  Writes the children's leaf outputs into `value_col`.
    Returns (payload, aux, num_left) — num_left counts only rows whose
    count-mask survives in the caller's accounting; here it is the raw
    routed-row count used for segment offsets.  Composed of the stage
    (A+B) and commit (C) passes the frontier-batched grower runs apart.
    """
    aux, num_left = partition_segment_stage(payload, aux, start, count, pred)
    payload = partition_segment_commit(payload, aux, start, count, num_left,
                                       left_value, right_value, value_col)
    return payload, aux, num_left


def segment_histogram(payload: jax.Array, start: jax.Array, count: jax.Array,
                      *, num_features: int, num_bins: int,
                      grad_col: int, hess_col: int, cnt_col: int,
                      quantized: bool = False) -> jax.Array:
    """hist[F, B, 3] over payload rows [start, start+count).

    Only ceil(count / CHUNK) chunks are touched — the O(rows-touched)
    guarantee of the reference's ordered bins, with the scatter-free joint
    (feature, bin) one-hot contraction in place of per-row accumulation.

    quantized=True (gradient_quantization mode, ops.quantize): the
    grad/hess columns hold integer-VALUED f32 quantized gradients and the
    histogram accumulates int32.  On the scatter path the integers add
    directly; on the contraction path each CHUNK's partial histogram is
    f32-EXACT by construction (<= CHUNK * qmax < 2^23 per cell under the
    derive_qmax bound, and the bf16 part decomposition keeps products
    exact), so casting the per-chunk result to int32 before accumulating
    is exact at ANY total count — integer addition never rounds, which is
    what makes subtraction-trick siblings and cross-shard psums bit-exact.
    """
    C = CHUNK
    F, B = num_features, num_bins
    P = payload.shape[1]
    nch = (count + C - 1) // C
    iota_b = jnp.arange(B, dtype=jnp.int32)
    hist_dtype = jnp.int32 if quantized else jnp.float32
    # CPU test meshes scatter quickly but choke on one-hot contractions;
    # TPU is the inverse (and normally runs the Pallas kernels anyway)
    use_scatter = jax.default_backend() != "tpu"

    def body(carry):
        k, hist = carry
        chunk = lax.dynamic_slice(payload, (start + k * C, 0), (C, P))
        ok = (jnp.arange(C, dtype=jnp.int32) < (count - k * C)).astype(
            payload.dtype)
        binsf = chunk[:, :F].astype(jnp.int32)                 # [C, F]
        vals = jnp.stack([chunk[:, grad_col] * ok,
                          chunk[:, hess_col] * ok,
                          chunk[:, cnt_col] * ok], axis=1)     # [C, 3]
        if use_scatter:
            jidx = (binsf + iota_b[0] +
                    jnp.arange(F, dtype=jnp.int32)[None, :] * B)  # [C, F]
            upd = jnp.broadcast_to(vals[:, None, :], (C, F, 3)).reshape(-1, 3)
            if quantized:
                upd = upd.astype(jnp.int32)
            hist = hist.reshape(F * B, 3).at[jidx.reshape(-1)].add(
                upd).reshape(F, B, 3)
        else:
            from .histogram import _decompose_vals, _recombine_hist
            onehot = (binsf[:, :, None] == iota_b[None, None, :]).astype(
                payload.dtype)                                 # [C, F, B]
            # bf16-exact part columns keep the MXU contraction one-pass
            # AND exact (the default f32 matmul is one bf16 pass)
            chunk_hist = _recombine_hist(
                jnp.einsum("cfb,cd->fbd", onehot, _decompose_vals(vals),
                           preferred_element_type=jnp.float32))
            if quantized:
                chunk_hist = chunk_hist.astype(jnp.int32)
            hist = hist + chunk_hist
        return k + 1, hist

    hist0 = jnp.zeros((F, B, 3), hist_dtype)
    _, hist = lax.while_loop(lambda c: c[0] < nch, body,
                             (jnp.int32(0), hist0))
    return hist


def segment_histogram_batched(payload: jax.Array, starts: jax.Array,
                              counts: jax.Array, *, num_features: int,
                              num_bins: int, grad_col: int, hess_col: int,
                              cnt_col: int,
                              quantized: bool = False) -> jax.Array:
    """hist[K, F, B, 3] over K disjoint segments — portable batched engine.

    One traced region serves the whole frontier batch of the
    frontier-batched grower; a zero count yields a zero histogram (padding
    slots of a short frontier).  Each slice [k] is computed by the SAME
    per-chunk accumulation as `segment_histogram(payload, starts[k],
    counts[k])` — bit-identical per segment, which is what lets the batched
    grower stay byte-identical to the sequential one.  The TPU-native
    single-dispatch version is `pallas_segment.segment_histogram_batched`
    (staged behind FRONTIER_BATCH_VALIDATED)."""
    K = starts.shape[0]

    def body(k, hist):
        h = segment_histogram(payload, starts[k], counts[k],
                              num_features=num_features, num_bins=num_bins,
                              grad_col=grad_col, hess_col=hess_col,
                              cnt_col=cnt_col, quantized=quantized)
        return lax.dynamic_update_slice(hist, h[None], (k, 0, 0, 0))

    hist0 = jnp.zeros((K, num_features, num_bins, 3),
                      jnp.int32 if quantized else jnp.float32)
    return lax.fori_loop(0, K, body, hist0)
