"""Histogram construction — the hot kernel of histogram GBDT.

Replaces the reference's CPU gather-accumulate (src/io/dense_bin.hpp:66-132
DenseBin::ConstructHistogram) and the OpenCL kernels
(src/treelearner/ocl/histogram{16,64,256}.cl) with a TPU-native formulation:

    hist[f, b, :] = sum over rows i with bin(f, i) == b of [grad_i, hess_i, 1_i]

expressed as a one-hot × values batched matmul so the reduction over rows runs
on the MXU, chunked with `lax.scan` to bound the transient one-hot.  TPU has no
fast random scatter-add; the one-hot contraction is the idiomatic mapping (the
compare-and-broadcast producer fuses into the dot on TPU).

This masked full-data formulation backs the legacy grower and the parallel
tree learners; the partitioned grower (boosting/grower2.py) replaces it with
O(rows-touched) segment kernels (ops/segment.py, ops/pallas_segment.py,
selected via Config.tpu_histogram_impl).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..runtime import xla_obs


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


@functools.partial(xla_obs.jit, site="ops.build_histogram",
                   static_argnames=("num_bins", "row_chunk"))
def build_histogram(bins: jax.Array, vals: jax.Array, *, num_bins: int,
                    row_chunk: int = 16384) -> jax.Array:
    """hist[F, num_bins, 3] from bins[F, N] (integer) and vals[N, 3] float32.

    Rows are masked by zeroing their vals (grad, hess, count-weight) — a row
    with vals == 0 contributes nothing, which is how leaf masks, bagging and
    padding are applied without changing this kernel.

    Backend dispatch: on TPU the one-hot MXU contraction; elsewhere (CPU
    tests, virtual-device meshes) an XLA scatter-add, which is fast on CPU
    but would serialize on TPU.
    """
    F, N = bins.shape
    assert vals.shape == (N, 3)
    if jax.default_backend() != "tpu":
        return _hist_scatter(bins, vals, num_bins)
    if N <= row_chunk:
        return _hist_one_chunk(bins, vals, num_bins)
    assert N % row_chunk == 0, "caller pads N to a multiple of row_chunk"
    nchunk = N // row_chunk
    bins_c = bins.reshape(F, nchunk, row_chunk).transpose(1, 0, 2)
    vals_c = vals.reshape(nchunk, row_chunk, 3)

    def body(acc, xs):
        b, v = xs
        return acc + _hist_one_chunk(b, v, num_bins), None

    acc0 = jnp.zeros((F, num_bins, 3), jnp.float32)
    hist, _ = lax.scan(body, acc0, (bins_c, vals_c))
    return hist


def _hist_scatter(bins: jax.Array, vals: jax.Array, num_bins: int) -> jax.Array:
    """Scatter-add formulation for CPU backends."""
    F, N = bins.shape
    idx = bins.astype(jnp.int32) + jnp.arange(F, dtype=jnp.int32)[:, None] * num_bins
    updates = jnp.broadcast_to(vals[None, :, :], (F, N, 3)).reshape(-1, 3)
    flat = jnp.zeros((F * num_bins, 3), jnp.float32)
    flat = flat.at[idx.reshape(-1)].add(updates)
    return flat.reshape(F, num_bins, 3)


def _decompose_vals(vals: jax.Array) -> jax.Array:
    """[C, 3] (g, h, cnt) → [C, 7] exact bf16 parts (g_hi, g_mid, g_lo,
    h_hi, h_mid, h_lo, cnt).

    The MXU's default f32 matmul is ONE bf16 pass, which would round the
    gradients to 8 mantissa bits; each part here IS bf16-representable, so
    a one-pass contraction against a 0/1 one-hot is exact and the f32
    histogram is recovered as the sum of the part-histograms — the same
    trick as the Pallas kernel's vals rows, at none of HIGHEST's 3-6x
    pass cost."""
    gh = vals[:, :2]
    # NOT astype(bf16).astype(f32): under --xla_allow_excess_precision
    # (set by some TPU runtimes) XLA deletes that round trip, silently
    # collapsing the parts to (x, 0, 0); reduce_precision is contractual
    hi = lax.reduce_precision(gh, exponent_bits=8, mantissa_bits=7)
    r1 = gh - hi
    mid = lax.reduce_precision(r1, exponent_bits=8, mantissa_bits=7)
    lo = r1 - mid
    return jnp.concatenate(
        [hi[:, :1], mid[:, :1], lo[:, :1],
         hi[:, 1:], mid[:, 1:], lo[:, 1:], vals[:, 2:]], axis=1)


def _recombine_hist(parts: jax.Array) -> jax.Array:
    """[F, B, 7] part-histograms → [F, B, 3]."""
    return jnp.stack([parts[..., 0] + parts[..., 1] + parts[..., 2],
                      parts[..., 3] + parts[..., 4] + parts[..., 5],
                      parts[..., 6]], axis=-1)


def _hist_one_chunk(bins: jax.Array, vals: jax.Array, num_bins: int) -> jax.Array:
    """One-hot contraction over a row chunk: [F, C] × [C, 7] → [F, B, 3]."""
    iota = lax.broadcasted_iota(jnp.int32, (1, 1, num_bins), 2)
    onehot = (bins.astype(jnp.int32)[:, :, None] == iota).astype(jnp.float32)
    # batch dim F; contract the row-chunk dim (MXU reduction) with the
    # bf16-exact part columns — one-pass precision, exact products
    return _recombine_hist(
        jnp.einsum("fcb,cd->fbd", onehot, _decompose_vals(vals),
                   preferred_element_type=jnp.float32))


def subtract_histogram(parent: jax.Array, child: jax.Array) -> jax.Array:
    """Sibling histogram via subtraction (reference FeatureHistogram::Subtract,
    feature_histogram.hpp:68-74) — compute only the smaller child's histogram
    and derive the other."""
    return parent - child
