"""User-facing Dataset and Booster.

Role parity with the reference Python binding python-package/lightgbm/basic.py
(Dataset at :683+, Booster at :1412+), minus the ctypes layer: the "native"
side here is the JAX engine, so handles are plain Python objects.  Lazy
construction, validation-set alignment to the training mappers, and the
update/eval/predict/save surface mirror the reference binding.
"""
from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from .boosting.gbdt import GBDT
from .boosting.variants import create_boosting
from .config import Config
from .io.dataset import BinnedDataset, Metadata
from .metric import create_metrics
from .models.gbdt_model import GBDTModel
from .objective import create_objective, create_objective_from_model_string
from .utils.log import LightGBMError, Log


def _is_dataframe(data) -> bool:
    return hasattr(data, "dtypes") and hasattr(data, "columns")


def _data_from_pandas(data, feature_name, categorical_feature,
                      pandas_categorical):
    """DataFrame -> (X f64, names, categorical indices, pandas_categorical).

    Reference basic.py _data_from_pandas semantics: category-dtype columns
    become their category CODES (-1/unseen -> NaN); the per-column category
    lists are captured on the training set and re-applied positionally to
    validation/prediction frames so codes stay consistent."""
    cat_cols = [c for c in data.columns if str(data[c].dtype) == "category"]
    if pandas_categorical is None:          # training frame defines them
        pandas_categorical = [list(data[c].cat.categories) for c in cat_cols]
    elif len(cat_cols) != len(pandas_categorical):
        raise LightGBMError(
            "train and valid dataset categorical_feature do not match")
    if cat_cols:
        data = data.copy()
        for c, cats in zip(cat_cols, pandas_categorical):
            col = data[c]
            if list(col.cat.categories) != list(cats):
                col = col.cat.set_categories(cats)
            codes = np.asarray(col.cat.codes, dtype=np.float64)
            codes = np.where(codes < 0, np.nan, codes)
            data[c] = codes
    if feature_name in ("auto", None):
        names = [str(c) for c in data.columns]
    else:
        names = list(feature_name)
    cols = [str(c) for c in data.columns]

    def _pos(name):
        # category columns are located by their DataFrame position, so a
        # user-renaming feature_name list still works; user-named
        # categorical_feature entries must exist in the names
        if name in names:
            return names.index(name)
        if name in cols:
            return cols.index(name)
        raise LightGBMError("categorical column %r not found among the "
                            "feature names %s" % (name, names))

    cat_idx = []
    if categorical_feature in ("auto", None):
        cat_idx = [_pos(str(c)) for c in cat_cols]
    else:
        for cf in categorical_feature:
            cat_idx.append(_pos(cf) if isinstance(cf, str) else int(cf))
        for c in cat_cols:
            i = _pos(str(c))
            if i not in cat_idx:
                cat_idx.append(i)
    X = data.to_numpy(dtype=np.float64)
    return X, names, sorted(set(cat_idx)), pandas_categorical


def _load_pandas_categorical(model_text: str):
    """Parse the python-binding's trailing pandas_categorical line
    (reference basic.py _load_pandas_categorical)."""
    import json as _json
    idx = model_text.rfind("\npandas_categorical:")
    if idx < 0:
        return None
    line = model_text[idx + len("\npandas_categorical:"):].split("\n")[0]
    try:
        return _json.loads(line)
    except ValueError:
        return None


def _is_scipy_sparse(data) -> bool:
    return data.__class__.__module__.startswith("scipy.sparse")


def _sparse_rows(data, idx: np.ndarray) -> np.ndarray:
    """Row-slice a scipy.sparse matrix while still sparse, densify only
    the slice (cv folds / subsets of large sparse inputs must never
    materialize the full dense matrix)."""
    return np.asarray(data.tocsr()[idx].toarray(), dtype=np.float64)


def _slice_rows(data, idx: np.ndarray) -> np.ndarray:
    """Row-slice any supported input matrix (sparse checked before the
    `.values` duck test — dok_matrix subclasses dict, whose .values method
    would otherwise win)."""
    if _is_scipy_sparse(data):
        return _sparse_rows(data, idx)
    return _to_2d_float(data)[idx]


def _to_2d_float(data, pandas_categorical=None) -> np.ndarray:
    if _is_dataframe(data):
        data, _, _, _ = _data_from_pandas(data, "auto", "auto",
                                          pandas_categorical)
    elif _is_scipy_sparse(data):
        # reference basic.py accepts csr/csc/coo/...; the binning layer is
        # dense-columnar (EFB recovers the storage win for one-hot-style
        # sparsity — docs/STORAGE.md), so densify at the boundary.  Checked
        # BEFORE the .values duck test: dok_matrix subclasses dict, whose
        # .values method would shadow this branch.
        data = data.toarray()
    elif hasattr(data, "values"):  # pandas Series
        data = data.values
    arr = np.asarray(data, dtype=np.float64)
    if arr.ndim == 1:
        arr = arr.reshape(-1, 1)
    return arr


class Dataset:
    """Raw data + lazily-constructed binned form (basic.py Dataset semantics)."""

    def __init__(self, data, label=None, reference: Optional["Dataset"] = None,
                 weight=None, group=None, init_score=None, feature_name="auto",
                 categorical_feature="auto", params: Optional[Dict] = None,
                 free_raw_data: bool = False):
        self.data = data
        self.label = label
        self.reference = reference
        self.weight = weight
        self.group = group
        self.init_score = init_score
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.params = dict(params) if params else {}
        self.free_raw_data = free_raw_data
        self._binned: Optional[BinnedDataset] = None
        self.used_indices: Optional[np.ndarray] = None
        self.pandas_categorical = None  # per-column category lists

    # -- construction --------------------------------------------------------
    def construct(self, config: Optional[Config] = None) -> "Dataset":
        if self._binned is not None:
            return self
        if config is None:
            config = Config(self.params)
        from .io.stream import StreamingDatasetBuilder
        if isinstance(self.data, StreamingDatasetBuilder) or \
                (hasattr(self.data, "__next__")
                 and not isinstance(self.data, np.ndarray)):
            # streaming ingest (ISSUE 8): a chunk iterator or an explicit
            # StreamingDatasetBuilder — chunks were (or are now) pushed
            # without a file detour, and finalize() produces the same
            # binned dataset the parser path would
            return self._construct_stream(config)
        if isinstance(self.data, str):
            # a path: binary dataset cache (save_binary) or a text data file.
            # A validation set given as a path still aligns to the training
            # mappers/bundles through self.reference (Dataset::CreateValid).
            ref_mappers = ref_bundle = None
            if self.reference is not None:
                self.reference.construct(config)
                ref_mappers = self.reference._binned.bin_mappers
                ref_bundle = self.reference._binned.bundle_info
            if BinnedDataset.is_binary_file(self.data):
                if ref_mappers is not None:
                    Log.fatal("A binary dataset cache carries its own bin "
                              "mappers and cannot be re-aligned to a "
                              "reference dataset; rebuild the cache from "
                              "the validation data instead")
                self._binned = BinnedDataset.load_binary(self.data)
            else:
                from .io.parser import parse_file
                X, label = parse_file(self.data)
                self._binned = BinnedDataset.from_matrix(
                    X, config, bin_mappers=ref_mappers,
                    reference_bundle=ref_bundle)
                if label is not None and self.label is None:
                    self.label = label
            md = self._binned.metadata
            if self.label is not None:
                md.set_label(np.asarray(self.label))
            if self.weight is not None:
                md.set_weight(self.weight)
            if self.init_score is not None:
                md.set_init_score(self.init_score)
            if self.group is not None:
                md.set_query(self.group)
            return self
        ref_mappers = None
        ref_bundle = None
        if self.reference is not None:
            self.reference.construct(config)
            ref_mappers = self.reference._binned.bin_mappers
            ref_bundle = self.reference._binned.bundle_info
        if _is_dataframe(self.data):
            ref_pc = (self.reference.pandas_categorical
                      if self.reference is not None else None)
            X, names, cat_idx, self.pandas_categorical = _data_from_pandas(
                self.data, self.feature_name, self.categorical_feature,
                ref_pc)
            fn = names
            cats: Sequence[int] = cat_idx
        else:
            X = _to_2d_float(self.data)
            fn = None if self.feature_name == "auto" \
                else list(self.feature_name)
            cats = ()
            if self.categorical_feature != "auto" and self.categorical_feature:
                cats = [int(c) for c in self.categorical_feature]
        self._binned = BinnedDataset.from_matrix(
            X, config, bin_mappers=ref_mappers, feature_names=fn,
            categorical_feature=cats, reference_bundle=ref_bundle)
        md = self._binned.metadata
        if self.label is not None:
            md.set_label(np.asarray(self.label))
        md.set_weight(self.weight)
        md.set_init_score(self.init_score)
        md.set_query(self.group)
        return self

    def _construct_stream(self, config: Config) -> "Dataset":
        """Construct from a StreamingDatasetBuilder or a chunk iterator
        (chunks: X, (X, y) or (X, y, w); see io/stream.py)."""
        from .io.stream import StreamingDatasetBuilder
        builder = self.data
        if not isinstance(builder, StreamingDatasetBuilder):
            it = builder
            builder = StreamingDatasetBuilder(params=self.params)
            for chunk in it:
                builder.push(chunk)
            self.data = builder
        ref_mappers = ref_bundle = None
        if self.reference is not None:
            self.reference.construct(config)
            ref_mappers = self.reference._binned.bin_mappers
            ref_bundle = self.reference._binned.bundle_info
        fn = None if self.feature_name == "auto" else list(self.feature_name)
        cats: Sequence[int] = ()
        if self.categorical_feature != "auto" and self.categorical_feature:
            cats = [int(c) for c in self.categorical_feature]
        self._binned = builder.finalize(
            config, bin_mappers=ref_mappers, reference_bundle=ref_bundle,
            feature_names=fn, categorical_feature=cats)
        if self.label is None:
            self.label = builder.labels()
        if self.weight is None:
            self.weight = builder.weights()
        md = self._binned.metadata
        if self.label is not None:
            md.set_label(np.asarray(self.label))
        md.set_weight(self.weight)
        md.set_init_score(self.init_score)
        md.set_query(self.group)
        return self

    def push_rows(self, data, start_row: int = -1) -> "Dataset":
        """Streaming row push (LGBM_DatasetPushRows): only valid on a
        Dataset whose data is a StreamingDatasetBuilder (created with one,
        or through LGBM_DatasetCreateByReference) and not yet
        constructed."""
        self._stream_builder().push_dense(np.asarray(data),
                                          start_row=start_row)
        return self

    def push_rows_csr(self, indptr, indices, values, num_col: int,
                      start_row: int = -1) -> "Dataset":
        """Streaming CSR push (LGBM_DatasetPushRowsByCSR)."""
        self._stream_builder().push_csr(indptr, indices, values, num_col,
                                        start_row=start_row)
        return self

    def _stream_builder(self):
        from .io.stream import StreamingDatasetBuilder
        if self._binned is not None:
            raise LightGBMError(
                "Cannot push rows after the dataset is constructed")
        if not isinstance(self.data, StreamingDatasetBuilder):
            raise LightGBMError(
                "push_rows needs a streaming Dataset: create it from a "
                "StreamingDatasetBuilder (or LGBM_DatasetCreateByReference)")
        return self.data

    @classmethod
    def _from_binned(cls, binned: BinnedDataset,
                     params: Optional[Dict] = None) -> "Dataset":
        """Wrap an already-binned dataset (GetSubset results, C-ABI
        plumbing) in the user-facing handle."""
        ds = cls(None, params=params)
        ds._binned = binned
        return ds

    @property
    def binned(self) -> BinnedDataset:
        if self._binned is None:
            self.construct()
        return self._binned

    def save_binary(self, filename: str) -> "Dataset":
        """Write the constructed dataset to a binary cache file that later
        Dataset(filename) calls load directly (reference save_binary)."""
        self.binned.save_binary(filename)
        return self

    def create_valid(self, data, label=None, weight=None, group=None,
                     init_score=None, params=None) -> "Dataset":
        return Dataset(data, label=label, reference=self, weight=weight,
                       group=group, init_score=init_score, params=params)

    # -- accessors (binding surface) -----------------------------------------
    def num_data(self) -> int:
        return self.binned.num_data

    def get_ref_chain(self, ref_limit: int = 100) -> set:
        """Chain of Dataset references: this dataset, its reference, its
        reference's reference, ... until ref_limit or a loop (basic.py
        get_ref_chain)."""
        head = self
        ref_chain: set = set()
        while len(ref_chain) < ref_limit:
            if isinstance(head, Dataset):
                ref_chain.add(head)
                if head.reference is not None and head.reference not in ref_chain:
                    head = head.reference
                else:
                    break
            else:
                break
        return ref_chain

    def num_feature(self) -> int:
        return self.binned.num_features

    def get_label(self) -> np.ndarray:
        return self.binned.metadata.label

    def get_weight(self):
        return self.binned.metadata.weight

    def get_group(self):
        qb = self.binned.metadata.query_boundaries
        return None if qb is None else np.diff(qb)

    def set_label(self, label) -> None:
        self.label = label
        if self._binned is not None:
            self._binned.metadata.set_label(np.asarray(label))

    def set_weight(self, weight) -> None:
        self.weight = weight
        if self._binned is not None:
            self._binned.metadata.set_weight(weight)

    def set_group(self, group) -> None:
        self.group = group
        if self._binned is not None:
            self._binned.metadata.set_query(group)

    def set_init_score(self, init_score) -> None:
        self.init_score = init_score
        if self._binned is not None:
            self._binned.metadata.set_init_score(init_score)

    def get_init_score(self):
        return self.binned.metadata.init_score

    def get_field(self, field_name: str):
        """Generic field accessor (reference Dataset.get_field)."""
        if field_name == "label":
            return self.get_label()
        if field_name == "weight":
            return self.get_weight()
        if field_name == "init_score":
            return self.get_init_score()
        if field_name in ("group", "query"):
            return self.get_group()
        raise LightGBMError("Unknown field name: %s" % field_name)

    def set_field(self, field_name: str, data) -> "Dataset":
        if field_name == "label":
            self.set_label(data)
        elif field_name == "weight":
            self.set_weight(data)
        elif field_name == "init_score":
            self.set_init_score(data)
        elif field_name in ("group", "query"):
            self.set_group(data)
        else:
            raise LightGBMError("Unknown field name: %s" % field_name)
        return self

    def set_categorical_feature(self, categorical_feature) -> "Dataset":
        if self._binned is not None and \
                categorical_feature != self.categorical_feature:
            raise LightGBMError(
                "Cannot change categorical_feature after the dataset is "
                "constructed; create a new Dataset")
        self.categorical_feature = categorical_feature
        return self

    def set_feature_name(self, feature_name) -> "Dataset":
        self.feature_name = feature_name
        if self._binned is not None and feature_name != "auto":
            if len(feature_name) != self._binned.num_features:
                raise LightGBMError(
                    "Length of feature names does not equal the number "
                    "of features")
            self._binned.feature_names = list(feature_name)
        return self

    def set_reference(self, reference: "Dataset") -> "Dataset":
        if self._binned is not None and self.reference is not reference:
            raise LightGBMError(
                "Cannot set reference after the dataset is constructed; "
                "create a new Dataset")
        self.reference = reference
        return self

    def subset(self, used_indices, params=None) -> "Dataset":
        idx = np.asarray(used_indices)
        from .io.stream import StreamingDatasetBuilder
        if self.data is None or isinstance(self.data, (str,
                                                       StreamingDatasetBuilder)) \
                or hasattr(self.data, "__next__"):
            # no raw matrix to re-bin (path-backed or streaming ingest):
            # gather the BINNED rows directly (reference GetSubset)
            self.construct()
            return Dataset._from_binned(
                self.binned.subset(np.sort(np.unique(idx))),
                params=params or self.params)
        X = _slice_rows(self.data, idx)
        y = None if self.label is None else np.asarray(self.label)[idx]
        w = None if self.weight is None else np.asarray(self.weight)[idx]
        return Dataset(X, label=y, weight=w, reference=self,
                       params=params or self.params)


class Booster:
    """Training/prediction handle (basic.py Booster; c_api.cpp Booster)."""

    def __init__(self, params: Optional[Dict] = None, train_set: Optional[Dataset] = None,
                 model_file: Optional[str] = None, model_str: Optional[str] = None,
                 init_model: Optional[GBDTModel] = None):
        params = dict(params) if params else {}
        self.params = params
        self.best_iteration = -1
        self.best_score: Dict = {}
        self._valid_names: List[str] = ["training"]
        self._valid_data: List = []
        self._engine: Optional[GBDT] = None
        self._model: Optional[GBDTModel] = None
        self._objective = None
        self.config: Optional[Config] = None

        if train_set is not None:
            self.config = Config(params)
            self.config.warn_unimplemented()
            # reference-binding parity: a cluster config on the Booster
            # brings the network up (basic.py:1470 machines -> NetworkInit);
            # here that is jax.distributed over the same machine list
            from .parallel.launch import maybe_init_distributed
            maybe_init_distributed(self.config)
            train_set.construct(self.config)
            obj = self.config.objective
            self._objective = create_objective(obj, self.config) \
                if isinstance(obj, str) else None
            binned = train_set.binned
            if self._objective is not None and binned.metadata.label is None:
                Log.fatal("Label should not be None for training")
            metrics = create_metrics(self.config.metric, self.config)
            for m in metrics:
                m.init(binned.metadata.label, binned.metadata.weight,
                       binned.metadata.query_boundaries)
            self._engine = create_boosting(str(self.config.boosting), self.config,
                                           binned, self._objective, metrics,
                                           init_model=copy.deepcopy(init_model)
                                           if init_model is not None else None)
            self._model = self._engine.model
            self.train_set = train_set
            self.pandas_categorical = train_set.pandas_categorical
        elif model_file is not None or model_str is not None:
            text = model_str if model_str is not None else open(model_file).read()
            self.config = Config(params)
            self._load_from_string(text)
        else:
            raise LightGBMError("Booster needs train_set or model file")

    # -- pickling (reference basic.py Booster __getstate__/__setstate__:
    # serialize as the model string; the engine/device state is not portable)
    def __getstate__(self) -> Dict:
        self._drain()
        state = self.__dict__.copy()
        state.pop("_engine", None)
        state.pop("train_set", None)
        state.pop("_valid_data", None)  # holds full datasets via .reference
        state.pop("_objective", None)
        state.pop("_dev_predictor", None)   # holds device arrays
        state.pop("_dev_pred_key", None)
        if self._model is not None:
            state["_model_str"] = self._model.save_model_to_string()
        state.pop("_model", None)
        return state

    def __setstate__(self, state: Dict) -> None:
        model_str = state.pop("_model_str", None)
        self.__dict__.update(state)
        self._engine = None
        self.train_set = None
        self._valid_data = []
        if model_str is not None:
            pc = getattr(self, "pandas_categorical", None)
            self._load_from_string(model_str)
            if pc is not None:  # pickled attr wins (string has no line)
                self.pandas_categorical = pc
        else:
            self._model = None
            self._objective = None

    # -- training ------------------------------------------------------------
    def add_valid(self, data: Dataset, name: str) -> "Booster":
        if self._engine is None:
            raise LightGBMError("Cannot add validation data to a loaded Booster")
        # the reference MUST be attached BEFORE construct(): validation
        # bins are only meaningful against the TRAINING bin mappers (the
        # reference binding force-sets it in engine.train via
        # set_reference(train_set)).  A valid set already constructed
        # against different mappers is re-binned — scoring it would
        # traverse training split_bins over foreign bin ids.
        if data is self.train_set:
            # eval-on-train (cv eval_train_metric, add_valid(train_set)):
            # already binned with its own mappers BY DEFINITION — attaching
            # a self-reference would wipe the engine's binning and recurse
            pass
        elif data.reference is not self.train_set:
            if data._binned is not None:
                Log.warning("Validation set was constructed without "
                            "reference=train_set; re-binning with training "
                            "mappers")
                data._binned = None
            else:
                Log.warning("Validation set was not created with "
                            "reference=train_set; binning with training "
                            "mappers")
            data.reference = self.train_set
        data.construct(self.config)
        metrics = create_metrics(self.config.metric, self.config)
        self._engine.add_valid(name, data.binned, metrics)
        self._valid_names.append(name)
        self._valid_data.append((name, data))
        return self

    def update(self, train_set=None, fobj=None) -> bool:
        if self._engine is None:
            raise LightGBMError("Cannot update a loaded Booster")
        from .runtime import resilience
        # fault-injection seam (LGBM_TPU_FAULT=die_at_iter:K /
        # sigterm_at_iter:K): the iteration boundary is where an abrupt
        # death or a preemption notice lands in testing
        resilience.maybe_die_or_preempt(self)
        self._model_version = getattr(self, "_model_version", 0) + 1
        guard = resilience.SentinelGuard(self._engine)
        try:
            # observability seam (ISSUE 9): one iteration's wall time,
            # the iteration counter, the per-iteration sync-audit gauges
            # and the LGBM_TPU_PROFILE training hook — here because this
            # is the one chokepoint EVERY boosting variant goes through
            from .runtime import telemetry
            with telemetry.train_iteration():
                if fobj is not None:
                    grad, hess = fobj(
                        self._engine.raw_train_score().reshape(-1),
                        self.train_set)
                    return self._engine.train_one_iter(grad, hess)
                return self._engine.train_one_iter()
        except resilience.NonFiniteDetected as e:
            # abort re-raises naming the iteration; rollback restores the
            # pre-iteration scores, drops the trees and reports finished
            return guard.handle(e, Log)

    def rollback_one_iter(self) -> "Booster":
        self._model_version = getattr(self, "_model_version", 0) + 1
        self._engine.rollback_one_iter()
        return self

    def _drain(self) -> None:
        """Flush the engine's async dispatch pipeline so model reads see
        every dispatched tree (no-op for loaded boosters and for an empty
        pipeline).  Every Booster entry point that observes the model
        object goes through here — `update()` may legitimately return
        with up to `pipeline_depth` tree assemblies still in flight."""
        if self._engine is not None:
            self._engine.flush()

    def current_iteration(self) -> int:
        """Number of completed iterations (reference Booster method)."""
        self._drain()
        return self._model.current_iteration

    def phase_timings(self):
        """Accumulated {phase: seconds} when tpu_profile_phases=true (the
        reference's TIMETAG counters); empty dict otherwise."""
        if self._engine is None:
            return {}
        return dict(self._engine.timer.seconds)

    # -- reference Booster surface parity ------------------------------------
    def num_model_per_iteration(self) -> int:
        return self._model.num_tree_per_iteration

    def num_feature(self) -> int:
        """Number of features the model was trained on (basic.py
        num_feature / LGBM_BoosterGetNumFeature)."""
        return self._model.max_feature_idx + 1

    def reset_parameter(self, params: Dict) -> "Booster":
        """Reset Booster parameters mid-training (basic.py reset_parameter
        -> Booster::ResetConfig): live-applied into the engine config so
        e.g. learning_rate / bagging_fraction changes take effect on the
        next iteration.  Engine-less (loaded) boosters update their
        prediction-time config."""
        if self._engine is not None:
            self._engine.reset_config(params)
        elif self.config is not None:
            self.config.set(params)
        self.params.update(params)
        return self

    def get_leaf_output(self, tree_id: int, leaf_id: int) -> float:
        self._drain()
        return float(self._model.trees[tree_id].leaf_value[leaf_id])

    def attr(self, key: str):
        return getattr(self, "_attr", {}).get(key)

    def set_attr(self, **kwargs) -> "Booster":
        store = getattr(self, "_attr", None)
        if store is None:
            store = self._attr = {}
        for k, v in kwargs.items():
            if v is None:
                store.pop(k, None)
            elif isinstance(v, str):
                store[k] = v
            else:
                raise LightGBMError("Only string values are accepted")
        return self

    def _load_from_string(self, model_str: str) -> None:
        """The one load-from-string sequence shared by __init__,
        __setstate__ and model_from_string."""
        self._model = GBDTModel.load_model_from_string(model_str)
        self.pandas_categorical = _load_pandas_categorical(model_str)
        cfg = self.config if self.config is not None else Config({})
        self._objective = create_objective_from_model_string(
            self._model.objective_str, cfg)
        self._model_version = getattr(self, "_model_version", 0) + 1

    def model_from_string(self, model_str: str,
                          verbose: bool = True) -> "Booster":
        """Re-initialize from a model string (drops any training engine)."""
        self._engine = None
        self.train_set = None
        self._load_from_string(model_str)
        if verbose:
            Log.info("Finished loading model, total used %d iterations",
                     self._model.current_iteration)
        return self

    def shuffle_models(self, start_iteration: int = 0,
                       end_iteration: int = -1) -> "Booster":
        """Randomly permute tree order in [start, end) iterations
        (reference Booster.shuffle_models)."""
        self._drain()
        k = self._model.num_tree_per_iteration
        total = self._model.current_iteration
        end = total if end_iteration <= 0 else min(end_iteration, total)
        if not 0 <= start_iteration <= end:
            raise LightGBMError(
                "shuffle_models range [%d, %d) is invalid for a %d-iteration "
                "model" % (start_iteration, end, total))
        idx = np.arange(start_iteration, end)
        np.random.shuffle(idx)
        trees = self._model.trees
        blocks = [trees[i * k:(i + 1) * k] for i in range(total)]
        reordered = blocks[:start_iteration] + \
            [blocks[i] for i in idx] + blocks[end:]
        self._model.trees = [t for b in reordered for t in b]
        self._model_version = getattr(self, "_model_version", 0) + 1
        return self

    def set_train_data_name(self, name: str) -> "Booster":
        self._train_data_name = name
        return self

    def free_dataset(self) -> "Booster":
        self.train_set = None
        return self

    def free_network(self) -> "Booster":
        return self  # XLA owns transport; nothing to tear down

    def set_network(self, *args, **kwargs) -> "Booster":
        Log.warning("set_network is a no-op: XLA/ICI owns transport; "
                    "launch with jax.distributed for multi-host")
        return self

    def __copy__(self) -> "Booster":
        return self.__deepcopy__(None)

    def __deepcopy__(self, _) -> "Booster":
        return Booster(model_str=self.model_to_string())

    def num_trees(self) -> int:
        self._drain()
        return self._model.num_total_trees

    # -- evaluation ----------------------------------------------------------
    def eval(self, data: Dataset, name: str, feval=None) -> List:
        """Evaluate the current model on an arbitrary Dataset
        (reference Booster.eval)."""
        self._drain()
        data.construct(self.config)
        label = data.get_label()
        if isinstance(data.data, str):
            # path-backed Dataset: re-parse the raw matrix (construct()
            # keeps only the binned form)
            from .io.parser import parse_file
            X, _ = parse_file(data.data)
        else:
            X = _to_2d_float(data.data,
                             getattr(self, "pandas_categorical", None))
        raw = self._model.predict_raw(X).T                   # [K, N]
        metrics = create_metrics(self.config.metric, self.config) \
            if self.config else []
        out = []
        qb = data.binned.metadata.query_boundaries
        for m in metrics:
            m.init(label, data.get_weight(), qb)
            score = raw if getattr(m, "multiclass", False) else \
                (raw[0] if raw.shape[0] == 1 else raw.reshape(-1))
            out.append((name, m.name, float(m.eval(score, self._objective)),
                        m.is_higher_better))
        if feval is not None:
            preds = raw[0] if raw.shape[0] == 1 else raw.reshape(-1)
            mname, val, hib = feval(preds, data)
            out.append((name, mname, val, hib))
        return out

    def eval_train(self, feval=None) -> List:
        return self._wrap_eval(self._engine.eval_train(), feval, "training")

    def eval_valid(self, feval=None) -> List:
        out = self._wrap_eval(self._engine.eval_valid(), None, None)
        if feval is not None:
            # custom metric runs on every validation set too (engine.py
            # _agg_standard_result over all eval sets in the reference)
            for i, (name, ds) in enumerate(self._valid_data):
                raw = self._engine.raw_valid_score(i)
                preds = raw[0] if raw.shape[0] == 1 else raw.reshape(-1)
                mname, val, hib = feval(preds, ds)
                out.append((name, mname, val, hib))
        return out

    def eval_round(self, feval=None, include_train: bool = False):
        """One evaluation round — (train results, valid results) — off a
        SINGLE packed device fetch (engine.eval_all), so metric_freq=1
        doesn't pay one D2H round trip per dataset.  Used by the train()
        driver; eval_train/eval_valid keep the reference per-surface
        behavior for direct callers."""
        tr_res, va_res = self._engine.eval_all(include_train)
        train_out = self._wrap_eval(tr_res, feval, "training") \
            if include_train else []
        valid_out = self._wrap_eval(va_res, None, None)
        if feval is not None:
            for i, (name, ds) in enumerate(self._valid_data):
                raw = self._engine.raw_valid_score(i)
                preds = raw[0] if raw.shape[0] == 1 else raw.reshape(-1)
                mname, val, hib = feval(preds, ds)
                valid_out.append((name, mname, val, hib))
        return train_out, valid_out

    def _wrap_eval(self, results, feval, dataset_name):
        out = [(name, metric, val, hib) for (name, metric, val, hib) in results]
        if feval is not None:
            raw = self._engine.raw_train_score().reshape(-1) if dataset_name == "training" \
                else None
            if raw is not None:
                name, val, hib = feval(raw, self.train_set)
                out.append((dataset_name, name, val, hib))
        return out

    # -- prediction ----------------------------------------------------------
    def predict(self, data, num_iteration: int = -1, raw_score: bool = False,
                pred_leaf: bool = False, pred_contrib: bool = False,
                pred_early_stop: bool = False, pred_early_stop_freq: int = 10,
                pred_early_stop_margin: float = 10.0,
                device: bool = False, start_iteration: int = 0,
                out_dtype=None, leaf_quant: Optional[str] = None,
                **kwargs) -> np.ndarray:
        """device=True runs the jitted tree-parallel inference engine
        (models/device_predictor.py: f32 thresholds, categorical bitsets
        on device, shape-bucketed program cache, micro-batched transfer)
        instead of the exact f64 host traversal — the throughput path
        for large matrices.

        ISSUE 16 serving knobs (device path only): `out_dtype=
        np.float32` fetches and returns float32 — half the D2H bytes,
        and exactly the float64 answer `.astype(float32)` (output
        transforms still run in f64 on the exact upcast).  `leaf_quant=
        "int8"` opts into the int8-quantized leaf table; when the
        staged `device_predictor.LEAF_QUANT_VALIDATED` flag is ON it
        becomes the default (pass leaf_quant="none" to opt out)."""
        self._drain()
        X = _to_2d_float(data, getattr(self, "pandas_categorical", None))
        if pred_leaf:
            return self._model.predict_leaf_index(X, num_iteration)
        if pred_contrib:
            return self._model.predict_contrib(X, num_iteration)
        # shared NeedAccuratePrediction gating so host and device paths
        # truncate sums identically (gbdt_model.early_stop_mode)
        early = self._model.early_stop_mode(pred_early_stop)
        if device:
            from .models import device_predictor as dpr
            lq = leaf_quant
            if lq is None and dpr.LEAF_QUANT_VALIDATED:
                lq = "int8"            # staged default once validated
            if lq in ("none", "float32"):
                lq = None              # explicit opt-out of the staged flag
            end = self._model.num_prediction_iterations(start_iteration,
                                                        num_iteration)
            key = (start_iteration, end, len(self._model.trees),
                   getattr(self, "_model_version", 0), lq)
            if getattr(self, "_dev_pred_key", None) != key:
                self._dev_predictor = dpr.DevicePredictor(
                    self._model, start_iteration, num_iteration,
                    leaf_quant=lq)
                self._dev_pred_key = key
            raw = self._dev_predictor.predict_raw(
                X, early_stop=early,
                early_stop_freq=pred_early_stop_freq,
                early_stop_margin=pred_early_stop_margin,
                out_dtype=np.float32 if np.dtype(out_dtype or np.float64)
                == np.float32 else np.float64)
            return self._finish_predict(raw, raw_score, num_iteration,
                                        start_iteration)
        raw = self._model.predict_raw(X, start_iteration=start_iteration,
                                      num_iteration=num_iteration,
                                      early_stop=early,
                                      early_stop_freq=pred_early_stop_freq,
                                      early_stop_margin=pred_early_stop_margin)
        return self._finish_predict(raw, raw_score, num_iteration,
                                    start_iteration)

    def _finish_predict(self, raw: np.ndarray, raw_score: bool,
                        num_iteration: int = -1,
                        start_iteration: int = 0) -> np.ndarray:
        # f32 raw scores (ISSUE 16 out_dtype path): run the output
        # transform in f64 on the EXACT upcast, then downcast — so the
        # f32 surface equals the f64 surface .astype(float32), bit for
        # bit, and transform math never degrades
        f32 = raw.dtype == np.float32
        if f32:
            raw = raw.astype(np.float64)
        if raw.shape[1] == 1:
            raw = raw[:, 0]
        if raw_score:
            out = raw
        elif self._model.average_output:
            # averaged pre-converted outputs; no ConvertOutput on top
            # (gbdt_prediction.cpp Predict, average_output_ branch)
            out = raw / self._model.num_prediction_iterations(
                start_iteration, num_iteration)
        elif self._objective is None:
            out = raw
        else:
            out = self._objective.convert_output(raw)
        return out.astype(np.float32) if f32 else out

    def refit(self, data, label, weight=None, group=None,
              decay_rate: Optional[float] = None) -> "Booster":
        """Refit existing tree structures to new data (gbdt.cpp RefitTree
        :338-361 + FitByExistingTree, serial_tree_learner.cpp:223-248): keep
        every split, recompute leaf values from the new data's gradients with
        leaf_output = decay*old + (1-decay)*new*shrinkage, iterating so later
        trees see the refit scores of earlier ones."""
        import jax
        import jax.numpy as jnp

        if self._objective is None:
            raise LightGBMError("Cannot refit with a custom objective")
        self._drain()
        X = _to_2d_float(data, getattr(self, "pandas_categorical", None))
        label = np.asarray(label, dtype=np.float64).reshape(-1)
        n = X.shape[0]
        model = copy.deepcopy(self._model)
        cfg = self.config
        decay = float(cfg.refit_decay_rate) if decay_rate is None else float(decay_rate)
        l1, l2 = float(cfg.lambda_l1), float(cfg.lambda_l2)
        mds = float(cfg.max_delta_step)
        K = model.num_tree_per_iteration
        num_iters = model.current_iteration

        objective = create_objective(self.config.objective, self.config) \
            if isinstance(self.config.objective, str) else self._objective
        qb = None
        if group is not None:
            qb = np.concatenate([[0], np.cumsum(np.asarray(group, np.int64))])
        objective.init(label, weight, qb)
        leaf_pred = model.predict_leaf_index(X).astype(np.int64)   # [n, T]
        w_dev = jnp.asarray(np.ones(n, np.float32) if weight is None
                            else np.asarray(weight, np.float32))
        label_dev = jnp.asarray(label.astype(np.float32))
        scores = np.zeros((K, n), dtype=np.float64)

        from .runtime import syncs
        for it in range(num_iters):
            g, h = objective.get_gradients_multi(
                jnp.asarray(scores.astype(np.float32)), label_dev, w_dev)
            g, h = syncs.device_get((g, h), label="refit_fetch")
            g = np.asarray(g, np.float64)
            h = np.asarray(h, np.float64)
            for k in range(K):
                tree = model.trees[it * K + k]
                nl = tree.num_leaves
                leaves = leaf_pred[:, it * K + k]
                sum_g = np.bincount(leaves, weights=g[k], minlength=nl)[:nl]
                sum_h = np.bincount(leaves, weights=h[k], minlength=nl)[:nl] + 1e-15
                out = -np.sign(sum_g) * np.maximum(np.abs(sum_g) - l1, 0.0) / (sum_h + l2)
                if mds > 0.0:
                    out = np.clip(out, -mds, mds)
                tree.leaf_value[:nl] = decay * tree.leaf_value[:nl] + \
                    (1.0 - decay) * out * tree.shrinkage
                scores[k] += tree.leaf_value[leaves]
        new_booster = Booster(params=dict(self.params),
                              model_str=model.save_model_to_string())
        return new_booster

    # -- model IO ------------------------------------------------------------
    def _pandas_categorical_line(self) -> str:
        """The python-binding's trailing category-lists record (reference
        _save_pandas_categorical); empty when no category columns, so CLI
        byte-parity is kept for non-pandas models.  numpy scalars serialize
        as native numbers — stringified categories would never match an
        int/float categorical column again at load time."""
        if not getattr(self, "pandas_categorical", None):
            return ""
        import json as _json

        def _default(o):
            if hasattr(o, "item"):
                return o.item()
            return str(o)

        return "\npandas_categorical:%s\n" % _json.dumps(
            self.pandas_categorical, default=_default)

    def save_model(self, filename: str, num_iteration: int = -1,
                   start_iteration: int = 0) -> "Booster":
        self._drain()
        params = self.config.to_string() if self.config else ""
        self._model.save_model(filename, start_iteration, num_iteration,
                               parameters=params)
        line = self._pandas_categorical_line()
        if line:
            with open(filename, "a") as fh:
                fh.write(line)
        return self

    def model_to_string(self, num_iteration: int = -1, start_iteration: int = 0) -> str:
        self._drain()
        return self._model.save_model_to_string(start_iteration,
                                                num_iteration) + \
            self._pandas_categorical_line()

    def dump_model(self, num_iteration: int = -1) -> Dict:
        self._drain()
        return self._model.dump_model(num_iteration)

    def feature_importance(self, importance_type: str = "split",
                           iteration: int = -1) -> np.ndarray:
        self._drain()
        return self._model.feature_importance(iteration, importance_type)

    def feature_name(self) -> List[str]:
        return list(self._model.feature_names)
