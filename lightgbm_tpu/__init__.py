"""LightGBM-TPU: a TPU-native gradient-boosting framework (JAX/XLA/Pallas).

Public surface mirrors the reference python-package/lightgbm/__init__.py.
"""
from .basic import Booster, Dataset
from .callback import (early_stopping, log_evaluation, print_evaluation,
                       record_evaluation, reset_parameter)
from .config import Config
from .engine import CVBooster, cv, predict, train
from .plotting import (create_tree_digraph, plot_importance, plot_metric,
                       plot_tree)
from .parallel.launch import init_distributed
from .sklearn import LGBMClassifier, LGBMModel, LGBMRanker, LGBMRegressor
from .utils.log import LightGBMError

__version__ = "0.1.0"

__all__ = ["Dataset", "Booster", "Config", "train", "cv", "predict",
           "CVBooster",
           "LightGBMError",
           "early_stopping", "log_evaluation", "print_evaluation",
           "record_evaluation", "reset_parameter",
           "LGBMModel", "LGBMRegressor", "LGBMClassifier", "LGBMRanker",
           "plot_importance", "plot_metric", "plot_tree",
           "create_tree_digraph", "init_distributed"]
