"""Training callbacks.

Role parity with the reference python-package/lightgbm/callback.py:
print/log evaluation, record evaluation, reset_parameter, early stopping via
EarlyStopException.
"""
from __future__ import annotations

import collections
from typing import Any, Callable, Dict, List, NamedTuple, Optional

from .utils.log import Log


class CallbackEnv(NamedTuple):
    model: Any
    params: Dict
    iteration: int
    begin_iteration: int
    end_iteration: int
    evaluation_result_list: Optional[List]


class EarlyStopException(Exception):
    def __init__(self, best_iteration: int, best_score: List):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


def _format_eval_result(value, show_stdv: bool = True) -> str:
    if len(value) == 4:
        return "%s's %s: %g" % (value[0], value[1], value[2])
    if len(value) == 5:
        if show_stdv:
            return "%s's %s: %g + %g" % (value[0], value[1], value[2], value[4])
        return "%s's %s: %g" % (value[0], value[1], value[2])
    raise ValueError("Wrong metric value")


def log_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    def _callback(env: CallbackEnv) -> None:
        if period > 0 and env.evaluation_result_list \
                and (env.iteration + 1) % period == 0:
            result = "\t".join(_format_eval_result(x, show_stdv)
                               for x in env.evaluation_result_list)
            Log.info("[%d]\t%s", env.iteration + 1, result)
    _callback.order = 10
    return _callback


# reference-era alias
print_evaluation = log_evaluation


def record_evaluation(eval_result: Dict) -> Callable:
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result should be a dictionary")
    eval_result.clear()

    def _callback(env: CallbackEnv) -> None:
        for entry in env.evaluation_result_list or []:
            name, metric, value = entry[0], entry[1], entry[2]
            eval_result.setdefault(name, collections.OrderedDict())
            eval_result[name].setdefault(metric, [])
            eval_result[name][metric].append(value)
    _callback.order = 20
    return _callback


def reset_parameter(**kwargs) -> Callable:
    def _callback(env: CallbackEnv) -> None:
        new_params = {}
        for key, value in kwargs.items():
            if isinstance(value, list):
                if len(value) != env.end_iteration - env.begin_iteration:
                    raise ValueError("Length of list %r has to be equal to 'num_boost_round'" % key)
                new_params[key] = value[env.iteration - env.begin_iteration]
            elif callable(value):
                new_params[key] = value(env.iteration - env.begin_iteration)
            else:
                raise ValueError("Only list and callable values are "
                                 "supported as a parameter schedule")
        if new_params:
            # cv() passes the CVBooster; apply to every fold booster
            # through the one shared ResetConfig path
            boosters = getattr(env.model, "boosters", [env.model])
            for bst in boosters:
                bst.reset_parameter(new_params)
            env.params.update(new_params)
    _callback.before_iteration = True
    _callback.order = 10
    return _callback


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True) -> Callable:
    best_score: List[float] = []
    best_iter: List[int] = []
    best_score_list: List[List] = []
    cmp_op: List[Callable] = []
    enabled = [True]

    def _init(env: CallbackEnv) -> None:
        enabled[0] = bool(env.evaluation_result_list)
        if not enabled[0]:
            Log.warning("Early stopping is not available in dart mode or without valid sets")
            return
        if verbose:
            Log.info("Training until validation scores don't improve for %d rounds.",
                     stopping_rounds)
        for _ in env.evaluation_result_list:
            best_iter.append(0)
            best_score_list.append(None)
        # entries are (name, metric, value, higher_better) from train(), or
        # ('cv_agg', 'ds metric', mean, higher_better, std) from cv()
        for entry in env.evaluation_result_list:
            higher_better = entry[3]
            if higher_better:
                best_score.append(float("-inf"))
                cmp_op.append(lambda x, y: x > y)
            else:
                best_score.append(float("inf"))
                cmp_op.append(lambda x, y: x < y)

    def _callback(env: CallbackEnv) -> None:
        if not best_score:
            _init(env)
        if not enabled[0]:
            return
        for i, entry in enumerate(env.evaluation_result_list):
            name, metric, score = entry[0], entry[1], entry[2]
            if best_score_list[i] is None or cmp_op[i](score, best_score[i]):
                best_score[i] = score
                best_iter[i] = env.iteration
                best_score_list[i] = env.evaluation_result_list
            if name == "training" or \
                    (name == "cv_agg" and metric.startswith("train ")):
                continue  # train metric does not trigger stopping
            if env.iteration - best_iter[i] >= stopping_rounds:
                if verbose:
                    Log.info("Early stopping, best iteration is: [%d]\t%s",
                             best_iter[i] + 1,
                             "\t".join(_format_eval_result(x) for x in best_score_list[i]))
                raise EarlyStopException(best_iter[i], best_score_list[i])
            if env.iteration == env.end_iteration - 1:
                if verbose:
                    Log.info("Did not meet early stopping. Best iteration is: [%d]\t%s",
                             best_iter[i] + 1,
                             "\t".join(_format_eval_result(x) for x in best_score_list[i]))
                raise EarlyStopException(best_iter[i], best_score_list[i])
            if first_metric_only:
                break
    _callback.order = 30
    return _callback
