"""train() / cv() drivers.

Role parity with the reference python-package/lightgbm/engine.py
(train at :18-316, cv at :317+): callback environment, early stopping via
exception, evaluation-result bookkeeping and best_iteration.
"""
from __future__ import annotations

import collections
import copy
from typing import Any, Dict, List, Optional

import numpy as np

from .basic import Booster, Dataset
from .callback import CallbackEnv, EarlyStopException, log_evaluation
from .utils.log import Log


def train(params: Dict, train_set: Dataset, num_boost_round: int = 100,
          valid_sets: Optional[List[Dataset]] = None,
          valid_names: Optional[List[str]] = None,
          fobj=None, feval=None, init_model=None,
          keep_training_booster: bool = True,
          callbacks: Optional[List] = None,
          early_stopping_rounds: Optional[int] = None,
          verbose_eval=True) -> Booster:
    params = dict(params)
    if fobj is not None:
        params["objective"] = "none"
    init = None
    if init_model is not None:
        # continued training: accept a filename, Booster or raw model
        if isinstance(init_model, str):
            from .models.gbdt_model import GBDTModel
            init = GBDTModel.load_model(init_model)
        elif isinstance(init_model, Booster):
            init = init_model._model
        else:
            init = init_model

    booster = Booster(params=params, train_set=train_set, init_model=init)
    is_valid_contain_train = False
    train_data_name = "training"
    if valid_sets is not None:
        for i, valid in enumerate(valid_sets):
            name = valid_names[i] if valid_names else "valid_%d" % i
            if valid is train_set:
                is_valid_contain_train = True
                train_data_name = name
                continue
            booster.add_valid(valid, name)

    callbacks = list(callbacks) if callbacks else []
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        from .callback import early_stopping
        callbacks.append(early_stopping(early_stopping_rounds, verbose=bool(verbose_eval)))
    if verbose_eval is True:
        callbacks.append(log_evaluation(1))
    elif isinstance(verbose_eval, int) and verbose_eval > 0:
        callbacks.append(log_evaluation(verbose_eval))
    callbacks_before = [cb for cb in callbacks if getattr(cb, "before_iteration", False)]
    callbacks_after = [cb for cb in callbacks if not getattr(cb, "before_iteration", False)]

    evaluation_result_list: List = []
    for i in range(num_boost_round):
        env = CallbackEnv(model=booster, params=params, iteration=i,
                          begin_iteration=0, end_iteration=num_boost_round,
                          evaluation_result_list=None)
        for cb in callbacks_before:
            cb(env)
        is_finished = booster.update(fobj=fobj)

        evaluation_result_list = []
        if is_valid_contain_train:
            evaluation_result_list.extend(
                [(train_data_name, m, v, h) for (_, m, v, h) in booster.eval_train(feval)])
        if booster._engine.valid_sets:
            evaluation_result_list.extend(booster.eval_valid(feval))
        env = CallbackEnv(model=booster, params=params, iteration=i,
                          begin_iteration=0, end_iteration=num_boost_round,
                          evaluation_result_list=evaluation_result_list)
        try:
            for cb in callbacks_after:
                cb(env)
        except EarlyStopException as e:
            booster.best_iteration = e.best_iteration + 1
            evaluation_result_list = e.best_score
            break
        if is_finished:
            Log.info("Finished training at iteration %d", i + 1)
            break

    booster.best_score = collections.defaultdict(dict)
    for name, metric, value, _ in evaluation_result_list:
        booster.best_score[name][metric] = value
    return booster


def cv(params: Dict, train_set: Dataset, num_boost_round: int = 100,
       folds=None, nfold: int = 5, stratified: bool = True, shuffle: bool = True,
       metrics=None, fobj=None, feval=None, init_model=None,
       early_stopping_rounds=None, seed: int = 0,
       callbacks=None, eval_train_metric: bool = False) -> Dict[str, List[float]]:
    """K-fold cross-validation (engine.py cv:317+)."""
    train_set.construct()
    n = train_set.num_data()
    y = train_set.get_label()
    rng = np.random.default_rng(seed)

    if folds is None:
        idx = np.arange(n)
        if stratified and y is not None and len(np.unique(y)) <= max(2, int(params.get("num_class", 2))):
            folds = []
            pieces = [[] for _ in range(nfold)]
            for cls in np.unique(y):
                cls_idx = idx[y == cls]
                if shuffle:
                    rng.shuffle(cls_idx)
                for k, part in enumerate(np.array_split(cls_idx, nfold)):
                    pieces[k].append(part)
            folds = [(np.setdiff1d(idx, np.concatenate(p)), np.concatenate(p))
                     for p in pieces]
        else:
            if shuffle:
                rng.shuffle(idx)
            parts = np.array_split(idx, nfold)
            folds = [(np.setdiff1d(np.arange(n), p), p) for p in parts]

    boosters = []
    for train_idx, test_idx in folds:
        tr = train_set.subset(np.sort(train_idx))
        te = tr.create_valid(_subset_matrix(train_set, np.sort(test_idx)),
                             label=np.asarray(y)[np.sort(test_idx)])
        bst = Booster(params=dict(params), train_set=tr)
        bst.add_valid(te, "valid")
        boosters.append(bst)

    results = collections.defaultdict(list)
    for i in range(num_boost_round):
        all_evals = collections.defaultdict(list)
        for bst in boosters:
            bst.update(fobj=fobj)
            for (name, metric, value, hib) in bst.eval_valid(feval):
                all_evals[metric].append((value, hib))
        stop = False
        for metric, vals in all_evals.items():
            mean = float(np.mean([v for v, _ in vals]))
            std = float(np.std([v for v, _ in vals]))
            results[metric + "-mean"].append(mean)
            results[metric + "-stdv"].append(std)
        if early_stopping_rounds and i >= early_stopping_rounds:
            for metric, vals in all_evals.items():
                hib = vals[0][1]
                series = results[metric + "-mean"]
                best_idx = int(np.argmax(series)) if hib else int(np.argmin(series))
                if best_idx <= i - early_stopping_rounds:
                    stop = True
        if stop:
            for key in results:
                results[key] = results[key][: i + 1]
            break
    return dict(results)


def _subset_matrix(ds: Dataset, idx: np.ndarray):
    data = ds.data
    if hasattr(data, "values"):
        data = data.values
    return np.asarray(data, dtype=np.float64)[idx]
