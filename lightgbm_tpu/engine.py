"""train() / cv() drivers.

Role parity with the reference python-package/lightgbm/engine.py
(train at :18-316, cv at :317+): callback environment, early stopping via
exception, evaluation-result bookkeeping and best_iteration.
"""
from __future__ import annotations

import collections
import copy
import os
from typing import Any, Dict, List, Optional

import numpy as np

from .basic import Booster, Dataset, _slice_rows
from .callback import CallbackEnv, EarlyStopException, log_evaluation
from .utils.log import LightGBMError, Log


def train(params: Dict, train_set: Dataset, num_boost_round: int = 100,
          valid_sets: Optional[List[Dataset]] = None,
          valid_names: Optional[List[str]] = None,
          fobj=None, feval=None, init_model=None,
          feature_name="auto", categorical_feature="auto",
          learning_rates=None,
          keep_training_booster: bool = True,
          callbacks: Optional[List] = None,
          early_stopping_rounds: Optional[int] = None,
          verbose_eval=True) -> Booster:
    params = dict(params)
    if feature_name != "auto":
        train_set.set_feature_name(feature_name)
    if categorical_feature != "auto":
        train_set.set_categorical_feature(categorical_feature)
    num_boost_round, early_stopping_rounds = _rounds_from_params(
        params, num_boost_round, early_stopping_rounds)
    if fobj is not None:
        params["objective"] = "none"
    # continued training: accept a filename, Booster or raw model
    init = _resolve_init_model(init_model)

    booster = Booster(params=params, train_set=train_set, init_model=init)
    is_valid_contain_train = False
    train_data_name = "training"
    if isinstance(valid_sets, Dataset):   # reference accepts a bare Dataset
        valid_sets = [valid_sets]
    if isinstance(valid_names, str):
        valid_names = [valid_names]
    if valid_sets is not None:
        for i, valid in enumerate(valid_sets):
            name = valid_names[i] if valid_names else "valid_%d" % i
            if valid is train_set:
                is_valid_contain_train = True
                train_data_name = name
                continue
            booster.add_valid(valid, name)

    callbacks = list(callbacks) if callbacks else []
    if learning_rates is not None:
        from .callback import reset_parameter
        callbacks.append(reset_parameter(learning_rate=learning_rates))
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        from .callback import early_stopping
        callbacks.append(early_stopping(early_stopping_rounds, verbose=bool(verbose_eval)))
    if verbose_eval is True:
        callbacks.append(log_evaluation(1))
    elif isinstance(verbose_eval, int) and verbose_eval > 0:
        callbacks.append(log_evaluation(verbose_eval))
    callbacks_before = [cb for cb in callbacks if getattr(cb, "before_iteration", False)]
    callbacks_after = [cb for cb in callbacks if not getattr(cb, "before_iteration", False)]

    evaluation_result_list: List = []
    for i in range(num_boost_round):
        env = CallbackEnv(model=booster, params=params, iteration=i,
                          begin_iteration=0, end_iteration=num_boost_round,
                          evaluation_result_list=None)
        for cb in callbacks_before:
            cb(env)
        eng = booster._engine
        if eng is not None and hasattr(eng, "_win_horizon"):
            # observation horizon for the fused boosting window: with an
            # eval round every iteration the window must not run ahead
            # at all; otherwise it may run to the end of training
            eng._win_horizon = (1 if (is_valid_contain_train
                                      or eng.valid_sets)
                                else num_boost_round - i)
        is_finished = booster.update(fobj=fobj)

        # one packed device fetch per eval round (Booster.eval_round):
        # train metrics + every valid set come off a single device_get,
        # and the round doubles as the async pipeline's flush barrier
        evaluation_result_list = []
        if is_valid_contain_train or booster._engine.valid_sets:
            train_res, valid_res = booster.eval_round(
                feval, include_train=is_valid_contain_train)
            evaluation_result_list.extend(
                [(train_data_name, m, v, h) for (_, m, v, h) in train_res])
            evaluation_result_list.extend(valid_res)
        env = CallbackEnv(model=booster, params=params, iteration=i,
                          begin_iteration=0, end_iteration=num_boost_round,
                          evaluation_result_list=evaluation_result_list)
        try:
            for cb in callbacks_after:
                cb(env)
        except EarlyStopException as e:
            booster.best_iteration = e.best_iteration + 1
            evaluation_result_list = e.best_score
            break
        if is_finished:
            Log.info("Finished training at iteration %d", i + 1)
            break

    booster.best_score = collections.defaultdict(dict)
    for name, metric, value, _ in evaluation_result_list:
        booster.best_score[name][metric] = value
    if booster._engine is not None:
        # drain the dispatch pipeline: the returned booster's model must
        # hold every dispatched tree (runs without eval rounds never hit
        # another flush barrier)
        booster._engine.flush()
        booster._engine.timer.report()
    return booster


def _rounds_from_params(params: Dict, num_boost_round, early_stopping_rounds):
    """Honor num_iterations / early_stopping_round given as PARAMS (the
    reference engine pops the aliases and they override the kwarg).
    Conflicting aliases: the canonical key wins deterministically, with a
    warning (reference _choose_param_value behavior)."""
    from ._params import ALIASES
    found: Dict[str, Dict] = {"num_iterations": {},
                              "early_stopping_round": {}}
    for key in list(params):
        canon = ALIASES.get(key, key)
        if canon in found:
            found[canon][key] = params.pop(key)
    for canon, hits in found.items():
        if not hits:
            continue
        if len({str(v) for v in hits.values()}) > 1:
            Log.warning("conflicting aliases for %s (%s); using %s", canon,
                        ", ".join("%s=%s" % kv for kv in hits.items()),
                        canon if canon in hits else next(iter(hits)))
        value = hits[canon] if canon in hits else next(iter(hits.values()))
        if canon == "num_iterations":
            num_boost_round = int(value)
        else:
            early_stopping_rounds = int(value)
    return num_boost_round, early_stopping_rounds


class CVBooster:
    """Container of the per-fold Boosters (engine.py CVBooster:235-253).

    Method calls are redirected to every fold's booster; the return value is
    the list of per-fold results, in fold order."""

    def __init__(self):
        self.boosters: List[Booster] = []
        self.best_iteration = -1

    def append(self, booster: Booster) -> None:
        self.boosters.append(booster)

    def __getattr__(self, name):
        if name.startswith("_"):  # don't shadow protocol probes (deepcopy…)
            raise AttributeError(name)

        def handler(*args, **kwargs):
            return [getattr(b, name)(*args, **kwargs) for b in self.boosters]
        return handler


def _group_folds(group_sizes: np.ndarray, nfold: int):
    """GroupKFold over ranking queries: whole queries are assigned to folds,
    balancing fold sizes by rows (role of sklearn's GroupKFold in
    engine.py:266-275) — queries largest-first onto the lightest fold."""
    if len(group_sizes) < nfold:
        raise ValueError(
            "Cannot build %d group-aware folds from only %d queries; "
            "reduce nfold" % (nfold, len(group_sizes)))
    starts = np.concatenate([[0], np.cumsum(group_sizes)]).astype(np.int64)
    order = np.argsort(group_sizes)[::-1]
    fold_rows = np.zeros(nfold, np.int64)
    fold_of_query = np.zeros(len(group_sizes), np.int32)
    for q in order:
        k = int(np.argmin(fold_rows))
        fold_of_query[q] = k
        fold_rows[k] += group_sizes[q]
    for k in range(nfold):
        test_q = np.where(fold_of_query == k)[0]
        train_q = np.where(fold_of_query != k)[0]
        test_idx = np.concatenate(
            [np.arange(starts[q], starts[q + 1]) for q in test_q])
        train_idx = np.concatenate(
            [np.arange(starts[q], starts[q + 1]) for q in train_q])
        yield (train_idx, test_idx,
               group_sizes[train_q], group_sizes[test_q])


def _resolve_init_model(init_model):
    """Filename / Booster / raw GBDTModel -> GBDTModel (shared by train()
    and cv(); the reference engine accepts the same three spellings)."""
    if init_model is None:
        return None
    if isinstance(init_model, str):
        from .models.gbdt_model import GBDTModel
        return GBDTModel.load_model(init_model)
    if isinstance(init_model, Booster):
        return init_model._model
    return init_model


def _make_n_folds(train_set: Dataset, folds, nfold: int, params: Dict,
                  seed: int, fpreproc, stratified: bool, shuffle: bool,
                  eval_train_metric: bool, init_model=None) -> CVBooster:
    """Build the per-fold Boosters (engine.py _make_n_folds:256-301)."""
    train_set.construct()
    n = train_set.num_data()
    y = train_set.get_label()
    rng = np.random.default_rng(seed)
    group = train_set.get_group()

    fold_group = None
    if folds is not None:
        if not hasattr(folds, "__iter__"):
            raise AttributeError(
                "folds should be an iterable of (train_idx, test_idx)")
        folds = [(np.asarray(tr), np.asarray(te)) for tr, te in folds]
    elif group is not None:
        rich = list(_group_folds(np.asarray(group), nfold))
        folds = [(tr, te) for tr, te, _, _ in rich]
        fold_group = [(gtr, gte) for _, _, gtr, gte in rich]
    elif stratified and y is not None and \
            len(np.unique(y)) <= max(2, int(params.get("num_class", 2))):
        idx = np.arange(n)
        pieces = [[] for _ in range(nfold)]
        for cls in np.unique(y):
            cls_idx = idx[y == cls]
            if shuffle:
                rng.shuffle(cls_idx)
            for k, part in enumerate(np.array_split(cls_idx, nfold)):
                pieces[k].append(part)
        folds = [(np.setdiff1d(idx, np.concatenate(p)), np.concatenate(p))
                 for p in pieces]
    else:
        idx = np.arange(n)
        if shuffle:
            rng.shuffle(idx)
        parts = np.array_split(idx, nfold)
        folds = [(np.setdiff1d(np.arange(n), p), p) for p in parts]

    cvbooster = CVBooster()
    for k, (train_idx, test_idx) in enumerate(folds):
        train_idx = np.sort(np.asarray(train_idx))
        test_idx = np.sort(np.asarray(test_idx))
        tr = train_set.subset(train_idx)
        te_label = None if y is None else np.asarray(y)[test_idx]
        te = tr.create_valid(_subset_matrix(train_set, test_idx),
                             label=te_label)
        if fold_group is not None:
            tr.set_group(fold_group[k][0])
            te.set_group(fold_group[k][1])
        w = train_set.get_weight()
        if w is not None:  # subset() already sliced the train-fold weight
            te.set_weight(np.asarray(w)[test_idx])
        fold_params = dict(params)
        if fpreproc is not None:
            tr, te, fold_params = fpreproc(tr, te, fold_params)
        # continued training per fold (reference cv supports init_model the
        # same way train does: every fold booster replays the loaded trees
        # onto its own fold's scores); Booster deep-copies the model, so
        # the folds never share mutable tree state
        bst = Booster(params=fold_params, train_set=tr,
                      init_model=init_model)
        if eval_train_metric:
            bst.add_valid(tr, "train")
        bst.add_valid(te, "valid")
        cvbooster.append(bst)
    return cvbooster


def _agg_cv_result(raw_results):
    """[(dataset, metric, mean, is_higher_better, std)] across folds
    (engine.py _agg_cv_result:304-314), keyed by (dataset, metric) so
    eval_train_metric keeps train/valid series separate."""
    cvmap = collections.OrderedDict()
    metric_hib = {}
    for one_result in raw_results:
        for ds_name, metric, value, hib in one_result:
            key = (ds_name, metric)
            metric_hib[key] = hib
            cvmap.setdefault(key, []).append(value)
    return [(ds, m, float(np.mean(v)), metric_hib[(ds, m)], float(np.std(v)))
            for (ds, m), v in cvmap.items()]


def cv(params: Dict, train_set: Dataset, num_boost_round: int = 100,
       folds=None, nfold: int = 5, stratified: bool = True, shuffle: bool = True,
       metrics=None, fobj=None, feval=None, init_model=None,
       early_stopping_rounds=None, fpreproc=None, verbose_eval=None,
       show_stdv: bool = True, seed: int = 0,
       callbacks=None, eval_train_metric: bool = False,
       return_cvbooster: bool = False) -> Dict[str, Any]:
    """K-fold cross-validation (engine.py cv:317+).

    Returns {metric-mean: [...], metric-stdv: [...]} (stdv omitted when
    show_stdv=False); with return_cvbooster=True the dict also carries the
    CVBooster under "cvbooster".  Folds are query-aware for ranking
    datasets (whole queries per fold), stratified for classification."""
    params = dict(params)
    num_boost_round, early_stopping_rounds = _rounds_from_params(
        params, num_boost_round, early_stopping_rounds)
    if metrics is not None:
        params["metric"] = metrics
    if fobj is not None:
        params["objective"] = "none"

    cvfolds = _make_n_folds(train_set, folds, nfold, params, seed, fpreproc,
                            stratified, shuffle, eval_train_metric,
                            init_model=_resolve_init_model(init_model))
    results = collections.defaultdict(list)
    best_iter, best_metric_val, best_hib = -1, None, True

    callbacks = list(callbacks) if callbacks else []
    if verbose_eval is True:
        callbacks.append(log_evaluation(1, show_stdv))
    elif isinstance(verbose_eval, int) and not isinstance(verbose_eval, bool) \
            and verbose_eval > 0:
        callbacks.append(log_evaluation(verbose_eval, show_stdv))
    callbacks_before = [c for c in callbacks
                        if getattr(c, "before_iteration", False)]
    callbacks_after = [c for c in callbacks
                       if not getattr(c, "before_iteration", False)]

    for i in range(num_boost_round):
        env = CallbackEnv(model=cvfolds, params=params, iteration=i,
                          begin_iteration=0, end_iteration=num_boost_round,
                          evaluation_result_list=None)
        for cb in callbacks_before:
            cb(env)
        cvfolds.update(fobj=fobj)
        # with eval_train_metric the fold boosters carry the training fold
        # as an extra valid set named "train", so eval_valid covers both
        raw = cvfolds.eval_valid(feval)
        agg = _agg_cv_result(raw)
        for ds_name, metric, mean, hib, std in agg:
            key = metric if ds_name == "valid" else f"{ds_name} {metric}"
            results[key + "-mean"].append(mean)
            if show_stdv:
                results[key + "-stdv"].append(std)

        # early stopping on the first valid metric's mean
        valid_agg = [a for a in agg if a[0] == "valid"]
        if valid_agg:
            _, _, mean, hib, _ = valid_agg[0]
            if best_metric_val is None or (mean > best_metric_val if hib
                                           else mean < best_metric_val):
                best_metric_val, best_iter, best_hib = mean, i, hib
        env = CallbackEnv(model=cvfolds, params=params, iteration=i,
                          begin_iteration=0, end_iteration=num_boost_round,
                          evaluation_result_list=[
                              ("cv_agg", "%s %s" % (ds, m), mean, hib, std)
                              for ds, m, mean, hib, std in agg])
        try:
            for cb in callbacks_after:
                cb(env)
        except EarlyStopException as e:
            best_iter = e.best_iteration
            for key in results:
                results[key] = results[key][: best_iter + 1]
            break
        if early_stopping_rounds and valid_agg and \
                best_iter <= i - early_stopping_rounds:
            for key in results:
                results[key] = results[key][: best_iter + 1]
            break

    cvfolds.best_iteration = best_iter + 1
    out: Dict[str, Any] = dict(results)
    if return_cvbooster:
        out["cvbooster"] = cvfolds
    return out


def _subset_matrix(ds: Dataset, idx: np.ndarray):
    return _slice_rows(ds.data, idx)


def predict(model, data, device: bool = True, **kwargs) -> np.ndarray:
    """One-shot serving entry: run prediction through the tree-parallel
    device inference engine (models/device_predictor.py) without the
    caller managing a Booster — the engine-level sibling of train()/cv()
    for prediction traffic.  `model` may be a live Booster, a model file
    path, or a full model string; device=False selects the exact f64
    host traversal instead.  Extra kwargs flow to Booster.predict
    (num_iteration, start_iteration, raw_score, pred_early_stop, ...)."""
    if isinstance(model, Booster):
        bst = model
    elif isinstance(model, str) and "\n" in model:
        bst = Booster(model_str=model)
    elif isinstance(model, (str, bytes, os.PathLike)):
        bst = Booster(model_file=os.fspath(model))
    else:
        raise LightGBMError("predict() needs a Booster, a model file "
                            "path, or a model string")
    return bst.predict(data, device=device, **kwargs)
