"""Leaf-wise (best-first) tree growth as one jitted fixed-trip-count loop.

Role parity with the reference SerialTreeLearner
(src/treelearner/serial_tree_learner.cpp: Train at :157-221, BeforeFindBestSplit
at :350-428, FindBestSplits at :430-445, Split at :703-777) redesigned for
XLA's compilation model:

- the leaf frontier is *data*, not control flow: a per-row leaf-id vector plus
  per-leaf state arrays sized [num_leaves], updated with masked scatters inside
  `lax.fori_loop` — no recompilation, no dynamic shapes;
- the reference's histogram-pool pointer juggling (feature_histogram.hpp:655+)
  becomes a dense [num_leaves, F, B, 3] histogram tensor in HBM;
- the one algorithmic trick that matters is preserved: per split, only the
  smaller child's histogram is built from rows; the sibling is parent - child
  (histogram subtraction, serial_tree_learner.cpp:475-544);
- rows excluded by bagging/padding carry zeroed (grad, hess, count) so they
  fall out of every sum while still being partitioned for score updates.
"""
from __future__ import annotations

import functools
from typing import Dict, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.bundle import decode_bin, expand_histogram
from ..ops.histogram import build_histogram
from ..ops.split import (FeatureMeta, K_MIN_SCORE, MISSING_NAN, MISSING_ZERO,
                         SplitResult, find_best_split,
                         find_best_split_batched, leaf_output,
                         pad_feature_meta, per_feature_best_gains)
from ..runtime import xla_obs
from ..utils import compat


class GrowerConfig(NamedTuple):
    """Static scalars baked into the compiled grower."""
    num_leaves: int
    max_depth: int
    lambda_l1: float
    lambda_l2: float
    max_delta_step: float
    min_data_in_leaf: int
    min_sum_hessian_in_leaf: float
    min_gain_to_split: float
    row_chunk: int = 16384
    # categorical split knobs (feature_histogram.hpp:112-273)
    with_categorical: bool = False
    max_cat_threshold: int = 32
    cat_l2: float = 10.0
    cat_smooth: float = 10.0
    max_cat_to_onehot: int = 4
    min_data_per_group: int = 100
    # segment-engine implementation for the partitioned grower
    # (Config.tpu_histogram_impl): "auto" | "pallas" | "lax"
    hist_impl: str = "auto"
    # any feature carries a monotone constraint: per-leaf value bounds are
    # tracked and propagated through monotone splits (LeafSplits
    # min/max_constraint, serial_tree_learner.cpp:765-777)
    with_monotone: bool = False
    # histogram pool slots for the partitioned grower (reference
    # HistogramPool, feature_histogram.hpp:655-826, histogram_pool_size
    # param): 0 = one slot per leaf (unbounded); otherwise LRU-evicted
    # cache with recompute-on-miss over the leaf's row segment
    hist_pool_slots: int = 0
    # frontier-batch window (Config.tpu_frontier_batch): > 1 lets the
    # partitioned grower evaluate up to this many frontier leaves per
    # round (one batched histogram dispatch + one fused cross-leaf split
    # search) while committing splits in exact sequential argmax order —
    # byte-identical models, fewer sequential rounds per tree
    frontier_batch: int = 1


def propagate_monotone_bounds(blo, bro, is_num, mono_f, pmin, pmax):
    """Children's value bounds after a split (serial_tree_learner.cpp:
    765-777): inherit the parent's, and a numerical split on a monotone
    feature pins the shared boundary at the midpoint of the split outputs.
    Tightened (max/min), never replaced, so an out-of-bounds midpoint
    (possible for forced splits) cannot loosen a child's bounds."""
    mid = (blo + bro) * 0.5
    lmin = jnp.where(is_num & (mono_f < 0), jnp.maximum(mid, pmin), pmin)
    lmax = jnp.where(is_num & (mono_f > 0), jnp.minimum(mid, pmax), pmax)
    rmin = jnp.where(is_num & (mono_f > 0), jnp.maximum(mid, pmin), pmin)
    rmax = jnp.where(is_num & (mono_f < 0), jnp.minimum(mid, pmax), pmax)
    return lmin, lmax, rmin, rmax


def make_winner_sync(axis_name: str, my, f_offset):
    """SyncUpGlobalBestSplit (parallel_tree_learner.h:183-206): gain pmax +
    lowest-shard tie-break, then the whole SplitResult packed into ONE f32
    buffer for a single one-hot psum (the reference likewise ships a
    fixed-size SplitInfo blob).  Integer fields (feature, bin) are exact in
    f32 below 2^24.  Shared by the masked and partitioned mesh growers."""

    def bcast_from_winner(res):
        gain_max = lax.pmax(res.gain, axis_name)
        big = jnp.int32(1 << 30)
        winner = lax.pmin(jnp.where(res.gain == gain_max, my, big),
                          axis_name)
        is_w = my == winner
        payload = jnp.concatenate([
            jnp.stack([
                res.gain,
                (res.feature + f_offset).astype(jnp.float32),
                res.threshold_bin.astype(jnp.float32),
                res.default_left.astype(jnp.float32),
                res.left_sum_g, res.left_sum_h, res.left_count,
                res.is_cat.astype(jnp.float32),
                res.left_output, res.right_output,
            ]),
            res.cat_bitset.astype(jnp.float32)])
        payload = lax.psum(jnp.where(is_w, payload,
                                     jnp.zeros_like(payload)), axis_name)
        return SplitResult(
            gain=payload[0],
            feature=payload[1].astype(jnp.int32),
            threshold_bin=payload[2].astype(jnp.int32),
            default_left=payload[3] > 0,
            left_sum_g=payload[4],
            left_sum_h=payload[5],
            left_count=payload[6],
            is_cat=payload[7] > 0,
            cat_bitset=payload[10:] > 0,
            left_output=payload[8],
            right_output=payload[9])

    return bcast_from_winner


def make_tree_grower(meta: FeatureMeta, cfg: GrowerConfig, num_bins_max: int,
                     axis_name: str = None, jit: bool = True,
                     mode: str = "data", num_machines: int = 1,
                     top_k: int = 20, bundle_map=None, forced=None):
    """Returns grow(bins[F,N], vals[N,3], feature_mask[F]) -> tree arrays dict,
    jit-compiled once per (shape, config).

    axis_name: when set, the grower runs as a *parallel tree learner* inside
    shard_map over that mesh axis, in one of three modes mirroring the
    reference's parallel learners with XLA collectives in place of
    src/network/:

    - mode="data" (DataParallelTreeLearner, data_parallel_tree_learner.cpp:
      147-246): rows sharded, histograms `psum`ed over ICI, replicated split
      application.
    - mode="feature" (FeatureParallelTreeLearner, feature_parallel_tree_
      learner.cpp:21-69): features sharded, rows replicated; each shard finds
      the best split over its own features, the winner is chosen by a
      gain-keyed pmax/pmin pair (the SyncUpGlobalBestSplit allreduce-max) and
      its row partition is broadcast from the owning shard with one psum.
    - mode="voting" (VotingParallelTreeLearner, voting_parallel_tree_
      learner.cpp / PV-Tree): rows sharded but histograms stay LOCAL; each
      shard votes its top_k features by local split gain, the global top-2k
      vote winners' histograms alone are `psum`ed, and the best split is
      found on that subset — bounding the wire volume exactly like the
      reference's selective ReduceScatter.  Local vote constraints are
      scaled by 1/num_machines (:53-55).
    """
    L = cfg.num_leaves
    B = num_bins_max
    feature_mode = axis_name is not None and mode == "feature"
    voting_mode = axis_name is not None and mode == "voting"
    data_mode = axis_name is not None and mode == "data"
    bundled = bundle_map is not None
    assert not (bundled and axis_name is not None), \
        "EFB-bundled datasets train with the serial learner"
    assert not (forced is not None and axis_name is not None), \
        "forced splits run on the serial learners only"
    if forced is not None:
        from .forced import PRIORITY_UNIT, make_forced_machinery
        fc_lnext, fc_rnext, forced_override = \
            make_forced_machinery(forced, meta, cfg)
    # per-leaf bounds are replicated scalars every shard tracks identically
    # (all shards apply identical splits), so propagation runs on the
    # parallel learners too — each shard clamps its local candidates
    with_mono = cfg.with_monotone

    def hist_view(h):
        """[G, B, 3] bundle histogram -> [F, B, 3] split view (EFB)."""
        if not bundled:
            return h
        return expand_histogram(h, bundle_map, meta.num_bin,
                                meta.default_bin, B)

    find_kwargs = dict(
        l1=cfg.lambda_l1, l2=cfg.lambda_l2, max_delta_step=cfg.max_delta_step,
        min_data_in_leaf=cfg.min_data_in_leaf,
        min_sum_hessian_in_leaf=cfg.min_sum_hessian_in_leaf,
        min_gain_to_split=cfg.min_gain_to_split,
        max_cat_threshold=cfg.max_cat_threshold, cat_l2=cfg.cat_l2,
        cat_smooth=cfg.cat_smooth, max_cat_to_onehot=cfg.max_cat_to_onehot,
        min_data_per_group=cfg.min_data_per_group,
        with_categorical=cfg.with_categorical)
    find = functools.partial(find_best_split, meta=meta, **find_kwargs)

    out_fn = functools.partial(leaf_output, l1=cfg.lambda_l1, l2=cfg.lambda_l2,
                               max_delta_step=cfg.max_delta_step)

    _winner_sync = functools.partial(make_winner_sync, axis_name)

    def grow(bins: jax.Array, vals: jax.Array, feature_mask: jax.Array) -> Dict[str, jax.Array]:
        F, N = bins.shape

        reduce_hist = lambda h: h  # serial / feature / voting: local

        if feature_mode:
            my = lax.axis_index(axis_name)
            f_offset = my * F
            meta_local = FeatureMeta(*[lax.dynamic_slice_in_dim(a, f_offset, F)
                                       for a in meta])
            find_local = functools.partial(find_best_split, meta=meta_local,
                                           **find_kwargs)
            bcast_from_winner = _winner_sync(my, f_offset)

            def find_split(hist, sg, sh, cnt, fmask, **constraints):
                return bcast_from_winner(find_local(hist, sg, sh, cnt, fmask,
                                                    **constraints))

        elif data_mode:
            # DataParallelTreeLearner with the reference's actual wire
            # pattern (data_parallel_tree_learner.cpp:159-246): histograms
            # ReduceScatter over the feature axis so each shard owns F/n
            # features, split search runs only on owned features, and the
            # global winner is an allreduce-max of one SplitInfo blob —
            # psum_scatter + the shared winner sync, NOT a full psum with
            # replicated search.
            n = max(num_machines, 1)
            Fp = ((F + n - 1) // n) * n
            padf = Fp - F
            Floc = Fp // n
            if padf:
                bins_h = jnp.pad(bins, ((0, padf), (0, 0)))
                fmask_p = jnp.pad(feature_mask, (0, padf))
                meta_p = pad_feature_meta(meta, Fp)
            else:
                bins_h, fmask_p, meta_p = bins, feature_mask, meta
            my = lax.axis_index(axis_name)
            f_offset = my * Floc
            meta_local = FeatureMeta(
                *[lax.dynamic_slice_in_dim(a, f_offset, Floc)
                  for a in meta_p])
            find_local = functools.partial(find_best_split, meta=meta_local,
                                           **find_kwargs)
            bcast_from_winner = _winner_sync(my, f_offset)

            def reduce_hist(h):
                return lax.psum_scatter(h, axis_name, scatter_dimension=0,
                                        tiled=True)

            def find_split(hist_loc, sg, sh, cnt, fmask, **constraints):
                fmask_loc = lax.dynamic_slice_in_dim(fmask_p, f_offset, Floc)
                return bcast_from_winner(
                    find_local(hist_loc, sg, sh, cnt, fmask_loc,
                               **constraints))

        elif voting_mode:
            k_vote = min(top_k, F)
            S = min(2 * k_vote, F)
            vote_kwargs = dict(find_kwargs)
            vote_kwargs["min_data_in_leaf"] = cfg.min_data_in_leaf / max(num_machines, 1)
            vote_kwargs["min_sum_hessian_in_leaf"] = \
                cfg.min_sum_hessian_in_leaf / max(num_machines, 1)

            def find_split(hist_local, sg, sh, cnt, fmask, **constraints):
                # phase 1: vote top_k features by LOCAL split gain with
                # 1/num_machines-scaled constraints (:53-55, :322-342)
                local_tot = jnp.sum(hist_local[0], axis=0)
                local_gains = per_feature_best_gains(
                    hist_local, local_tot[0], local_tot[1], local_tot[2],
                    fmask, meta=meta, **vote_kwargs)
                top_vals, top_idx = lax.top_k(local_gains, k_vote)
                # a shard with no valid local split casts no votes (the
                # reference only votes splittable features)
                valid_vote = (top_vals > K_MIN_SCORE).astype(jnp.int32)
                all_top = lax.all_gather(top_idx, axis_name)
                all_valid = lax.all_gather(valid_vote, axis_name)
                votes = jnp.zeros(F, jnp.int32).at[all_top.reshape(-1)].add(
                    all_valid.reshape(-1))
                _, sel = lax.top_k(votes, S)
                # phase 2: reduce ONLY the winners' histograms, find on them
                hsel = lax.psum(hist_local[sel], axis_name)
                meta_sel = FeatureMeta(*[a[sel] for a in meta])
                res = find_best_split(hsel, sg, sh, cnt, fmask[sel],
                                      meta=meta_sel, **find_kwargs,
                                      **constraints)
                return res._replace(feature=sel[res.feature])

        else:
            def find_split(hist, sg, sh, cnt, fmask, **constraints):
                return find(hist_view(hist), sg, sh, cnt, fmask,
                            **constraints)

        if axis_name is None and not with_mono:
            # serial children evaluations run through the SAME stacked-fori
            # search as the partitioned growers (find_best_split_batched's
            # exactness note): the search compiles identically at every
            # batch size, so gains stay bit-comparable across engines
            def find_split2(hl, hr, lg, lh, lc, rg, rh, rc, fmask):
                hists = jnp.stack([hl, hr])
                if bundled:
                    hists = jax.vmap(hist_view)(hists)
                res2 = find_best_split_batched(
                    hists, jnp.stack([lg, rg]), jnp.stack([lh, rh]),
                    jnp.stack([lc, rc]), fmask, meta=meta, **find_kwargs)
                return (jax.tree_util.tree_map(lambda a: a[0], res2),
                        jax.tree_util.tree_map(lambda a: a[1], res2))

        totals = jnp.sum(vals, axis=0)
        if axis_name and not feature_mode:
            totals = lax.psum(totals, axis_name)
        root_g, root_h, root_c = totals[0], totals[1], totals[2]
        hist_bins = bins_h if data_mode else bins   # padded F in data mode
        Fh = (bins_h.shape[0] // max(num_machines, 1)) if data_mode else F
        hist_root = reduce_hist(
            build_histogram(hist_bins, vals, num_bins=B,
                            row_chunk=cfg.row_chunk))
        if with_mono:
            res0 = find_split(hist_root, root_g, root_h, root_c,
                              feature_mask,
                              min_constraint=jnp.float32(-jnp.inf),
                              max_constraint=jnp.float32(jnp.inf))
        else:
            res0 = find_split(hist_root, root_g, root_h, root_c, feature_mask)

        real0 = res0.gain
        root_rank = jnp.int32(-1)
        if forced is not None:
            res0, real0, root_rank = forced_override(
                jnp.int32(0), hist_view(hist_root), root_g, root_h, root_c,
                res0)

        ni = max(L - 1, 1)
        leaf_id0 = jnp.zeros(N, jnp.int32)
        if axis_name and not feature_mode:
            # mark the per-row carry device-varying so shard_map's replication
            # checker tracks it correctly through the fori_loop (rows are
            # sharded; in feature mode rows are replicated instead)
            leaf_id0 = compat.pvary(leaf_id0, axis_name)
        state = {
            "hist": jnp.zeros((L, Fh, B, 3), jnp.float32).at[0].set(hist_root),
            "leaf_id": leaf_id0,
            "sum_g": jnp.zeros(L, jnp.float32).at[0].set(root_g),
            "sum_h": jnp.zeros(L, jnp.float32).at[0].set(root_h),
            "cnt": jnp.zeros(L, jnp.float32).at[0].set(root_c),
            # value assigned to each leaf at creation (reference Tree keeps
            # leaf_value_, seeded 0 for the root, set by Split for children —
            # sorted-subset categorical children carry the cat_l2-regularized
            # output, so the value is bound at split time, not recomputed)
            "leaf_val": jnp.zeros(L, jnp.float32),
            "bgain": jnp.full(L, K_MIN_SCORE, jnp.float32).at[0].set(res0.gain),
            "bfeat": jnp.zeros(L, jnp.int32).at[0].set(res0.feature),
            "bbin": jnp.zeros(L, jnp.int32).at[0].set(res0.threshold_bin),
            "bdleft": jnp.zeros(L, jnp.bool_).at[0].set(res0.default_left),
            "blg": jnp.zeros(L, jnp.float32).at[0].set(res0.left_sum_g),
            "blh": jnp.zeros(L, jnp.float32).at[0].set(res0.left_sum_h),
            "blc": jnp.zeros(L, jnp.float32).at[0].set(res0.left_count),
            "bcat": jnp.zeros(L, jnp.bool_).at[0].set(res0.is_cat),
            "bbitset": jnp.zeros((L, B), jnp.bool_).at[0].set(res0.cat_bitset),
            "blo": jnp.zeros(L, jnp.float32).at[0].set(res0.left_output),
            "bro": jnp.zeros(L, jnp.float32).at[0].set(res0.right_output),
            "leaf_depth": jnp.zeros(L, jnp.int32),
            "leaf_parent": jnp.full(L, -1, jnp.int32),
            "split_feature": jnp.zeros(ni, jnp.int32),
            "split_bin": jnp.zeros(ni, jnp.int32),
            "split_gain": jnp.zeros(ni, jnp.float32),
            "default_left": jnp.zeros(ni, jnp.bool_),
            "split_is_cat": jnp.zeros(ni, jnp.bool_),
            "split_cat_bitset": jnp.zeros((ni, B), jnp.bool_),
            "left_child": jnp.zeros(ni, jnp.int32),
            "right_child": jnp.zeros(ni, jnp.int32),
            "internal_value": jnp.zeros(ni, jnp.float32),
            "internal_count": jnp.zeros(ni, jnp.float32),
            "num_leaves": jnp.int32(1),
            "done": jnp.bool_(False),
        }
        if forced is not None:
            state["fleaf"] = jnp.full(L, -1, jnp.int32).at[0].set(root_rank)
            state["breal"] = jnp.full(L, K_MIN_SCORE,
                                      jnp.float32).at[0].set(real0)
        if with_mono:
            state["mincon"] = jnp.full(L, -jnp.inf, jnp.float32)
            state["maxcon"] = jnp.full(L, jnp.inf, jnp.float32)

        def body(s, st):
            best_leaf = jnp.argmax(st["bgain"]).astype(jnp.int32)
            gain = st["bgain"][best_leaf]
            do = jnp.logical_and(~st["done"], gain > 0.0)
            node = s - 1

            f = st["bfeat"][best_leaf]
            t = st["bbin"][best_leaf]
            dl = st["bdleft"][best_leaf]
            cat = st["bcat"][best_leaf]
            bitset = st["bbitset"][best_leaf]

            # -- partition rows of the split leaf (DataPartition::Split /
            #    Bin::Split[Categorical], dense_bin.hpp:190-283) -------------
            if feature_mode:
                # only the shard owning the winning feature has its bin
                # column; it computes the row routing and broadcasts it (the
                # reference needs no exchange because every rank holds full
                # data — here the one-psum broadcast replaces that copy)
                owner = (f // F) == my
                f_loc = jnp.clip(f - f_offset, 0, F - 1)
                fbin = bins[f_loc].astype(jnp.int32)
            elif bundled:
                raw = bins[bundle_map.f_group[f]]
                fbin = decode_bin(raw, bundle_map.f_identity[f],
                                  bundle_map.f_offset[f], meta.num_bin[f],
                                  meta.default_bin[f])
            else:
                fbin = bins[f].astype(jnp.int32)
            mt = meta.missing_type[f]
            is_missing_bin = ((mt == MISSING_NAN) & (fbin == meta.num_bin[f] - 1)) | \
                             ((mt == MISSING_ZERO) & (fbin == meta.default_bin[f]))
            go_left_num = jnp.where(is_missing_bin, dl, fbin <= t)
            go_left = jnp.where(cat, bitset[fbin], go_left_num)
            if feature_mode:
                go_left = lax.psum(jnp.where(owner, go_left.astype(jnp.int32), 0),
                                   axis_name) > 0
            in_leaf = st["leaf_id"] == best_leaf
            leaf_id = jnp.where(do & in_leaf & ~go_left, s, st["leaf_id"])

            # -- child aggregates: left from the stored split, right by diff --
            lg, lh, lcnt = st["blg"][best_leaf], st["blh"][best_leaf], st["blc"][best_leaf]
            pg, ph, pc = st["sum_g"][best_leaf], st["sum_h"][best_leaf], st["cnt"][best_leaf]
            rg, rh, rcnt = pg - lg, ph - lh, pc - lcnt

            # -- histograms: build only the smaller child, subtract for sibling
            left_smaller = lcnt <= rcnt
            small_slot = jnp.where(left_smaller, best_leaf, s)
            mask = ((leaf_id == small_slot) & do).astype(jnp.float32)
            hist_small = reduce_hist(
                build_histogram(hist_bins, vals * mask[:, None],
                                num_bins=B, row_chunk=cfg.row_chunk))
            hist_parent = st["hist"][best_leaf]
            hist_big = hist_parent - hist_small
            new_left = jnp.where(left_smaller, hist_small, hist_big)
            new_right = jnp.where(left_smaller, hist_big, hist_small)
            hist = st["hist"]
            hist = hist.at[best_leaf].set(jnp.where(do, new_left, hist_parent))
            hist = hist.at[s].set(jnp.where(do, new_right, hist[s]))

            # -- best splits of the two children ------------------------------
            child_depth = st["leaf_depth"][best_leaf] + 1
            if with_mono:
                lmin, lmax, rmin, rmax = propagate_monotone_bounds(
                    st["blo"][best_leaf], st["bro"][best_leaf], ~cat,
                    meta.monotone[f], st["mincon"][best_leaf],
                    st["maxcon"][best_leaf])
                res_l = find_split(new_left, lg, lh, lcnt, feature_mask,
                                   min_constraint=lmin, max_constraint=lmax)
                res_r = find_split(new_right, rg, rh, rcnt, feature_mask,
                                   min_constraint=rmin, max_constraint=rmax)
            elif axis_name is None:
                lmin = lmax = rmin = rmax = None
                res_l, res_r = find_split2(new_left, new_right, lg, lh,
                                           lcnt, rg, rh, rcnt, feature_mask)
            else:
                lmin = lmax = rmin = rmax = None
                res_l = find_split(new_left, lg, lh, lcnt, feature_mask)
                res_r = find_split(new_right, rg, rh, rcnt, feature_mask)
            real_l, real_r = res_l.gain, res_r.gain
            if forced is not None:
                jp = st["fleaf"][best_leaf]
                applied = (jp >= 0) & \
                    (st["bgain"][best_leaf] >= 0.5 * PRIORITY_UNIT)
                jp0 = jnp.maximum(jp, 0)
                jl = jnp.where(applied, fc_lnext[jp0], -1)
                jr = jnp.where(applied, fc_rnext[jp0], -1)
                res_l, real_l, jl = forced_override(
                    jl, hist_view(new_left), lg, lh, lcnt, res_l,
                    min_constraint=lmin, max_constraint=lmax)
                res_r, real_r, jr = forced_override(
                    jr, hist_view(new_right), rg, rh, rcnt, res_r,
                    min_constraint=rmin, max_constraint=rmax)
            if cfg.max_depth > 0:
                depth_ok = child_depth < cfg.max_depth
            else:
                depth_ok = jnp.bool_(True)
            gain_l = jnp.where(depth_ok, res_l.gain, K_MIN_SCORE)
            gain_r = jnp.where(depth_ok, res_r.gain, K_MIN_SCORE)

            def set2(arr, vl, vr):
                arr = arr.at[best_leaf].set(jnp.where(do, vl, arr[best_leaf]))
                return arr.at[s].set(jnp.where(do, vr, arr[s]))

            st_new = dict(st)
            st_new["hist"] = hist
            st_new["leaf_id"] = leaf_id
            st_new["sum_g"] = set2(st["sum_g"], lg, rg)
            st_new["sum_h"] = set2(st["sum_h"], lh, rh)
            st_new["cnt"] = set2(st["cnt"], lcnt, rcnt)
            st_new["bgain"] = set2(st["bgain"], gain_l, gain_r)
            st_new["bfeat"] = set2(st["bfeat"], res_l.feature, res_r.feature)
            st_new["bbin"] = set2(st["bbin"], res_l.threshold_bin, res_r.threshold_bin)
            st_new["bdleft"] = set2(st["bdleft"], res_l.default_left, res_r.default_left)
            st_new["blg"] = set2(st["blg"], res_l.left_sum_g, res_r.left_sum_g)
            st_new["blh"] = set2(st["blh"], res_l.left_sum_h, res_r.left_sum_h)
            st_new["blc"] = set2(st["blc"], res_l.left_count, res_r.left_count)
            st_new["bcat"] = set2(st["bcat"], res_l.is_cat, res_r.is_cat)
            bs = st["bbitset"]
            bs = bs.at[best_leaf].set(jnp.where(do, res_l.cat_bitset, bs[best_leaf]))
            st_new["bbitset"] = bs.at[s].set(jnp.where(do, res_r.cat_bitset, bs[s]))
            st_new["blo"] = set2(st["blo"], res_l.left_output, res_r.left_output)
            st_new["bro"] = set2(st["bro"], res_l.right_output, res_r.right_output)
            # children take the value their creating split computed
            st_new["leaf_val"] = set2(st["leaf_val"], st["blo"][best_leaf],
                                      st["bro"][best_leaf])
            st_new["leaf_depth"] = set2(st["leaf_depth"], child_depth, child_depth)
            if forced is not None:
                st_new["fleaf"] = set2(st["fleaf"], jl, jr)
                st_new["breal"] = set2(st["breal"], real_l, real_r)
            if with_mono:
                st_new["mincon"] = set2(st["mincon"], lmin, rmin)
                st_new["maxcon"] = set2(st["maxcon"], lmax, rmax)

            # -- record the internal node (Tree::Split, tree.h:404-448) -------
            def setn(arr, v):
                return arr.at[node].set(jnp.where(do, v, arr[node]))

            gain_rec = st["breal"][best_leaf] if forced is not None else gain
            st_new["split_feature"] = setn(st["split_feature"], f)
            st_new["split_bin"] = setn(st["split_bin"], t)
            st_new["split_gain"] = setn(st["split_gain"], gain_rec)
            st_new["default_left"] = setn(st["default_left"], dl)
            st_new["split_is_cat"] = setn(st["split_is_cat"], cat)
            st_new["split_cat_bitset"] = st["split_cat_bitset"].at[node].set(
                jnp.where(do, bitset, st["split_cat_bitset"][node]))
            # internal_value = the split leaf's creation value (tree.cpp:419)
            st_new["internal_value"] = setn(st["internal_value"],
                                            st["leaf_val"][best_leaf])
            st_new["internal_count"] = setn(st["internal_count"], pc)
            left_child = setn(st["left_child"], ~best_leaf)
            right_child = setn(st["right_child"], ~s)
            # re-point the grandparent's child slot from ~best_leaf to node
            parent_node = st["leaf_parent"][best_leaf]
            has_par = (parent_node >= 0) & do
            pn = jnp.maximum(parent_node, 0)
            was_left = left_child[pn] == ~best_leaf
            left_child = left_child.at[pn].set(
                jnp.where(has_par & was_left, node, left_child[pn]))
            right_child = right_child.at[pn].set(
                jnp.where(has_par & ~was_left, node, right_child[pn]))
            st_new["left_child"] = left_child
            st_new["right_child"] = right_child
            st_new["leaf_parent"] = set2(st["leaf_parent"], node, node)

            st_new["num_leaves"] = st["num_leaves"] + do.astype(jnp.int32)
            st_new["done"] = st["done"] | (gain <= 0.0)
            return st_new

        st = lax.fori_loop(1, L, body, state) if L > 1 else state

        # leaves keep the value bound at their creating split; an unsplit root
        # (stump) falls back to its own Newton step
        leaf_value = jnp.where(
            (jnp.arange(L) == 0) & (st["num_leaves"] == 1),
            out_fn(st["sum_g"], st["sum_h"]), st["leaf_val"])
        return {
            "num_leaves": st["num_leaves"],
            "leaf_id": st["leaf_id"],
            "leaf_value": leaf_value,
            "leaf_count": st["cnt"],
            "leaf_sum_g": st["sum_g"],
            "leaf_sum_h": st["sum_h"],
            "split_feature": st["split_feature"],
            "split_bin": st["split_bin"],
            "split_gain": st["split_gain"],
            "default_left": st["default_left"],
            "split_is_cat": st["split_is_cat"],
            "split_cat_bitset": st["split_cat_bitset"],
            "left_child": st["left_child"],
            "right_child": st["right_child"],
            "internal_value": st["internal_value"],
            "internal_count": st["internal_count"],
        }

    return xla_obs.jit(grow, site="grower.serial") if jit else grow
