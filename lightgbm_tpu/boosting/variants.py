"""Boosting variants: GOSS, DART, RF.

Role parity with the reference src/boosting/goss.hpp (gradient-based one-side
sampling), dart.hpp (dropout boosting with tree-weight renormalization) and
rf.hpp (random forest: bagged trees of the zero-score gradients, running
average of converted outputs).  Factory in create_boosting below mirrors
src/boosting/boosting.cpp:30-64.

TPU-first notes: GOSS's per-thread reservoir walk (goss.hpp BaggingHelper)
becomes one jitted top-k + masked uniform draw over the padded row vector —
the amplification (1-a)/b rides the gradient-scale mask consumed by the
histogram kernel, while the count mask stays 0/1.  DART/RF reuse the bin-level
tree traversal to replay score adjustments entirely on device.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..runtime import xla_obs
from ..utils.log import Log
from ..utils.random import Random, partition_seed
from .gbdt import GBDT


class GOSS(GBDT):
    """Gradient-based One-Side Sampling (goss.hpp:26-210)."""

    def __init__(self, config, train_set, objective, metrics, init_model=None):
        super().__init__(config, train_set, objective, metrics, init_model)
        if config.top_rate + config.other_rate > 1.0:
            Log.fatal("top_rate + other_rate cannot be larger than 1.0")
        if config.top_rate <= 0.0 or config.other_rate <= 0.0:
            Log.fatal("top_rate and other_rate must be positive for GOSS")
        if config.bagging_freq > 0 and config.bagging_fraction != 1.0:
            Log.fatal("Cannot use bagging in GOSS")
        Log.info("Using GOSS")
        self._goss_key = jax.random.PRNGKey(
            partition_seed(int(config.seed or 0) + int(config.bagging_seed), 3))
        # one copy of the selection parameters serves BOTH engines
        # (goss.hpp:88-138): top/other counts, amplification, warmup length
        n = train_set.num_data
        self._goss_top_k = max(1, int(n * config.top_rate))
        self._goss_other_k = max(1, int(n * config.other_rate))
        self._goss_multiply = float(
            (n - self._goss_top_k) / self._goss_other_k)
        self._goss_warmup = int(1.0 / config.learning_rate)

        def _hook(g, h, valid, key, enabled):
            """Fast-path sampling hook: same selection math as the masked
            path (selection is row-order-free; the uniform draw differs by
            permutation only, so the two engines draw statistically
            identical — not bitwise-identical — samples)."""
            gw, cm = _goss_masks(g, h, valid > 0, key, self._goss_top_k,
                                 self._goss_other_k, self._goss_multiply)
            gw = jnp.where(enabled, gw, valid)
            cm = jnp.where(enabled, cm, valid)
            return gw, cm

        self._fast_sample_hook = _hook

    def _fast_sample_args(self):
        """(per-iteration PRNG key, sampling-enabled flag) — no sampling
        during the first 1/learning_rate iterations (goss.hpp:137)."""
        key = jax.random.fold_in(self._goss_key, self.iter)
        return key, jnp.bool_(self.iter >= self._goss_warmup)

    def _bagging_masks(self, grads, hesss):
        # no subsampling for the first 1/learning_rate iterations (goss.hpp:137)
        if self.iter < self._goss_warmup:
            m = jnp.asarray(self.bag_mask_host)
            return m, m
        key = jax.random.fold_in(self._goss_key, self.iter)
        valid = jnp.asarray(self.bag_mask_host) > 0
        return _goss_masks(grads, hesss, valid, key, self._goss_top_k,
                           self._goss_other_k, self._goss_multiply)


@functools.partial(xla_obs.jit, site="variants.goss_masks",
                   static_argnames=("top_k", "other_k"))
def _goss_masks(grads, hesss, valid, key, top_k: int, other_k: int, multiply):
    """Select the top_k rows by sum_k |g*h|, sample other_k of the rest
    uniformly, amplify the sampled rest by (n - top_k) / other_k."""
    gh = jnp.sum(jnp.abs(grads * hesss), axis=0)
    gh = jnp.where(valid, gh, -jnp.inf)
    thresh = jax.lax.top_k(gh, top_k)[0][-1]
    is_top = valid & (gh >= thresh)
    rest = valid & ~is_top
    # draw exactly other_k of the rest: rank random draws, keep the smallest
    r = jax.random.uniform(key, gh.shape)
    r = jnp.where(rest, r, jnp.inf)
    kth = -jax.lax.top_k(-r, other_k)[0][-1]
    sampled = rest & (r <= kth)
    gmask = jnp.where(is_top, 1.0, jnp.where(sampled, multiply, 0.0)).astype(jnp.float32)
    cmask = (is_top | sampled).astype(jnp.float32)
    return gmask, cmask


class DART(GBDT):
    """Dropout boosting (dart.hpp:17-200): drop a random subset of existing
    trees before computing gradients, shrink the new tree by lr/(1+k), then
    renormalize the dropped trees so train/valid scores stay consistent."""

    def __init__(self, config, train_set, objective, metrics, init_model=None):
        super().__init__(config, train_set, objective, metrics, init_model)
        self.random_for_drop = Random(int(config.drop_seed))
        self.tree_weight: list = []
        self.sum_weight = 0.0
        self.drop_index: list = []
        # drop/normalize score edits route through the payload's own bin
        # columns on the fast path (GBDT._add_tree_to_train_score)
        self._fast_variant_ok = True
        Log.info("Using DART")

    def _run_tree(self, i: int, k: int):
        """Tree k of this run's iteration i, past any loaded model's trees."""
        K = self.num_tree_per_iteration
        return self.model.trees[(self.num_init_iteration + i) * K + k]

    def train_one_iter(self, grad=None, hess=None) -> bool:
        if self._pipe_stop_iter is not None:
            # a pipelined earlier iteration turned out to stop training;
            # settle it BEFORE drawing drop RNG / touching scores
            self.flush()
            self._pipe_stop_iter = None
            return True
        self._dropping_trees()
        stopped = super().train_one_iter(grad, hess)
        if stopped:
            return stopped
        self._normalize()
        if not bool(self.config.uniform_drop):
            self.tree_weight.append(self.shrinkage_rate)
            self.sum_weight += self.shrinkage_rate
        return False

    def _dropping_trees(self) -> None:
        cfg = self.config
        K = self.num_tree_per_iteration
        self.drop_index = []
        # drop candidates and the drop/normalize replay read HOST trees of
        # every earlier iteration, so DART is an every-iteration pipeline
        # barrier: drain deferred assemblies (and settle any pending
        # no-split stop) before the candidate window is fixed.  The
        # pipeline still overlaps the host half of each tree with the
        # remainder of its own iteration.
        if self.iter > 0:
            self.flush()
        is_skip = self.random_for_drop.next_float() < float(cfg.skip_drop)
        n_iter = self.iter
        if not is_skip and n_iter > 0:
            drop_rate = float(cfg.drop_rate)
            max_drop = int(cfg.max_drop)
            if not bool(cfg.uniform_drop):
                if self.sum_weight > 0:
                    inv_avg = len(self.tree_weight) / self.sum_weight
                    if max_drop > 0:
                        drop_rate = min(drop_rate, max_drop * inv_avg / self.sum_weight)
                    for i in range(n_iter):
                        if self.random_for_drop.next_float() < \
                                drop_rate * self.tree_weight[i] * inv_avg:
                            self.drop_index.append(i)
                            if max_drop > 0 and len(self.drop_index) >= max_drop:
                                break
            else:
                if max_drop > 0:
                    drop_rate = min(drop_rate, max_drop / float(n_iter))
                for i in range(n_iter):
                    if self.random_for_drop.next_float() < drop_rate:
                        self.drop_index.append(i)
                        if max_drop > 0 and len(self.drop_index) >= max_drop:
                            break
        # remove dropped trees from the training score (dart.hpp:119-126);
        # drop candidates are this run's trees, offset past any loaded model
        # (dart.hpp pushes num_init_iteration_ + i)
        for i in self.drop_index:
            for k in range(K):
                self._add_tree_to_train_score(self._run_tree(i, k), k, -1.0)
        k_cnt = float(len(self.drop_index))
        lr = float(self.config.learning_rate)
        if not bool(cfg.xgboost_dart_mode):
            self.shrinkage_rate = lr / (1.0 + k_cnt)
        else:
            self.shrinkage_rate = lr if not self.drop_index else lr / (lr + k_cnt)

    def _normalize(self) -> None:
        """dart.hpp Normalize: dropped trees end rescaled by k/(k+1)
        (or k/(k+lr) in xgboost mode); train score regains factor*tree, valid
        score loses (1-factor)*tree."""
        k = float(len(self.drop_index))
        if k == 0:
            return
        cfg = self.config
        lr = float(cfg.learning_rate)
        K = self.num_tree_per_iteration
        if not bool(cfg.xgboost_dart_mode):
            factor = k / (k + 1.0)
            weight_scale = k / (k + 1.0)
            weight_sub = 1.0 / (k + 1.0)
        else:
            factor = k / (k + lr)
            weight_scale = k / (k + lr)
            weight_sub = 1.0 / (k + lr)
        for i in self.drop_index:
            for kk in range(K):
                tree = self._run_tree(i, kk)
                self._add_tree_to_valid_scores(tree, kk, factor - 1.0)
                self._add_tree_to_train_score(tree, kk, factor)
                tree.apply_shrinkage(factor)
            if not bool(cfg.uniform_drop):
                self.sum_weight -= self.tree_weight[i] * weight_sub
                self.tree_weight[i] *= weight_scale


class RF(GBDT):
    """Random forest mode (rf.hpp:18-207): every tree fits the gradients of
    the zero score, bagging + feature sampling are mandatory, leaf outputs are
    converted through the objective, and the score is the running average."""

    def __init__(self, config, train_set, objective, metrics, init_model=None):
        if not (config.bagging_freq > 0 and 0.0 < config.bagging_fraction < 1.0):
            Log.fatal("RF mode requires bagging (bagging_freq > 0, bagging_fraction in (0,1))")
        if not (0.0 < config.feature_fraction < 1.0):
            Log.fatal("RF mode requires feature_fraction in (0, 1)")
        if objective is None:
            Log.fatal("RF mode requires an objective function (no custom fobj)")
        super().__init__(config, train_set, objective, metrics, init_model)
        if self.num_tree_per_iteration != 1:
            Log.fatal("Cannot use RF for multi-class")
        if train_set.metadata.init_score is not None:
            Log.fatal("Cannot use init_score in RF mode")
        self.shrinkage_rate = 1.0
        self.model.average_output = True
        # continued training: GBDT.__init__ replayed the loaded trees as a
        # SUM; RF scores are running averages (rf.hpp:33-38)
        if self.num_init_iteration > 0:
            self._multiply_scores(0, 1.0 / self.num_init_iteration)
        obj = self.objective
        self._leaf_transform = lambda lv: obj.convert_output(lv)
        self._metric_objective = None
        self._fast_variant_ok = True  # custom fast iteration below
        Log.info("Using RF")

    def _boost_from_average(self) -> float:
        return 0.0

    def reset_config(self, new_params) -> None:
        # rf.hpp ResetConfig: RF scores are running averages — shrinkage
        # stays pinned at 1.0 whatever learning_rate says
        super().reset_config(new_params)
        self.shrinkage_rate = 1.0

    def _gradients(self):
        # gradients of the zero score, every iteration (rf.hpp Boosting)
        if self._grad_fn is None:
            obj = self.objective

            def gradfn(score, label, weight):
                return obj.get_gradients_multi(jnp.zeros_like(score), label, weight)

            self._grad_fn = xla_obs.jit(gradfn,
                                        site="variants.rf_gradients")
        return self._grad_fn(self.score, self.label_dev, self.weight_dev)

    def _train_one_iter_fast_rf(self) -> bool:
        """RF on the partition-ordered fast path: gradients of the ZERO
        score masked by the bagged count column, growth, and the
        running-average score fold (score = (score*m + tree)/(m+1),
        rf.hpp:118-122) — the tree step and the score fold are each ONE
        device dispatch (_FastState._step_rf / _rf_score_update)."""
        from .gbdt import _traverse_update
        fs = self._fast_enter()
        self._fast_refresh_bag(fs)
        fmask = self._feature_sample()
        out, fs.payload, fs.aux = fs._step_rf(fs.payload, fs.aux, fmask)
        tree, tree_dev, leaf_out = self._finish_tree(out, 0.0, None)
        m = float(self.iter + self.num_init_iteration)
        if tree.num_leaves > 1:
            fs.payload = fs._rf_score_update(fs.payload, tree_dev, leaf_out,
                                             jnp.float32(m))
            depth_iters = max(self.grower_cfg.num_leaves - 1, 1)
            for vs in self.valid_sets:
                vs[3] = vs[3].at[0].multiply(jnp.float32(m / (m + 1.0)))
                vs[3] = _traverse_update(
                    vs[2], vs[3], leaf_out / jnp.float32(m + 1.0), tree_dev,
                    self.meta_dev, self.bundle_map, depth_iters, 0)
        else:
            tree.leaf_value[0] = 0.0
        self.model.trees.append(tree)
        self.iter += 1
        return False

    def train_one_iter(self, grad=None, hess=None) -> bool:
        from .gbdt import _make_vals, _update_score_k, _traverse_update
        # the fused RF step computes zero-score gradients from the
        # PARTITION-ORDERED label/weight columns, which is only valid for
        # row-independent objectives (a query-coupled objective would pair
        # permuted labels with original-order query boundaries)
        if grad is None and hess is None and self._fast_eligible() \
                and getattr(self.objective, "is_rowwise", True):
            return self._train_one_iter_fast_rf()
        self._fast_sync_back()
        if grad is None or hess is None:
            grads, hesss = self._gradients()
        else:
            grads, hesss = self._pad_custom_gradients(grad, hess)
        gmask, cmask = self._bagging_masks(grads, hesss)
        self._bag_cmask = cmask
        fmask = self._feature_sample()
        m = float(self.iter + self.num_init_iteration)
        for k in range(self.num_tree_per_iteration):
            vals = _make_vals(grads, hesss, gmask, cmask, k)
            out = self.grower(self.bins_dev, vals, fmask)
            tree, tree_dev, leaf_out = self._finish_tree(out, 0.0, None)
            if tree.num_leaves > 1:
                # running average: score = (score*m + tree) / (m+1) (rf.hpp:118-122)
                self._multiply_scores(k, m)
                self.score = _update_score_k(self.score, out["leaf_id"], leaf_out, k)
                depth_iters = max(self.grower_cfg.num_leaves - 1, 1)
                for vs in self.valid_sets:
                    vs[3] = _traverse_update(vs[2], vs[3], leaf_out, tree_dev,
                                             self.meta_dev, self.bundle_map,
                                             depth_iters, k)
                self._multiply_scores(k, 1.0 / (m + 1.0))
            else:
                # reference appends a fresh zero stump when no split is found
                # (rf.hpp:100-131) — undo the leaf transform so prediction's
                # sum/average sees a 0 contribution like the training score
                tree.leaf_value[0] = 0.0
            self.model.trees.append(tree)
        self.iter += 1
        return False


def create_boosting(boosting_type: str, config, train_set, objective, metrics,
                    init_model=None) -> GBDT:
    """Factory keyed on config.boosting (boosting.cpp:30-64)."""
    if boosting_type == "gbdt" or boosting_type == "gbrt":
        return GBDT(config, train_set, objective, metrics, init_model)
    if boosting_type == "dart":
        return DART(config, train_set, objective, metrics, init_model)
    if boosting_type == "goss":
        return GOSS(config, train_set, objective, metrics, init_model)
    if boosting_type in ("rf", "random_forest"):
        return RF(config, train_set, objective, metrics, init_model)
    Log.fatal("Unknown boosting type %s", boosting_type)
