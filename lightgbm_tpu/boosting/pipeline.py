"""Deferred host-half assembler for the async boosting pipeline (ISSUE 5).

The fused fast path's device step for tree t+1 does not depend on tree
t's host `Tree` object — `_step` consumes only `(payload, aux)`, which
never leave the device.  The only reason the classic loop stalled once
per tree was the synchronous packed fetch inside `_finish_tree`.  This
module provides the bounded FIFO that takes that fetch (and the ~2 ms of
host assembly behind it) off the dispatch path:

* `submit(fn)` enqueues one DRAIN UNIT's host half and applies
  backpressure: at most `depth` units are pending-or-running.  A unit
  is one tree on the per-tree fast path (packed fetch -> `Tree`
  assembly -> `model.trees.append`); the fused boosting window
  (boost_window=J, ISSUE 13) submits MULTI-TREE units — one packed
  fetch draining J*K parked trees — so `trees=` tells the queue how
  many trees a unit carries and `pending_trees` reports how far the
  device is ahead of the host model in TREES, not units.
* the halves run on ONE worker thread in strict submission order —
  `model.trees` grows in exactly the order the trees were dispatched,
  which is what byte-identical model files require.
* `flush()` drains everything, joins the worker, and re-raises the first
  deferred exception.  After `flush()` returns no thread is alive — a
  process with a thousand short-lived boosters never accumulates parked
  workers.

jax is thread-safe for this use: the host half only runs jitted *reads*
of committed output arrays (the packed fetch); nothing in it donates or
mutates device buffers the dispatch thread still owns.
"""
from __future__ import annotations

import collections
import threading
from typing import Callable, Deque, Optional, Tuple

from ..runtime import telemetry, tracing


class TreeAssembler:
    """Bounded, strictly-ordered, single-worker deferred queue."""

    def __init__(self, depth: int):
        self.depth = max(1, int(depth))
        self._cv = threading.Condition()
        self._fifo: Deque[Tuple[Callable[[], None], int]] = \
            collections.deque()
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._stopping = False

    @property
    def pending(self) -> int:
        """Drain units submitted but not yet finished."""
        with self._cv:
            return len(self._fifo)

    @property
    def pending_trees(self) -> int:
        """Trees carried by the pending drain units (a boosting-window
        unit counts its whole J*K batch)."""
        with self._cv:
            return sum(n for _, n in self._fifo)

    def submit(self, fn: Callable[[], None], trees: int = 1) -> None:
        """Enqueue one drain unit carrying `trees` parked trees; blocks
        while `depth` units are already pending (the in-flight one
        counts), bounding how far the device runs ahead.  A deferred
        error from an earlier unit re-raises here rather than silently
        dropping trees."""
        # cross-thread trace propagation (ISSUE 14): the host half runs
        # on the worker thread but belongs to the dispatching iteration's
        # causal chain — capture the dispatcher's context here and replay
        # it (plus a drain span) around the deferred fn.  Disabled
        # tracing returns fn unchanged.
        fn = tracing.bind(fn, "assembler/drain", trees=trees)
        with self._cv:
            if self._error is not None:
                err, self._error = self._error, None
                raise err
            while len(self._fifo) >= self.depth:
                self._cv.wait()
                if self._error is not None:
                    err, self._error = self._error, None
                    raise err
            self._fifo.append((fn, max(1, int(trees))))
            # live queue depth (ISSUE 9): how far the device is running
            # ahead of the host model right now
            telemetry.gauge("lgbm_pipeline_queue_depth").set(
                len(self._fifo))
            if self._thread is None:
                self._stopping = False
                self._thread = threading.Thread(
                    target=self._run, name="lgbm-tpu-assembler", daemon=True)
                self._thread.start()
            self._cv.notify_all()

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._fifo and not self._stopping:
                    self._cv.wait()
                if not self._fifo:
                    return
                fn, _n = self._fifo[0]  # keep queued: in-flight counts
                                        # against the depth bound
            try:
                fn()
            except BaseException as e:  # deferred to submit()/flush()
                with self._cv:
                    if self._error is None:
                        self._error = e
            with self._cv:
                self._fifo.popleft()
                telemetry.gauge("lgbm_pipeline_queue_depth").set(
                    len(self._fifo))
                self._cv.notify_all()

    def flush(self) -> None:
        """Drain every pending half, stop the worker, and re-raise the
        first deferred error.  Idempotent; cheap when already empty."""
        with self._cv:
            while self._fifo:
                self._cv.wait()
            self._stopping = True
            self._cv.notify_all()
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join()
        with self._cv:
            err, self._error = self._error, None
        if err is not None:
            raise err
