"""GBDT boosting driver.

Role parity with the reference src/boosting/gbdt.cpp: Init (:64-169),
TrainOneIter (:387-482), Bagging (:213-295), BoostFromAverage (:363-385),
UpdateScore / ScoreUpdater (src/boosting/score_updater.hpp), RollbackOneIter
(:484-500).

TPU-first: raw scores live on device for the whole run; one boosting
iteration is (jitted gradient) → (jitted tree grower) → (jitted score
gather-update per dataset).  Only the finished tree's small arrays come back
to the host, where the reference-format model is assembled.
"""
from __future__ import annotations

import functools
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
import numpy as np

from ..io.binning import BIN_TYPE_CATEGORICAL
from ..io.dataset import BinnedDataset
from ..models.gbdt_model import GBDTModel
from ..models.tree import Tree
from ..ops.split import FeatureMeta
from ..runtime import resilience, syncs, telemetry, tracing, xla_obs
from ..utils import compat
from ..utils.log import Log
from ..utils.random import Random, partition_seed
from ..utils.timer import PhaseTimer
from ..ops import segment as seg
from ..ops.bundle import (BundleMap, bundle_map_from_info, decode_bin,
                          identity_bundle_map)
from .grower import GrowerConfig, make_tree_grower
from .grower2 import (PayloadCols, TREE_DEVICE_FIELDS,
                      make_partitioned_grower)
from .pipeline import TreeAssembler

K_EPSILON = 1e-15


def _construct_bitset(vals) -> list:
    """Common::ConstructBitset — uint32 words spanning [0, max(vals)]."""
    if not vals:
        return []
    words = [0] * (max(vals) // 32 + 1)
    for v in vals:
        words[v // 32] |= 1 << (v % 32)
    return words

# Reuse compiled growers across boosters: jax.jit caches per wrapper object,
# so two boosters with identical feature metadata + config would otherwise
# recompile the identical program (slow on every lgb.train call).
_GROWER_CACHE: Dict = {}


def _bundle_key(ds: BinnedDataset):
    info = ds.bundle_info
    if info is None:
        return None
    return (info.f_group.tobytes(), info.f_offset.tobytes(),
            info.f_identity.tobytes())


def _cached_grower(meta_dev: FeatureMeta, cfg, max_num_bin: int, ds: BinnedDataset,
                   bundle_map=None, forced=None):
    key = (cfg, max_num_bin, ds.bins.shape, _bundle_key(ds), forced,
           tuple((m.num_bin, m.missing_type, m.default_bin, m.is_trivial, m.bin_type)
                 for m in ds.bin_mappers),
           ds.monotone_constraints.tobytes(), ds.feature_penalty.tobytes())
    grower = _GROWER_CACHE.get(key)
    if grower is None:
        xla_obs.cache_event("gbdt.grower_cache", "miss")
        grower = make_tree_grower(meta_dev, cfg, max_num_bin,
                                  bundle_map=bundle_map, forced=forced)
        _GROWER_CACHE[key] = grower
    else:
        xla_obs.cache_event("gbdt.grower_cache", "hit")
    return grower


_PGROWER_CACHE: Dict = {}

#: row count past which the fast path's f32 index column splits into
#: radix-4096 (hi, lo) halves (f32 integers are exact below 2^24; tests
#: lower this to exercise the wide layout at small N)
_IDX_WIDE_THRESHOLD = 1 << 24

#: radix of the split index
_IDX_RADIX = 4096.0

#: packed-fetch program cache, bounded so long-lived serving/training
#: processes cycling through many output specs (different num_leaves,
#: grower variants, eval-round shapes) cannot grow it without limit;
#: LRU eviction — steady-state training uses one or two specs
_PACK_CACHE: "OrderedDict" = OrderedDict()
_PACK_CACHE_MAX = 64


def _pack_cache_put(cache: "OrderedDict", key, entry,
                    site: str = "gbdt.pack_cache") -> None:
    cache[key] = entry
    while len(cache) > _PACK_CACHE_MAX:
        cache.popitem(last=False)
        xla_obs.cache_event(site, "evict")


def _fetch_packed(out: Dict, label: str = "tree_fetch") -> Dict[str, np.ndarray]:
    """device_get of the grower's (small) outputs in ONE transfer.

    A tunneled/remote TPU pays a full round trip per fetched array;
    device_get of the ~17-entry tree dict cost ~90 ms/tree on the bench
    chip against ~2 ms of actual host assembly.  All values are exact in
    f32 (counts/ids < 2^24, flags 0/1), so flatten+concat on device, fetch
    once, and split on host.  The big per-row leaf_id array (legacy grower)
    is excluded and fetched only by the paths that need it."""
    spec = tuple(sorted((k, tuple(v.shape), str(v.dtype))
                        for k, v in out.items() if k != "leaf_id"))
    entry = _PACK_CACHE.get(spec)
    if entry is None:
        xla_obs.cache_event("gbdt.pack_cache", "miss")
        keys = [k for k, _, _ in spec]
        shapes = {k: s for k, s, _ in spec}
        dtypes = {k: d for k, _, d in spec}
        sizes = [int(np.prod(shapes[k], dtype=np.int64)) for k in keys]
        offs = np.concatenate([[0], np.cumsum(sizes)]).astype(np.int64)

        @functools.partial(xla_obs.jit, site="gbdt.pack_fetch")
        def pack(o):
            return jnp.concatenate(
                [o[k].astype(jnp.float32).reshape(-1) for k in keys])

        entry = (keys, shapes, dtypes, offs, pack)
        _pack_cache_put(_PACK_CACHE, spec, entry)
    else:
        xla_obs.cache_event("gbdt.pack_cache", "hit")
        _PACK_CACHE.move_to_end(spec)
    keys, shapes, dtypes, offs, pack = entry
    flat = np.asarray(syncs.device_get(pack(out), label=label))
    host = {}
    for i, k in enumerate(keys):
        a = flat[offs[i]:offs[i + 1]].reshape(shapes[k])
        host[k] = a if dtypes[k] == "float32" else a.astype(dtypes[k])
    return host


#: eval-round pack cache (same pattern/bound as _PACK_CACHE): one jitted
#: flatten+concat program per tuple-of-shapes of the round's score arrays
_EVAL_PACK_CACHE: "OrderedDict" = OrderedDict()


#: grower2 tree-dict fields that are replicated in value across a mesh
#: (everything except the per-device row-segment bookkeeping)
_PTREE_REPLICATED = (
    "num_leaves", "split_rounds", "leaf_value", "leaf_count", "leaf_sum_g",
    "leaf_sum_h", "split_feature", "split_bin", "split_gain", "default_left",
    "split_is_cat", "split_cat_bitset", "left_child", "right_child",
    "internal_value", "internal_count")


def _cached_pgrower(meta_dev: FeatureMeta, cfg, max_num_bin: int,
                    ds: BinnedDataset, cols: PayloadCols, payload_width: int,
                    bundle_map=None, forced=None, mesh=None, mesh_axis=None,
                    mode="data", top_k=20, quantized=False, qmax=0):
    from ..ops import pallas_segment as _pseg
    key = (cfg, max_num_bin, ds.bins.shape, cols, payload_width,
           _bundle_key(ds), forced, mesh, mesh_axis, mode, top_k,
           quantized, qmax,
           # every staged flag that flips grower structure or kernel
           # choice when toggled: an in-process flip (bench probe,
           # exp/flip_validated.py rerun) must always rebuild the grower,
           # as the flag docstrings promise
           _pseg.PARTITION_HIST_VALIDATED,
           _pseg.HIST_COLBLOCK_VALIDATED,
           _pseg.PARTITION_BLOCKS_VALIDATED,
           _pseg.PARTITION_RING4_VALIDATED,
           _pseg.FRONTIER_BATCH_VALIDATED,
           _pseg.HIST_QUANT_VALIDATED,
           tuple((m.num_bin, m.missing_type, m.default_bin, m.is_trivial, m.bin_type)
                 for m in ds.bin_mappers),
           ds.monotone_constraints.tobytes(), ds.feature_penalty.tobytes())
    grower = _PGROWER_CACHE.get(key)
    if grower is None:
        xla_obs.cache_event("gbdt.pgrower_cache", "miss")
        if mesh is None:
            grower = make_partitioned_grower(
                meta_dev, cfg, max_num_bin, cols, ds.num_features,
                bundle_map=bundle_map, num_columns=ds.bins.shape[0],
                forced=forced, payload_width=payload_width,
                quantized=quantized, qmax=qmax)
        else:
            # the mesh fast path: the SAME partitioned engine per shard
            # (local row blocks partition locally), collectives at the
            # histogram boundary only — the reference's learner inheritance
            # (data_parallel_tree_learner.cpp:147 IS SerialTreeLearner +
            # network), kept structurally
            from jax.sharding import PartitionSpec as P
            ax = mesh_axis
            grow = make_partitioned_grower(
                meta_dev, cfg, max_num_bin, cols, ds.num_features,
                jit=False, bundle_map=bundle_map,
                num_columns=ds.bins.shape[0], forced=forced,
                axis_name=ax, mode=mode,
                num_machines=int(mesh.shape[ax]), top_k=top_k,
                payload_width=payload_width,
                quantized=quantized, qmax=qmax)
            tree_specs = dict.fromkeys(_PTREE_REPLICATED, P())
            # per-device row segments come back stacked [ndev * L]
            tree_specs["seg_start"] = P(ax)
            tree_specs["seg_cnt"] = P(ax)
            # quantized growers take the replicated [2] scale pair as a
            # fourth argument (scales are global maxima, so every shard
            # holds the same values)
            in_specs = (P(ax, None), P(ax, None), P(None))
            if quantized:
                in_specs = in_specs + (P(),)
            grower = xla_obs.jit(compat.shard_map(
                grow, mesh=mesh,
                in_specs=in_specs,
                out_specs=(tree_specs, P(ax, None), P(ax, None)),
                check_vma=False), donate_argnums=(0, 1),
                site="gbdt.pgrower_mesh")
        _PGROWER_CACHE[key] = grower
    else:
        xla_obs.cache_event("gbdt.pgrower_cache", "hit")
    return grower


class _FastState:
    """Partition-ordered training state for the serial fast path.

    The whole of training state — bin columns, label/weight, per-class raw
    scores, per-iteration grad/hess and the current tree's per-row output —
    lives in ONE row-major payload matrix that the partitioned grower
    reorders in place (rows of each leaf contiguous).  Everything downstream
    of the grower becomes elementwise: gradients, score updates, and the
    count-mask column (which doubles as the bagging mask, refreshed through
    the index column on resample).  Original row order is recovered through the index column
    only when a consumer needs it (metrics, sync back to the legacy path).
    """

    def __init__(self, gbdt: "GBDT"):
        ds = gbdt.train_set
        G = ds.bins.shape[0]   # storage columns (EFB bundles, G <= F)
        K = gbdt.num_tree_per_iteration
        n_pad = ds.num_data_padded
        # mesh fast path: rows live in ndev device blocks of n_loc real rows
        # + a GUARD-row tail EACH (the partition kernels overrun into the
        # guard, so it must sit at the end of every LOCAL block, not just
        # the global tail).  Guard rows carry idx == n_pad — a dead slot
        # that every original-order consumer (bag refresh, score sync)
        # filters or routes to a zero entry.  Serial is the ndev == 1 case.
        #
        # feature-parallel: every block is the FULL row set (the reference
        # learner holds full data per rank) with the storage columns
        # permuted owned-first; original-order consumers work unchanged
        # because their idx-routed scatters are idempotent across the
        # duplicate blocks.
        mesh = gbdt.mesh if gbdt.parallel_mode in ("data", "voting",
                                                   "feature") else None
        feature_par = mesh is not None and gbdt.parallel_mode == "feature"
        self.feature_par = feature_par
        if feature_par:
            # the padded feature axis (shard multiple) IS the storage width
            G = G + gbdt._fmask_pad
        self.G, self.K, self.n_pad = G, K, n_pad
        self.mesh = mesh
        ndev = int(mesh.shape[gbdt.mesh_axis]) if mesh is not None else 1
        self.ndev = ndev
        n_loc = n_pad if feature_par else n_pad // ndev
        self.n_loc = n_loc
        n_rows = (n_loc + seg.GUARD) * ndev
        self.n_rows = n_rows
        self.label_col = G
        self.weight_col = G + 1
        self.cnt_col = G + 2
        self.idx_col = G + 3
        self.score0 = G + 4
        # multiclass trains K trees per iteration, all from the SAME
        # pre-iteration scores (gbdt.cpp Boosting computes every class's
        # gradients before any tree), but each tree reorders the rows — so
        # the pre-iteration scores are snapshotted into columns that ride
        # the partition, and each class's gradients are recomputed from the
        # snapshot in whatever order the rows currently sit
        self.snap0 = G + 4 + K if K > 1 else self.score0
        self.grad_col = self.snap0 + (K if K > 1 else 1)
        self.hess_col = self.grad_col + 1
        self.value_col = self.grad_col + 2
        # pristine valid mask: the cnt column is a WORKING mask (bagging /
        # GOSS selection overwrite it per iteration).  The gradient-weight
        # column carries the sampling amplification so multiclass can draw
        # one selection per iteration that RIDES the per-tree partitions.
        self.bvalid_col = self.value_col + 1
        self.gweight_col = self.bvalid_col + 1
        # past ~2^24 rows an f32 index column loses exactness; split the
        # index into radix-4096 (hi, lo) halves — both remain exact through
        # the one-hot permutation matmuls (each output is a single-term sum)
        self.wide_idx = (n_pad + 1) >= _IDX_WIDE_THRESHOLD
        self.idxhi_col = self.gweight_col + 1 if self.wide_idx else None
        self.P = (self.idxhi_col if self.wide_idx else self.gweight_col) + 1
        if jax.default_backend() == "tpu":
            # Mosaic DMA slices must span whole 128-lane tiles; a [N, P]
            # f32 array is physically padded to 128 lanes on TPU anyway,
            # so declaring the pad costs no extra HBM
            self.P = -(-self.P // 128) * 128
        self.cols = PayloadCols(grad=self.grad_col, hess=self.hess_col,
                                cnt=self.cnt_col, value=self.value_col)
        payload_gb = self.n_rows * self.P * 4 / 2**30
        Log.info("fast path payload: %d rows x %d cols, %.2f GB "
                 "(+%.2f GB partition scratch)%s", self.n_rows, self.P,
                 payload_gb, payload_gb,
                 " sharded over %d devices" % ndev if ndev > 1 else "")

        P, score0, idx_col = self.P, self.score0, self.idx_col
        cnt_col_, bvalid_col_ = self.cnt_col, self.bvalid_col

        wide_idx, idxhi_col = self.wide_idx, self.idxhi_col

        def write_idx(pay, rows, idx):
            """Store integer row indices into the index column(s)."""
            if wide_idx:
                pay = pay.at[rows, idxhi_col].set(
                    jnp.floor_divide(idx, jnp.int32(_IDX_RADIX))
                    .astype(jnp.float32))
                idx = jnp.remainder(idx, jnp.int32(_IDX_RADIX))
            return pay.at[rows, idx_col].set(idx.astype(jnp.float32))

        def read_idx(payload):
            """Integer row indices from the index column(s)."""
            idx = payload[:, idx_col].astype(jnp.int32)
            if wide_idx:
                idx = idx + payload[:, idxhi_col].astype(jnp.int32) \
                    * jnp.int32(_IDX_RADIX)
            return idx

        def build_block(bins, label, weight, vmask, score, idx0):
            """One device block: n_loc_b real rows + the GUARD-row tail,
            guard idx pinned to the dead slot."""
            n_loc_b = label.shape[0]
            pay = jnp.zeros((n_loc_b + seg.GUARD, P), jnp.float32)
            pay = pay.at[:n_loc_b, :G].set(bins.T.astype(jnp.float32))
            pay = pay.at[:n_loc_b, G].set(label)
            pay = pay.at[:n_loc_b, G + 1].set(weight)
            pay = pay.at[:n_loc_b, cnt_col_].set(vmask)
            pay = pay.at[:n_loc_b, bvalid_col_].set(vmask)
            pay = write_idx(pay, slice(None),
                            jnp.full(pay.shape[0], n_pad, jnp.int32))
            pay = write_idx(pay, slice(None, n_loc_b),
                            idx0 + jnp.arange(n_loc_b, dtype=jnp.int32))
            pay = pay.at[:n_loc_b, score0:score0 + K].set(score.T)
            return pay

        if mesh is None:
            build = xla_obs.jit(functools.partial(build_block,
                                                 idx0=jnp.int32(0)),
                                site="gbdt.payload_build")
        elif feature_par:
            from jax.sharding import PartitionSpec as PS
            ax = gbdt.mesh_axis
            Gloc_f = G // ndev

            def build_local_feat(bins_l, label_f, weight_f, vmask_f,
                                 score_f):
                # bins arrive feature-sharded [Gloc, N]; gather the full
                # matrix once and lay this shard's columns first — the
                # partitioned grower's histogram then walks only the
                # leading Gloc columns
                my = lax.axis_index(ax)
                bins_all = lax.all_gather(bins_l, ax, axis=0, tiled=True)
                off = my * Gloc_f
                l_ = jnp.arange(G, dtype=jnp.int32)
                perm = jnp.where(l_ < Gloc_f, off + l_,
                                 jnp.where(l_ - Gloc_f < off,
                                           l_ - Gloc_f, l_))
                return build_block(bins_all[perm], label_f, weight_f,
                                   vmask_f, score_f, jnp.int32(0))

            build = xla_obs.jit(compat.shard_map(
                build_local_feat, mesh=mesh,
                in_specs=(PS(ax, None), PS(), PS(), PS(), PS(None, None)),
                out_specs=PS(ax, None), check_vma=False),
                site="gbdt.payload_build_feature_mesh")
        else:
            from jax.sharding import PartitionSpec as PS
            ax = gbdt.mesh_axis

            def build_local(bins_l, label_l, weight_l, vmask_l, score_l):
                my = lax.axis_index(ax)
                return build_block(bins_l, label_l, weight_l, vmask_l,
                                   score_l, my * n_loc)

            build = xla_obs.jit(compat.shard_map(
                build_local, mesh=mesh,
                in_specs=(PS(None, ax), PS(ax), PS(ax), PS(ax),
                          PS(None, ax)),
                out_specs=PS(ax, None), check_vma=False),
                site="gbdt.payload_build_mesh")

        self._build = build
        self.reset(gbdt)
        # quantized-gradient mode (ops.quantize): integer grad/hess
        # columns, int32 histograms, dequantize at the split boundary
        self.quant_on = bool(getattr(gbdt, "_quant_enabled", False))
        self.qmax = int(getattr(gbdt, "_qmax", 0))
        self.grower = _cached_pgrower(gbdt.meta_dev, gbdt.grower_cfg,
                                      ds.max_num_bin, ds, self.cols, self.P,
                                      bundle_map=gbdt.bundle_map
                                      if ds.bundle_info is not None else None,
                                      forced=gbdt.forced_schedule,
                                      mesh=mesh, mesh_axis=gbdt.mesh_axis,
                                      mode=gbdt.parallel_mode or "data",
                                      top_k=int(getattr(gbdt.config, "top_k",
                                                        20) or 20),
                                      quantized=self.quant_on,
                                      qmax=self.qmax)

        obj = gbdt.objective
        snap0, cnt_col = self.snap0, self.cnt_col
        grad_col, hess_col = self.grad_col, self.hess_col

        @functools.partial(xla_obs.jit, site="gbdt.snap_scores",
                           donate_argnums=(0,))
        def snap_scores(payload):
            # K lane-masked passes, not a slice DUS — see
            # seg.payload_col_write (the K wheres fuse into one pass)
            for kk in range(K):
                payload = seg.payload_col_write(payload, snap0 + kk,
                                                payload[:, score0 + kk])
            return payload

        idx_col = self.idx_col

        @functools.partial(xla_obs.jit, site="gbdt.set_bag",
                           donate_argnums=(0,))
        def set_bag(payload, combined):
            """Refresh the count-mask column from an ORIGINAL-order
            valid*bag vector — rows sit in partition order, so the index
            column routes the gather (Bagging, gbdt.cpp:213-295).  Guard
            rows route to the appended dead slot and stay masked out."""
            combined = jnp.concatenate([combined, jnp.zeros(1, jnp.float32)])
            return seg.payload_col_write(payload, cnt_col,
                                         combined[read_idx(payload)])

        rowwise = getattr(obj, "is_rowwise", True) if obj is not None else True
        label_orig, weight_orig = gbdt.label_dev, gbdt.weight_dev

        if rowwise:
            def _class_grads(payload, k):
                """Class k's masked (gradient, hessian) vectors in the
                payload's current row order — shared by the f32 fill and
                the quantized fill."""
                snap = payload[:, snap0:snap0 + K].T
                g, h = obj.get_gradients_multi(snap, payload[:, G],
                                               payload[:, G + 1])
                valid = payload[:, cnt_col]
                return (jnp.take(g, k, axis=0) * valid,
                        jnp.take(h, k, axis=0) * valid)
        else:
            def _class_grads(payload, k):
                """Non-rowwise objectives (lambdarank/xendcg: gradients
                couple rows within a query): scatter the snapshot scores
                back to ORIGINAL row order through the index column,
                compute gradients against the original-order label/weight
                (where the query boundaries live), and gather the results
                into the current partition order.  Two permutations per
                class tree — cheap next to the histogram work."""
                idx = read_idx(payload)
                snap = payload[:, snap0:snap0 + K]
                score_orig = jnp.zeros((K, n_pad + 1), jnp.float32) \
                    .at[:, idx].set(snap.T)[:, :n_pad]
                g, h = obj.get_gradients_multi(score_orig, label_orig,
                                               weight_orig)
                gp = jnp.pad(g, ((0, 0), (0, 1)))
                hp = jnp.pad(h, ((0, 0), (0, 1)))
                valid = payload[:, cnt_col]
                return (jnp.take(gp, k, axis=0)[idx] * valid,
                        jnp.take(hp, k, axis=0)[idx] * valid)

        def _fill_body(payload, k):
            """Write class k's gradients into the grad/hess columns —
            shared by the piecewise (profiled) and fused paths."""
            gk, hk = _class_grads(payload, k)
            payload = seg.payload_col_write(payload, grad_col, gk)
            return seg.payload_col_write(payload, hess_col, hk)

        @functools.partial(xla_obs.jit, site="gbdt.fill_class",
                           donate_argnums=(0,), static_argnames=("k",))
        def fill_class(payload, k):
            return _fill_body(payload, k)

        if self.quant_on:
            from ..ops.quantize import quantize_pair
            qmax_f = float(self.qmax)

            def _fill_body_quant(payload, k, qseed):
                """Quantized fill: class k's masked gradients are scaled
                to the integer grid and stochastically rounded; the
                integer-valued columns feed the int32 histogram engine
                and the [2] scale pair rides to the grower's dequantize
                boundary."""
                gk, hk = _class_grads(payload, k)
                qg, qh, qscale = quantize_pair(gk, hk, qseed, qmax_f)
                payload = seg.payload_col_write(payload, grad_col, qg)
                payload = seg.payload_col_write(payload, hess_col, qh)
                return payload, qscale

            @functools.partial(xla_obs.jit,
                               site="gbdt.fill_class_quant",
                               donate_argnums=(0,),
                               static_argnames=("k",))
            def fill_class_quant(payload, k, qseed):
                return _fill_body_quant(payload, k, qseed)

        @functools.partial(xla_obs.jit, site="gbdt.apply_score",
                           donate_argnums=(0,), static_argnames=("k",))
        def apply_score(payload, lr, k):
            upd = payload[:, self.value_col] * lr
            return seg.payload_col_write(payload, score0 + k, upd, "add")

        grower = self.grower
        value_col = self.value_col
        bvalid_col = self.bvalid_col
        sample_hook = getattr(gbdt, "_fast_sample_hook", None)

        def _grow_and_score(payload, aux, fmask, lr, k, qscale=None):
            args = (payload, aux, fmask) if qscale is None \
                else (payload, aux, fmask, qscale)
            out, payload, aux = grower.__wrapped__(*args) \
                if hasattr(grower, "__wrapped__") else grower(*args)
            # stumps must not move the scores (gbdt.cpp stops instead)
            upd = jnp.where(out["num_leaves"] > 1,
                            payload[:, value_col] * lr, 0.0)
            payload = seg.payload_col_write(payload, score0 + k, upd, "add")
            return out, payload, aux

        @functools.partial(xla_obs.jit, site="gbdt.step",
                           donate_argnums=(0, 1))
        def step(payload, aux, fmask, lr, k):
            """One fused tree: gradients -> grow -> conditional score add.
            A tunneled TPU pays a round trip per dispatch; fusing the
            per-tree chain into one program leaves a single launch plus
            the packed result fetch.  k is traced (one compile serves
            every class)."""
            payload = _fill_body(payload, k)
            return _grow_and_score(payload, aux, fmask, lr, k)

        if self.quant_on:
            @functools.partial(xla_obs.jit, site="gbdt.step_quant",
                               donate_argnums=(0, 1))
            def step_quant(payload, aux, fmask, lr, k, qseed):
                """Quantized fused tree: the scale pair never leaves the
                program — quantize, int32-histogram growth and the score
                add are one dispatch, like the f32 step."""
                payload, qscale = _fill_body_quant(payload, k, qseed)
                return _grow_and_score(payload, aux, fmask, lr, k, qscale)

        def _all_grads(payload):
            snap = payload[:, snap0:snap0 + K].T
            return obj.get_gradients_multi(snap, payload[:, G],
                                           payload[:, G + 1])

        def _write_sampled(payload, g, h, k, gw, cm):
            payload = seg.payload_col_write(payload, grad_col,
                                            jnp.take(g, k, axis=0) * gw)
            payload = seg.payload_col_write(payload, hess_col,
                                            jnp.take(h, k, axis=0) * gw)
            return seg.payload_col_write(payload, cnt_col, cm)

        @functools.partial(xla_obs.jit, site="gbdt.step_sampled",
                           donate_argnums=(0, 1))
        def step_sampled(payload, aux, fmask, lr, k, key, enabled):
            """Fused tree with a per-iteration row-sampling hook (GOSS):
            gradients for ALL classes come from the snapshot, the hook
            derives (gradient-weight, count-mask) from them off the
            pristine valid column, and class k's weighted gradients plus
            the selection mask land in the working columns."""
            g, h = _all_grads(payload)
            valid = payload[:, bvalid_col]
            gw, cm = sample_hook(g * valid, h * valid, valid, key, enabled)
            payload = _write_sampled(payload, g, h, k, gw, cm)
            return _grow_and_score(payload, aux, fmask, lr, k)

        gweight_col = self.gweight_col

        @functools.partial(xla_obs.jit, site="gbdt.apply_sample_masks",
                           donate_argnums=(0,))
        def apply_sample_masks(payload, key, enabled):
            """Multiclass prelude: the selection is identical for every
            class tree of an iteration, so it is drawn ONCE and written
            into payload COLUMNS (gweight + cnt) — each class tree
            repartitions the rows, and columns ride the partition while
            standalone mask arrays would go stale after the first tree."""
            g, h = _all_grads(payload)
            valid = payload[:, bvalid_col]
            gw, cm = sample_hook(g * valid, h * valid, valid, key, enabled)
            payload = seg.payload_col_write(payload, gweight_col, gw)
            return seg.payload_col_write(payload, cnt_col, cm)

        @functools.partial(xla_obs.jit, site="gbdt.step_masked",
                           donate_argnums=(0, 1))
        def step_masked(payload, aux, fmask, lr, k):
            g, h = _all_grads(payload)
            payload = _write_sampled(payload, g, h, k,
                                     payload[:, gweight_col],
                                     payload[:, cnt_col])
            return _grow_and_score(payload, aux, fmask, lr, k)

        bmap_fs = gbdt.bundle_map
        meta_fs = gbdt.meta_dev
        depth_iters_fs = max(gbdt.grower_cfg.num_leaves - 1, 1)

        def _tree_add_body(payload, tree_dev, leaf_scaled, k, col_of):
            """score[:, k] += leaf_scaled[leaf(x)] routed by the payload's
            OWN bin columns — rows sit in partition order and the bins ride
            along, so DART's drop/normalize score edits (and any other
            tree replay) never need the original row order.  col_of maps a
            per-row global storage-column array to this payload's layout
            (identity everywhere except feature-parallel's owned-first
            permutation)."""
            bins_cols = payload[:, :G]
            body = _make_decision_body(
                tree_dev, meta_fs, bmap_fs,
                lambda f: jnp.take_along_axis(
                    bins_cols, col_of(bmap_fs.f_group[f])[:, None],
                    axis=1)[:, 0].astype(jnp.int32))
            nd = lax.fori_loop(0, depth_iters_fs, body,
                               jnp.zeros(payload.shape[0], jnp.int32))
            return seg.payload_col_write(payload, score0 + k,
                                         leaf_scaled[~nd], "add")

        if feature_par:
            from jax.sharding import PartitionSpec as PS
            ax_f = gbdt.mesh_axis
            Gloc_pta = G // ndev

            def _pta_local(payload_l, tree_dev, leaf_scaled, k):
                my = lax.axis_index(ax_f)
                off = my * Gloc_pta

                def col_of(g):
                    return jnp.where(g < off, Gloc_pta + g,
                                     jnp.where(g < off + Gloc_pta,
                                               g - off, g))

                return _tree_add_body(payload_l, tree_dev, leaf_scaled, k,
                                      col_of)

            payload_tree_add = xla_obs.jit(compat.shard_map(
                _pta_local, mesh=mesh,
                in_specs=(PS(ax_f, None), PS(), PS(), PS()),
                out_specs=PS(ax_f, None), check_vma=False),
                donate_argnums=(0,),
                site="gbdt.payload_tree_add_mesh")
        else:
            @functools.partial(xla_obs.jit,
                               site="gbdt.payload_tree_add",
                               donate_argnums=(0,))
            def payload_tree_add(payload, tree_dev, leaf_scaled, k):
                return _tree_add_body(payload, tree_dev, leaf_scaled, k,
                                      lambda g: g)

        @functools.partial(xla_obs.jit, site="gbdt.apply_const_score",
                           donate_argnums=(0,))
        def apply_const_score(payload, delta, k):
            return seg.payload_col_write(payload, score0 + k, delta, "add")

        @functools.partial(xla_obs.jit, site="gbdt.scale_score",
                           donate_argnums=(0,))
        def scale_score(payload, factor, k):
            return seg.payload_col_write(payload, score0 + k, factor, "mul")

        @functools.partial(xla_obs.jit, site="gbdt.step_rf",
                           donate_argnums=(0, 1))
        def step_rf(payload, aux, fmask):
            """RF's fused tree (rf.hpp Boosting): gradients of the ZERO
            score masked by the bagged count column, then growth — one
            dispatch, like the base fast path's _step.  Scoring is the
            caller's job (running average, not an additive update)."""
            zeros = jnp.zeros((K, n_rows), jnp.float32)
            g, h = obj.get_gradients_multi(zeros, payload[:, G],
                                           payload[:, G + 1])
            valid = payload[:, cnt_col]
            payload = seg.payload_col_write(payload, grad_col, g[0] * valid)
            payload = seg.payload_col_write(payload, hess_col, h[0] * valid)
            return grower.__wrapped__(payload, aux, fmask) \
                if hasattr(grower, "__wrapped__") else grower(payload, aux,
                                                              fmask)

        @functools.partial(xla_obs.jit, site="gbdt.rf_score_update",
                           donate_argnums=(0,))
        def rf_score_update(payload, tree_dev, leaf_scaled, m):
            """score = (score*m + tree)/(m+1) in one dispatch."""
            payload = seg.payload_col_write(payload, score0,
                                            m / (m + 1.0), "mul")
            return payload_tree_add.__wrapped__(
                payload, tree_dev, leaf_scaled / (m + 1.0), jnp.int32(0))

        self._payload_tree_add = payload_tree_add
        self._apply_const_score = apply_const_score
        self._scale_score = scale_score
        self._step_rf = step_rf
        self._rf_score_update = rf_score_update
        self._snap_scores = snap_scores
        self._fill_class = fill_class
        self._apply_score = apply_score
        self._step = step
        self._fill_class_quant = fill_class_quant if self.quant_on else None
        self._step_quant = step_quant if self.quant_on else None
        self._step_sampled = step_sampled if sample_hook is not None else None
        self._apply_sample_masks = apply_sample_masks \
            if sample_hook is not None else None
        self._step_masked = step_masked if sample_hook is not None else None
        self._set_bag = set_bag
        #: fused boosting-window programs keyed by (J, with_bag) — built
        #: lazily by window_program(); survive sync-backs like the other
        #: jitted closures
        self._window_cache: Dict = {}

    def window_program(self, J: int, with_bag: bool):
        """One jitted, donated device program for a whole boosting window:
        a lax.scan over J iterations whose body is EXACTLY the sequential
        fast path's per-iteration programs inlined (`_set_bag` ->
        `_snap_scores` -> K x `_step` through their ``__wrapped__`` seam),
        so every scan step computes the same graph the per-tree dispatch
        loop would — the byte-identity contract of boost_window.  Inputs:
        payload, aux (donated), the per-step feature masks [J, F], the
        per-step ORIGINAL-order bag masks [J, n_pad] (a dummy [J, 1] when
        bagging is off), and the shrinkage scalar.  Outputs: the stacked
        packed split records [J, K, ...] plus the carried payload/aux —
        the records come back to the host in ONE `_fetch_packed`
        transfer."""
        key = (int(J), bool(with_bag))
        prog = self._window_cache.get(key)
        if prog is not None:
            xla_obs.cache_event("gbdt.window_cache", "hit")
            return prog
        xla_obs.cache_event("gbdt.window_cache", "miss")
        K = self.K
        step_fn = self._step.__wrapped__
        snap_fn = self._snap_scores.__wrapped__
        bag_fn = self._set_bag.__wrapped__

        def window(payload, aux, fmasks, bags, lr):
            def step(carry, xs):
                payload, aux = carry
                if with_bag:
                    payload = bag_fn(payload, xs["bag"])
                if K > 1:
                    payload = snap_fn(payload)
                outs = []
                for k in range(K):
                    out, payload, aux = step_fn(payload, aux, xs["fmask"],
                                                lr, jnp.int32(k))
                    outs.append(out)
                stacked = jax.tree_util.tree_map(
                    lambda *a: jnp.stack(a), *outs)
                return (payload, aux), stacked

            xs = {"fmask": fmasks}
            if with_bag:
                xs["bag"] = bags
            (payload, aux), recs = lax.scan(step, (payload, aux), xs,
                                            length=J)
            return recs, payload, aux

        prog = xla_obs.jit(window, site="gbdt.window",
                           donate_argnums=(0, 1))
        self._window_cache[key] = prog
        return prog

    def reset(self, gbdt: "GBDT") -> None:
        """(Re)build the payload from the legacy-order state — used on first
        entry and when re-entering the fast path after a sync back (the
        jitted closures and the grower survive, so no retracing)."""
        self.payload = self._build(gbdt.bins_dev, gbdt.label_dev,
                                   gbdt.weight_dev, gbdt.valid_mask,
                                   gbdt.score)
        self.aux = jnp.zeros_like(self.payload)
        self._bag_dirty = True  # cnt col holds the plain valid mask

    def host_idx(self) -> np.ndarray:
        """Integer original-row indices of every payload row (host)."""
        idx = np.asarray(syncs.device_get(
            self.payload[:, self.idx_col], label="score_fetch")) \
            .astype(np.int64)
        if self.wide_idx:
            hi = np.asarray(syncs.device_get(
                self.payload[:, self.idxhi_col],
                label="score_fetch")).astype(np.int64)
            idx = idx + hi * int(_IDX_RADIX)
        return idx

    def score_cols_device(self) -> List[jax.Array]:
        """Device views whose host fetch reconstructs the original-order
        scores: the contiguous [idx | score_0..score_{K-1}] column block,
        plus the radix-hi index column on the wide layout.  Exposed so an
        eval round can fold them into ONE packed transfer."""
        cols = [self.payload[:, self.idx_col:self.score0 + self.K]]
        if self.wide_idx:
            cols.append(self.payload[:, self.idxhi_col])
        return cols

    def scores_from_host(self, h: np.ndarray,
                         hi: Optional[np.ndarray] = None) -> np.ndarray:
        """[K, n_pad] ORIGINAL-order scores from the fetched column block
        (and radix-hi column on the wide layout).  Guard rows carry the
        dead-slot index and are dropped."""
        idx = h[:, 0].astype(np.int64)
        if self.wide_idx:
            idx = idx + hi.astype(np.int64) * int(_IDX_RADIX)
        keep = idx < self.n_pad
        out = np.zeros((self.K, self.n_pad), np.float32)
        out[:, idx[keep]] = h[keep, 1:1 + self.K].T
        return out

    def raw_scores(self) -> np.ndarray:
        """[K, n_pad] scores in ORIGINAL row order (host)."""
        host = syncs.device_get(self.score_cols_device(),
                                label="score_fetch")
        h = np.asarray(host[0])
        hi = np.asarray(host[1]) if self.wide_idx else None
        return self.scores_from_host(h, hi)


def _feature_meta_device(ds: BinnedDataset) -> FeatureMeta:
    m = ds.bin_mappers
    return FeatureMeta(
        num_bin=jnp.asarray([mm.num_bin for mm in m], jnp.int32),
        missing_type=jnp.asarray([mm.missing_type for mm in m], jnp.int32),
        default_bin=jnp.asarray([mm.default_bin for mm in m], jnp.int32),
        is_trivial=jnp.asarray([mm.is_trivial for mm in m], jnp.bool_),
        is_categorical=jnp.asarray([mm.bin_type == BIN_TYPE_CATEGORICAL for mm in m], jnp.bool_),
        penalty=jnp.asarray(ds.feature_penalty, jnp.float32),
        monotone=jnp.asarray(ds.monotone_constraints, jnp.int32),
    )


@functools.partial(xla_obs.jit, site="gbdt.make_vals",
                   static_argnames=("k",))
def _make_vals(grads, hesss, gmask, cmask, k):
    """Per-row (grad, hess, count) columns for the histogram kernel.  gmask
    scales gradient/hessian mass (bagging zeroes, GOSS amplifies), cmask is
    the 0/1 row-count weight (min_data_in_leaf, leaf counts)."""
    return jnp.stack([grads[k] * gmask, hesss[k] * gmask, cmask], axis=1)


@functools.partial(xla_obs.jit, site="gbdt.update_score_k",
                   static_argnames=("k",))
def _update_score_k(score, leaf_id, leaf_out, k):
    return score.at[k].add(leaf_out[leaf_id])


def _make_decision_body(tree_dev, meta: FeatureMeta, bmap: BundleMap,
                        gather_raw):
    """One traversal step over per-row node ids (Tree::DecisionInner
    semantics, tree.h:234-249 / 288-295), shared by the column-major
    score replay and the payload-order replay — only the raw-bin gather
    differs between the two layouts."""
    sf, sb, dl, lc, rc = (tree_dev["split_feature"], tree_dev["split_bin"],
                          tree_dev["default_left"], tree_dev["left_child"],
                          tree_dev["right_child"])
    is_cat = tree_dev["split_is_cat"]
    cat_bitset = tree_dev["split_cat_bitset"]

    def body(_, nd):
        is_leaf = nd < 0
        ndc = jnp.maximum(nd, 0)
        f = sf[ndc]
        raw = gather_raw(f)
        fbin = decode_bin(raw, bmap.f_identity[f], bmap.f_offset[f],
                          meta.num_bin[f], meta.default_bin[f])
        mt = meta.missing_type[f]
        is_missing = ((mt == 2) & (fbin == meta.num_bin[f] - 1)) | \
                     ((mt == 1) & (fbin == meta.default_bin[f]))
        go_left_num = jnp.where(is_missing, dl[ndc], fbin <= sb[ndc])
        go_left = jnp.where(is_cat[ndc], cat_bitset[ndc, fbin], go_left_num)
        child = jnp.where(go_left, lc[ndc], rc[ndc])
        return jnp.where(is_leaf, nd, child)

    return body


def _mark_critical_path(fn):
    """Run `fn` under the sync-audit's tree->tree critical-path marker:
    any blocking host fetch inside it is a pipeline stall and counts
    against the `host_syncs_per_iter.critical_path` pin."""
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with syncs.critical_path():
            return fn(*args, **kwargs)
    return wrapped


@functools.partial(xla_obs.jit, site="gbdt.traverse_update",
                   static_argnames=("depth_iters", "k"))
def _traverse_update(bins_v, score_kv, leaf_out, tree_dev, meta: FeatureMeta,
                     bmap: BundleMap, depth_iters: int, k: int):
    """Add one tree's (shrunk) outputs to row k of a [K, M] score matrix by
    vectorized bin-level traversal."""
    M = bins_v.shape[1]
    rows = jnp.arange(M)
    body = _make_decision_body(
        tree_dev, meta, bmap,
        lambda f: bins_v[bmap.f_group[f], rows].astype(jnp.int32))
    nd = jax.lax.fori_loop(0, depth_iters, body, jnp.zeros(M, jnp.int32))
    return score_kv.at[k].add(leaf_out[~nd])


class GBDT:
    """The boosting engine behind Booster."""

    def __init__(self, config, train_set: BinnedDataset, objective,
                 metrics: List, init_model: Optional[GBDTModel] = None):
        self.config = config
        self.train_set = train_set
        self.objective = objective
        self.train_metrics = metrics
        self.iter = 0
        self.timer = PhaseTimer(bool(getattr(config, "tpu_profile_phases",
                                             False)))
        # frontier-batch telemetry: sequential device rounds the growers
        # paid, accumulated per finished tree (bench split_rounds_per_tree;
        # == num_leaves-1 per tree unless tpu_frontier_batch > 1 engaged)
        self.split_rounds_total = 0
        self.trees_finished = 0
        self.shrinkage_rate = float(config.learning_rate)
        self.num_class = int(config.num_class)
        self.num_tree_per_iteration = objective.num_model_per_iteration \
            if objective is not None else self.num_class

        self.model = init_model if init_model is not None else GBDTModel()
        self.model.num_class = self.num_class
        self.model.num_tree_per_iteration = self.num_tree_per_iteration
        self.model.max_feature_idx = train_set.num_features - 1
        self.model.feature_names = list(train_set.feature_names)
        self.model.feature_infos = train_set.feature_infos()
        if objective is not None:
            self.model.objective_str = objective.to_string()
        self.num_init_iteration = self.model.current_iteration

        # -- parallel learner selection (tree_learner factory parity,
        #    src/treelearner/tree_learner.cpp:9-33: the requested mode times
        #    the visible device count decides the learner) ------------------
        self.parallel_mode: Optional[str] = None
        self.mesh = None
        self.mesh_axis = "workers"
        self._fmask_pad = 0
        tl = str(getattr(config, "tree_learner", "serial") or "serial")
        if tl != "serial":
            devices = jax.devices()
            # num_machines semantics DIFFER from the reference on purpose:
            # there it counts socket/MPI HOSTS; here the parallel unit is a
            # mesh DEVICE (jax.devices() already spans all hosts under
            # jax.distributed), so num_machines caps the devices used.
            # Reference configs that set num_machines=<hosts> get at least
            # that much parallelism.  See docs/DISTRIBUTED.md.
            nm = int(getattr(config, "num_machines", 1) or 1)
            ndev = len(devices) if nm <= 1 else min(nm, len(devices))
            n_pad_ = train_set.num_data_padded
            if ndev <= 1:
                Log.warning(
                    "tree_learner=%s requested but only one device is "
                    "visible; training with the serial learner", tl)
            elif tl in ("data", "voting") and n_pad_ % ndev != 0:
                Log.warning(
                    "tree_learner=%s: padded row count %d is not divisible "
                    "by %d devices; training with the serial learner",
                    tl, n_pad_, ndev)
            else:
                from jax.sharding import Mesh
                self.parallel_mode = tl
                self.mesh = Mesh(np.array(devices[:ndev]), (self.mesh_axis,))
                Log.info("Using %s-parallel tree learner over %d devices",
                         tl, ndev)

        # forced splits: compile the JSON into a static BFS schedule for the
        # partitioned grower (serial_tree_learner.cpp:546-701)
        self.forced_schedule = None
        fs_path = str(getattr(config, "forcedsplits_filename", "") or "")
        if fs_path:
            from .forced import build_forced_schedule, load_forced_json
            self.forced_schedule = build_forced_schedule(
                load_forced_json(fs_path), train_set.bin_mappers,
                int(config.num_leaves))
            if self.forced_schedule is not None:
                Log.info("Loaded forced splits from %s (%d nodes)",
                         fs_path, len(self.forced_schedule.feat))

        # quantized-gradient training (gradient_quantization, ops.quantize):
        # per-iteration int gradient/hessian columns + int32 histograms on
        # the partition-ordered fast path.  Plain gbdt boosting only (GOSS
        # amplifies gradients inside its fused step, DART/RF replay trees
        # through their own steps) and unforced (the forced override reads
        # raw f32 hist views); anything else trains f32 with a warning.
        self._quant_enabled = False
        self._qmax = 0
        self.quant_report = None
        if bool(getattr(config, "gradient_quantization", False)):
            if type(self) is not GBDT or self.forced_schedule is not None:
                Log.warning(
                    "gradient_quantization supports plain gbdt boosting "
                    "without forced splits; training with f32 gradients")
            else:
                from ..ops.quantize import (F32_GH_BYTES, QUANT_GH_BYTES,
                                            derive_qmax)
                qdtype = str(getattr(config, "gradient_quant_dtype",
                                     "int16") or "int16")
                # trace-time int32 overflow guard: rows-per-leaf x max|q|
                # must stay below 2^31 (raises when it cannot)
                self._qmax = derive_qmax(train_set.num_data_padded, qdtype)
                self._quant_enabled = True
                gh_bytes = QUANT_GH_BYTES[qdtype]
                self.quant_report = {
                    "dtype": qdtype, "qmax": self._qmax,
                    "hist_gh_bytes_per_row": gh_bytes,
                    "hist_bytes_reduction_vs_f32": F32_GH_BYTES / gh_bytes,
                }
                Log.info(
                    "gradient quantization on: %s grid (qmax=%d, %.1fx "
                    "fewer grad/hess bytes per histogram dispatch)",
                    qdtype, self._qmax, F32_GH_BYTES / gh_bytes)

        # EFB bundle decode map (identity when the dataset is unbundled).
        # Bundled + data/voting parallel trains on the MESH FAST PATH
        # (partitioned engine per shard, full-psum of the small bundled
        # histogram, replicated search — grower2 mesh modes); the masked
        # legacy mesh grower cannot decode bundles, so feature-parallel or
        # a fast-ineligible config falls back to the serial learner.
        self._mesh_fast_only = False
        if train_set.bundle_info is not None:
            self.bundle_map = bundle_map_from_info(train_set.bundle_info)
            if self.parallel_mode == "feature":
                Log.warning("EFB-bundled dataset: feature-parallel is not "
                            "supported with bundling; training with the "
                            "serial learner")
                self.parallel_mode = None
                self.mesh = None
            elif self.parallel_mode is not None:
                self._mesh_fast_only = True
        else:
            self.bundle_map = identity_bundle_map(train_set.num_features)

        # -- device state ----------------------------------------------------
        if self.parallel_mode == "feature":
            # uploaded padded + feature-sharded in _setup_parallel_learner;
            # avoid a second full-matrix host->device transfer here
            self.bins_dev = None
        else:
            from ..io.nbits import get_packed, should_pack, \
                unpack_nibbles_device
            if should_pack(train_set):
                # dense_nbits_bin parity at the transfer boundary: ship the
                # nibble-packed matrix (half the H2D bytes), unpack on chip
                self.bins_dev = unpack_nibbles_device(
                    get_packed(train_set), train_set.bins.shape[0])
            else:
                self.bins_dev = jnp.asarray(train_set.bins)
        self.meta_dev = _feature_meta_device(train_set)
        self.valid_mask = jnp.asarray(train_set.valid_row_mask())
        md = train_set.metadata
        self.label_dev = jnp.asarray(train_set.padded(md.label))
        self.weight_dev = jnp.asarray(train_set.padded(
            md.weight if md.weight is not None else np.ones(train_set.num_data, np.float32)))
        n_pad = train_set.num_data_padded

        row_chunk = 16384 if n_pad % 16384 == 0 else n_pad
        has_cat = any(m.bin_type == BIN_TYPE_CATEGORICAL and not m.is_trivial
                      for m in train_set.bin_mappers)
        self.grower_cfg = GrowerConfig(
            num_leaves=int(config.num_leaves),
            max_depth=int(config.max_depth),
            lambda_l1=float(config.lambda_l1),
            lambda_l2=float(config.lambda_l2),
            max_delta_step=float(config.max_delta_step),
            min_data_in_leaf=int(config.min_data_in_leaf),
            min_sum_hessian_in_leaf=float(config.min_sum_hessian_in_leaf),
            min_gain_to_split=float(config.min_gain_to_split),
            row_chunk=row_chunk,
            with_categorical=has_cat,
            max_cat_threshold=int(config.max_cat_threshold),
            cat_l2=float(config.cat_l2),
            cat_smooth=float(config.cat_smooth),
            max_cat_to_onehot=int(config.max_cat_to_onehot),
            min_data_per_group=int(config.min_data_per_group),
            hist_impl=str(getattr(config, "tpu_histogram_impl", "auto")
                          or "auto"),
            hist_pool_slots=self._hist_pool_slots(config, train_set),
            with_monotone=bool(np.any(train_set.monotone_constraints)),
            frontier_batch=max(1, int(getattr(config, "tpu_frontier_batch",
                                              1) or 1)))
        self.grower = _cached_grower(self.meta_dev, self.grower_cfg,
                                     train_set.max_num_bin, train_set,
                                     bundle_map=self.bundle_map
                                     if train_set.bundle_info is not None
                                     else None,
                                     forced=self.forced_schedule
                                     if self.parallel_mode is None else None)
        # partition-ordered fast path (built lazily on first eligible iter;
        # the state object survives sync-backs so re-entry never retraces)
        self._fast: Optional[_FastState] = None
        self._fast_active = False

        # scores: [K, N_pad] on device
        K = self.num_tree_per_iteration
        self.score = jnp.zeros((K, n_pad), jnp.float32)
        self.init_score_value = 0.0
        if md.init_score is not None:
            init = train_set.padded(md.init_score.astype(np.float32))
            self.score = jnp.broadcast_to(init, (K, n_pad)).astype(jnp.float32)
        if objective is not None:
            objective.init(md.label, md.weight, md.query_boundaries)

        # validation sets
        self.valid_sets: List[Tuple[str, BinnedDataset, jax.Array, jax.Array, List]] = []

        # non-finite sentinel (runtime/resilience.py): screen every
        # iteration's fetched tree outputs for NaN/inf under the
        # configurable abort-vs-rollback policy.  'off' costs nothing.
        self._sentinel_policy = str(getattr(config, "sentinel_nonfinite",
                                            "off") or "off").lower()
        if self._sentinel_policy not in ("off", "abort", "rollback"):
            Log.warning("sentinel_nonfinite=%s is not off|abort|rollback; "
                        "using abort", self._sentinel_policy)
            self._sentinel_policy = "abort"

        # async boosting pipeline (pipeline_depth, ISSUE 5): how many trees
        # the device may run ahead of host Tree assembly on the fused fast
        # path.  0 = synchronous classic loop; 1 (default) overlaps tree
        # t's packed D2H fetch + host assembly with tree t+1's device
        # compute; 2 runs two trees ahead.  The legacy/profiled/renew/RF
        # paths always run synchronously (honest fallback), and an armed
        # non-finite sentinel disables the pipeline — its abort/rollback
        # contract screens every iteration's outputs before the next one
        # is dispatched.
        self._pipeline_depth = max(0, min(
            int(getattr(config, "pipeline_depth", 1) or 0), 8))
        if self._sentinel_policy != "off" and self._pipeline_depth > 0:
            Log.info("sentinel_nonfinite=%s: the dispatch pipeline is "
                     "disabled so each iteration's tree outputs are "
                     "screened before the next dispatch",
                     self._sentinel_policy)
        self._assembler: Optional[TreeAssembler] = None
        #: engine-run iteration whose trees ALL failed to split, observed
        #: by the assembler thread after later iterations were already
        #: dispatched; flush() rolls the over-dispatch back
        self._pipe_stop_iter: Optional[int] = None
        self._pipe_k_seen = 0
        self._pipe_any_split = False
        self._in_flush = False

        # fused boosting window (boost_window=J, ISSUE 13): one donated
        # lax.scan program trains J iterations per dispatch; the driver
        # below consumes the window one update() at a time (parked host
        # trees + lazy valid-score replay), truncating to the reported
        # iteration by exact snapshot replay when an observation point
        # (eval, snapshot, rollback, reset_parameter) lands mid-window.
        self._boost_window = max(1, int(getattr(config, "boost_window", 1)
                                        or 1))
        #: the open (still-consuming) window, or None
        self._win: Optional[Dict] = None
        #: fully-consumed windows whose parked trees have not all been
        #: appended yet (drain still in flight) — strictly ordered
        self._win_unappended: List[Dict] = []
        #: adaptive effective window length: shrinks to the observed
        #: truncation point when observations land mid-window, grows back
        #: toward boost_window after consecutive clean windows
        self._win_adapt = self._boost_window
        self._win_clean = 0
        #: engine.train's look-ahead hint: iterations until the next
        #: observation point (None = unknown; adaptive length governs)
        self._win_horizon: Optional[int] = None

        # deterministic per-subsystem RNG (bagging / feature sampling)
        seed = int(getattr(config, "seed", 0) or 0)
        self.bagging_rng = Random(partition_seed(seed + int(config.bagging_seed), 1))
        self.feature_rng = Random(partition_seed(seed + int(config.feature_fraction_seed), 2))
        self.bag_mask_host = np.ones(n_pad, dtype=np.float32)
        self.bag_mask_host[train_set.num_data:] = 0.0

        self._boosted_from_average = False
        self._grad_fn = None
        self._leaf_transform = None
        self._bag_cmask = jnp.asarray(self.bag_mask_host)
        # RF evaluates metrics with objective=None: scores already hold
        # converted outputs (rf.hpp EvalOneMetric)
        self._metric_objective = objective

        if self.parallel_mode is not None:
            self._setup_parallel_learner()

        # continued training (input_model / init_model, gbdt.cpp:64-169 with
        # num_init_iteration_ > 0): map the loaded trees' double thresholds
        # back onto this dataset's bins, then replay them onto the score
        # entirely on device
        if self.num_init_iteration > 0:
            K = self.num_tree_per_iteration
            for idx, tree in enumerate(self.model.trees):
                tree.set_bin_thresholds(train_set.bin_mappers)
                self._add_tree_to_train_score(tree, idx % K, 1.0)

    @staticmethod
    def _hist_pool_slots(config, train_set: BinnedDataset) -> int:
        """histogram_pool_size (MB, reference HistogramPool semantics) ->
        pool slot count for the partitioned grower.  -1 keeps one slot per
        leaf unless that alone would exceed a 4 GB HBM budget, in which
        case the pool auto-caps with a warning."""
        L = int(config.num_leaves)
        slot_bytes = (train_set.bins.shape[0] * train_set.max_num_bin
                      * 3 * 4)
        pool_mb = float(getattr(config, "histogram_pool_size", -1.0) or -1.0)
        if pool_mb > 0:
            return max(2, min(L, int(pool_mb * 1024 * 1024 // max(slot_bytes, 1))))
        budget = 4 << 30
        if L * slot_bytes > budget:
            slots = max(2, int(budget // max(slot_bytes, 1)))
            Log.warning(
                "histogram memory for %d leaves would be %.1f GB; capping "
                "the histogram pool at %d slots (set histogram_pool_size "
                "to control this)", L, L * slot_bytes / 2**30, slots)
            return slots
        return 0

    def _setup_parallel_learner(self) -> None:
        """Build the shard_map'd grower and place training state on the mesh.

        data/voting: rows sharded (bins [F, N] over N, per-row vectors over
        N, scores [K, N] over N); feature: features sharded (bins/fmask over
        F, rows replicated).  The grower output tree is replicated except
        the per-row leaf ids, which follow the row sharding."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..parallel.feature_parallel import pad_features, pad_feature_meta

        mode = self.parallel_mode
        ax = self.mesh_axis
        n = self.mesh.shape[ax]
        meta = self.meta_dev
        if mode == "feature":
            bins_h, _, f_padded = pad_features(
                self.train_set.bins, np.ones(self.train_set.num_features,
                                             bool), n)
            self._fmask_pad = f_padded - self.train_set.num_features
            meta = pad_feature_meta(meta, f_padded)
            self.bins_dev = jax.device_put(
                jnp.asarray(bins_h), NamedSharding(self.mesh, P(ax, None)))
            row_spec, vals_spec, score_spec = P(), P(), P()
            bins_spec, fmask_spec = P(ax, None), P(ax)
            leaf_id_spec = P()
        else:
            self.bins_dev = jax.device_put(
                self.bins_dev, NamedSharding(self.mesh, P(None, ax)))
            row_spec, vals_spec, score_spec = P(ax), P(ax, None), P(None, ax)
            bins_spec, fmask_spec = P(None, ax), P()
            leaf_id_spec = P(ax)
        self._row_sharding = NamedSharding(self.mesh, row_spec)
        self._score_sharding = NamedSharding(self.mesh, score_spec)

        for attr in ("valid_mask", "label_dev", "weight_dev", "_bag_cmask"):
            setattr(self, attr, jax.device_put(
                getattr(self, attr), self._row_sharding))
        self.score = jax.device_put(self.score, self._score_sharding)

        if self._mesh_fast_only:
            # bundled dataset: only the partitioned mesh fast path can
            # decode EFB columns — the masked mesh grower is not built, and
            # a fast-ineligible config falls back to the serial learner
            return

        cfg = self.grower_cfg
        if mode in ("data", "voting"):
            # inside shard_map the histogram kernel sees only the local
            # shard's rows; its chunking invariant must hold for N/n
            local_n = self.train_set.num_data_padded // n
            cfg = cfg._replace(
                row_chunk=16384 if local_n % 16384 == 0 else local_n)
        grow_core = make_tree_grower(
            meta, cfg, self.train_set.max_num_bin,
            axis_name=ax, jit=False, mode=mode, num_machines=n,
            top_k=int(getattr(self.config, "top_k", 20)))
        out_specs = dict.fromkeys((
            "num_leaves", "leaf_value", "leaf_count", "leaf_sum_g",
            "leaf_sum_h", "split_feature", "split_bin", "split_gain",
            "default_left", "split_is_cat", "split_cat_bitset", "left_child",
            "right_child", "internal_value", "internal_count"), P())
        out_specs["leaf_id"] = leaf_id_spec
        # check_vma off: every shard carries the replicated winner through
        # the fori_loop, which the varying-axes tracker cannot prove
        self.grower = xla_obs.jit(compat.shard_map(
            grow_core, mesh=self.mesh,
            in_specs=(bins_spec, vals_spec, fmask_spec),
            out_specs=out_specs, check_vma=False),
            site="gbdt.mesh_grower")

    # -- validation ----------------------------------------------------------
    def add_valid(self, name: str, valid: BinnedDataset, metrics: List) -> None:
        bins_v = jnp.asarray(valid.bins)
        K = self.num_tree_per_iteration
        score_v = jnp.zeros((K, valid.num_data_padded), jnp.float32)
        if valid.metadata.init_score is not None:
            init = valid.padded(valid.metadata.init_score.astype(np.float32))
            score_v = jnp.broadcast_to(init, score_v.shape).astype(jnp.float32)
        # replay every existing tree (loaded model and/or earlier iterations)
        # onto the new validation score
        for idx, tree in enumerate(self.model.trees):
            if tree.num_leaves <= 1:
                score_v = score_v.at[idx % K].add(jnp.float32(tree.leaf_value[0]))
                continue
            tree_dev, leaf_out = self._tree_to_device(tree)
            score_v = _traverse_update(bins_v, score_v, leaf_out, tree_dev,
                                       self.meta_dev, self.bundle_map, self._depth_iters(tree),
                                       idx % K)
        for m in metrics:
            m.init(valid.metadata.label, valid.metadata.weight,
                   valid.metadata.query_boundaries)
        self.valid_sets.append([name, valid, bins_v, score_v, metrics])

    # -- one boosting iteration (gbdt.cpp:387-482) ---------------------------
    def _fast_eligible(self) -> bool:
        """The partition-ordered fast path covers the serial GBDT (with or
        without bagging), ALL THREE mesh learners (tree_learner=
        data|voting run the partitioned engine per row shard with
        collectives at the histogram boundary; tree_learner=feature runs
        it per feature shard over replicated rows with owned-first column
        permutation — except under forced splits or GOSS, which keep the
        legacy masked engine), ranking objectives (original-order gradient
        fill through the index column), leaf-output renewal (except under
        GOSS), and row counts up to 2^31 (radix-split index columns past
        2^24)."""
        cfg = self.config
        return ((type(self) is GBDT
                 or getattr(self, "_fast_sample_hook", None) is not None
                 or getattr(self, "_fast_variant_ok", False))
                and (self.mesh is None
                     or self.parallel_mode in ("data", "voting")
                     or (self.parallel_mode == "feature"
                         and self.forced_schedule is None
                         and getattr(self, "_fast_sample_hook", None)
                         is None))
                and self.objective is not None
                # non-rowwise objectives (ranking) ride the fast path via
                # the original-order gradient fill; GOSS's fused sampling
                # step has no such fill, so rank+GOSS keeps the legacy path
                and (getattr(self.objective, "is_rowwise", True)
                     or getattr(self, "_fast_sample_hook", None) is None)
                # leaf renewal runs on the fast path (per-segment leaf
                # membership + idx-column original-order mapping) except
                # under GOSS, whose fused sampling step is incompatible
                # with the pre-update-score renewal ordering
                and (not self.objective.renew_tree_output_required()
                     or getattr(self, "_fast_sample_hook", None) is None)
                # int32 row positions in the segment engine; past 2^24 the
                # payload's index column switches to the radix-split layout
                and self.train_set.num_data_padded < (1 << 31))

    # -- async pipeline drain ------------------------------------------------
    def flush(self, sync_scores: bool = False) -> None:
        """Drain the dispatch pipeline: after this returns, model.trees
        holds every REPORTED iteration's trees in dispatch order and any
        deferred assembly error has been re-raised.  Every point that
        observes the model calls this — metric eval, early-stop
        callbacks, snapshot writes / PreemptionGuard, rollback_one_iter,
        save_model, _fast_sync_back, and the train() exit path.

        `sync_scores=True` additionally settles the DEVICE training state
        at the reported iteration: an open boosting window (boost_window
        >= 2 ran the device ahead of the reported iteration) is truncated
        by exact snapshot replay.  Score observers (eval rounds,
        raw_train_score, snapshot capture, sync-back) pass True; pure
        model-view reads (current_iteration, save_model of the trees so
        far) keep the cheap default and never pay a truncation.

        If a drained iteration turned out to have no splittable leaves,
        the iterations dispatched past it are rolled back here — the
        synchronous loop would have stopped before training them."""
        if self._assembler is not None:
            self._assembler.flush()
        self._window_append_ready()
        if sync_scores:
            self._window_truncate()
        if self._in_flush:
            return
        stop = self._pipe_stop_iter
        if stop is not None and self.iter > stop + 1:
            # over-reported iterations exist; settle any open window at
            # its consumed boundary first so the payload scores the
            # rollback edits match the reported iteration exactly
            self._window_truncate()
            self._in_flush = True
            try:
                # rollback IN PLACE (payload score replay on the fast
                # path) rather than via rollback_one_iter, which would
                # sync the engine off the fast path — a state change the
                # synchronous loop never makes on a no-split stop
                K = self.num_tree_per_iteration
                for _ in range(self.iter - (stop + 1)):
                    for k in reversed(range(K)):
                        tree = self.model.trees.pop()
                        if tree.num_leaves <= 1:
                            continue
                        self._add_tree_to_train_score(tree, k, -1.0)
                        self._add_tree_to_valid_scores(tree, k, -1.0)
                    self.iter -= 1
            finally:
                self._in_flush = False

    def _note_tree_drained(self, num_leaves: int, it: int) -> None:
        """Assembler-thread bookkeeping, strictly in tree order: when a
        full iteration's trees have drained and none found a split, the
        run should have stopped at that iteration."""
        self._pipe_k_seen += 1
        if num_leaves > 1:
            self._pipe_any_split = True
        if self._pipe_k_seen >= self.num_tree_per_iteration:
            if not self._pipe_any_split and self._pipe_stop_iter is None:
                self._pipe_stop_iter = it
                Log.warning("Stopped training because there are no more "
                            "leaves that meet the split requirements")
            self._pipe_k_seen = 0
            self._pipe_any_split = False

    # -- fused boosting window (boost_window=J, ISSUE 13) --------------------
    def _window_len(self) -> int:
        """Effective boosting-window length for the next dispatch: the
        configured boost_window clamped by the adaptive truncation
        history and engine.train's observation horizon; 1 (the sequential
        per-tree loop) whenever the config sits outside the validated
        window envelope."""
        J = self._boost_window
        if J <= 1 or type(self) is not GBDT or self.mesh is not None:
            return 1
        if (self.objective is None
                or self.objective.renew_tree_output_required()
                or self._quant_enabled
                or self.forced_schedule is not None
                or getattr(self, "_fast_sample_hook", None) is not None
                or self.timer.enabled
                or self._sentinel_policy != "off"):
            return 1
        J = min(J, max(1, self._win_adapt))
        if self._win_horizon is not None:
            J = min(J, max(1, int(self._win_horizon)))
        return J

    def _window_dispatch(self, J: int) -> bool:
        """Train J boosting iterations in ONE device dispatch: pre-draw
        the J per-iteration host RNG decisions (feature masks, bagging
        re-draws — the same stream positions the sequential loop would
        consume), snapshot the window-start device state for exact
        truncation, run the donated scan program, and hand the stacked
        [J*K] split records to the assembler as ONE drain unit.  Only
        iteration 0 is reported to the caller; the rest are consumed by
        the following update() calls with zero device work."""
        init_score = self._boost_from_average()
        fs = self._fast_enter()
        cfg = self.config
        K = self.num_tree_per_iteration
        bag_on = cfg.bagging_freq > 0 and cfg.bagging_fraction < 1.0
        it0 = self.iter
        import copy as _copy
        rng0 = (_copy.deepcopy(self.bagging_rng._rng.bit_generator.state),
                _copy.deepcopy(self.feature_rng._rng.bit_generator.state),
                self.bag_mask_host.copy(), fs._bag_dirty)
        fmasks = np.empty((J, self.train_set.num_features
                           + self._fmask_pad), bool)
        bag_rows = (np.empty((J, self.train_set.num_data_padded),
                             np.float32) if bag_on else None)
        for j in range(J):
            fmasks[j] = self._feature_sample_host()
            if bag_on:
                bag_rows[j] = self._bagging_host(it0 + j)
        lr = self.shrinkage_rate
        # explicit window-start copies: the scan program donates its
        # payload/aux inputs, and truncation needs the exact start bits
        snap = (jnp.copy(fs.payload), jnp.copy(fs.aux))
        prog = fs.window_program(J, bag_on)
        bag_dev = (jnp.asarray(bag_rows) if bag_on
                   else jnp.zeros((J, 1), jnp.float32))
        # the window dispatch as a named span (ISSUE 14): the J stays in
        # the series name (telemetry.SPAN_KEEP_KEYS) — J=2 and J=4
        # windows are different stages, and the trace slice shows which
        # iteration paid this dispatch
        with telemetry.span("window dispatch J=%d" % J), \
                syncs.critical_path():
            recs, fs.payload, fs.aux = prog(fs.payload, fs.aux,
                                            jnp.asarray(fmasks), bag_dev,
                                            jnp.float32(lr))
        if bag_on:
            fs._bag_dirty = False
        w = {"iter0": it0, "total": J, "consumed": 0, "appended": 0,
             "recs": recs, "lr": lr, "snap": snap, "rng0": rng0,
             "trees": [], "drained": threading.Event()}
        self._win = w
        telemetry.counter("lgbm_window_iterations_total").inc(J)
        t_dispatch = time.monotonic()

        def host_half():
            host = _fetch_packed(recs, label="window_drain")
            trees = []
            stop_at = None
            for j in range(J):
                any_split = False
                for k in range(K):
                    one = {key: val[j, k] for key, val in host.items()}
                    tree = self._finish_tree_host(
                        one, init_score if j == 0 else 0.0, lr)
                    trees.append(tree)
                    if tree.num_leaves > 1:
                        any_split = True
                if not any_split and stop_at is None:
                    stop_at = it0 + j
            w["trees"] = trees
            if stop_at is not None and self._pipe_stop_iter is None:
                self._pipe_stop_iter = stop_at
                Log.warning("Stopped training because there are no more "
                            "leaves that meet the split requirements")
            w["drained"].set()
            telemetry.histogram("lgbm_pipeline_drain_seconds").observe(
                time.monotonic() - t_dispatch)

        if self._pipeline_depth > 0:
            if self._assembler is None:
                self._assembler = TreeAssembler(self._pipeline_depth)
            self._assembler.submit(host_half, trees=J * K)
        else:
            host_half()
        return self._window_consume_one()

    #: grower-output fields whose [j, k] slices form the device half a
    #: valid-set replay needs (matches _tree_device_half's tree_dev;
    #: the tuple itself is the gbdt<->grower2 stacked-record contract)
    _WINDOW_TREE_DEV = TREE_DEVICE_FIELDS

    def _window_consume_one(self) -> bool:
        """Report one already-trained window iteration: replay its trees
        onto the valid scores from the stacked device records (lazily, so
        valid state never runs ahead of the reported iteration), append
        its parked host trees when the drain has landed, and surface the
        sequential loop's no-split stop."""
        w = self._win
        j = w["consumed"]
        K = self.num_tree_per_iteration
        recs, lr = w["recs"], w["lr"]
        if self.valid_sets:
            depth_iters = max(self.grower_cfg.num_leaves - 1, 1)
            with syncs.critical_path():
                for k in range(K):
                    tree_dev = {f: recs[f][j, k]
                                for f in self._WINDOW_TREE_DEV}
                    leaf_out = jnp.where(
                        recs["num_leaves"][j, k] > 1,
                        recs["leaf_value"][j, k] * jnp.float32(lr),
                        jnp.float32(0.0))
                    for vs in self.valid_sets:
                        vs[3] = _traverse_update(
                            vs[2], vs[3], leaf_out, tree_dev,
                            self.meta_dev, self.bundle_map, depth_iters, k)
        w["consumed"] = j + 1
        self.iter += 1
        finished = False
        if w["drained"].is_set() and w["trees"]:
            finished = all(t.num_leaves <= 1
                           for t in w["trees"][j * K:(j + 1) * K])
        self._window_append_ready()
        if w["consumed"] >= w["total"]:
            # fully consumed: the window can never truncate again — free
            # the start snapshot now, and keep the parked trees around
            # only until their drain lands
            self._win = None
            w["snap"] = None
            if w["appended"] < w["total"] * K:
                self._win_unappended.append(w)
            self._win_clean += 1
            if self._win_clean >= 2 and self._win_adapt < self._boost_window:
                self._win_clean = 0
                self._win_adapt = min(self._boost_window,
                                      max(2, self._win_adapt * 2))
        if finished and self._pipe_stop_iter is not None \
                and self.iter > self._pipe_stop_iter:
            self._pipe_stop_iter = None
        return finished

    def _window_append_ready(self) -> None:
        """Append parked window trees to the model, strictly in dispatch
        order, up to the reported (consumed) iteration.  Trees whose
        drain has not landed stay parked — flush()'s assembler barrier
        guarantees completeness for every observer."""
        K = self.num_tree_per_iteration
        while self._win_unappended:
            w0 = self._win_unappended[0]
            if not w0["drained"].is_set():
                return    # strict order: later windows must wait too
            while w0["appended"] < w0["total"] * K:
                self.model.trees.append(w0["trees"][w0["appended"]])
                w0["appended"] += 1
            self._win_unappended.pop(0)
        w = self._win
        if w is None or not w["drained"].is_set():
            return
        while w["appended"] < w["consumed"] * K:
            self.model.trees.append(w["trees"][w["appended"]])
            w["appended"] += 1

    def _window_truncate(self) -> None:
        """Settle an open window at its consumed boundary: drop the
        unreported parked trees, restore the window-start device payload
        and host RNG/bag state, and replay the consumed iterations
        through the sequential fused steps — bit-identical to a run that
        never windowed (the scan step and `_step` trace the same graph).
        Costs `consumed` sequential re-dispatches; the adaptive window
        length shrinks to the observed truncation point so repeated
        mid-window observations stop paying it."""
        w = self._win
        if w is None:
            return
        if self._assembler is not None:
            self._assembler.flush()
        self._window_append_ready()
        self._win = None
        c = w["consumed"]
        self._win_adapt = max(1, min(self._win_adapt, c))
        self._win_clean = 0
        telemetry.counter("lgbm_window_truncations_total").inc()
        fs = self._fast
        fs.payload, fs.aux = w["snap"]
        w["snap"] = None
        bag_state, feat_state, bag_mask0, bag_dirty0 = w["rng0"]
        self.bagging_rng._rng.bit_generator.state = bag_state
        self.feature_rng._rng.bit_generator.state = feat_state
        self.bag_mask_host = bag_mask0
        fs._bag_dirty = bag_dirty0
        it_end = self.iter
        self.iter = w["iter0"]
        lr_now = self.shrinkage_rate
        self.shrinkage_rate = w["lr"]
        try:
            for _ in range(c):
                fmask = self._feature_sample()
                self._fast_refresh_bag(fs)
                if fs.K > 1:
                    fs.payload = fs._snap_scores(fs.payload)
                for k in range(fs.K):
                    _, fs.payload, fs.aux = fs._step(
                        fs.payload, fs.aux, fmask, jnp.float32(w["lr"]),
                        jnp.int32(k))
                self.iter += 1
        finally:
            self.shrinkage_rate = lr_now
            self.iter = it_end
        # a stop discovered in the truncated (never-reported) region
        # never happened; the continued run rediscovers it if real
        if self._pipe_stop_iter is not None \
                and self._pipe_stop_iter > self.iter - 1:
            self._pipe_stop_iter = None

    def _tree_device_half(self, out: Dict, lr: float, masked: bool = False):
        """The half of _finish_tree the NEXT device step may depend on,
        derived from the grower output without any host fetch: the
        traversal arrays plus the shrunk leaf outputs.  With masked=True
        a stump's outputs are zeroed so deferred consumers (valid-set
        _traverse_update, DART/RF replay) add +0.0 instead of needing the
        host-side num_leaves gate."""
        tree_dev = {
            "split_feature": out["split_feature"],
            "split_bin": out["split_bin"],
            "default_left": out["default_left"],
            "split_is_cat": out["split_is_cat"],
            "split_cat_bitset": out["split_cat_bitset"],
            "left_child": out["left_child"],
            "right_child": out["right_child"],
        }
        leaf_out = out["leaf_value"] * jnp.float32(lr)
        if masked:
            leaf_out = jnp.where(out["num_leaves"] > 1, leaf_out,
                                 jnp.float32(0.0))
        return tree_dev, leaf_out

    def _defer_finish(self, out: Dict, init_score: float, lr: float,
                      k: int) -> None:
        """Pipeline one tree's host half: the packed fetch + Tree assembly
        + model append run on the assembler thread (bounded at
        pipeline_depth in flight, strict dispatch order), while this
        thread goes on to dispatch the next tree.  The valid-set score
        replay runs NOW from the device half, so it never waits on the
        fetch either."""
        if self.valid_sets:
            tree_dev, leaf_out = self._tree_device_half(out, lr, masked=True)
            depth_iters = max(self.grower_cfg.num_leaves - 1, 1)
            for vs in self.valid_sets:
                vs[3] = _traverse_update(vs[2], vs[3], leaf_out, tree_dev,
                                         self.meta_dev, self.bundle_map,
                                         depth_iters, k)
        if self._assembler is None:
            self._assembler = TreeAssembler(self._pipeline_depth)
        it = self.iter
        t_dispatch = time.monotonic()
        # dispatch mark on the causal timeline: the matching drain span
        # lands on the assembler thread under the same iteration context
        tracing.instant("tree dispatch", it=it, k=k)

        def host_half():
            host = _fetch_packed(out, label="pipeline_drain")
            tree = self._finish_tree_host(host, init_score, lr)
            self.model.trees.append(tree)
            self._note_tree_drained(tree.num_leaves, it)
            # dispatch-to-append latency of this tree's deferred host
            # half: queue wait + packed fetch + Tree assembly (ISSUE 9)
            telemetry.histogram("lgbm_pipeline_drain_seconds").observe(
                time.monotonic() - t_dispatch)

        self._assembler.submit(host_half)

    def _fast_sync_back(self) -> None:
        """Leave the fast path: restore original-order scores into the
        legacy score matrix.  The state object is kept for cheap re-entry."""
        self.flush(sync_scores=True)
        if not self._fast_active:
            return
        self.score = jnp.asarray(self._fast.raw_scores())
        if getattr(self, "_score_sharding", None) is not None:
            self.score = jax.device_put(self.score, self._score_sharding)
        self._fast_active = False

    def _fast_enter(self) -> "_FastState":
        if self._fast is None:
            self._fast = _FastState(self)
            self._fast_active = True
        elif not self._fast_active:
            self._fast.reset(self)
            self._fast_active = True
        return self._fast

    def _fast_refresh_bag(self, fs) -> None:
        cfg = self.config
        if not (cfg.bagging_freq > 0 and cfg.bagging_fraction < 1.0):
            return
        # same RNG stream as the masked path, so both paths draw
        # identical bags (equality-testable).  The cnt column rides
        # the partition, so only an actual resample (or a rebuilt
        # payload) needs the gather+scatter refresh.
        resampled = self.iter % cfg.bagging_freq == 0
        with self.timer.phase("bagging"):
            bag = self._bagging()    # advances the RNG on resample
            if resampled or fs._bag_dirty:
                # bag_mask_host is already zero on padded rows
                fs.payload = fs._set_bag(fs.payload,
                                         bag.astype(jnp.float32))
                fs._bag_dirty = False
            self.timer.sync(fs.payload)

    def _train_one_iter_fast(self) -> bool:
        if self._pipe_stop_iter is not None and \
                self.iter > self._pipe_stop_iter:
            # a drained host half found an iteration with no splittable
            # leaves; flush() rolls back anything dispatched past it and
            # this update reports finished (one-to-two updates later than
            # the synchronous loop, with an identical final model).  The
            # flag clears once reported so a caller that keeps driving
            # update() manually trains again, like the synchronous loop.
            # (A boosting window can discover the stop AHEAD of the
            # reported iteration — the guard keeps consuming up to it.)
            self.flush()
            self._pipe_stop_iter = None
            return True
        if self._win is not None:
            # an open boosting window already trained this iteration on
            # device; reporting it is pure host bookkeeping
            return self._window_consume_one()
        J = self._window_len()
        if J >= 2:
            return self._window_dispatch(J)
        init_score = self._boost_from_average()
        fs = self._fast_enter()
        fmask = self._feature_sample()
        if fs.feature_par and self._fmask_pad:
            # the partitioned grower pads the mask to the shard multiple
            # itself; _feature_sample's padding serves the legacy masked
            # engine only
            fmask = fmask[:self.train_set.num_features]
        self._fast_refresh_bag(fs)
        if fs.K > 1:
            fs.payload = fs._snap_scores(fs.payload)

        lr = self.shrinkage_rate
        should_continue = False
        renew = (self.objective is not None
                 and self.objective.renew_tree_output_required())
        # pipelined iterations cover exactly the fused steps (_step /
        # _step_quant / _step_sampled / _step_masked); the piecewise
        # profiled path and leaf renewal observe per-tree host state by
        # construction and stay synchronous
        use_pipe = (self._pipeline_depth > 0 and not renew
                    and not self.timer.enabled
                    and self._sentinel_policy == "off")
        if not use_pipe:
            # deferred appends from earlier pipelined iterations must land
            # before this iteration's inline appends
            self.flush()
        return self._run_iter_trees(fs, fmask, init_score, lr, renew,
                                    use_pipe, should_continue)

    @_mark_critical_path
    def _run_iter_trees(self, fs, fmask, init_score, lr, renew, use_pipe,
                        should_continue) -> bool:
        for k in range(self.num_tree_per_iteration):
            if renew:
                # leaf-output renewal (RenewTreeOutput, serial_tree_learner
                # .cpp:780-818): grow WITHOUT the fused score add — the
                # robust per-leaf statistic needs the pre-update scores —
                # then renew on host and replay the renewed outputs through
                # the payload's bin-traversal score add.
                with self.timer.phase("boosting (gradients)"):
                    if fs.quant_on:
                        fs.payload, qsc = fs._fill_class_quant(
                            fs.payload, k=k, qseed=self._quant_seed(k))
                    else:
                        fs.payload = fs._fill_class(fs.payload, k=k)
                with self.timer.phase("tree (hist+split+partition)"):
                    gargs = (fs.payload, fs.aux, fmask) if not fs.quant_on \
                        else (fs.payload, fs.aux, fmask, qsc)
                    out, fs.payload, fs.aux = fs.grower(*gargs)
                    self.timer.sync(fs.payload)
                with self.timer.phase("leaf renewal (host)"):
                    renewed = self._renew_leaf_values_fast(fs, out, k)
                with self.timer.phase("tree assemble (host)"):
                    tree, tree_dev, leaf_out = self._finish_tree(
                        out, init_score, renewed)
                if tree.num_leaves > 1:
                    should_continue = True
                    with self.timer.phase("train score update"):
                        fs.payload = fs._payload_tree_add(
                            fs.payload, tree_dev, leaf_out, jnp.int32(k))
                        self.timer.sync(fs.payload)
                    depth_iters = max(self.grower_cfg.num_leaves - 1, 1)
                    with self.timer.phase("valid score update"):
                        for vs in self.valid_sets:
                            vs[3] = _traverse_update(
                                vs[2], vs[3], leaf_out, tree_dev,
                                self.meta_dev, self.bundle_map, depth_iters,
                                k)
                self.model.trees.append(tree)
                continue
            if fs._step_sampled is not None:
                # row-sampling boosting (GOSS): always the fused path —
                # the hook needs all-class gradients in one program.
                # Multiclass draws the (identical) selection once per
                # iteration and reuses it for every class tree.
                key, enabled = self._fast_sample_args()
                with self.timer.phase("tree (hist+split+partition)"):
                    if fs.K == 1:
                        out, fs.payload, fs.aux = fs._step_sampled(
                            fs.payload, fs.aux, fmask, jnp.float32(lr),
                            jnp.int32(k), key, enabled)
                    else:
                        if k == 0:
                            fs.payload = fs._apply_sample_masks(
                                fs.payload, key, enabled)
                        out, fs.payload, fs.aux = fs._step_masked(
                            fs.payload, fs.aux, fmask, jnp.float32(lr),
                            jnp.int32(k))
                    self.timer.sync(fs.payload)
            elif not self.timer.enabled:
                # one dispatch for the whole tree (gradients + growth +
                # score add); profiling uses the piecewise path below
                if fs.quant_on:
                    out, fs.payload, fs.aux = fs._step_quant(
                        fs.payload, fs.aux, fmask, jnp.float32(lr),
                        jnp.int32(k), self._quant_seed(k))
                else:
                    out, fs.payload, fs.aux = fs._step(
                        fs.payload, fs.aux, fmask, jnp.float32(lr),
                        jnp.int32(k))
            else:
                with self.timer.phase("boosting (gradients)"):
                    if fs.quant_on:
                        fs.payload, qsc = fs._fill_class_quant(
                            fs.payload, k=k, qseed=self._quant_seed(k))
                    else:
                        fs.payload = fs._fill_class(fs.payload, k=k)
                    self.timer.sync(fs.payload)
                with self.timer.phase("tree (hist+split+partition)"):
                    gargs = (fs.payload, fs.aux, fmask) if not fs.quant_on \
                        else (fs.payload, fs.aux, fmask, qsc)
                    out, fs.payload, fs.aux = fs.grower(*gargs)
                    self.timer.sync(fs.payload)
            if use_pipe:
                # the host half (packed fetch -> Tree assembly -> append)
                # drains off-path; the device already applied the masked
                # score add inside the fused step, and _defer_finish
                # replays the valid sets from the device half.  The
                # no-split stop is signaled by the drain (see
                # _note_tree_drained) — report continue optimistically.
                self._defer_finish(out, init_score, lr, k)
                should_continue = True
                continue
            with self.timer.phase("tree assemble (host)"):
                tree, tree_dev, leaf_out = self._finish_tree(out, init_score)
            if tree.num_leaves > 1:
                should_continue = True
                # the fused steps already applied the score add on device
                if self.timer.enabled and fs._step_sampled is None:
                    with self.timer.phase("train score update"):
                        fs.payload = fs._apply_score(fs.payload,
                                                     jnp.float32(lr), k=k)
                        self.timer.sync(fs.payload)
                depth_iters = max(self.grower_cfg.num_leaves - 1, 1)
                with self.timer.phase("valid score update"):
                    for vs in self.valid_sets:
                        vs[3] = _traverse_update(vs[2], vs[3], leaf_out,
                                                 tree_dev, self.meta_dev,
                                                 self.bundle_map,
                                                 depth_iters, k)
                    if self.valid_sets:
                        self.timer.sync(self.valid_sets[-1][3])
            self.model.trees.append(tree)
        self.iter += 1
        if not should_continue:
            Log.warning("Stopped training because there are no more leaves that meet the split requirements")
        return not should_continue

    def _quant_seed(self, k: int) -> jax.Array:
        """Deterministic stochastic-rounding seed per (iteration, class):
        reruns of the same config quantize identically, and no two trees
        share a rounding draw."""
        base = int(getattr(self.config, "seed", 0) or 0)
        return jnp.int32((base + self.iter * self.num_tree_per_iteration
                          + k) & 0x7FFFFFFF)

    def train_one_iter(self, grad: Optional[np.ndarray] = None,
                       hess: Optional[np.ndarray] = None) -> bool:
        if grad is None and hess is None and self._fast_eligible():
            return self._train_one_iter_fast()
        self._fast_sync_back()
        if self._quant_enabled and not getattr(self, "_warned_quant_legacy",
                                               False):
            Log.warning("gradient_quantization rides the fast path only; "
                        "this iteration trains with f32 gradients")
            self._warned_quant_legacy = True
        if self.forced_schedule is not None and self.parallel_mode is not None \
                and not getattr(self, "_warned_forced_legacy", False):
            Log.warning("forcedsplits_filename is honored by the serial "
                        "learners only; the parallel tree learners train "
                        "WITHOUT forced splits")
            self._warned_forced_legacy = True
        if self._mesh_fast_only and not getattr(self, "_warned_mesh_fast",
                                                False):
            Log.warning("EFB-bundled parallel training rides the fast path "
                        "only; this configuration trains with the serial "
                        "learner")
            self._warned_mesh_fast = True
        init_score = 0.0
        with self.timer.phase("boosting (gradients)"):
            if grad is None or hess is None:
                init_score = self._boost_from_average()
                grads, hesss = self._gradients()
            else:
                grads, hesss = self._pad_custom_gradients(grad, hess)
            self.timer.sync(grads)

        with self.timer.phase("bagging"):
            gmask, cmask = self._bagging_masks(grads, hesss)
            self.timer.sync(gmask)
        self._bag_cmask = cmask
        fmask = self._feature_sample()

        renew = self.objective is not None and self.objective.renew_tree_output_required()
        should_continue = False
        for k in range(self.num_tree_per_iteration):
            vals = _make_vals(grads, hesss, gmask, cmask, k)
            with self.timer.phase("tree (hist+split+partition)"):
                out = self.grower(self.bins_dev, vals, fmask)
                self.timer.sync(out)
            renewed = None
            if renew:
                renewed = self._renew_leaf_values(out, k)
            with self.timer.phase("tree assemble (host)"):
                tree, tree_dev, leaf_out = self._finish_tree(out, init_score,
                                                             renewed)
            if tree.num_leaves > 1:
                should_continue = True
                with self.timer.phase("train score update"):
                    self.score = _update_score_k(self.score, out["leaf_id"],
                                                 leaf_out, k)
                    self.timer.sync(self.score)
                # fixed trip count (num_leaves-1 covers any depth) so the
                # traversal compiles exactly once per config
                depth_iters = max(self.grower_cfg.num_leaves - 1, 1)
                with self.timer.phase("valid score update"):
                    for vs in self.valid_sets:
                        vs[3] = _traverse_update(vs[2], vs[3], leaf_out,
                                                 tree_dev, self.meta_dev,
                                                 self.bundle_map,
                                                 depth_iters, k)
                    if self.valid_sets:
                        self.timer.sync(self.valid_sets[-1][3])
            self.model.trees.append(tree)
        self.iter += 1
        if not should_continue:
            Log.warning("Stopped training because there are no more leaves that meet the split requirements")
        return not should_continue

    def reset_config(self, new_params: Dict) -> None:
        """Booster::ResetConfig: live-apply parameter changes into the
        engine config so they take effect on the next iteration (shared by
        Booster.reset_parameter and the reset_parameter callback)."""
        from ..config import Config
        if self._win is not None:
            # parameter changes are observation points: iterations the
            # open window trained past the reported one used the OLD
            # parameters — settle at the boundary before applying
            self.flush(sync_scores=True)
        self.config.set(new_params)
        if any(Config.resolve_alias(k) == "learning_rate"
               for k in new_params):
            self.shrinkage_rate = float(self.config.learning_rate)

    def rollback_one_iter(self) -> None:
        """RollbackOneIter (gbdt.cpp:484-500): drop the last iteration's trees
        and subtract their contribution from every score vector by re-running
        the bin-level traversal with negated leaf outputs."""
        if self.iter <= 0:
            return
        self._fast_sync_back()
        K = self.num_tree_per_iteration
        for k in reversed(range(K)):
            tree = self.model.trees.pop()
            if tree.num_leaves <= 1:
                continue
            tree_dev, neg_out = self._tree_to_device(tree, negate=True)
            depth_iters = max(self.grower_cfg.num_leaves - 1, 1)
            self.score = _traverse_update(self.bins_dev, self.score, neg_out,
                                          tree_dev, self.meta_dev, self.bundle_map, depth_iters, k)
            for vs in self.valid_sets:
                vs[3] = _traverse_update(vs[2], vs[3], neg_out, tree_dev,
                                         self.meta_dev, self.bundle_map, depth_iters, k)
        self.iter -= 1

    def _depth_iters(self, tree: Tree) -> int:
        """Traversal trip count covering this run's grower and any loaded
        tree (which may be larger than the current num_leaves)."""
        return max(self.grower_cfg.num_leaves - 1, tree.num_leaves - 1, 1)

    def _add_tree_to_train_score(self, tree: Tree, k: int, scale: float) -> None:
        """score[k] += scale * tree(x) over the training bins (DART drop /
        normalize, RF running average, continued-training replay).  On the
        fast path the edit lands in the partition-ordered payload score
        column, routed by the payload's own bin columns."""
        if self._fast_active and tree.num_leaves > self.grower_cfg.num_leaves:
            # the payload traversal's trip count covers only trees this
            # run's grower can produce; replay oversized loaded trees
            # through the legacy path (it sizes the traversal per tree)
            self._fast_sync_back()
        if self._fast_active:
            fs = self._fast
            if tree.num_leaves <= 1:
                fs.payload = fs._apply_const_score(
                    fs.payload, jnp.float32(scale * tree.leaf_value[0]),
                    jnp.int32(k))
                return
            tree_dev, leaf_out = self._tree_to_device(tree)
            fs.payload = fs._payload_tree_add(
                fs.payload, tree_dev, leaf_out * jnp.float32(scale),
                jnp.int32(k))
            return
        if tree.num_leaves <= 1:
            self.score = self.score.at[k].add(jnp.float32(scale * tree.leaf_value[0]))
            return
        tree_dev, leaf_out = self._tree_to_device(tree)
        self.score = _traverse_update(self.bins_dev, self.score,
                                      leaf_out * jnp.float32(scale), tree_dev,
                                      self.meta_dev, self.bundle_map, self._depth_iters(tree), k)

    def _add_tree_to_valid_scores(self, tree: Tree, k: int, scale: float) -> None:
        if tree.num_leaves <= 1:
            for vs in self.valid_sets:
                vs[3] = vs[3].at[k].add(jnp.float32(scale * tree.leaf_value[0]))
            return
        depth_iters = self._depth_iters(tree)
        tree_dev, leaf_out = self._tree_to_device(tree)
        leaf_out = leaf_out * jnp.float32(scale)
        for vs in self.valid_sets:
            vs[3] = _traverse_update(vs[2], vs[3], leaf_out, tree_dev,
                                     self.meta_dev, self.bundle_map, depth_iters, k)

    def _multiply_scores(self, k: int, factor: float) -> None:
        """ScoreUpdater::MultiplyScore on plane k, train + valid (rf.hpp)."""
        self.score = self.score.at[k].multiply(jnp.float32(factor))
        for vs in self.valid_sets:
            vs[3] = vs[3].at[k].multiply(jnp.float32(factor))

    def _tree_to_device(self, tree: Tree, negate: bool = False):
        """Device arrays for bin-level traversal of a host tree (trees built
        this run carry bin thresholds + inner categorical bitsets)."""
        ni = max(tree.num_leaves - 1, 1)
        B = self.train_set.max_num_bin
        is_cat = (tree.decision_type[:ni] & 1) != 0
        bitset = np.zeros((ni, B), dtype=bool)
        for node in np.nonzero(is_cat)[0]:
            ci = int(tree.threshold_in_bin[node])
            lo, hi = tree.cat_boundaries_inner[ci], tree.cat_boundaries_inner[ci + 1]
            for wi in range(lo, hi):
                word = tree.cat_threshold_inner[wi]
                for bit in range(32):
                    b = (wi - lo) * 32 + bit
                    if b < B and (word >> bit) & 1:
                        bitset[node, b] = True
        tree_dev = {
            "split_feature": jnp.asarray(tree.split_feature[:ni], jnp.int32),
            "split_bin": jnp.asarray(np.where(is_cat, 0, tree.threshold_in_bin[:ni]),
                                     jnp.int32),
            "default_left": jnp.asarray((tree.decision_type[:ni] & 2) != 0),
            "split_is_cat": jnp.asarray(is_cat),
            "split_cat_bitset": jnp.asarray(bitset),
            "left_child": jnp.asarray(tree.left_child[:ni], jnp.int32),
            "right_child": jnp.asarray(tree.right_child[:ni], jnp.int32),
        }
        lv = tree.leaf_value[: max(tree.num_leaves, 1)].astype(np.float32)
        leaf_out = jnp.asarray(-lv if negate else lv)
        return tree_dev, leaf_out

    # -- internals -----------------------------------------------------------
    def _pad_custom_gradients(self, grad, hess):
        """Reshape caller-supplied fobj gradients to the padded [K, N] layout."""
        K, n = self.num_tree_per_iteration, self.train_set.num_data
        grads = jnp.asarray(np.asarray(grad, np.float32).reshape(K, n))
        hesss = jnp.asarray(np.asarray(hess, np.float32).reshape(K, n))
        pad = self.train_set.num_data_padded - n
        if pad:
            grads = jnp.pad(grads, ((0, 0), (0, pad)))
            hesss = jnp.pad(hesss, ((0, 0), (0, pad)))
        return grads, hesss

    def _gradients(self):
        if self._grad_fn is None:
            obj = self.objective

            def gradfn(score, label, weight):
                return obj.get_gradients_multi(score, label, weight)

            self._grad_fn = xla_obs.jit(gradfn, site="gbdt.gradients")
        return self._grad_fn(self.score, self.label_dev, self.weight_dev)

    def _boost_from_average(self) -> float:
        if self._boosted_from_average or self.model.current_iteration > 0 \
                or self.train_set.metadata.init_score is not None \
                or self.num_class > 1 or self.objective is None:
            return 0.0
        self._boosted_from_average = True
        if not bool(self.config.boost_from_average):
            return 0.0
        init = self.objective.boost_from_score()
        if abs(init) > K_EPSILON:
            self.score = self.score + jnp.float32(init)
            for vs in self.valid_sets:
                vs[3] = vs[3] + jnp.float32(init)
            Log.info("Start training from score %f", init)
            self.init_score_value = init
            return init
        return 0.0

    def _bagging_host(self, it: int) -> np.ndarray:
        """Host half of _bagging: advance the bagging stream to iteration
        `it` (resample when it lands on the bagging_freq grid) and return
        the current host mask.  The window dispatcher pre-draws J steps
        through this, so the stream position stays identical to the
        sequential loop's."""
        cfg = self.config
        n = self.train_set.num_data
        if cfg.bagging_freq > 0 and cfg.bagging_fraction < 1.0:
            if it % cfg.bagging_freq == 0:
                bag_cnt = int(n * cfg.bagging_fraction)
                idx = self.bagging_rng.sample(n, bag_cnt)
                mask = np.zeros(self.train_set.num_data_padded, dtype=np.float32)
                mask[idx] = 1.0
                self.bag_mask_host = mask
        return self.bag_mask_host

    def _bagging(self) -> jax.Array:
        mask = self._bagging_host(self.iter)
        if self.mesh is not None:
            return jax.device_put(mask, self._row_sharding)
        return jnp.asarray(mask)

    def _bagging_masks(self, grads, hesss):
        """(gradient-scale mask, count mask) per row.  Plain bagging uses the
        same 0/1 mask for both; GOSS overrides with an amplified gradient mask
        (goss.hpp BaggingHelper)."""
        m = self._bagging()
        return m, m

    def _feature_sample_host(self) -> np.ndarray:
        """Host half of _feature_sample (one per-iteration draw); the
        window dispatcher stacks J of these into one device upload."""
        cfg = self.config
        f = self.train_set.num_features
        mask = np.zeros(f, dtype=bool)
        if cfg.feature_fraction < 1.0:
            used = max(1, int(f * cfg.feature_fraction))
            mask[self.feature_rng.sample(f, used)] = True
        else:
            mask[:] = True
        if self._fmask_pad:
            # feature-parallel pads the feature axis to a shard multiple;
            # padded columns never enter split search
            mask = np.concatenate([mask, np.zeros(self._fmask_pad, bool)])
        return mask

    def _feature_sample(self) -> jax.Array:
        return jnp.asarray(self._feature_sample_host())

    def _renew_leaf_values_fast(self, fs: "_FastState", out: Dict,
                                k: int) -> Optional[np.ndarray]:
        """RenewTreeOutput on the partitioned fast path: leaf membership
        falls out of the row segments (every leaf's rows are contiguous per
        device block), and the payload's index column maps the
        partition-ordered scores/bag back to original row order so the
        objective's renewal code runs UNCHANGED — bit-identical to the
        legacy path."""
        nl = int(syncs.device_get(out["num_leaves"], label="renew_fetch"))
        if nl <= 1:
            return None
        # one round of transfers: the contiguous column block (cnt/bag,
        # idx, per-class scores) plus the segment tables and leaf values
        h, ss, sc, lv = syncs.device_get(
            (fs.payload[:, fs.cnt_col:fs.score0 + fs.K],
             out["seg_start"], out["seg_cnt"], out["leaf_value"]),
            label="renew_fetch")
        h = np.asarray(h)
        cnt = h[:, 0]
        idx = fs.host_idx() if fs.wide_idx else h[:, 1].astype(np.int64)
        score_k = h[:, 2 + k].astype(np.float64)
        ss = np.asarray(ss).astype(np.int64)
        sc = np.asarray(sc).astype(np.int64)
        L = ss.size // fs.ndev
        R = fs.n_rows // fs.ndev
        lid_part = np.full(fs.n_rows, nl, np.int64)
        for d in range(fs.ndev):
            off = d * R
            for leaf in range(nl):
                s = off + ss[d * L + leaf]
                lid_part[s:s + sc[d * L + leaf]] = leaf
        keep = idx < fs.n_pad
        lid = np.full(fs.n_pad, nl, np.int64)
        lid[idx[keep]] = lid_part[keep]
        pred = np.zeros(fs.n_pad, np.float64)
        pred[idx[keep]] = score_k[keep]
        in_bag = np.zeros(fs.n_pad, bool)
        in_bag[idx[keep]] = cnt[keep] > 0
        lv = np.asarray(lv, dtype=np.float64)
        return self.objective.renew_leaf_values(lv[:nl], lid, pred, in_bag)

    def _renew_leaf_values(self, out: Dict, k: int) -> Optional[np.ndarray]:
        """RenewTreeOutput wiring (gbdt.cpp:441-448 →
        serial_tree_learner.cpp:780-818): replace leaf outputs with the
        objective's robust statistic (e.g. L1 median of residuals) computed
        over the bagged rows of each leaf, before shrinkage."""
        nl = int(syncs.device_get(out["num_leaves"], label="renew_fetch"))
        if nl <= 1:
            return None
        leaf_id, pred_k, lv, in_bag = syncs.device_get(
            (out["leaf_id"], self.score[k], out["leaf_value"],
             self._bag_cmask), label="renew_fetch")
        leaf_id = np.asarray(leaf_id)
        pred_k = np.asarray(pred_k, dtype=np.float64)
        lv = np.asarray(lv, dtype=np.float64)
        in_bag = np.asarray(in_bag) > 0
        return self.objective.renew_leaf_values(lv[:nl], leaf_id, pred_k, in_bag)

    def _finish_tree(self, out: Dict, init_score: float,
                     renewed: Optional[np.ndarray] = None):
        """Fetch grower output, assemble the host Tree (reference numbering),
        apply shrinkage and first-tree bias (gbdt.cpp:450-456) — the
        synchronous form; the pipelined fast path defers the host half
        through _defer_finish instead."""
        host = _fetch_packed(out)
        # the outputs are on host anyway — the non-finite sentinel rides
        # this fetch for free (raises NonFiniteDetected under
        # sentinel_nonfinite=abort|rollback; Booster.update arbitrates)
        resilience.sentinel_check(self, host)
        lr = self.shrinkage_rate
        tree = self._finish_tree_host(host, init_score, lr, renewed)
        if renewed is not None or self._leaf_transform is not None:
            leaf_value_dev_f = jnp.asarray(
                (host["leaf_value"] * lr).astype(np.float32))
            tree_dev, _ = self._tree_device_half(out, lr)
        else:
            tree_dev, leaf_value_dev_f = self._tree_device_half(out, lr)
        return tree, tree_dev, leaf_value_dev_f

    def _finish_tree_host(self, host: Dict[str, np.ndarray],
                          init_score: float, lr: float,
                          renewed: Optional[np.ndarray] = None) -> Tree:
        """The pure-host half of _finish_tree: fetched outputs -> reference
        Tree.  Runs inline (classic loop) or on the assembler thread
        (pipelined loop); `lr` is the shrinkage captured AT DISPATCH —
        DART and reset_parameter may have moved self.shrinkage_rate by
        drain time."""
        nl = int(host["num_leaves"])
        # legacy masked grower reports no round counter: its loop is one
        # round per split by construction
        self.split_rounds_total += int(host.get("split_rounds",
                                                max(nl - 1, 0)))
        self.trees_finished += 1
        L = self.grower_cfg.num_leaves
        tree = Tree(max(L, 2))
        tree.num_leaves = nl
        host_lv = host["leaf_value"]
        if renewed is not None:
            host_lv = host_lv.copy()
            host_lv[: len(renewed)] = renewed
        if self._leaf_transform is not None:
            # RF converts leaf outputs through the objective before scoring
            # (rf.hpp ConvertTreeOutput)
            host_lv = self._leaf_transform(np.asarray(host_lv, np.float64))
        if renewed is not None or self._leaf_transform is not None:
            host["leaf_value"] = host_lv

        if nl > 1:
            ni = nl - 1
            ds = self.train_set
            tree.split_feature[:ni] = host["split_feature"][:ni]
            is_cat_nodes = host["split_is_cat"][:ni].astype(bool)
            tree.split_gain[:ni] = host["split_gain"][:ni]
            dt = np.zeros(ni, dtype=np.int8)
            dt |= np.where(is_cat_nodes, 0,
                           host["default_left"][:ni].astype(np.int8) << 1)
            dt |= np.where(is_cat_nodes, 1, 0).astype(np.int8)
            miss = np.asarray([ds.bin_mappers[int(f)].missing_type
                               for f in host["split_feature"][:ni]], dtype=np.int8)
            dt |= (miss << 2)
            tree.decision_type[:ni] = dt
            for node in range(ni):
                f = int(host["split_feature"][node])
                if is_cat_nodes[node]:
                    # categorical: threshold slots hold the cat index; bitsets
                    # over bins (training traversal) and over category values
                    # (raw prediction + model file), tree.cpp SplitCategorical
                    chosen = np.nonzero(host["split_cat_bitset"][node])[0]
                    cat_idx = tree.num_cat
                    tree.threshold_in_bin[node] = cat_idx
                    tree.threshold[node] = float(cat_idx)
                    tree.num_cat += 1
                    mapper = ds.bin_mappers[f]
                    vals = [int(mapper.bin_2_categorical[int(b)]) for b in chosen
                            if int(b) < len(mapper.bin_2_categorical)]
                    tree.cat_threshold.extend(_construct_bitset(vals))
                    tree.cat_boundaries.append(len(tree.cat_threshold))
                    tree.cat_threshold_inner.extend(
                        _construct_bitset([int(b) for b in chosen]))
                    tree.cat_boundaries_inner.append(len(tree.cat_threshold_inner))
                else:
                    b = int(host["split_bin"][node])
                    tree.threshold_in_bin[node] = b
                    tree.threshold[node] = ds.real_threshold(f, b)
            tree.left_child[:ni] = host["left_child"][:ni]
            tree.right_child[:ni] = host["right_child"][:ni]
            tree.internal_value[:ni] = host["internal_value"][:ni] * lr
            tree.internal_count[:ni] = host["internal_count"][:ni].astype(np.int64)
            tree.leaf_value[:nl] = host["leaf_value"][:nl].astype(np.float64) * lr
            tree.leaf_count[:nl] = host["leaf_count"][:nl].astype(np.int64)
            tree.leaf_parent[:] = -1
            for node in range(ni):
                for child in (tree.left_child[node], tree.right_child[node]):
                    if child < 0:
                        tree.leaf_parent[~child] = node
            tree.shrinkage = lr
            if abs(init_score) > K_EPSILON:
                tree.leaf_value[:nl] += init_score
                tree.shrinkage = 1.0
        else:
            tree.leaf_value[0] = float(host["leaf_value"][0]) * lr + init_score
            tree.shrinkage = 1.0
        return tree

    def split_rounds_per_tree(self) -> Optional[float]:
        """Mean sequential grower rounds per finished tree (telemetry for
        the frontier-batch fixed-cost claim: < num_leaves - 1 means the
        batched grower committed more than one split per round)."""
        if self.trees_finished == 0:
            return None
        return self.split_rounds_total / self.trees_finished

    # -- evaluation ----------------------------------------------------------
    def raw_train_score(self) -> np.ndarray:
        self.flush(sync_scores=True)
        if self._fast_active:
            return self._fast.raw_scores()[:, : self.train_set.num_data]
        return syncs.device_get(
            self.score, label="score_fetch")[:, : self.train_set.num_data]

    def raw_valid_score(self, i: int) -> np.ndarray:
        name, valid, _, score_v, _ = self.valid_sets[i]
        return syncs.device_get(score_v,
                                label="score_fetch")[:, : valid.num_data]

    def _packed_eval_fetch(self, arrays: List[jax.Array]) -> List[np.ndarray]:
        """ONE blocking D2H for a whole eval round (the _fetch_packed
        pattern on the f32 score arrays): flatten+concat on device, fetch
        once, split on host — metric_freq=1 must not serialize one
        round trip per dataset.  Mesh runs fetch the list as one pytree
        device_get instead (a cross-sharding concat would insert
        collectives); jax still overlaps every leaf's transfer."""
        if not arrays:
            return []
        if self.mesh is not None or len(arrays) == 1:
            return [np.asarray(a) for a in
                    syncs.device_get(arrays, label="eval_fetch")]
        spec = tuple(tuple(a.shape) for a in arrays)
        entry = _EVAL_PACK_CACHE.get(spec)
        if entry is None:
            xla_obs.cache_event("gbdt.eval_pack_cache", "miss")
            sizes = [int(np.prod(s, dtype=np.int64)) for s in spec]
            offs = np.concatenate([[0], np.cumsum(sizes)]).astype(np.int64)

            @functools.partial(xla_obs.jit, site="gbdt.eval_pack")
            def pack(xs):
                return jnp.concatenate([x.reshape(-1) for x in xs])

            entry = (offs, pack)
            _pack_cache_put(_EVAL_PACK_CACHE, spec, entry,
                            site="gbdt.eval_pack_cache")
        else:
            xla_obs.cache_event("gbdt.eval_pack_cache", "hit")
            _EVAL_PACK_CACHE.move_to_end(spec)
        offs, pack = entry
        flat = np.asarray(syncs.device_get(pack(arrays), label="eval_fetch"))
        return [flat[offs[i]:offs[i + 1]].reshape(s)
                for i, s in enumerate(spec)]

    def _eval_raws(self, want_train: bool, want_valid: bool):
        """(train raw, [valid raws]) for an eval round, off one packed
        transfer.  Flushing here makes every eval a pipeline barrier —
        callbacks that observe the model (early stopping bookkeeping,
        snapshot schedules) run against a fully-assembled tree list —
        and settles any open boosting window at the reported iteration
        (score observation)."""
        self.flush(sync_scores=True)
        fs = self._fast if self._fast_active else None
        arrays: List[jax.Array] = []
        if want_train:
            arrays.extend(fs.score_cols_device() if fs is not None
                          else [self.score])
        if want_valid:
            arrays.extend(vs[3] for vs in self.valid_sets)
        host = self._packed_eval_fetch(arrays)
        i = 0
        train_raw = None
        if want_train:
            if fs is not None:
                cols = host[i]
                i += 1
                hi = None
                if fs.wide_idx:
                    hi = host[i]
                    i += 1
                train_raw = fs.scores_from_host(cols, hi)
            else:
                train_raw = host[i]
                i += 1
            train_raw = train_raw[:, : self.train_set.num_data]
        valid_raws = []
        if want_valid:
            for (_name, valid, _b, _s, _m) in self.valid_sets:
                valid_raws.append(host[i][:, : valid.num_data])
                i += 1
        return train_raw, valid_raws

    @staticmethod
    def _metric_input(raw: np.ndarray, m) -> np.ndarray:
        """Metrics see the 1D score plane, except multiclass metrics which
        consume the full [K, N] matrix (multiclass_metric.hpp Eval)."""
        return raw if getattr(m, "multiclass", False) else raw[0]

    def _eval_train_results(self, raw) -> List[Tuple[str, str, float, bool]]:
        return [("training", m.name,
                 m.eval(self._metric_input(raw, m), self._metric_objective),
                 m.is_higher_better)
                for m in self.train_metrics]

    def _eval_valid_results(self, raws) -> List[Tuple[str, str, float, bool]]:
        out = []
        for (name, _valid, _b, _s, metrics), raw in zip(self.valid_sets,
                                                        raws):
            for m in metrics:
                out.append((name, m.name,
                            m.eval(self._metric_input(raw, m),
                                   self._metric_objective),
                            m.is_higher_better))
        return out

    def eval_train(self) -> List[Tuple[str, str, float, bool]]:
        raw, _ = self._eval_raws(True, False)
        return self._eval_train_results(raw)

    def eval_valid(self) -> List[Tuple[str, str, float, bool]]:
        _, raws = self._eval_raws(False, True)
        return self._eval_valid_results(raws)

    def eval_all(self, include_train: bool):
        """One eval round — train metrics (optional) plus every valid set
        — off a single packed device_get (see _packed_eval_fetch)."""
        raw, raws = self._eval_raws(include_train, True)
        train_res = self._eval_train_results(raw) if include_train else []
        return train_res, self._eval_valid_results(raws)
