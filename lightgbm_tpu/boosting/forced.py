"""Forced splits: forcedsplits_filename -> a static BFS schedule.

Role of the reference's ForceSplits (serial_tree_learner.cpp:546-701): a
JSON tree {"feature": int, "threshold": float, "left": {...}, "right":
{...}} is imposed before gain-driven growth, breadth-first.  Redesigned for
the jitted grower: the JSON is compiled host-side into per-rank arrays
(feature, bin, BFS child links), and the grower carries a per-leaf pending
rank.  Forced leaves get gain priorities far above any real gain, so the
in-loop argmax applies them first in BFS order; an infeasible forced split
(min_data / min_sum_hessian violated, or a categorical feature) falls back
to the leaf's gain-driven best and — like the reference's aborted forcing
queue — its forced descendants are dropped.
"""
from __future__ import annotations

import json
from typing import NamedTuple, Optional, Tuple

# priority unit: forced rank j gets gain (n_forced - j) * UNIT, which
# dominates any real gain and preserves BFS order under argmax
PRIORITY_UNIT = 1e30


class ForcedSchedule(NamedTuple):
    """Hashable (all-tuple) forced-split plan, indexed by BFS rank."""
    feat: Tuple[int, ...]    # [n] split feature per rank
    bin: Tuple[int, ...]     # [n] threshold bin per rank
    gain: Tuple[float, ...]  # [n] argmax priority per rank
    lnext: Tuple[int, ...]   # [n] rank forced on the left child, -1 if none
    rnext: Tuple[int, ...]   # [n] rank forced on the right child, -1 if none


def load_forced_json(path: str):
    with open(path) as fh:
        return json.load(fh)


def make_forced_machinery(forced: "ForcedSchedule", meta, cfg):
    """Device arrays + the override closure shared by both growers.

    Returns (lnext, rnext, forced_override): the BFS child-link arrays the
    growers thread through their state, and forced_override(rank,
    hist_fview, sg, sh, sc, normal_res) -> (result, real_gain,
    surviving_rank)."""
    import jax.numpy as jnp

    from ..ops.split import SplitResult, evaluate_split_at

    fc_feat = jnp.asarray(forced.feat, jnp.int32)
    fc_bin = jnp.asarray(forced.bin, jnp.int32)
    fc_gain = jnp.asarray(forced.gain, jnp.float32)
    fc_lnext = jnp.asarray(forced.lnext, jnp.int32)
    fc_rnext = jnp.asarray(forced.rnext, jnp.int32)

    def forced_override(rank, hist_fview, sg, sh, sc, normal_res,
                        min_constraint=None, max_constraint=None):
        r0 = jnp.maximum(rank, 0)
        fres = evaluate_split_at(
            hist_fview, sg, sh, sc, fc_feat[r0], fc_bin[r0], meta=meta,
            l1=cfg.lambda_l1, l2=cfg.lambda_l2,
            max_delta_step=cfg.max_delta_step,
            min_data_in_leaf=cfg.min_data_in_leaf,
            min_sum_hessian_in_leaf=cfg.min_sum_hessian_in_leaf,
            min_constraint=min_constraint, max_constraint=max_constraint)
        use = (rank >= 0) & jnp.isfinite(fres.gain)
        real = jnp.where(use, fres.gain, normal_res.gain)
        res = SplitResult(*[jnp.where(use, a, b) for a, b in
                            zip(fres._replace(gain=fc_gain[r0]), normal_res)])
        return res, real, jnp.where(use, rank, -1)

    return fc_lnext, fc_rnext, forced_override


def build_forced_schedule(root_json, bin_mappers,
                          num_leaves: int) -> Optional[ForcedSchedule]:
    """Compile the forced-split JSON into a ForcedSchedule (BFS ranks).

    Thresholds are real feature values, converted through each feature's
    BinMapper (BinMapper::ValueToBin) exactly as the reference does when it
    materializes a forced SplitInfo."""
    if not root_json:
        return None
    feat, bins, lnext, rnext = [], [], [], []
    queue = [(root_json, None, 0)]   # (node, parent_rank, side)
    while queue and len(feat) < num_leaves - 1:
        node, parent, side = queue.pop(0)
        rank = len(feat)
        f = int(node["feature"])
        if not 0 <= f < len(bin_mappers):
            raise ValueError("forced split names feature %d but the dataset "
                             "has %d features" % (f, len(bin_mappers)))
        mapper = bin_mappers[f]
        b = int(mapper.value_to_bin(float(node["threshold"])))
        # a forced threshold at/above the last bin can never send rows right
        b = min(b, max(int(mapper.num_bin) - 2, 0))
        feat.append(f)
        bins.append(b)
        lnext.append(-1)
        rnext.append(-1)
        if parent is not None:
            (lnext if side == 0 else rnext)[parent] = rank
        if node.get("left"):
            queue.append((node["left"], rank, 0))
        if node.get("right"):
            queue.append((node["right"], rank, 1))

    n = len(feat)
    if n == 0:
        return None
    gain = [(n - j) * PRIORITY_UNIT for j in range(n)]
    return ForcedSchedule(feat=tuple(feat), bin=tuple(bins),
                          gain=tuple(gain), lnext=tuple(lnext),
                          rnext=tuple(rnext))
