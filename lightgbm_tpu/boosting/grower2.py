"""Partitioned leaf-wise tree grower — O(rows-touched) histogram work.

Same split semantics as `grower.make_tree_grower` (reference
SerialTreeLearner, src/treelearner/serial_tree_learner.cpp:157-221) but with
the reference's actual cost model restored: rows of every leaf are kept
physically contiguous in a payload matrix (DataPartition,
src/treelearner/data_partition.hpp), each split stably partitions only the
split leaf's rows, and only the smaller child's histogram is built from rows
(serial_tree_learner.cpp:447-544) — the sibling comes from subtraction.

Histogram + partition run on the segment engine (`ops.segment`), whose TPU
hot paths are Pallas kernels; everything here is shape-static and jitted
once per (shape, config).

Differences from the masked grower (grower.py):
- no per-row leaf-id vector; leaf locations are (start, count) segments;
- the payload is both input and output: the caller owns extra columns
  (label / weight / scores) that ride along through every partition, so
  training state can stay partition-ordered across trees;
- per-row leaf outputs are written into a payload column at split time,
  making the score update an elementwise add instead of a gather.
"""
from __future__ import annotations

import functools
from typing import Dict, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..runtime import xla_obs

from ..ops.bundle import BundleMap, expand_histogram, identity_bundle_map
from ..ops.split import (FeatureMeta, K_MIN_SCORE, SplitResult,
                         dequantize_hist, find_best_split,
                         find_best_split_batched, leaf_output,
                         pad_feature_meta, per_feature_best_gains)
from ..ops import segment as seg
from ..ops.segment import SplitPredicate
from .forced import PRIORITY_UNIT, ForcedSchedule
from .grower import GrowerConfig, make_winner_sync


class PayloadCols(NamedTuple):
    """Static column indices of the value columns inside the payload
    (bin columns occupy [0, F))."""
    grad: int
    hess: int
    cnt: int       # 0/1 count-mask (valid & bagged)
    value: int     # per-row current-tree leaf output


#: grower-output fields forming the DEVICE HALF of a finished tree —
#: everything a bin-level traversal replay (gbdt._traverse_update: valid
#: scores, DART/RF replay, rollback) consumes.  The fused boosting
#: window slices these [j, k] planes out of its stacked [J, K, ...]
#: record emission, so the tuple is the gbdt<->grower2 contract for
#: scan-composed growth: grow() is pure and shape-static (jit=False
#: composes under lax.scan through the __wrapped__ seam), and every
#: field below must stay present in the returned tree dict.
TREE_DEVICE_FIELDS = ("split_feature", "split_bin", "default_left",
                      "split_is_cat", "split_cat_bitset", "left_child",
                      "right_child")


def make_partitioned_grower(meta: FeatureMeta, cfg: GrowerConfig,
                            num_bins_max: int, cols: PayloadCols,
                            num_features: int, jit: bool = True,
                            bundle_map: BundleMap = None,
                            num_columns: int = None,
                            forced: ForcedSchedule = None,
                            axis_name: str = None, mode: str = "data",
                            num_machines: int = 1, top_k: int = 20,
                            merged_hist: bool = None,
                            payload_width: int = None,
                            quantized: bool = False, qmax: int = 0):
    """Returns grow(payload, aux, feature_mask[, qscale]) ->
    (tree arrays dict, payload, aux).

    payload/aux: [N_pad + GUARD, P] f32 with a GUARD-row tail whose
    count-mask is 0.  Valid rows are [0, N_pad); the root segment covers all
    of them regardless of the ordering left behind by previous trees.

    With EFB (bundle_map set), the payload holds num_columns < num_features
    bundled bin columns; histograms are built bundled (state stays [L, G,
    B, 3] — the memory win) and expanded to per-feature views only for
    split finding.

    axis_name: when set, the grower is one shard of a row-sharded parallel
    tree learner inside shard_map over that mesh axis — the reference's
    DataParallel / VotingParallel learners ARE its serial learner plus a
    network boundary (data_parallel_tree_learner.cpp:147-246 inherits
    SerialTreeLearner), and this grower keeps the same shape: per-device
    payload segments partition locally, and only histograms cross the wire:

    - mode="data": local per-leaf histograms are ReduceScattered over the
      storage-column axis (`psum_scatter`), split search runs on owned
      columns only, and one SyncUpGlobalBestSplit allreduce broadcasts the
      winner (data_parallel_tree_learner.cpp:159-246).  When the dataset is
      EFB-bundled or forced splits are active, the learner switches to a
      full `psum` with replicated search: bundling already shrank G (so the
      full blob is small on the wire) and both features need the whole
      histogram on every shard.
    - mode="voting": histograms stay local; shards vote top_k features by
      local gain, only the vote winners' histograms are `psum`ed (PV-Tree,
      voting_parallel_tree_learner.cpp), constraints scaled 1/num_machines.
    - mode="feature": FULL rows per shard with the payload's storage
      columns permuted OWNED-FIRST (shard r's columns [r*Gloc, (r+1)*Gloc)
      lead its payload); histograms/search cover only the owned leading
      columns — the O(rows-touched) cost model with 1/n of the column
      work — the winner crosses the wire as one SyncUpGlobalBestSplit
      blob, and each shard partitions its full rows locally with the
      winner's column translated into its own layout.  This mirrors
      FeatureParallelTreeLearner (feature_parallel_tree_learner.cpp:21-69:
      full data per rank, feature-sliced search, no row movement).
      Unbundled/unforced only; the caller builds the permuted payload.

    quantized (gradient_quantization mode, ops.quantize): the payload's
    grad/hess columns hold integer-valued quantized gradients with grid
    half-range `qmax`; histograms accumulate int32 (exact — subtraction
    siblings and cross-shard psum/psum_scatter are bit-exact and every
    engine agrees to the bit), and `grow` takes a fourth argument, the
    [2] f32 (gradient, hessian) scale vector, dequantizing with
    `ops.split.dequantize_hist` exactly at the split-search boundary so
    the gain arithmetic is the f32 code unchanged.  Serial + mesh modes;
    forced splits and the merged partition+hist kernel are f32-only.
    """
    L = cfg.num_leaves
    B = num_bins_max
    F = num_features
    G = num_columns if num_columns is not None else F
    bundled = bundle_map is not None
    bmap = bundle_map if bundled else identity_bundle_map(F)
    meshed = axis_name is not None
    # full-psum + replicated search when scatter/vote can't see whole
    # features (EFB) or need the whole histogram everywhere (forced splits)
    replicated = meshed and (bundled or forced is not None)
    scatter_mode = meshed and not replicated and mode == "data"
    voting_mode = meshed and not replicated and mode == "voting"
    feature_mode = meshed and mode == "feature"
    if meshed:
        assert mode in ("data", "voting", "feature"), \
            "partitioned mesh grower supports data|voting|feature"
    if feature_mode:
        # feature-parallel keeps full rows per shard with an OWNED-FIRST
        # column permutation (the caller lays the payload out that way),
        # so the histogram walk covers only the shard's own columns; EFB
        # and forced splits need whole-histogram views and stay on the
        # replicated/legacy paths (gbdt falls back before reaching here)
        assert not bundled and forced is None, \
            "feature-parallel partitioned engine is unbundled/unforced only"
    n_mach = max(num_machines, 1)
    if scatter_mode or feature_mode:
        Gp = -(-G // n_mach) * n_mach
        padg = Gp - G
        Gloc = Gp // n_mach
    # width of a pooled histogram: the owned slice in data/feature mode,
    # the full (local or replicated) blob otherwise
    Gh = Gloc if (scatter_mode or feature_mode) else G

    find_kwargs = dict(
        l1=cfg.lambda_l1, l2=cfg.lambda_l2, max_delta_step=cfg.max_delta_step,
        min_data_in_leaf=cfg.min_data_in_leaf,
        min_sum_hessian_in_leaf=cfg.min_sum_hessian_in_leaf,
        min_gain_to_split=cfg.min_gain_to_split,
        max_cat_threshold=cfg.max_cat_threshold, cat_l2=cfg.cat_l2,
        cat_smooth=cfg.cat_smooth, max_cat_to_onehot=cfg.max_cat_to_onehot,
        min_data_per_group=cfg.min_data_per_group,
        with_categorical=cfg.with_categorical)
    find = functools.partial(find_best_split, meta=meta, **find_kwargs)
    out_fn = functools.partial(leaf_output, l1=cfg.lambda_l1, l2=cfg.lambda_l2,
                               max_delta_step=cfg.max_delta_step)

    # feature mode's payload columns are permuted owned-first, so the
    # histogram (and its engine/VMEM-fit choice) covers Gloc columns only
    Ghist = Gloc if feature_mode else G
    hist_kwargs = dict(num_features=Ghist, num_bins=B, grad_col=cols.grad,
                       hess_col=cols.hess, cnt_col=cols.cnt)
    if quantized:
        # f32-only machinery stays off the quantized path: forced splits
        # read raw f32 hist views in their override, and the merged
        # partition+hist kernel accumulates f32 (gbdt gates eligibility
        # before building a quantized grower, so these are invariants)
        assert forced is None, "quantized grower is unforced-only"
        assert qmax >= 2, "quantized grower needs the derive_qmax grid"
    # the real payload width reaches the VMEM gate: the kernel DMAs full
    # rows even when it histograms only the owned leading columns
    # (feature-parallel), so the num_features-based estimate under-budgeted
    # exactly where Ghist << payload_width
    impl = seg.resolve_impl(cfg.hist_impl, Ghist, B, payload_width)
    hist_engine = impl
    if quantized:
        from ..ops import pallas_segment as pseg
        if (impl == "pallas" and pseg.HIST_QUANT_VALIDATED and qmax <= 127):
            # staged int8 x one-hot -> int32 MXU kernel; bit-exact with
            # the portable int engine (integer accumulation never rounds)
            hist_fn = functools.partial(pseg.segment_histogram_quant,
                                        **hist_kwargs)
            hist_engine = "pallas-quant"
        else:
            hist_fn = functools.partial(seg.segment_histogram,
                                        quantized=True, **hist_kwargs)
            hist_engine = "lax"
    elif impl == "pallas":
        from ..ops import pallas_segment as pseg
        hist_fn = functools.partial(pseg.segment_histogram, **hist_kwargs)
    else:
        # ultra-wide payloads (raw Allstate 4228x256, Epsilon-dense) fall
        # off the single-pass kernel's VMEM plan; the column-block sibling
        # engine serves them once hardware-validated
        from ..ops import pallas_segment as pseg
        colblock = (cfg.hist_impl != "lax"
                    and jax.default_backend() == "tpu"
                    and pseg.HIST_COLBLOCK_VALIDATED
                    and payload_width is not None
                    and pseg.fits_vmem_colblock(
                        Ghist, B, payload_width, cols.grad, cols.hess,
                        cols.cnt))
        if colblock:
            hist_fn = functools.partial(pseg.segment_histogram_colblock,
                                        **hist_kwargs)
            hist_engine = "colblock"
        else:
            hist_fn = functools.partial(seg.segment_histogram, **hist_kwargs)
            hist_engine = "lax"

    # the partition kernel is gated separately from the histogram: it is
    # exact at any bin count (HIGHEST-precision permutation) but spans the
    # full payload width, so Epsilon-wide P overflows its un-tiled VMEM
    # plan while e.g. a >256-bin config only falls off the HISTOGRAM kernel
    pallas_part = (cfg.hist_impl != "lax"
                   and jax.default_backend() == "tpu")

    def part_fn(payload, aux, start, count, pred, lv, rv):
        if pallas_part:
            from ..ops import pallas_segment as pseg
            if (pseg.PARTITION_ACC_VALIDATED
                    and pseg.partition_acc_fits_vmem(payload.shape[1], B)):
                return pseg.partition_segment_acc(payload, aux, start, count,
                                                  pred, lv, rv, cols.value, B)
            if pseg.partition_fits_vmem(payload.shape[1], B):
                return pseg.partition_segment(payload, aux, start, count,
                                              pred, lv, rv, cols.value, B)
            if (pseg.PARTITION_BLOCKS_VALIDATED
                    and payload.shape[1] % 128 == 0
                    and pseg.partition_blocks_fits_vmem(
                        payload.shape[1], B)):
                # ultra-wide payloads: per-lane-window passes with a
                # shared routing read (Epsilon/raw-Allstate class)
                return pseg.partition_segment_acc_blocks(
                    payload, aux, start, count, pred, lv, rv,
                    cols.value, B)
        return seg.partition_segment(payload, aux, start, count, pred,
                                     lv, rv, cols.value)

    # ---- merged partition+hist mode (serial only): one kernel per split
    # computes the partition AND both children's histograms from the same
    # row pass — the parent histogram, subtraction trick and device
    # histogram pool all retire (their roles fold into the partition walk;
    # reference feature_histogram.hpp:505-826).  Auto = hardware-validated
    # flag + pallas kernels + VMEM fit; tests may force it on the portable
    # engines (partition, then walk each child's contiguous rows).
    if merged_hist is None:
        from ..ops import pallas_segment as _pseg
        # the VMEM fit is part of the AUTO decision: a non-fitting shape
        # would land on part_hist_fn's portable fallback, which walks BOTH
        # children (strictly worse than smaller-child + subtraction)
        merged_hist = (not meshed and pallas_part and impl == "pallas"
                       and not quantized
                       and _pseg.PARTITION_HIST_VALIDATED
                       and payload_width is not None
                       and _pseg.partition_hist_fits_vmem(payload_width,
                                                          G, B))
    merged_hist = bool(merged_hist) and not meshed and not quantized

    if merged_hist:
        from ..ops import pallas_segment as _pseg

        def part_hist_fn(payload, aux, start, count, pred, lv, rv):
            if (pallas_part and impl == "pallas"
                    and _pseg.partition_hist_fits_vmem(
                        payload.shape[1], G, B)):
                return _pseg.partition_segment_hist(
                    payload, aux, start, count, pred, lv, rv,
                    cols.value, B, num_features=G, grad_col=cols.grad,
                    hess_col=cols.hess, cnt_col=cols.cnt)
            payload, aux, nl = part_fn(payload, aux, start, count, pred,
                                       lv, rv)
            hl = hist_fn(payload, start, nl)
            hr = hist_fn(payload, start + nl, count - nl)
            return payload, aux, nl, hl, hr

    def hist_view(hist_g):
        """[G, B, 3] bundle histogram -> [F, B, 3] per-feature split view."""
        if not bundled:
            return hist_g
        return expand_histogram(hist_g, bmap, meta.num_bin, meta.default_bin,
                                B)

    # histogram pool (reference HistogramPool, feature_histogram.hpp:655-826):
    # POOL < L caches per-leaf histograms with LRU eviction; a split whose
    # parent was evicted recomputes it by walking the (still contiguous)
    # parent segment — cheap under the O(rows-touched) engine
    POOL = cfg.hist_pool_slots if 0 < cfg.hist_pool_slots < L else L
    pooled = POOL < L and not merged_hist
    if merged_hist:
        POOL = 1   # no device hist state at all in merged mode
    else:
        assert POOL >= 2, "histogram pool needs at least 2 slots"

    # ---- frontier batching (Config.tpu_frontier_batch > 1) --------------
    # A gain-ordered window of up to K frontier leaves is EVALUATED per
    # round (K partitions of disjoint segments, ONE batched histogram
    # dispatch for the K smaller children, ONE fused cross-leaf split
    # search over the 2K children), then splits COMMIT by replaying the
    # sequential grower's argmax order against the cached evaluations — a
    # pop outside the evaluated window ends the round.  Leaf-wise
    # semantics are exact (byte-identical models): splitting one leaf
    # never changes another frontier leaf's rows, histogram or best split
    # (segments are disjoint and the partition is stable), so an
    # evaluation is the same bits whenever it runs, and the commit replay
    # IS the sequential order.  Serial unforced/unpooled/non-monotone
    # configs only; everything else keeps the sequential loop.
    fb_req = max(int(getattr(cfg, "frontier_batch", 1) or 1), 1)
    # every serial unforced non-monotone grower evaluates children through
    # the SAME stacked-fori search (find_best_split_batched), whatever its
    # window size — XLA compiles a find embedded directly in the do_split
    # body differently than one in a fori body (duplicated-consumer fma
    # contraction), and the ~1e-5 gain drift would break the batched
    # grower's byte-identical-model guarantee against the K = 1 grower
    stacked_find = not meshed and forced is None and not cfg.with_monotone
    frontier_batched = (fb_req > 1 and L > 2 and stacked_find
                       and not merged_hist and not pooled)
    if frontier_batched and hist_engine == "pallas":
        # staged OFF like the other TPU levers: the sequential grower
        # stays the hardware-validated path until the batched kernel's
        # Mosaic lowering is proven on a real chip (smoke FRONTIER
        # section, then exp/flip_validated.py frontier)
        from ..ops import pallas_segment as _pseg_fb
        frontier_batched = _pseg_fb.FRONTIER_BATCH_VALIDATED
    elif frontier_batched and quantized:
        # quantized engines are bit-exact across dispatch shapes (integer
        # accumulation never rounds), so the portable quantized batched
        # engine serves every quantized config — including pallas-quant,
        # which has no batched sibling (yet) — without an exactness gate
        pass
    elif frontier_batched and hist_engine != "lax":
        frontier_batched = False   # no batched colblock sibling (yet)
    frontier_k = min(fb_req, L - 1) if frontier_batched else 1
    if frontier_batched:
        if quantized:
            hist_batched_fn = functools.partial(
                seg.segment_histogram_batched, quantized=True,
                **hist_kwargs)
        elif hist_engine == "pallas":
            from ..ops import pallas_segment as _pseg_fb2
            hist_batched_fn = functools.partial(
                _pseg_fb2.segment_histogram_batched, **hist_kwargs)
        else:
            hist_batched_fn = functools.partial(
                seg.segment_histogram_batched, **hist_kwargs)

    if forced is not None:
        from .forced import make_forced_machinery
        fc_lnext, fc_rnext, forced_override = \
            make_forced_machinery(forced, meta, cfg)

    def grow(payload: jax.Array, aux: jax.Array,
             feature_mask: jax.Array, qscale: jax.Array = None):
        n_rows = jnp.int32(payload.shape[0] - seg.GUARD)

        # dequantize-at-the-boundary: int32 histograms become f32 views
        # exactly where the split search consumes them; identity in f32
        # mode so the default path's trace is unchanged
        if quantized:
            assert qscale is not None, "quantized grow needs the scale pair"
            deq = functools.partial(dequantize_hist, gscale=qscale[0],
                                    hscale=qscale[1])
        else:
            def deq(h):
                return h

        # mesh-mode machinery is built at trace time (axis_index exists only
        # inside shard_map); find_split closes over the feature mask so the
        # split loop below is mode-agnostic
        localize_col = None
        if scatter_mode or feature_mode:
            # shared owned-column search: shard `my` owns global storage
            # columns [my*Gloc, (my+1)*Gloc) — in data mode as its
            # psum_scatter slice of the reduced histogram, in feature mode
            # as the leading columns of its permuted payload — and the
            # winner is broadcast with the SyncUpGlobalBestSplit allreduce
            # (parallel_tree_learner.h:183-206)
            my = lax.axis_index(axis_name)
            f_offset = my * Gloc
            meta_p = pad_feature_meta(meta, Gp) if padg else meta
            meta_local = FeatureMeta(
                *[lax.dynamic_slice_in_dim(a, f_offset, Gloc)
                  for a in meta_p])
            find_local = functools.partial(find_best_split, meta=meta_local,
                                           **find_kwargs)
            bcast_from_winner = make_winner_sync(axis_name, my, f_offset)
            fmask_p = (jnp.pad(feature_mask, (0, padg)) if padg
                       else feature_mask)
            fmask_loc = lax.dynamic_slice_in_dim(fmask_p, f_offset, Gloc)

            if scatter_mode:
                def reduce_hist(h):
                    if padg:
                        h = jnp.pad(h, ((0, padg), (0, 0), (0, 0)))
                    return lax.psum_scatter(h, axis_name,
                                            scatter_dimension=0, tiled=True)
            else:
                # feature mode: hist_fn already produced the owned slice
                # over the full rows — nothing crosses the wire
                # (FeatureParallelTreeLearner holds full data per rank,
                # feature_parallel_tree_learner.cpp:21-69)
                def reduce_hist(h):
                    return h

                def localize_col(g):
                    # inverse of the owned-first column permutation:
                    # [owned block | columns before it | columns after it]
                    return jnp.where(
                        g < f_offset, Gloc + g,
                        jnp.where(g < f_offset + Gloc, g - f_offset, g))

            def find_split(hist_loc, sg, sh, cnt, **constraints):
                return bcast_from_winner(
                    find_local(deq(hist_loc), sg, sh, cnt, fmask_loc,
                               **constraints))

        elif voting_mode:
            k_vote = min(top_k, F)
            S = min(2 * k_vote, F)
            vote_kwargs = dict(find_kwargs)
            vote_kwargs["min_data_in_leaf"] = cfg.min_data_in_leaf / n_mach
            vote_kwargs["min_sum_hessian_in_leaf"] = \
                cfg.min_sum_hessian_in_leaf / n_mach

            def reduce_hist(h):
                return h

            def find_split(hist_local, sg, sh, cnt, **constraints):
                # phase 1: vote top_k features by LOCAL split gain with
                # 1/num_machines-scaled constraints; phase 2: reduce ONLY
                # the vote winners' histograms and find on them (PV-Tree)
                hist_local_f = deq(hist_local)
                local_tot = jnp.sum(hist_local_f[0], axis=0)
                local_gains = per_feature_best_gains(
                    hist_local_f, local_tot[0], local_tot[1], local_tot[2],
                    feature_mask, meta=meta, **vote_kwargs)
                top_vals, top_idx = lax.top_k(local_gains, k_vote)
                valid_vote = (top_vals > K_MIN_SCORE).astype(jnp.int32)
                all_top = lax.all_gather(top_idx, axis_name)
                all_valid = lax.all_gather(valid_vote, axis_name)
                votes = jnp.zeros(F, jnp.int32).at[all_top.reshape(-1)].add(
                    all_valid.reshape(-1))
                _, sel = lax.top_k(votes, S)
                # the vote winners' histograms cross the wire as integers
                # in quantized mode (exact psum, 0 ulp shard-order drift)
                hsel = deq(lax.psum(hist_local[sel], axis_name))
                meta_sel = FeatureMeta(*[a[sel] for a in meta])
                res = find_best_split(hsel, sg, sh, cnt, feature_mask[sel],
                                      meta=meta_sel, **find_kwargs,
                                      **constraints)
                return res._replace(feature=sel[res.feature])

        else:
            def reduce_hist(h):
                return lax.psum(h, axis_name) if replicated else h

            def find_split(h, sg, sh, cnt, **constraints):
                return find(hist_view(deq(h)), sg, sh, cnt, feature_mask,
                            **constraints)

        if stacked_find:
            def find_split_batched(hists, sgs, shs, cnts):
                """Fused search over a [Q, Gh, B, 3] stack of children."""
                hists = deq(hists)
                if bundled:
                    hists = jax.vmap(hist_view)(hists)
                return find_best_split_batched(hists, sgs, shs, cnts,
                                               feature_mask, meta=meta,
                                               **find_kwargs)

        hist_root_local = hist_fn(payload, jnp.int32(0), n_rows)
        # every row lands in exactly one bin of storage column 0, so the
        # root totals fall out of the histogram — no separate full-data pass
        totals = jnp.sum(hist_root_local[0], axis=0)
        if meshed and not feature_mode:
            totals = lax.psum(totals, axis_name)
        elif feature_mode:
            # every shard sees FULL rows, so its local column-0 totals are
            # already global IN VALUE — but fp summation order differs per
            # column at ulp level, and the winner's split outputs are
            # computed against these totals by whichever shard owns it.
            # Pin global column 0's totals (shard 0's, the exact sums the
            # serial engine uses) onto every shard so all shards — and the
            # serial learner — agree bit-for-bit.
            totals = lax.psum(jnp.where(my == 0, totals,
                                        jnp.zeros_like(totals)), axis_name)
        hist_root = reduce_hist(hist_root_local)
        # quantized mode: totals crossed the wire as exact integers; the
        # f32 leaf aggregates exist only from this boundary on
        totals = deq(totals)
        root_g, root_h, root_c = totals[0], totals[1], totals[2]
        if cfg.with_monotone:
            res0 = find_split(hist_root, root_g, root_h, root_c,
                              min_constraint=jnp.float32(-jnp.inf),
                              max_constraint=jnp.float32(jnp.inf))
        else:
            res0 = find_split(hist_root, root_g, root_h, root_c)

        # rows start as one root segment with the root Newton step as the
        # per-row output (covers the unsplittable-stump case)
        root_out = out_fn(root_g, root_h)
        payload = seg.payload_col_write(payload, cols.value, root_out)

        real0 = res0.gain
        root_rank = jnp.int32(-1)
        if forced is not None:
            res0, real0, root_rank = forced_override(
                jnp.int32(0), hist_view(hist_root), root_g, root_h, root_c,
                res0)

        ni = max(L - 1, 1)
        state = {
            "payload": payload,
            "aux": aux,
            "seg_start": jnp.zeros(L, jnp.int32),
            "seg_cnt": jnp.zeros(L, jnp.int32).at[0].set(n_rows),
            "sum_g": jnp.zeros(L, jnp.float32).at[0].set(root_g),
            "sum_h": jnp.zeros(L, jnp.float32).at[0].set(root_h),
            "cnt": jnp.zeros(L, jnp.float32).at[0].set(root_c),
            # creation value: 0 for the root (it has no creating split), set
            # by do_split for children — matches grower.py / Tree semantics
            # so internal_value of the first split agrees with the reference
            "leaf_val": jnp.zeros(L, jnp.float32),
            "bgain": jnp.full(L, K_MIN_SCORE, jnp.float32).at[0].set(res0.gain),
            "bfeat": jnp.zeros(L, jnp.int32).at[0].set(res0.feature),
            "bbin": jnp.zeros(L, jnp.int32).at[0].set(res0.threshold_bin),
            "bdleft": jnp.zeros(L, jnp.bool_).at[0].set(res0.default_left),
            "blg": jnp.zeros(L, jnp.float32).at[0].set(res0.left_sum_g),
            "blh": jnp.zeros(L, jnp.float32).at[0].set(res0.left_sum_h),
            "blc": jnp.zeros(L, jnp.float32).at[0].set(res0.left_count),
            "bcat": jnp.zeros(L, jnp.bool_).at[0].set(res0.is_cat),
            "bbitset": jnp.zeros((L, B), jnp.bool_).at[0].set(res0.cat_bitset),
            "blo": jnp.zeros(L, jnp.float32).at[0].set(res0.left_output),
            "bro": jnp.zeros(L, jnp.float32).at[0].set(res0.right_output),
            "leaf_depth": jnp.zeros(L, jnp.int32),
            "leaf_parent": jnp.full(L, -1, jnp.int32),
            "split_feature": jnp.zeros(ni, jnp.int32),
            "split_bin": jnp.zeros(ni, jnp.int32),
            "split_gain": jnp.zeros(ni, jnp.float32),
            "default_left": jnp.zeros(ni, jnp.bool_),
            "split_is_cat": jnp.zeros(ni, jnp.bool_),
            "split_cat_bitset": jnp.zeros((ni, B), jnp.bool_),
            "left_child": jnp.zeros(ni, jnp.int32),
            "right_child": jnp.zeros(ni, jnp.int32),
            "internal_value": jnp.zeros(ni, jnp.float32),
            "internal_count": jnp.zeros(ni, jnp.float32),
            "num_leaves": jnp.int32(1),
        }
        if not merged_hist:
            # per-leaf (or pooled) histogram state exists only for the
            # subtraction trick; merged mode gets both child histograms
            # from the partition kernel itself.  int32 in quantized mode
            # (the narrow-dtype plumbing: LRU slots, subtraction and the
            # frontier-batch dispatch all carry the integer histograms)
            state["hist"] = jnp.zeros((POOL, Gh, B, 3),
                                      hist_root.dtype).at[0].set(hist_root)
        if forced is not None:
            # pending forced rank per leaf, and the REAL (not priority) gain
            # of each leaf's stored best split, for honest split_gain records
            state["fleaf"] = jnp.full(L, -1, jnp.int32).at[0].set(root_rank)
            state["breal"] = jnp.full(L, K_MIN_SCORE,
                                      jnp.float32).at[0].set(real0)
        if cfg.with_monotone:
            state["mincon"] = jnp.full(L, -jnp.inf, jnp.float32)
            state["maxcon"] = jnp.full(L, jnp.inf, jnp.float32)
        if pooled:
            state["slot_of_leaf"] = jnp.full(L, -1, jnp.int32).at[0].set(0)
            state["leaf_of_slot"] = jnp.full(POOL, -1, jnp.int32).at[0].set(0)
            state["slot_use"] = jnp.zeros(POOL, jnp.int32)
        if frontier_batched:
            state["rounds"] = jnp.int32(0)

        def do_split(s, st, best_leaf):
            """Partition the split leaf and evaluate its children; runs only
            when a positive-gain split exists (under lax.cond)."""
            node = s - 1
            f = st["bfeat"][best_leaf]
            gcol = bmap.f_group[f]
            if localize_col is not None:
                # feature mode: the winner carries the GLOBAL feature id;
                # this shard's payload stores that column at its permuted
                # position
                gcol = localize_col(gcol)
            pred = SplitPredicate(
                col=gcol,
                threshold=st["bbin"][best_leaf],
                default_left=st["bdleft"][best_leaf],
                is_cat=st["bcat"][best_leaf],
                bitset=st["bbitset"][best_leaf],
                missing_type=meta.missing_type[f],
                num_bin=meta.num_bin[f],
                default_bin=meta.default_bin[f],
                offset=bmap.f_offset[f],
                identity=bmap.f_identity[f])

            start = st["seg_start"][best_leaf]
            count = st["seg_cnt"][best_leaf]

            # child aggregates: left from the stored split, right by diff
            lg, lh, lcnt = (st["blg"][best_leaf], st["blh"][best_leaf],
                            st["blc"][best_leaf])
            pg, ph, pc = (st["sum_g"][best_leaf], st["sum_h"][best_leaf],
                          st["cnt"][best_leaf])
            rg, rh, rcnt = pg - lg, ph - lh, pc - lcnt

            if merged_hist:
                # one kernel: partition + BOTH children's histograms from
                # the same row pass (no parent hist, no subtraction, no
                # pool).  Serial-only, so reduce_hist is identity.
                payload, aux, nl_raw, new_left, new_right = part_hist_fn(
                    st["payload"], st["aux"], start, count, pred,
                    st["blo"][best_leaf], st["bro"][best_leaf])
                nr_raw = count - nl_raw
            else:
                # parent histogram: read the pool slot, or rebuild it from
                # the (still contiguous) parent segment if it was evicted
                if pooled:
                    # NOTE: the rebuild branch runs a collective in mesh
                    # modes; the pool bookkeeping is replicated-in-value,
                    # so every shard takes the same branch and the psum
                    # pairs up
                    pslot = st["slot_of_leaf"][best_leaf]
                    hist_parent = lax.cond(
                        pslot >= 0,
                        lambda: st["hist"][jnp.maximum(pslot, 0)],
                        lambda: reduce_hist(hist_fn(st["payload"], start,
                                                    count)))
                else:
                    hist_parent = st["hist"][best_leaf]

                payload, aux, nl_raw = part_fn(
                    st["payload"], st["aux"], start, count, pred,
                    st["blo"][best_leaf], st["bro"][best_leaf])
                nr_raw = count - nl_raw

                # histograms: build only the smaller child, derive the
                # sibling by subtraction.  The choice uses masked counts
                # (like grower.py and the reference's num_data comparison)
                # so both growers build the direct histogram on the same
                # child and stay bit-comparable.
                left_smaller = lcnt <= rcnt
                h_start = jnp.where(left_smaller, start, start + nl_raw)
                h_count = jnp.where(left_smaller, nl_raw, nr_raw)
                hist_small = reduce_hist(hist_fn(payload, h_start, h_count))
                hist_big = hist_parent - hist_small
                new_left = jnp.where(left_smaller, hist_small, hist_big)
                new_right = jnp.where(left_smaller, hist_big, hist_small)
            if pooled:
                slot_of_leaf = st["slot_of_leaf"]
                leaf_of_slot = st["leaf_of_slot"]
                use = st["slot_use"]
                iota_pool = jnp.arange(POOL, dtype=jnp.int32)

                def evict(slot_of_leaf, leaf_of_slot, victim):
                    old = leaf_of_slot[victim]
                    oldc = jnp.maximum(old, 0)
                    slot_of_leaf = slot_of_leaf.at[oldc].set(
                        jnp.where(old >= 0, -1, slot_of_leaf[oldc]))
                    return slot_of_leaf

                # left child: reuse the parent's slot, else evict the LRU
                victim_l = jnp.argmin(use).astype(jnp.int32)
                lslot = jnp.where(pslot >= 0, pslot, victim_l)
                slot_of_leaf = jnp.where(
                    pslot >= 0, slot_of_leaf,
                    evict(slot_of_leaf, leaf_of_slot, victim_l))
                leaf_of_slot = leaf_of_slot.at[lslot].set(best_leaf)
                use = use.at[lslot].set(s)
                # right child: evict the LRU among the remaining slots
                prio = jnp.where(iota_pool == lslot, jnp.int32(1 << 30), use)
                rslot = jnp.argmin(prio).astype(jnp.int32)
                slot_of_leaf = evict(slot_of_leaf, leaf_of_slot, rslot)
                leaf_of_slot = leaf_of_slot.at[rslot].set(s)
                use = use.at[rslot].set(s)
                slot_of_leaf = slot_of_leaf.at[best_leaf].set(lslot)
                slot_of_leaf = slot_of_leaf.at[s].set(rslot)
                hist = st["hist"].at[lslot].set(new_left)
                hist = hist.at[rslot].set(new_right)
            elif not merged_hist:
                hist = st["hist"].at[best_leaf].set(new_left)
                hist = hist.at[s].set(new_right)

            child_depth = st["leaf_depth"][best_leaf] + 1
            if cfg.with_monotone:
                from .grower import propagate_monotone_bounds
                lmin, lmax, rmin, rmax = propagate_monotone_bounds(
                    st["blo"][best_leaf], st["bro"][best_leaf],
                    ~st["bcat"][best_leaf], meta.monotone[f],
                    st["mincon"][best_leaf], st["maxcon"][best_leaf])
                res_l = find_split(new_left, lg, lh, lcnt,
                                   min_constraint=lmin, max_constraint=lmax)
                res_r = find_split(new_right, rg, rh, rcnt,
                                   min_constraint=rmin, max_constraint=rmax)
            elif stacked_find:
                # the sequential loop must stay bit-comparable with the
                # frontier-batched grower: evaluate the two children
                # through the SAME stacked-fori search the batched rounds
                # use (see find_best_split_batched's exactness note),
                # then split the [2] rows back out
                lmin = lmax = rmin = rmax = None
                res2_ = find_split_batched(
                    jnp.stack([new_left, new_right]),
                    jnp.stack([lg, rg]), jnp.stack([lh, rh]),
                    jnp.stack([lcnt, rcnt]))
                res_l = jax.tree_util.tree_map(lambda a: a[0], res2_)
                res_r = jax.tree_util.tree_map(lambda a: a[1], res2_)
            else:
                lmin = lmax = rmin = rmax = None
                res_l = find_split(new_left, lg, lh, lcnt)
                res_r = find_split(new_right, rg, rh, rcnt)
            real_l, real_r = res_l.gain, res_r.gain
            if forced is not None:
                jp = st["fleaf"][best_leaf]
                applied = (jp >= 0) & \
                    (st["bgain"][best_leaf] >= 0.5 * PRIORITY_UNIT)
                jp0 = jnp.maximum(jp, 0)
                jl = jnp.where(applied, fc_lnext[jp0], -1)
                jr = jnp.where(applied, fc_rnext[jp0], -1)
                res_l, real_l, jl = forced_override(
                    jl, hist_view(new_left), lg, lh, lcnt, res_l,
                    min_constraint=lmin, max_constraint=lmax)
                res_r, real_r, jr = forced_override(
                    jr, hist_view(new_right), rg, rh, rcnt, res_r,
                    min_constraint=rmin, max_constraint=rmax)
            if cfg.max_depth > 0:
                depth_ok = child_depth < cfg.max_depth
            else:
                depth_ok = jnp.bool_(True)
            gain_l = jnp.where(depth_ok, res_l.gain, K_MIN_SCORE)
            gain_r = jnp.where(depth_ok, res_r.gain, K_MIN_SCORE)

            def set2(arr, vl, vr):
                return arr.at[best_leaf].set(vl).at[s].set(vr)

            st_new = dict(st)
            st_new["payload"] = payload
            st_new["aux"] = aux
            if not merged_hist:
                st_new["hist"] = hist
            if pooled:
                st_new["slot_of_leaf"] = slot_of_leaf
                st_new["leaf_of_slot"] = leaf_of_slot
                st_new["slot_use"] = use
            st_new["seg_start"] = set2(st["seg_start"], start, start + nl_raw)
            st_new["seg_cnt"] = set2(st["seg_cnt"], nl_raw, nr_raw)
            st_new["sum_g"] = set2(st["sum_g"], lg, rg)
            st_new["sum_h"] = set2(st["sum_h"], lh, rh)
            st_new["cnt"] = set2(st["cnt"], lcnt, rcnt)
            st_new["bgain"] = set2(st["bgain"], gain_l, gain_r)
            st_new["bfeat"] = set2(st["bfeat"], res_l.feature, res_r.feature)
            st_new["bbin"] = set2(st["bbin"], res_l.threshold_bin,
                                  res_r.threshold_bin)
            st_new["bdleft"] = set2(st["bdleft"], res_l.default_left,
                                    res_r.default_left)
            st_new["blg"] = set2(st["blg"], res_l.left_sum_g, res_r.left_sum_g)
            st_new["blh"] = set2(st["blh"], res_l.left_sum_h, res_r.left_sum_h)
            st_new["blc"] = set2(st["blc"], res_l.left_count, res_r.left_count)
            st_new["bcat"] = set2(st["bcat"], res_l.is_cat, res_r.is_cat)
            st_new["bbitset"] = set2(st["bbitset"], res_l.cat_bitset,
                                     res_r.cat_bitset)
            st_new["blo"] = set2(st["blo"], res_l.left_output,
                                 res_r.left_output)
            st_new["bro"] = set2(st["bro"], res_l.right_output,
                                 res_r.right_output)
            st_new["leaf_val"] = set2(st["leaf_val"], st["blo"][best_leaf],
                                      st["bro"][best_leaf])
            st_new["leaf_depth"] = set2(st["leaf_depth"], child_depth,
                                        child_depth)
            if forced is not None:
                st_new["fleaf"] = set2(st["fleaf"], jl, jr)
                st_new["breal"] = set2(st["breal"], real_l, real_r)
            if cfg.with_monotone:
                st_new["mincon"] = set2(st["mincon"], lmin, rmin)
                st_new["maxcon"] = set2(st["maxcon"], lmax, rmax)

            # record the internal node (Tree::Split, tree.h:404-448)
            gain = (st["breal"] if forced is not None
                    else st["bgain"])[best_leaf]
            st_new["split_feature"] = st["split_feature"].at[node].set(f)
            st_new["split_bin"] = st["split_bin"].at[node].set(
                st["bbin"][best_leaf])
            st_new["split_gain"] = st["split_gain"].at[node].set(gain)
            st_new["default_left"] = st["default_left"].at[node].set(
                st["bdleft"][best_leaf])
            st_new["split_is_cat"] = st["split_is_cat"].at[node].set(
                st["bcat"][best_leaf])
            st_new["split_cat_bitset"] = st["split_cat_bitset"].at[node].set(
                st["bbitset"][best_leaf])
            st_new["internal_value"] = st["internal_value"].at[node].set(
                st["leaf_val"][best_leaf])
            st_new["internal_count"] = st["internal_count"].at[node].set(pc)
            left_child = st["left_child"].at[node].set(~best_leaf)
            right_child = st["right_child"].at[node].set(~s)
            parent_node = st["leaf_parent"][best_leaf]
            has_par = parent_node >= 0
            pn = jnp.maximum(parent_node, 0)
            was_left = left_child[pn] == ~best_leaf
            left_child = left_child.at[pn].set(
                jnp.where(has_par & was_left, node, left_child[pn]))
            right_child = right_child.at[pn].set(
                jnp.where(has_par & ~was_left, node, right_child[pn]))
            st_new["left_child"] = left_child
            st_new["right_child"] = right_child
            st_new["leaf_parent"] = set2(st["leaf_parent"], node, node)
            st_new["num_leaves"] = st["num_leaves"] + 1
            return st_new

        # while-loop, not fori+cond: a cond with an identity pass-through
        # branch makes XLA copy the whole carried state — payload and aux
        # included, ~1 GB per split at Higgs scale — every iteration.  The
        # while body always splits; "no positive gain" simply ends the loop,
        # which also gives early exit for free.
        def loop_cond(st):
            return (st["num_leaves"] < L) & (jnp.max(st["bgain"]) > 0.0)

        def body(st):
            best_leaf = jnp.argmax(st["bgain"]).astype(jnp.int32)
            return do_split(st["num_leaves"], st, best_leaf)

        # ---- frontier-batched rounds (see the gate comment above) -------
        KB = frontier_k

        def round_body(st):
            # selection: the gain-ordered window.  lax.top_k is stable
            # (ties prefer the lower index), so slot 0 is exactly the
            # argmax the sequential grower would pop next — the first
            # commit of a round always succeeds and rounds always progress.
            top_gain, cand = lax.top_k(st["bgain"], KB)
            active = top_gain > 0.0
            start_c = st["seg_start"][cand]
            cnt_c = jnp.where(active, st["seg_cnt"][cand], 0)
            feat_c = st["bfeat"][cand]
            bbin_c = st["bbin"][cand]
            bdleft_c = st["bdleft"][cand]
            bcat_c = st["bcat"][cand]
            bbitset_c = st["bbitset"][cand]
            blo_c, bro_c = st["blo"][cand], st["bro"][cand]

            # eval phase A: STAGE every candidate's partition into the aux
            # scratch (passes A+B; payload is only read, so an evaluated
            # candidate that never commits leaves its rows — and every
            # later tree's accumulation order — exactly as the sequential
            # grower would).  Segments are disjoint; ascending start order
            # keeps each stage's one-chunk aux overrun inside regions
            # staged afterwards.  Inactive window slots run with count 0
            # (zero-trip loops) instead of lax.cond, which would copy aux.
            order = jnp.argsort(start_c)

            def eval_part(i, carry):
                aux, nls = carry
                k = order[i]
                f = feat_c[k]
                pred = SplitPredicate(
                    col=bmap.f_group[f],
                    threshold=bbin_c[k],
                    default_left=bdleft_c[k],
                    is_cat=bcat_c[k],
                    bitset=bbitset_c[k],
                    missing_type=meta.missing_type[f],
                    num_bin=meta.num_bin[f],
                    default_bin=meta.default_bin[f],
                    offset=bmap.f_offset[f],
                    identity=bmap.f_identity[f])
                aux, nl = seg.partition_segment_stage(
                    st["payload"], aux, start_c[k], cnt_c[k], pred)
                return aux, nls.at[k].set(nl)

            aux, nl_c = lax.fori_loop(
                0, KB, eval_part, (st["aux"], jnp.zeros(KB, jnp.int32)))
            payload = st["payload"]

            # eval phase B: ONE batched histogram dispatch over the K
            # smaller children, read from the STAGED aux rows — compacted
            # at the same offsets pass C will copy them back to, so the
            # chunk layout (and every f32 accumulation) is bit-identical
            # to the sequential grower's post-partition build.  Siblings
            # by batched subtraction, same masked-count smaller-child
            # choice as the sequential path.
            lg_c, lh_c, lc_c = (st["blg"][cand], st["blh"][cand],
                                st["blc"][cand])
            pg_c, ph_c, pc_c = (st["sum_g"][cand], st["sum_h"][cand],
                                st["cnt"][cand])
            rg_c, rh_c, rc_c = pg_c - lg_c, ph_c - lh_c, pc_c - lc_c
            left_smaller = lc_c <= rc_c
            h_start = jnp.where(left_smaller, start_c, start_c + nl_c)
            h_count = jnp.where(left_smaller, nl_c, cnt_c - nl_c)
            hist_small = hist_batched_fn(aux, h_start, h_count)
            hist_big = st["hist"][cand] - hist_small
            ls4 = left_smaller[:, None, None, None]
            new_left = jnp.where(ls4, hist_small, hist_big)
            new_right = jnp.where(ls4, hist_big, hist_small)

            # eval phase C: ONE fused split search over the 2K children
            res2 = find_split_batched(
                jnp.concatenate([new_left, new_right]),
                jnp.concatenate([lg_c, rg_c]),
                jnp.concatenate([lh_c, rh_c]),
                jnp.concatenate([lc_c, rc_c]))
            child_depth = st["leaf_depth"][cand] + 1
            if cfg.max_depth > 0:
                depth_ok = child_depth < cfg.max_depth
            else:
                depth_ok = jnp.ones(KB, jnp.bool_)
            gain_l = jnp.where(depth_ok, res2.gain[:KB], K_MIN_SCORE)
            gain_r = jnp.where(depth_ok, res2.gain[KB:], K_MIN_SCORE)
            lval_c = st["leaf_val"][cand]
            gain_stored = st["bgain"][cand]

            # commit phase: replay the sequential argmax order against the
            # evaluated window.  Small-state bookkeeping only (payload and
            # aux stay out of the carry); a pop outside the window — a
            # child created this round, an unevaluated leaf, exhausted
            # gain, or the leaf budget — ends the round.  `used` guards
            # against a committed candidate's id (now its LEFT child)
            # being popped again and replayed from the stale evaluation.
            st2 = {k_: v for k_, v in st.items()
                   if k_ not in ("payload", "aux")}

            def commit_body(k, carry):
                st2, used, stopped = carry
                best = jnp.argmax(st2["bgain"]).astype(jnp.int32)
                is_c = (cand == best) & active & ~used
                j = jnp.argmax(is_c).astype(jnp.int32)
                do = (is_c[j] & ~stopped & (st2["num_leaves"] < L)
                      & (st2["bgain"][best] > 0.0))
                stopped = stopped | ~do
                used = used.at[j].set(used[j] | do)
                s = st2["num_leaves"]
                s_c = jnp.minimum(s, L - 1)     # clamp no-op writes
                node = jnp.maximum(s - 1, 0)

                def set2(arr, vl, vr):
                    arr = arr.at[best].set(jnp.where(do, vl, arr[best]))
                    return arr.at[s_c].set(jnp.where(do, vr, arr[s_c]))

                def setn(arr, v):
                    return arr.at[node].set(jnp.where(do, v, arr[node]))

                start, nl = start_c[j], nl_c[j]
                st2["seg_start"] = set2(st2["seg_start"], start, start + nl)
                st2["seg_cnt"] = set2(st2["seg_cnt"], nl, cnt_c[j] - nl)
                st2["sum_g"] = set2(st2["sum_g"], lg_c[j], rg_c[j])
                st2["sum_h"] = set2(st2["sum_h"], lh_c[j], rh_c[j])
                st2["cnt"] = set2(st2["cnt"], lc_c[j], rc_c[j])
                st2["bgain"] = set2(st2["bgain"], gain_l[j], gain_r[j])
                st2["bfeat"] = set2(st2["bfeat"], res2.feature[j],
                                    res2.feature[KB + j])
                st2["bbin"] = set2(st2["bbin"], res2.threshold_bin[j],
                                   res2.threshold_bin[KB + j])
                st2["bdleft"] = set2(st2["bdleft"], res2.default_left[j],
                                     res2.default_left[KB + j])
                st2["blg"] = set2(st2["blg"], res2.left_sum_g[j],
                                  res2.left_sum_g[KB + j])
                st2["blh"] = set2(st2["blh"], res2.left_sum_h[j],
                                  res2.left_sum_h[KB + j])
                st2["blc"] = set2(st2["blc"], res2.left_count[j],
                                  res2.left_count[KB + j])
                st2["bcat"] = set2(st2["bcat"], res2.is_cat[j],
                                   res2.is_cat[KB + j])
                st2["bbitset"] = set2(st2["bbitset"], res2.cat_bitset[j],
                                      res2.cat_bitset[KB + j])
                st2["blo"] = set2(st2["blo"], res2.left_output[j],
                                  res2.left_output[KB + j])
                st2["bro"] = set2(st2["bro"], res2.right_output[j],
                                  res2.right_output[KB + j])
                st2["leaf_val"] = set2(st2["leaf_val"], blo_c[j], bro_c[j])
                st2["leaf_depth"] = set2(st2["leaf_depth"], child_depth[j],
                                         child_depth[j])
                st2["hist"] = st2["hist"].at[best].set(
                    jnp.where(do, new_left[j], st2["hist"][best]))
                st2["hist"] = st2["hist"].at[s_c].set(
                    jnp.where(do, new_right[j], st2["hist"][s_c]))

                # record the internal node (do_split's bookkeeping, with
                # the same round-start reads the sequential grower makes)
                st2["split_feature"] = setn(st2["split_feature"], feat_c[j])
                st2["split_bin"] = setn(st2["split_bin"], bbin_c[j])
                st2["split_gain"] = setn(st2["split_gain"], gain_stored[j])
                st2["default_left"] = setn(st2["default_left"], bdleft_c[j])
                st2["split_is_cat"] = setn(st2["split_is_cat"], bcat_c[j])
                st2["split_cat_bitset"] = setn(st2["split_cat_bitset"],
                                               bbitset_c[j])
                st2["internal_value"] = setn(st2["internal_value"], lval_c[j])
                st2["internal_count"] = setn(st2["internal_count"], pc_c[j])
                left_child = setn(st2["left_child"], ~best)
                right_child = setn(st2["right_child"], ~s)
                parent_node = st2["leaf_parent"][best]
                has_par = parent_node >= 0
                pn = jnp.maximum(parent_node, 0)
                was_left = left_child[pn] == ~best
                left_child = left_child.at[pn].set(
                    jnp.where(do & has_par & was_left, node, left_child[pn]))
                right_child = right_child.at[pn].set(
                    jnp.where(do & has_par & ~was_left, node,
                              right_child[pn]))
                st2["left_child"] = left_child
                st2["right_child"] = right_child
                st2["leaf_parent"] = set2(st2["leaf_parent"], node, node)
                st2["num_leaves"] = st2["num_leaves"] + do.astype(jnp.int32)
                return st2, used, stopped

            st2, committed, _ = lax.fori_loop(
                0, KB, commit_body,
                (st2, jnp.zeros(KB, jnp.bool_), jnp.bool_(False)))

            # commit pass C: copy the staged rows back for exactly the
            # splits that committed (count 0 skips the rest — their
            # payload rows were never touched).  Disjoint segments, so
            # slot order is free.
            def commit_part(j, pay):
                cnt = jnp.where(committed[j], cnt_c[j], 0)
                return seg.partition_segment_commit(
                    pay, aux, start_c[j], cnt, nl_c[j], blo_c[j], bro_c[j],
                    cols.value)

            payload = lax.fori_loop(0, KB, commit_part, payload)

            st2["rounds"] = st2["rounds"] + 1
            st2["payload"] = payload
            st2["aux"] = aux
            return st2

        if frontier_batched:
            st = lax.while_loop(loop_cond, round_body, state)
            split_rounds = st["rounds"]
        elif L > 1:
            st = lax.while_loop(loop_cond, body, state)
            split_rounds = st["num_leaves"] - 1
        else:
            st = state
            split_rounds = jnp.int32(0)

        leaf_value = jnp.where(
            (jnp.arange(L) == 0) & (st["num_leaves"] == 1),
            out_fn(st["sum_g"], st["sum_h"]), st["leaf_val"])
        tree = {
            "num_leaves": st["num_leaves"],
            # sequential device rounds this tree paid (== splits for the
            # sequential grower; < splits once frontier batching commits
            # more than one split per round) — bench telemetry
            "split_rounds": split_rounds.astype(jnp.int32),
            "leaf_value": leaf_value,
            "leaf_count": st["cnt"],
            "leaf_sum_g": st["sum_g"],
            "leaf_sum_h": st["sum_h"],
            "seg_start": st["seg_start"],
            "seg_cnt": st["seg_cnt"],
            "split_feature": st["split_feature"],
            "split_bin": st["split_bin"],
            "split_gain": st["split_gain"],
            "default_left": st["default_left"],
            "split_is_cat": st["split_is_cat"],
            "split_cat_bitset": st["split_cat_bitset"],
            "left_child": st["left_child"],
            "right_child": st["right_child"],
            "internal_value": st["internal_value"],
            "internal_count": st["internal_count"],
        }
        return tree, st["payload"], st["aux"]

    # payload/aux are donated: the training state is updated in place across
    # trees, never copied (HistogramPool-style buffer discipline without the
    # pointer juggling of feature_histogram.hpp:655-826)
    return xla_obs.jit(grow, site="grower2.partitioned",
                       donate_argnums=(0, 1)) if jit else grow
