"""Ranking metrics: NDCG@k and MAP@k.

Role parity with src/metric/rank_metric.hpp (NDCGMetric), map_metric.hpp
(MapMetric) and dcg_calculator.cpp.  Host-side numpy: metrics consume raw
scores fetched once per eval round, one per-query argsort per eval position.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..objective.rank import (check_rank_label, default_label_gain,
                              max_dcg_at_k, position_discounts)
from ..utils.log import Log


class RankMetric:
    """Shared query plumbing; query_weight = mean doc weight per query
    (metadata.cpp:464-472 LoadQueryWeights)."""
    is_higher_better = True
    multiclass = False

    def __init__(self, config, k: int):
        self.config = config
        self.k = int(k)

    def init(self, label, weight, query_boundaries=None) -> None:
        if query_boundaries is None:
            Log.fatal("The %s metric requires query information" % self.name)
        self.label = np.asarray(label, dtype=np.float64)
        self.qb = np.asarray(query_boundaries, dtype=np.int64)
        self.num_queries = len(self.qb) - 1
        if weight is None:
            self.query_weights = None
            self.sum_query_weights = float(self.num_queries)
        else:
            w = np.asarray(weight, dtype=np.float64)
            sums = np.add.reduceat(w, self.qb[:-1])
            self.query_weights = sums / np.maximum(np.diff(self.qb), 1)
            self.sum_query_weights = float(self.query_weights.sum())

    def _query_average(self, per_query: np.ndarray) -> float:
        if self.query_weights is not None:
            per_query = per_query * self.query_weights
        return float(per_query.sum() / self.sum_query_weights)


class NDCGAtK(RankMetric):
    def __init__(self, config, k: int):
        super().__init__(config, k)
        self.name = "ndcg@%d" % k
        gains = list(getattr(config, "label_gain", ()) or ())
        self.label_gain = np.asarray(gains, np.float64) if gains else default_label_gain()

    def init(self, label, weight, query_boundaries=None) -> None:
        super().init(label, weight, query_boundaries)
        check_rank_label(self.label, len(self.label_gain))
        self.inverse_max_dcg = np.zeros(self.num_queries)
        for qi in range(self.num_queries):
            lo, hi = int(self.qb[qi]), int(self.qb[qi + 1])
            mdcg = max_dcg_at_k(self.k, self.label[lo:hi], self.label_gain)
            # all-negative queries marked -1 -> scored as NDCG=1 (rank_metric.hpp:69-75)
            self.inverse_max_dcg[qi] = 1.0 / mdcg if mdcg > 0.0 else -1.0

    def eval(self, raw_score: np.ndarray, objective) -> float:
        score = np.asarray(raw_score, dtype=np.float64)
        out = np.zeros(self.num_queries)
        for qi in range(self.num_queries):
            lo, hi = int(self.qb[qi]), int(self.qb[qi + 1])
            if self.inverse_max_dcg[qi] <= 0.0:
                out[qi] = 1.0
                continue
            k = min(self.k, hi - lo)
            order = np.argsort(-score[lo:hi], kind="stable")[:k]
            disc = position_discounts(k)
            dcg = np.sum(self.label_gain[self.label[lo:hi][order].astype(np.int64)] * disc)
            out[qi] = dcg * self.inverse_max_dcg[qi]
        return self._query_average(out)


class MAPAtK(RankMetric):
    def __init__(self, config, k: int):
        super().__init__(config, k)
        self.name = "map@%d" % k

    def init(self, label, weight, query_boundaries=None) -> None:
        super().init(label, weight, query_boundaries)
        self.npos = np.add.reduceat((self.label > 0.5).astype(np.int64), self.qb[:-1])

    def eval(self, raw_score: np.ndarray, objective) -> float:
        score = np.asarray(raw_score, dtype=np.float64)
        out = np.zeros(self.num_queries)
        for qi in range(self.num_queries):
            lo, hi = int(self.qb[qi]), int(self.qb[qi + 1])
            npos = int(self.npos[qi])
            if npos <= 0:
                out[qi] = 1.0
                continue
            k = min(self.k, hi - lo)
            order = np.argsort(-score[lo:hi], kind="stable")[:k]
            hits = self.label[lo:hi][order] > 0.5
            cum_hits = np.cumsum(hits)
            ap = np.sum(np.where(hits, cum_hits / (np.arange(k) + 1.0), 0.0))
            out[qi] = ap / min(npos, k)
        return self._query_average(out)
