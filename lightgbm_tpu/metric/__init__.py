"""Metrics — role parity with src/metric/ (factory at metric.cpp:11-56).

Host-side numpy implementations operating on raw scores; each returns
(name, value, is_higher_better).  The full zoo (NDCG, MAP, ...) lands with M2.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..utils.log import Log


class Metric:
    name = "metric"
    is_higher_better = False
    multiclass = False  # True -> eval() receives the full [K, N] score matrix

    def __init__(self, config):
        self.config = config

    def init(self, label: np.ndarray, weight: Optional[np.ndarray],
             query_boundaries: Optional[np.ndarray] = None) -> None:
        if label is None:
            Log.fatal("Label should not be None for metric evaluation")
        self.label = np.asarray(label, dtype=np.float64)
        self.weight = None if weight is None else np.asarray(weight, dtype=np.float64)
        self.sum_weight = float(len(self.label)) if self.weight is None \
            else float(np.sum(self.weight))

    def _wmean(self, values: np.ndarray) -> float:
        if self.weight is None:
            return float(np.mean(values))
        return float(np.sum(values * self.weight) / self.sum_weight)

    def eval(self, raw_score: np.ndarray, objective) -> float:
        raise NotImplementedError


class L2Metric(Metric):
    name = "l2"

    def eval(self, raw_score, objective) -> float:
        pred = objective.convert_output(raw_score) if objective is not None else raw_score
        return self._wmean((self.label - pred) ** 2)


class RMSEMetric(L2Metric):
    name = "rmse"

    def eval(self, raw_score, objective) -> float:
        return float(np.sqrt(super().eval(raw_score, objective)))


class L1Metric(Metric):
    name = "l1"

    def eval(self, raw_score, objective) -> float:
        pred = objective.convert_output(raw_score) if objective is not None else raw_score
        return self._wmean(np.abs(self.label - pred))


class QuantileMetric(Metric):
    """Pinball loss (regression_metric.hpp:141-158)."""
    name = "quantile"

    def eval(self, raw_score, objective) -> float:
        pred = objective.convert_output(raw_score) if objective is not None else raw_score
        alpha = float(getattr(self.config, "alpha", 0.9))
        delta = self.label - pred
        return self._wmean(np.where(delta < 0, (alpha - 1.0) * delta, alpha * delta))


class HuberMetric(Metric):
    """Huber loss (regression_metric.hpp:175-192)."""
    name = "huber"

    def eval(self, raw_score, objective) -> float:
        pred = objective.convert_output(raw_score) if objective is not None else raw_score
        a = float(getattr(self.config, "alpha", 0.9))
        diff = pred - self.label
        loss = np.where(np.abs(diff) <= a, 0.5 * diff * diff,
                        a * (np.abs(diff) - 0.5 * a))
        return self._wmean(loss)


class FairMetric(Metric):
    """Fair loss (regression_metric.hpp:196-210)."""
    name = "fair"

    def eval(self, raw_score, objective) -> float:
        pred = objective.convert_output(raw_score) if objective is not None else raw_score
        c = float(getattr(self.config, "fair_c", 1.0))
        x = np.abs(pred - self.label)
        return self._wmean(c * x - c * c * np.log(1.0 + x / c))


class PoissonMetric(Metric):
    """Poisson negative log-likelihood (regression_metric.hpp:213-228)."""
    name = "poisson"

    def eval(self, raw_score, objective) -> float:
        pred = objective.convert_output(raw_score) if objective is not None else raw_score
        pred = np.maximum(pred, 1e-10)
        return self._wmean(pred - self.label * np.log(pred))


class MAPEMetric(Metric):
    """MAPE with |label| clamped to >= 1 (regression_metric.hpp:232-243)."""
    name = "mape"

    def eval(self, raw_score, objective) -> float:
        pred = objective.convert_output(raw_score) if objective is not None else raw_score
        return self._wmean(np.abs(self.label - pred) / np.maximum(1.0, np.abs(self.label)))


class GammaMetric(Metric):
    """Gamma negative log-likelihood with psi=1 (regression_metric.hpp:245-261);
    at psi=1 the reference formula reduces to label/pred + log(pred)."""
    name = "gamma"

    def eval(self, raw_score, objective) -> float:
        pred = objective.convert_output(raw_score) if objective is not None else raw_score
        return self._wmean(self.label / pred + np.log(pred))


class GammaDevianceMetric(Metric):
    """2 * sum(label/pred - log(label/pred) - 1); a sum, not a weighted mean
    (regression_metric.hpp:264-279, AverageLoss override)."""
    name = "gamma-deviance"

    def eval(self, raw_score, objective) -> float:
        pred = objective.convert_output(raw_score) if objective is not None else raw_score
        tmp = self.label / (pred + 1e-9)
        loss = tmp - np.log(tmp) - 1.0
        if self.weight is not None:
            loss = loss * self.weight
        return float(2.0 * np.sum(loss))


class TweedieMetric(Metric):
    """Tweedie deviance-like loss (regression_metric.hpp:282-299)."""
    name = "tweedie"

    def eval(self, raw_score, objective) -> float:
        pred = objective.convert_output(raw_score) if objective is not None else raw_score
        rho = float(getattr(self.config, "tweedie_variance_power", 1.5))
        pred = np.maximum(pred, 1e-10)
        a = self.label * np.exp((1.0 - rho) * np.log(pred)) / (1.0 - rho)
        b = np.exp((2.0 - rho) * np.log(pred)) / (2.0 - rho)
        return self._wmean(-a + b)


class BinaryLoglossMetric(Metric):
    name = "binary_logloss"

    def eval(self, raw_score, objective) -> float:
        # objective is None (custom fobj): score is already a probability
        # (reference binary_metric.hpp Eval, objective==nullptr branch)
        prob = objective.convert_output(raw_score) if objective is not None else raw_score
        prob = np.clip(prob, 1e-15, 1.0 - 1e-15)
        loss = -(self.label * np.log(prob) + (1.0 - self.label) * np.log(1.0 - prob))
        return self._wmean(loss)


class BinaryErrorMetric(Metric):
    name = "binary_error"

    def eval(self, raw_score, objective) -> float:
        prob = objective.convert_output(raw_score) if objective is not None else raw_score
        return self._wmean(((prob > 0.5) != (self.label > 0)).astype(np.float64))


class AUCMetric(Metric):
    name = "auc"
    is_higher_better = True

    def eval(self, raw_score, objective) -> float:
        """Weighted ROC-AUC by rank accumulation, tie-aware
        (src/metric/binary_metric.hpp AUCMetric semantics)."""
        order = np.argsort(raw_score, kind="mergesort")
        score = raw_score[order]
        label = self.label[order]
        w = np.ones_like(label) if self.weight is None else self.weight[order]
        pos_w = np.where(label > 0, w, 0.0)
        neg_w = np.where(label > 0, 0.0, w)
        boundary = np.nonzero(np.diff(score))[0]
        seg_id = np.zeros(len(score), dtype=np.int64)
        seg_id[boundary + 1] = 1
        seg_id = np.cumsum(seg_id)
        nseg = int(seg_id[-1]) + 1 if len(score) else 0
        pos_per = np.bincount(seg_id, weights=pos_w, minlength=nseg)
        neg_per = np.bincount(seg_id, weights=neg_w, minlength=nseg)
        cum_neg_before = np.concatenate([[0.0], np.cumsum(neg_per)[:-1]])
        auc_sum = np.sum(pos_per * (cum_neg_before + 0.5 * neg_per))
        total_pos = pos_per.sum()
        total_neg = neg_per.sum()
        if total_pos <= 0 or total_neg <= 0:
            Log.warning("AUC undefined: data contains one class only")
            return 1.0
        return float(auc_sum / (total_pos * total_neg))


def _xent_loss(label: np.ndarray, prob: np.ndarray) -> np.ndarray:
    """XentLoss with the reference's 1e-12 log-argument clamp
    (xentropy_metric.hpp:31-46)."""
    eps = 1.0e-12
    a = label * np.log(np.maximum(prob, eps))
    b = (1.0 - label) * np.log(np.maximum(1.0 - prob, eps))
    return -(a + b)


class CrossEntropyMetric(Metric):
    """xentropy: weighted mean of XentLoss over p = ConvertOutput(score)
    (xentropy_metric.hpp:67-146)."""
    name = "xentropy"

    def eval(self, raw_score, objective) -> float:
        p = objective.convert_output(raw_score) if objective is not None else raw_score
        return self._wmean(_xent_loss(self.label, p))


class CrossEntropyLambdaMetric(Metric):
    """xentlambda: XentLoss on p = 1 - exp(-w * hhat), averaged over #data
    regardless of weights (xentropy_metric.hpp:162-221)."""
    name = "xentlambda"

    def eval(self, raw_score, objective) -> float:
        hhat = objective.convert_output(raw_score) if objective is not None \
            else np.log1p(np.exp(raw_score))
        w = self.weight if self.weight is not None else 1.0
        p = 1.0 - np.exp(-w * hhat)
        return float(np.mean(_xent_loss(self.label, p)))


class KLDivergenceMetric(Metric):
    """kldiv: cross-entropy plus the precomputed label-entropy offset
    (xentropy_metric.hpp:246-340)."""
    name = "kldiv"

    def init(self, label, weight, query_boundaries=None) -> None:
        super().init(label, weight, query_boundaries)
        p = self.label
        hp = np.where(p > 0, p * np.log(np.maximum(p, 1e-300)), 0.0) + \
            np.where(1.0 - p > 0, (1.0 - p) * np.log(np.maximum(1.0 - p, 1e-300)), 0.0)
        self.presum_label_entropy = self._wmean(hp)

    def eval(self, raw_score, objective) -> float:
        p = objective.convert_output(raw_score) if objective is not None else raw_score
        return self.presum_label_entropy + self._wmean(_xent_loss(self.label, p))


class MultiLoglossMetric(Metric):
    """Softmax logloss over [K, N] raw scores (multiclass_metric.hpp
    MultiSoftmaxLoglossMetric)."""
    name = "multi_logloss"
    multiclass = True

    def eval(self, raw_score, objective) -> float:
        # raw_score [K, N] -> probabilities [N, K] via the objective transform
        raw = np.asarray(raw_score, dtype=np.float64).T
        prob = objective.convert_output(raw) if objective is not None else raw
        k = self.label.astype(np.int64)
        p = prob[np.arange(len(k)), k]
        return self._wmean(-np.log(np.maximum(p, 1e-15)))


class MultiErrorMetric(Metric):
    """Top-1 error with the reference's tie rule: any other class with
    score >= the true class counts as an error (multiclass_metric.hpp
    MultiErrorMetric)."""
    name = "multi_error"
    multiclass = True

    def eval(self, raw_score, objective) -> float:
        raw = np.asarray(raw_score, dtype=np.float64).T
        prob = objective.convert_output(raw) if objective is not None else raw
        k = self.label.astype(np.int64)
        true_p = prob[np.arange(len(k)), k]
        others = prob.copy()
        others[np.arange(len(k)), k] = -np.inf
        err = (np.max(others, axis=1) >= true_p).astype(np.float64)
        return self._wmean(err)


from .rank import MAPAtK, NDCGAtK

_REGISTRY = {
    "l1": L1Metric, "l2": L2Metric, "rmse": RMSEMetric,
    "multi_logloss": MultiLoglossMetric, "multi_error": MultiErrorMetric,
    "quantile": QuantileMetric, "huber": HuberMetric, "fair": FairMetric,
    "poisson": PoissonMetric, "mape": MAPEMetric,
    "gamma": GammaMetric, "gamma_deviance": GammaDevianceMetric,
    "tweedie": TweedieMetric,
    "binary_logloss": BinaryLoglossMetric, "binary_error": BinaryErrorMetric,
    "auc": AUCMetric,
    "xentropy": CrossEntropyMetric, "xentlambda": CrossEntropyLambdaMetric,
    "kldiv": KLDivergenceMetric,
}


_RANK_METRICS = {"ndcg": NDCGAtK, "map": MAPAtK}


def _eval_positions(config) -> List[int]:
    """eval_at with the reference default 1..5 (DCGCalculator::DefaultEvalAt)."""
    at = list(getattr(config, "eval_at", ()) or ())
    return [int(k) for k in at] if at else [1, 2, 3, 4, 5]


def create_metric(name: str, config) -> Optional[Metric]:
    cls = _REGISTRY.get(name)
    if cls is None:
        Log.warning("Unknown metric type name: %s", name)
        return None
    return cls(config)


def create_metrics(names, config) -> List:
    """Expand metric names into instances; rank metrics ('ndcg', 'map',
    'ndcg@3') expand over eval_at positions (rank_metric.hpp:20, metric.cpp)."""
    out: List = []
    for name in names:
        base, _, at = str(name).partition("@")
        if base in _RANK_METRICS:
            cls = _RANK_METRICS[base]
            try:
                ks = [int(k) for k in at.split(",")] if at else _eval_positions(config)
            except ValueError:
                Log.warning("Unknown metric type name: %s", name)
                continue
            out.extend(cls(config, k) for k in ks)
        else:
            m = create_metric(name, config)
            if m is not None:
                out.append(m)
    return out
