"""ctypes loader for the native C API (cpp/lightgbm_tpu_c_api.h).

The shared library is the deployment-side runtime (model load + predict in
pure C++, no Python/JAX needed); this module is the convenience bridge for
Python callers and the test suite.  Build with `make -C cpp` (or
`ensure_built()`), which needs only g++.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

from .utils.log import LightGBMError

_CPP_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                        "cpp")
_LIB_PATH = os.path.join(_CPP_DIR, "lib_lightgbm_tpu.so")
_TRAIN_LIB_PATH = os.path.join(_CPP_DIR, "lib_lightgbm_tpu_train.so")

C_API_DTYPE_FLOAT32 = 0
C_API_DTYPE_FLOAT64 = 1
C_API_DTYPE_INT32 = 2
C_API_DTYPE_INT64 = 3
C_API_PREDICT_NORMAL = 0
C_API_PREDICT_RAW_SCORE = 1
C_API_PREDICT_LEAF_INDEX = 2
C_API_FEATURE_IMPORTANCE_SPLIT = 0
C_API_FEATURE_IMPORTANCE_GAIN = 1

_lib: Optional[ctypes.CDLL] = None


def ensure_built() -> str:
    """Build (or freshen) the shared library; returns its path.  make is
    a no-op when the .so is newer than the sources, so running it
    unconditionally keeps stale pre-built libraries from being loaded."""
    subprocess.run(["make", "-C", _CPP_DIR], check=True,
                   capture_output=True)
    return _LIB_PATH


def load_lib() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(ensure_built())
        lib.LGBM_GetLastError.restype = ctypes.c_char_p
        _lib = lib
    return _lib


def _check(rc: int) -> None:
    if rc != 0:
        raise LightGBMError(load_lib().LGBM_GetLastError().decode())


_train_lib: Optional[ctypes.CDLL] = None


def load_train_lib() -> ctypes.CDLL:
    """The TRAINING-side library (embedded-CPython ABI).  Its dlopen pulls
    the base prediction lib via $ORIGIN rpath and registers the dispatch
    hooks, so symbols from BOTH surfaces resolve through this handle."""
    global _train_lib
    if _train_lib is None:
        ensure_built()
        lib = ctypes.CDLL(_TRAIN_LIB_PATH)
        lib.LGBM_GetLastError.restype = ctypes.c_char_p
        _train_lib = lib
    return _train_lib


def _check_train(rc: int) -> None:
    if rc != 0:
        raise LightGBMError(load_train_lib().LGBM_GetLastError().decode())


def booster_reset_parameter(handle, parameters: str) -> None:
    """LGBM_BoosterResetParameter over a raw training BoosterHandle:
    live-apply "key=value ..." parameters (e.g. learning_rate) so they
    take effect on the next LGBM_BoosterUpdateOneIter."""
    _check_train(load_train_lib().LGBM_BoosterResetParameter(
        handle, parameters.encode()))


def booster_refit(handle, X: np.ndarray, y: np.ndarray) -> None:
    """LGBM_BoosterRefit over a raw training BoosterHandle: keep every
    split, refit leaf values to (X, y) — the handle's model is replaced
    in place (reference Booster.refit semantics, adapted signature: the
    data travels directly instead of pre-computed leaf assignments)."""
    X = np.ascontiguousarray(X, dtype=np.float64)
    y = np.ascontiguousarray(y, dtype=np.float32).reshape(-1)
    nrow, ncol = X.shape
    if y.size != nrow:
        raise LightGBMError("label length %d != nrow %d" % (y.size, nrow))
    _check_train(load_train_lib().LGBM_BoosterRefit(
        handle, X.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_int32(nrow), ctypes.c_int32(ncol)))


def network_init(machines: str, local_listen_port: int = 12400,
                 listen_time_out: int = 120, num_machines: int = 1) -> None:
    """LGBM_NetworkInit: reference machine-list bootstrap (maps onto
    jax.distributed — docs/DISTRIBUTED.md)."""
    _check_train(load_train_lib().LGBM_NetworkInit(
        machines.encode(), ctypes.c_int(local_listen_port),
        ctypes.c_int(listen_time_out), ctypes.c_int(num_machines)))


def network_free() -> None:
    """LGBM_NetworkFree (idempotent, reference Network::Dispose)."""
    _check_train(load_train_lib().LGBM_NetworkFree())


def _dtype_code(arr: np.ndarray) -> int:
    code = {np.dtype(np.float32): C_API_DTYPE_FLOAT32,
            np.dtype(np.float64): C_API_DTYPE_FLOAT64,
            np.dtype(np.int32): C_API_DTYPE_INT32,
            np.dtype(np.int64): C_API_DTYPE_INT64}.get(arr.dtype)
    if code is None:
        raise LightGBMError("unsupported dtype %s" % arr.dtype)
    return code


class TrainDataset:
    """ctypes handle over the training-side LGBM_Dataset* surface,
    including the zero-copy streaming ingest block (ISSUE 8):
    CreateFromMat/CSR/CSC/File, CreateByReference + PushRows[ByCSR],
    GetSubset, SaveBinary and the feature-name accessors."""

    def __init__(self, handle: ctypes.c_void_p):
        self._handle = handle

    def __del__(self):
        if getattr(self, "_handle", None):
            load_train_lib().LGBM_DatasetFree(self._handle)
            self._handle = None

    # -- constructors --------------------------------------------------------
    @staticmethod
    def _ref_handle(reference: Optional["TrainDataset"]):
        return reference._handle if reference is not None else None

    @classmethod
    def from_mat(cls, X: np.ndarray, params: str = "",
                 reference: Optional["TrainDataset"] = None) -> "TrainDataset":
        X = np.ascontiguousarray(X)
        if X.dtype not in (np.float32, np.float64):
            X = np.ascontiguousarray(X, dtype=np.float64)
        h = ctypes.c_void_p()
        _check_train(load_train_lib().LGBM_DatasetCreateFromMat(
            X.ctypes.data_as(ctypes.c_void_p), _dtype_code(X),
            ctypes.c_int32(X.shape[0]), ctypes.c_int32(X.shape[1]), 1,
            params.encode(), cls._ref_handle(reference), ctypes.byref(h)))
        return cls(h)

    @classmethod
    def from_csr(cls, indptr, indices, values, num_col: int,
                 params: str = "",
                 reference: Optional["TrainDataset"] = None) -> "TrainDataset":
        indptr = np.ascontiguousarray(indptr)
        if indptr.dtype not in (np.int32, np.int64):
            indptr = np.ascontiguousarray(indptr, dtype=np.int64)
        indices = np.ascontiguousarray(indices, dtype=np.int32)
        values = np.ascontiguousarray(values)
        if values.dtype not in (np.float32, np.float64):
            values = np.ascontiguousarray(values, dtype=np.float64)
        h = ctypes.c_void_p()
        _check_train(load_train_lib().LGBM_DatasetCreateFromCSR(
            indptr.ctypes.data_as(ctypes.c_void_p), _dtype_code(indptr),
            indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            values.ctypes.data_as(ctypes.c_void_p), _dtype_code(values),
            ctypes.c_int64(len(indptr)), ctypes.c_int64(len(values)),
            ctypes.c_int64(num_col), params.encode(),
            cls._ref_handle(reference), ctypes.byref(h)))
        return cls(h)

    @classmethod
    def from_csc(cls, col_ptr, indices, values, num_row: int,
                 params: str = "",
                 reference: Optional["TrainDataset"] = None) -> "TrainDataset":
        col_ptr = np.ascontiguousarray(col_ptr)
        if col_ptr.dtype not in (np.int32, np.int64):
            col_ptr = np.ascontiguousarray(col_ptr, dtype=np.int64)
        indices = np.ascontiguousarray(indices, dtype=np.int32)
        values = np.ascontiguousarray(values)
        if values.dtype not in (np.float32, np.float64):
            values = np.ascontiguousarray(values, dtype=np.float64)
        h = ctypes.c_void_p()
        _check_train(load_train_lib().LGBM_DatasetCreateFromCSC(
            col_ptr.ctypes.data_as(ctypes.c_void_p), _dtype_code(col_ptr),
            indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            values.ctypes.data_as(ctypes.c_void_p), _dtype_code(values),
            ctypes.c_int64(len(col_ptr)), ctypes.c_int64(len(values)),
            ctypes.c_int64(num_row), params.encode(),
            cls._ref_handle(reference), ctypes.byref(h)))
        return cls(h)

    @classmethod
    def from_file(cls, path: str, params: str = "",
                  reference: Optional["TrainDataset"] = None) -> "TrainDataset":
        h = ctypes.c_void_p()
        _check_train(load_train_lib().LGBM_DatasetCreateFromFile(
            path.encode(), params.encode(), cls._ref_handle(reference),
            ctypes.byref(h)))
        return cls(h)

    @classmethod
    def by_reference(cls, reference: "TrainDataset",
                     num_total_rows: int) -> "TrainDataset":
        h = ctypes.c_void_p()
        _check_train(load_train_lib().LGBM_DatasetCreateByReference(
            reference._handle, ctypes.c_int64(num_total_rows),
            ctypes.byref(h)))
        return cls(h)

    # -- streaming push ------------------------------------------------------
    def push_rows(self, X: np.ndarray, start_row: int) -> "TrainDataset":
        X = np.ascontiguousarray(X)
        if X.dtype not in (np.float32, np.float64):
            X = np.ascontiguousarray(X, dtype=np.float64)
        _check_train(load_train_lib().LGBM_DatasetPushRows(
            self._handle, X.ctypes.data_as(ctypes.c_void_p), _dtype_code(X),
            ctypes.c_int32(X.shape[0]), ctypes.c_int32(X.shape[1]),
            ctypes.c_int32(start_row)))
        return self

    def push_rows_csr(self, indptr, indices, values, num_col: int,
                      start_row: int) -> "TrainDataset":
        indptr = np.ascontiguousarray(indptr)
        if indptr.dtype not in (np.int32, np.int64):
            indptr = np.ascontiguousarray(indptr, dtype=np.int64)
        indices = np.ascontiguousarray(indices, dtype=np.int32)
        values = np.ascontiguousarray(values)
        if values.dtype not in (np.float32, np.float64):
            values = np.ascontiguousarray(values, dtype=np.float64)
        _check_train(load_train_lib().LGBM_DatasetPushRowsByCSR(
            self._handle, indptr.ctypes.data_as(ctypes.c_void_p),
            _dtype_code(indptr),
            indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            values.ctypes.data_as(ctypes.c_void_p), _dtype_code(values),
            ctypes.c_int64(len(indptr)), ctypes.c_int64(len(values)),
            ctypes.c_int64(num_col), ctypes.c_int64(start_row)))
        return self

    # -- surface -------------------------------------------------------------
    @classmethod
    def from_mats(cls, mats, params: str = "",
                  reference: Optional["TrainDataset"] = None
                  ) -> "TrainDataset":
        """LGBM_DatasetCreateFromMats: concatenate row blocks sharing a
        column count into one dataset."""
        blocks = [np.ascontiguousarray(m, dtype=np.float64) for m in mats]
        ncol = blocks[0].shape[1]
        ptrs = (ctypes.c_void_p * len(blocks))(
            *[b.ctypes.data_as(ctypes.c_void_p).value for b in blocks])
        rows = (ctypes.c_int32 * len(blocks))(
            *[b.shape[0] for b in blocks])
        h = ctypes.c_void_p()
        _check_train(load_train_lib().LGBM_DatasetCreateFromMats(
            ctypes.c_int32(len(blocks)), ptrs, C_API_DTYPE_FLOAT64, rows,
            ctypes.c_int32(ncol), 1, params.encode(),
            cls._ref_handle(reference), ctypes.byref(h)))
        return cls(h)

    def set_field(self, name: str, data) -> "TrainDataset":
        arr = np.ascontiguousarray(data)
        if arr.dtype not in (np.float32, np.float64, np.int32, np.int64):
            arr = np.ascontiguousarray(arr, dtype=np.float32)
        _check_train(load_train_lib().LGBM_DatasetSetField(
            self._handle, name.encode(),
            arr.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(arr.size),
            _dtype_code(arr)))
        return self

    def get_field(self, name: str) -> np.ndarray:
        """LGBM_DatasetGetField: label/weight as float32, init_score as
        float64, group as CUMULATIVE int32 query boundaries (the
        reference layout).  The returned array is a COPY — the C buffer
        is only valid until the next get_field call on this handle."""
        out_len = ctypes.c_int(0)
        out_ptr = ctypes.c_void_p()
        out_type = ctypes.c_int(-1)
        _check_train(load_train_lib().LGBM_DatasetGetField(
            self._handle, name.encode(), ctypes.byref(out_len),
            ctypes.byref(out_ptr), ctypes.byref(out_type)))
        dt = {C_API_DTYPE_FLOAT32: np.float32,
              C_API_DTYPE_FLOAT64: np.float64,
              C_API_DTYPE_INT32: np.int32,
              C_API_DTYPE_INT64: np.int64}[out_type.value]
        n = out_len.value
        buf = ctypes.cast(out_ptr,
                          ctypes.POINTER(ctypes.c_char * (n * dt().nbytes)))
        return np.frombuffer(bytes(buf.contents), dtype=dt).copy()

    def feature_num_bin(self, feature_idx: int) -> int:
        """LGBM_DatasetGetFeatureNumBin: bins of one constructed
        feature."""
        out = ctypes.c_int32(0)
        _check_train(load_train_lib().LGBM_DatasetGetFeatureNumBin(
            self._handle, ctypes.c_int(feature_idx), ctypes.byref(out)))
        return out.value

    @property
    def num_data(self) -> int:
        out = ctypes.c_int32(0)
        _check_train(load_train_lib().LGBM_DatasetGetNumData(
            self._handle, ctypes.byref(out)))
        return out.value

    @property
    def num_feature(self) -> int:
        out = ctypes.c_int32(0)
        _check_train(load_train_lib().LGBM_DatasetGetNumFeature(
            self._handle, ctypes.byref(out)))
        return out.value

    def get_subset(self, used_indices, params: str = "") -> "TrainDataset":
        idx = np.ascontiguousarray(used_indices, dtype=np.int32)
        h = ctypes.c_void_p()
        _check_train(load_train_lib().LGBM_DatasetGetSubset(
            self._handle, idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ctypes.c_int32(len(idx)), params.encode(), ctypes.byref(h)))
        return TrainDataset(h)

    def save_binary(self, path: str) -> "TrainDataset":
        _check_train(load_train_lib().LGBM_DatasetSaveBinary(
            self._handle, path.encode()))
        return self

    def dump_text(self, path: str) -> "TrainDataset":
        """LGBM_DatasetDumpText: debug dump — self-describing header
        (num_data/num_features/feature names/bin counts/label presence)
        followed by the post-bundling integer bin matrix, one row per
        data row."""
        _check_train(load_train_lib().LGBM_DatasetDumpText(
            self._handle, path.encode()))
        return self

    def set_feature_names(self, names) -> "TrainDataset":
        arr = (ctypes.c_char_p * len(names))(
            *[str(n).encode() for n in names])
        _check_train(load_train_lib().LGBM_DatasetSetFeatureNames(
            self._handle, arr, ctypes.c_int(len(names))))
        return self

    def get_feature_names(self) -> list:
        n = self.num_feature
        bufs = [ctypes.create_string_buffer(128) for _ in range(n)]
        arr = (ctypes.c_char_p * n)(
            *[ctypes.cast(b, ctypes.c_char_p) for b in bufs])
        out_n = ctypes.c_int(0)
        _check_train(load_train_lib().LGBM_DatasetGetFeatureNames(
            self._handle, arr, ctypes.byref(out_n)))
        return [bufs[i].value.decode() for i in range(out_n.value)]


class TrainBooster:
    """ctypes handle over the training-side Booster surface
    (LGBM_BoosterCreate / AddValidData / UpdateOneIter[Custom] /
    RollbackOneIter / GetEval*); model IO and predict flow through the
    shared BoosterHandle entry points (NativeBooster's surface works on
    training handles too)."""

    def __init__(self, train_set: TrainDataset, params: str = ""):
        self._train_set = train_set           # keep the dataset alive
        self._handle = ctypes.c_void_p()
        _check_train(load_train_lib().LGBM_BoosterCreate(
            train_set._handle, params.encode(), ctypes.byref(self._handle)))

    def __del__(self):
        if getattr(self, "_handle", None):
            load_train_lib().LGBM_BoosterFree(self._handle)
            self._handle = None

    def add_valid(self, valid_set: TrainDataset) -> "TrainBooster":
        _check_train(load_train_lib().LGBM_BoosterAddValidData(
            self._handle, valid_set._handle))
        return self

    def update(self) -> bool:
        fin = ctypes.c_int(0)
        _check_train(load_train_lib().LGBM_BoosterUpdateOneIter(
            self._handle, ctypes.byref(fin)))
        return bool(fin.value)

    def update_custom(self, grad: np.ndarray, hess: np.ndarray) -> bool:
        g = np.ascontiguousarray(grad, dtype=np.float32)
        h = np.ascontiguousarray(hess, dtype=np.float32)
        fin = ctypes.c_int(0)
        _check_train(load_train_lib().LGBM_BoosterUpdateOneIterCustom(
            self._handle, g.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            h.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            ctypes.byref(fin)))
        return bool(fin.value)

    def rollback_one_iter(self) -> "TrainBooster":
        _check_train(load_train_lib().LGBM_BoosterRollbackOneIter(
            self._handle))
        return self

    @property
    def current_iteration(self) -> int:
        out = ctypes.c_int(0)
        _check_train(load_train_lib().LGBM_BoosterGetCurrentIteration(
            self._handle, ctypes.byref(out)))
        return out.value

    def eval_counts(self) -> int:
        out = ctypes.c_int(0)
        _check_train(load_train_lib().LGBM_BoosterGetEvalCounts(
            self._handle, ctypes.byref(out)))
        return out.value

    def eval_names(self) -> list:
        n = self.eval_counts()
        bufs = [ctypes.create_string_buffer(128) for _ in range(n)]
        arr = (ctypes.c_char_p * n)(
            *[ctypes.cast(b, ctypes.c_char_p) for b in bufs])
        out_n = ctypes.c_int(0)
        _check_train(load_train_lib().LGBM_BoosterGetEvalNames(
            self._handle, ctypes.byref(out_n), arr))
        return [bufs[i].value.decode() for i in range(out_n.value)]

    def get_eval(self, data_idx: int = 0) -> np.ndarray:
        n = self.eval_counts()
        out = np.zeros(max(n, 1), dtype=np.float64)
        out_len = ctypes.c_int(0)
        _check_train(load_train_lib().LGBM_BoosterGetEval(
            self._handle, ctypes.c_int(data_idx), ctypes.byref(out_len),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
        return out[: out_len.value]

    def model_to_string(self, num_iteration: int = -1) -> str:
        lib = load_train_lib()
        out_len = ctypes.c_int64(0)
        _check_train(lib.LGBM_BoosterSaveModelToString(
            self._handle, num_iteration, 0, ctypes.byref(out_len), None))
        buf = ctypes.create_string_buffer(out_len.value)
        _check_train(lib.LGBM_BoosterSaveModelToString(
            self._handle, num_iteration, out_len.value,
            ctypes.byref(out_len), buf))
        return buf.value.decode()

    # -- inner prediction buffer (reference GetNumPredict/GetPredict) --------
    def num_predict(self, data_idx: int = 0) -> int:
        """LGBM_BoosterGetNumPredict: size of the engine's current score
        buffer for the training data (0) or the data_idx-th valid set."""
        out = ctypes.c_int64(0)
        _check_train(load_train_lib().LGBM_BoosterGetNumPredict(
            self._handle, ctypes.c_int(data_idx), ctypes.byref(out)))
        return out.value

    def get_predict(self, data_idx: int = 0) -> np.ndarray:
        """LGBM_BoosterGetPredict: the incrementally-maintained scores
        with the objective transform applied, [num_class, num_data]
        (class-major, the reference GetPredictAt layout); squeezed to
        [num_data] for single-output objectives."""
        n = self.num_predict(data_idx)
        out = np.zeros(max(n, 1), dtype=np.float64)
        out_len = ctypes.c_int64(0)
        _check_train(load_train_lib().LGBM_BoosterGetPredict(
            self._handle, ctypes.c_int(data_idx), ctypes.byref(out_len),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
        out = out[: out_len.value]
        k = max(self.num_class, 1)
        return out.reshape(k, -1) if k > 1 else out

    @property
    def num_class(self) -> int:
        out = ctypes.c_int(0)
        _check_train(load_train_lib().LGBM_BoosterGetNumClasses(
            self._handle, ctypes.byref(out)))
        return out.value

    def calc_num_predict(self, num_row: int, predict_type: int = 0,
                         num_iteration: int = -1) -> int:
        """LGBM_BoosterCalcNumPredict: doubles a predict over num_row
        rows will write (works on training AND loaded boosters)."""
        out = ctypes.c_int64(0)
        _check_train(load_train_lib().LGBM_BoosterCalcNumPredict(
            self._handle, ctypes.c_int(num_row),
            ctypes.c_int(predict_type), ctypes.c_int(num_iteration),
            ctypes.byref(out)))
        return out.value


class NativeBooster:
    """Minimal handle over the C API, mirroring Booster's predict surface."""

    def __init__(self, model_file: Optional[str] = None,
                 model_str: Optional[str] = None):
        lib = load_lib()
        self._handle = ctypes.c_void_p()
        out_iters = ctypes.c_int(0)
        if model_file is not None:
            _check(lib.LGBM_BoosterCreateFromModelfile(
                model_file.encode(), ctypes.byref(out_iters),
                ctypes.byref(self._handle)))
        elif model_str is not None:
            _check(lib.LGBM_BoosterLoadModelFromString(
                model_str.encode(), ctypes.byref(out_iters),
                ctypes.byref(self._handle)))
        else:
            raise ValueError("model_file or model_str required")
        self.num_iterations = out_iters.value

    def __del__(self):
        if getattr(self, "_handle", None):
            load_lib().LGBM_BoosterFree(self._handle)
            self._handle = None

    @property
    def num_class(self) -> int:
        out = ctypes.c_int(0)
        _check(load_lib().LGBM_BoosterGetNumClasses(self._handle,
                                                    ctypes.byref(out)))
        return out.value

    @property
    def num_feature(self) -> int:
        out = ctypes.c_int(0)
        _check(load_lib().LGBM_BoosterGetNumFeature(self._handle,
                                                    ctypes.byref(out)))
        return out.value

    @property
    def num_model_per_iteration(self) -> int:
        """Trees per iteration (LGBM_BoosterNumModelPerIteration): 1 for
        binary/regression, num_class for multiclass."""
        out = ctypes.c_int(0)
        _check(load_lib().LGBM_BoosterNumModelPerIteration(
            self._handle, ctypes.byref(out)))
        return out.value

    @property
    def current_iteration(self) -> int:
        """Completed iterations (LGBM_BoosterGetCurrentIteration)."""
        out = ctypes.c_int(0)
        _check(load_lib().LGBM_BoosterGetCurrentIteration(
            self._handle, ctypes.byref(out)))
        return out.value

    @property
    def num_total_model(self) -> int:
        """Total trees in the booster (LGBM_BoosterNumberOfTotalModel):
        iterations x trees-per-iteration."""
        out = ctypes.c_int(0)
        _check(load_lib().LGBM_BoosterNumberOfTotalModel(
            self._handle, ctypes.byref(out)))
        return out.value

    def feature_names(self) -> list:
        """Model feature names (LGBM_BoosterGetFeatureNames; fixed
        128-byte buffers like the eval-names convention); Column_<i>
        when the model carries none."""
        n = self.num_feature
        bufs = [ctypes.create_string_buffer(128) for _ in range(n)]
        arr = (ctypes.c_char_p * n)(
            *[ctypes.cast(b, ctypes.c_char_p) for b in bufs])
        out_n = ctypes.c_int(0)
        _check(load_lib().LGBM_BoosterGetFeatureNames(
            self._handle, ctypes.byref(out_n), arr))
        return [bufs[i].value.decode() for i in range(out_n.value)]

    def predict_single_row(self, row: np.ndarray, raw_score: bool = False,
                           num_iteration: int = -1) -> np.ndarray:
        """Stateless one-row prediction
        (LGBM_BoosterPredictForMatSingleRow).  For hot serving loops use
        FastSingleRowPredictor, which pays schema validation once."""
        row = np.ascontiguousarray(row, dtype=np.float64).reshape(-1)
        out = np.zeros(max(self.num_class, 1), dtype=np.float64)
        out_len = ctypes.c_int64(0)
        ptype = C_API_PREDICT_RAW_SCORE if raw_score else C_API_PREDICT_NORMAL
        _check(load_lib().LGBM_BoosterPredictForMatSingleRow(
            self._handle, row.ctypes.data_as(ctypes.c_void_p),
            C_API_DTYPE_FLOAT64, ctypes.c_int(row.size), 1, ptype,
            ctypes.c_int(num_iteration), b"", ctypes.byref(out_len),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
        return out[: out_len.value]

    def predict_csr(self, indptr, indices, values, num_col: int,
                    raw_score: bool = False,
                    num_iteration: int = -1) -> np.ndarray:
        """Sparse prediction (LGBM_BoosterPredictForCSR): absent entries
        are 0.0."""
        indptr = np.ascontiguousarray(indptr)
        if indptr.dtype not in (np.int32, np.int64):
            indptr = np.ascontiguousarray(indptr, dtype=np.int64)
        indices = np.ascontiguousarray(indices, dtype=np.int32)
        values = np.ascontiguousarray(values)
        if values.dtype not in (np.float32, np.float64):
            values = np.ascontiguousarray(values, dtype=np.float64)
        nrow = len(indptr) - 1
        k = self.num_class
        ptype = C_API_PREDICT_RAW_SCORE if raw_score else C_API_PREDICT_NORMAL
        out = np.zeros(nrow * max(k, 1), dtype=np.float64)
        out_len = ctypes.c_int64(0)
        _check(load_lib().LGBM_BoosterPredictForCSR(
            self._handle, indptr.ctypes.data_as(ctypes.c_void_p),
            _dtype_code(indptr),
            indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            values.ctypes.data_as(ctypes.c_void_p), _dtype_code(values),
            ctypes.c_int64(len(indptr)), ctypes.c_int64(len(values)),
            ctypes.c_int64(num_col), ptype, ctypes.c_int(num_iteration),
            b"", ctypes.byref(out_len),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
        out = out[: out_len.value]
        per_row = out_len.value // max(nrow, 1)
        return out.reshape(nrow, per_row) if per_row > 1 else out

    def predict_csc(self, col_ptr, indices, values, num_row: int,
                    raw_score: bool = False,
                    num_iteration: int = -1) -> np.ndarray:
        """Column-major sparse prediction (LGBM_BoosterPredictForCSC):
        col_ptr per column, indices carry ROW ids; absent entries are
        0.0.  Bit-identical to transposing to CSR/dense client-side."""
        col_ptr = np.ascontiguousarray(col_ptr)
        if col_ptr.dtype not in (np.int32, np.int64):
            col_ptr = np.ascontiguousarray(col_ptr, dtype=np.int64)
        indices = np.ascontiguousarray(indices, dtype=np.int32)
        values = np.ascontiguousarray(values)
        if values.dtype not in (np.float32, np.float64):
            values = np.ascontiguousarray(values, dtype=np.float64)
        k = self.num_class
        ptype = C_API_PREDICT_RAW_SCORE if raw_score else C_API_PREDICT_NORMAL
        out = np.zeros(num_row * max(k, 1), dtype=np.float64)
        out_len = ctypes.c_int64(0)
        _check(load_lib().LGBM_BoosterPredictForCSC(
            self._handle, col_ptr.ctypes.data_as(ctypes.c_void_p),
            _dtype_code(col_ptr),
            indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            values.ctypes.data_as(ctypes.c_void_p), _dtype_code(values),
            ctypes.c_int64(len(col_ptr)), ctypes.c_int64(len(values)),
            ctypes.c_int64(num_row), ptype, ctypes.c_int(num_iteration),
            b"", ctypes.byref(out_len),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
        out = out[: out_len.value]
        per_row = out_len.value // max(num_row, 1)
        return out.reshape(num_row, per_row) if per_row > 1 else out

    def predict_csr_single_row(self, indices, values, num_col: int,
                               raw_score: bool = False,
                               num_iteration: int = -1) -> np.ndarray:
        """One sparse row (LGBM_BoosterPredictForCSRSingleRow): indices/
        values of the non-zero entries; absent entries are 0.0."""
        indices = np.ascontiguousarray(indices, dtype=np.int32)
        values = np.ascontiguousarray(values, dtype=np.float64)
        indptr = np.asarray([0, len(values)], dtype=np.int64)
        k = self.num_class
        ptype = C_API_PREDICT_RAW_SCORE if raw_score else C_API_PREDICT_NORMAL
        out = np.zeros(max(k, 1), dtype=np.float64)
        out_len = ctypes.c_int64(0)
        _check(load_lib().LGBM_BoosterPredictForCSRSingleRow(
            self._handle, indptr.ctypes.data_as(ctypes.c_void_p),
            _dtype_code(indptr),
            indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            values.ctypes.data_as(ctypes.c_void_p), _dtype_code(values),
            ctypes.c_int64(2), ctypes.c_int64(len(values)),
            ctypes.c_int64(num_col), ptype, ctypes.c_int(num_iteration),
            b"", ctypes.byref(out_len),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
        return out[: out_len.value]

    def calc_num_predict(self, num_row: int, predict_type: int = 0,
                         num_iteration: int = -1) -> int:
        """LGBM_BoosterCalcNumPredict: the number of doubles a predict
        over num_row rows writes — num_row*num_class for normal/raw,
        num_row*used_trees for leaf indices.  Size predict buffers with
        this instead of duplicating the width arithmetic."""
        out = ctypes.c_int64(0)
        _check(load_lib().LGBM_BoosterCalcNumPredict(
            self._handle, ctypes.c_int(num_row),
            ctypes.c_int(predict_type), ctypes.c_int(num_iteration),
            ctypes.byref(out)))
        return out.value

    def get_leaf_value(self, tree_idx: int, leaf_idx: int) -> float:
        """One leaf's output value (LGBM_BoosterGetLeafValue — the
        Python Booster.get_leaf_output mirror)."""
        out = ctypes.c_double(0.0)
        _check(load_lib().LGBM_BoosterGetLeafValue(
            self._handle, ctypes.c_int(tree_idx), ctypes.c_int(leaf_idx),
            ctypes.byref(out)))
        return out.value

    def set_leaf_value(self, tree_idx: int, leaf_idx: int,
                       value: float) -> None:
        """Patch one leaf in place (LGBM_BoosterSetLeafValue): the
        serving-side patch primitive.  Takes effect on every predict
        entry point AND on SaveModel/model_to_string round-trips (the
        stored model text is patched too)."""
        _check(load_lib().LGBM_BoosterSetLeafValue(
            self._handle, ctypes.c_int(tree_idx), ctypes.c_int(leaf_idx),
            ctypes.c_double(value)))

    def save_model(self, filename: str) -> None:
        _check(load_lib().LGBM_BoosterSaveModel(self._handle, -1,
                                                filename.encode()))

    def model_to_string(self) -> str:
        lib = load_lib()
        out_len = ctypes.c_int64(0)
        _check(lib.LGBM_BoosterSaveModelToString(
            self._handle, -1, 0, ctypes.byref(out_len), None))
        buf = ctypes.create_string_buffer(out_len.value)
        _check(lib.LGBM_BoosterSaveModelToString(
            self._handle, -1, out_len.value, ctypes.byref(out_len), buf))
        return buf.value.decode()

    def dump_model(self, start_iteration: int = 0,
                   num_iteration: int = -1) -> dict:
        """JSON model dump through LGBM_BoosterDumpModel (same recursive
        tree_structure schema as Booster.dump_model), parsed to a dict."""
        import json
        lib = load_lib()
        out_len = ctypes.c_int64(0)
        _check(lib.LGBM_BoosterDumpModel(
            self._handle, start_iteration, num_iteration, 0, 0,
            ctypes.byref(out_len), None))
        buf = ctypes.create_string_buffer(out_len.value)
        _check(lib.LGBM_BoosterDumpModel(
            self._handle, start_iteration, num_iteration, 0, out_len.value,
            ctypes.byref(out_len), buf))
        return json.loads(buf.value.decode())

    def feature_importance(self, importance_type: str = "split",
                           num_iteration: int = -1) -> np.ndarray:
        """Per-feature importance through LGBM_BoosterFeatureImportance
        ('split' counts, 'gain' sums non-negative split gains)."""
        itype = C_API_FEATURE_IMPORTANCE_GAIN if importance_type == "gain" \
            else C_API_FEATURE_IMPORTANCE_SPLIT
        out = np.zeros(self.num_feature, dtype=np.float64)
        _check(load_lib().LGBM_BoosterFeatureImportance(
            self._handle, ctypes.c_int(num_iteration), itype,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
        return out

    def predict_for_file(self, data_path: str, result_path: str,
                         data_has_header: bool = False,
                         raw_score: bool = False, pred_leaf: bool = False,
                         num_iteration: int = -1,
                         parameter: str = "") -> None:
        """File-to-file prediction in pure C (LGBM_BoosterPredictForFile):
        parse, predict and write without any Python in the loop — output
        files are byte-identical to `application.py task=predict`."""
        if pred_leaf:
            ptype = C_API_PREDICT_LEAF_INDEX
        else:
            ptype = C_API_PREDICT_RAW_SCORE if raw_score \
                else C_API_PREDICT_NORMAL
        _check(load_lib().LGBM_BoosterPredictForFile(
            self._handle, data_path.encode(),
            1 if data_has_header else 0, ptype,
            ctypes.c_int(num_iteration), parameter.encode(),
            result_path.encode()))

    def predict(self, X: np.ndarray, raw_score: bool = False,
                pred_leaf: bool = False,
                num_iteration: int = -1) -> np.ndarray:
        X = np.ascontiguousarray(X, dtype=np.float64)
        nrow, ncol = X.shape
        k = self.num_class
        iters = self.num_iterations if num_iteration <= 0 \
            else min(num_iteration, self.num_iterations)
        if pred_leaf:
            ptype = C_API_PREDICT_LEAF_INDEX
            # trees used = iters * num_tree_per_iteration (== num_class)
            width = iters * max(1, k)
        else:
            ptype = C_API_PREDICT_RAW_SCORE if raw_score else C_API_PREDICT_NORMAL
            width = k
        out = np.zeros(nrow * max(width, k), dtype=np.float64)
        out_len = ctypes.c_int64(0)
        _check(load_lib().LGBM_BoosterPredictForMat(
            self._handle, X.ctypes.data_as(ctypes.c_void_p),
            C_API_DTYPE_FLOAT64, ctypes.c_int32(nrow), ctypes.c_int32(ncol),
            1, ptype, ctypes.c_int(num_iteration), b"",
            ctypes.byref(out_len), out.ctypes.data_as(
                ctypes.POINTER(ctypes.c_double))))
        out = out[:out_len.value]
        per_row = out_len.value // nrow
        return out.reshape(nrow, per_row) if per_row > 1 else out


class FastSingleRowPredictor:
    """Reuse handle over LGBM_BoosterPredictForMatSingleRowFast: schema
    validation and buffers are paid once at construction, each predict()
    is a single C call — the low-latency point-lookup serving path."""

    def __init__(self, booster: NativeBooster, ncol: int,
                 raw_score: bool = False, num_iteration: int = -1):
        lib = load_lib()
        self._booster = booster          # keep the model handle alive
        self._fast = ctypes.c_void_p()
        ptype = C_API_PREDICT_RAW_SCORE if raw_score else C_API_PREDICT_NORMAL
        _check(lib.LGBM_BoosterPredictForMatSingleRowFastInit(
            booster._handle, ptype, C_API_DTYPE_FLOAT64,
            ctypes.c_int32(ncol), b"", ctypes.c_int(num_iteration),
            ctypes.byref(self._fast)))
        self._out = np.zeros(max(booster.num_class, 1), np.float64)
        self._out_ptr = self._out.ctypes.data_as(
            ctypes.POINTER(ctypes.c_double))
        self._len = ctypes.c_int64(0)

    def __del__(self):
        if getattr(self, "_fast", None):
            load_lib().LGBM_FastConfigFree(self._fast)
            self._fast = None

    def predict(self, row: np.ndarray) -> np.ndarray:
        row = np.ascontiguousarray(row, dtype=np.float64)
        _check(load_lib().LGBM_BoosterPredictForMatSingleRowFast(
            self._fast, row.ctypes.data_as(ctypes.c_void_p),
            ctypes.byref(self._len), self._out_ptr))
        return self._out[: self._len.value].copy()
