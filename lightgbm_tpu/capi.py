"""ctypes loader for the native C API (cpp/lightgbm_tpu_c_api.h).

The shared library is the deployment-side runtime (model load + predict in
pure C++, no Python/JAX needed); this module is the convenience bridge for
Python callers and the test suite.  Build with `make -C cpp` (or
`ensure_built()`), which needs only g++.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

from .utils.log import LightGBMError

_CPP_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                        "cpp")
_LIB_PATH = os.path.join(_CPP_DIR, "lib_lightgbm_tpu.so")
_TRAIN_LIB_PATH = os.path.join(_CPP_DIR, "lib_lightgbm_tpu_train.so")

C_API_DTYPE_FLOAT32 = 0
C_API_DTYPE_FLOAT64 = 1
C_API_PREDICT_NORMAL = 0
C_API_PREDICT_RAW_SCORE = 1
C_API_PREDICT_LEAF_INDEX = 2
C_API_FEATURE_IMPORTANCE_SPLIT = 0
C_API_FEATURE_IMPORTANCE_GAIN = 1

_lib: Optional[ctypes.CDLL] = None


def ensure_built() -> str:
    """Build (or freshen) the shared library; returns its path.  make is
    a no-op when the .so is newer than the sources, so running it
    unconditionally keeps stale pre-built libraries from being loaded."""
    subprocess.run(["make", "-C", _CPP_DIR], check=True,
                   capture_output=True)
    return _LIB_PATH


def load_lib() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(ensure_built())
        lib.LGBM_GetLastError.restype = ctypes.c_char_p
        _lib = lib
    return _lib


def _check(rc: int) -> None:
    if rc != 0:
        raise LightGBMError(load_lib().LGBM_GetLastError().decode())


_train_lib: Optional[ctypes.CDLL] = None


def load_train_lib() -> ctypes.CDLL:
    """The TRAINING-side library (embedded-CPython ABI).  Its dlopen pulls
    the base prediction lib via $ORIGIN rpath and registers the dispatch
    hooks, so symbols from BOTH surfaces resolve through this handle."""
    global _train_lib
    if _train_lib is None:
        ensure_built()
        lib = ctypes.CDLL(_TRAIN_LIB_PATH)
        lib.LGBM_GetLastError.restype = ctypes.c_char_p
        _train_lib = lib
    return _train_lib


def _check_train(rc: int) -> None:
    if rc != 0:
        raise LightGBMError(load_train_lib().LGBM_GetLastError().decode())


def booster_reset_parameter(handle, parameters: str) -> None:
    """LGBM_BoosterResetParameter over a raw training BoosterHandle:
    live-apply "key=value ..." parameters (e.g. learning_rate) so they
    take effect on the next LGBM_BoosterUpdateOneIter."""
    _check_train(load_train_lib().LGBM_BoosterResetParameter(
        handle, parameters.encode()))


def booster_refit(handle, X: np.ndarray, y: np.ndarray) -> None:
    """LGBM_BoosterRefit over a raw training BoosterHandle: keep every
    split, refit leaf values to (X, y) — the handle's model is replaced
    in place (reference Booster.refit semantics, adapted signature: the
    data travels directly instead of pre-computed leaf assignments)."""
    X = np.ascontiguousarray(X, dtype=np.float64)
    y = np.ascontiguousarray(y, dtype=np.float32).reshape(-1)
    nrow, ncol = X.shape
    if y.size != nrow:
        raise LightGBMError("label length %d != nrow %d" % (y.size, nrow))
    _check_train(load_train_lib().LGBM_BoosterRefit(
        handle, X.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_int32(nrow), ctypes.c_int32(ncol)))


class NativeBooster:
    """Minimal handle over the C API, mirroring Booster's predict surface."""

    def __init__(self, model_file: Optional[str] = None,
                 model_str: Optional[str] = None):
        lib = load_lib()
        self._handle = ctypes.c_void_p()
        out_iters = ctypes.c_int(0)
        if model_file is not None:
            _check(lib.LGBM_BoosterCreateFromModelfile(
                model_file.encode(), ctypes.byref(out_iters),
                ctypes.byref(self._handle)))
        elif model_str is not None:
            _check(lib.LGBM_BoosterLoadModelFromString(
                model_str.encode(), ctypes.byref(out_iters),
                ctypes.byref(self._handle)))
        else:
            raise ValueError("model_file or model_str required")
        self.num_iterations = out_iters.value

    def __del__(self):
        if getattr(self, "_handle", None):
            load_lib().LGBM_BoosterFree(self._handle)
            self._handle = None

    @property
    def num_class(self) -> int:
        out = ctypes.c_int(0)
        _check(load_lib().LGBM_BoosterGetNumClasses(self._handle,
                                                    ctypes.byref(out)))
        return out.value

    @property
    def num_feature(self) -> int:
        out = ctypes.c_int(0)
        _check(load_lib().LGBM_BoosterGetNumFeature(self._handle,
                                                    ctypes.byref(out)))
        return out.value

    @property
    def num_model_per_iteration(self) -> int:
        """Trees per iteration (LGBM_BoosterNumModelPerIteration): 1 for
        binary/regression, num_class for multiclass."""
        out = ctypes.c_int(0)
        _check(load_lib().LGBM_BoosterNumModelPerIteration(
            self._handle, ctypes.byref(out)))
        return out.value

    def get_leaf_value(self, tree_idx: int, leaf_idx: int) -> float:
        """One leaf's output value (LGBM_BoosterGetLeafValue — the
        Python Booster.get_leaf_output mirror)."""
        out = ctypes.c_double(0.0)
        _check(load_lib().LGBM_BoosterGetLeafValue(
            self._handle, ctypes.c_int(tree_idx), ctypes.c_int(leaf_idx),
            ctypes.byref(out)))
        return out.value

    def set_leaf_value(self, tree_idx: int, leaf_idx: int,
                       value: float) -> None:
        """Patch one leaf in place (LGBM_BoosterSetLeafValue): the
        serving-side patch primitive.  Takes effect on every predict
        entry point AND on SaveModel/model_to_string round-trips (the
        stored model text is patched too)."""
        _check(load_lib().LGBM_BoosterSetLeafValue(
            self._handle, ctypes.c_int(tree_idx), ctypes.c_int(leaf_idx),
            ctypes.c_double(value)))

    def save_model(self, filename: str) -> None:
        _check(load_lib().LGBM_BoosterSaveModel(self._handle, -1,
                                                filename.encode()))

    def model_to_string(self) -> str:
        lib = load_lib()
        out_len = ctypes.c_int64(0)
        _check(lib.LGBM_BoosterSaveModelToString(
            self._handle, -1, 0, ctypes.byref(out_len), None))
        buf = ctypes.create_string_buffer(out_len.value)
        _check(lib.LGBM_BoosterSaveModelToString(
            self._handle, -1, out_len.value, ctypes.byref(out_len), buf))
        return buf.value.decode()

    def dump_model(self, start_iteration: int = 0,
                   num_iteration: int = -1) -> dict:
        """JSON model dump through LGBM_BoosterDumpModel (same recursive
        tree_structure schema as Booster.dump_model), parsed to a dict."""
        import json
        lib = load_lib()
        out_len = ctypes.c_int64(0)
        _check(lib.LGBM_BoosterDumpModel(
            self._handle, start_iteration, num_iteration, 0, 0,
            ctypes.byref(out_len), None))
        buf = ctypes.create_string_buffer(out_len.value)
        _check(lib.LGBM_BoosterDumpModel(
            self._handle, start_iteration, num_iteration, 0, out_len.value,
            ctypes.byref(out_len), buf))
        return json.loads(buf.value.decode())

    def feature_importance(self, importance_type: str = "split",
                           num_iteration: int = -1) -> np.ndarray:
        """Per-feature importance through LGBM_BoosterFeatureImportance
        ('split' counts, 'gain' sums non-negative split gains)."""
        itype = C_API_FEATURE_IMPORTANCE_GAIN if importance_type == "gain" \
            else C_API_FEATURE_IMPORTANCE_SPLIT
        out = np.zeros(self.num_feature, dtype=np.float64)
        _check(load_lib().LGBM_BoosterFeatureImportance(
            self._handle, ctypes.c_int(num_iteration), itype,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
        return out

    def predict_for_file(self, data_path: str, result_path: str,
                         data_has_header: bool = False,
                         raw_score: bool = False, pred_leaf: bool = False,
                         num_iteration: int = -1,
                         parameter: str = "") -> None:
        """File-to-file prediction in pure C (LGBM_BoosterPredictForFile):
        parse, predict and write without any Python in the loop — output
        files are byte-identical to `application.py task=predict`."""
        if pred_leaf:
            ptype = C_API_PREDICT_LEAF_INDEX
        else:
            ptype = C_API_PREDICT_RAW_SCORE if raw_score \
                else C_API_PREDICT_NORMAL
        _check(load_lib().LGBM_BoosterPredictForFile(
            self._handle, data_path.encode(),
            1 if data_has_header else 0, ptype,
            ctypes.c_int(num_iteration), parameter.encode(),
            result_path.encode()))

    def predict(self, X: np.ndarray, raw_score: bool = False,
                pred_leaf: bool = False,
                num_iteration: int = -1) -> np.ndarray:
        X = np.ascontiguousarray(X, dtype=np.float64)
        nrow, ncol = X.shape
        k = self.num_class
        iters = self.num_iterations if num_iteration <= 0 \
            else min(num_iteration, self.num_iterations)
        if pred_leaf:
            ptype = C_API_PREDICT_LEAF_INDEX
            # trees used = iters * num_tree_per_iteration (== num_class)
            width = iters * max(1, k)
        else:
            ptype = C_API_PREDICT_RAW_SCORE if raw_score else C_API_PREDICT_NORMAL
            width = k
        out = np.zeros(nrow * max(width, k), dtype=np.float64)
        out_len = ctypes.c_int64(0)
        _check(load_lib().LGBM_BoosterPredictForMat(
            self._handle, X.ctypes.data_as(ctypes.c_void_p),
            C_API_DTYPE_FLOAT64, ctypes.c_int32(nrow), ctypes.c_int32(ncol),
            1, ptype, ctypes.c_int(num_iteration), b"",
            ctypes.byref(out_len), out.ctypes.data_as(
                ctypes.POINTER(ctypes.c_double))))
        out = out[:out_len.value]
        per_row = out_len.value // nrow
        return out.reshape(nrow, per_row) if per_row > 1 else out


class FastSingleRowPredictor:
    """Reuse handle over LGBM_BoosterPredictForMatSingleRowFast: schema
    validation and buffers are paid once at construction, each predict()
    is a single C call — the low-latency point-lookup serving path."""

    def __init__(self, booster: NativeBooster, ncol: int,
                 raw_score: bool = False, num_iteration: int = -1):
        lib = load_lib()
        self._booster = booster          # keep the model handle alive
        self._fast = ctypes.c_void_p()
        ptype = C_API_PREDICT_RAW_SCORE if raw_score else C_API_PREDICT_NORMAL
        _check(lib.LGBM_BoosterPredictForMatSingleRowFastInit(
            booster._handle, ptype, C_API_DTYPE_FLOAT64,
            ctypes.c_int32(ncol), b"", ctypes.c_int(num_iteration),
            ctypes.byref(self._fast)))
        self._out = np.zeros(max(booster.num_class, 1), np.float64)
        self._out_ptr = self._out.ctypes.data_as(
            ctypes.POINTER(ctypes.c_double))
        self._len = ctypes.c_int64(0)

    def __del__(self):
        if getattr(self, "_fast", None):
            load_lib().LGBM_FastConfigFree(self._fast)
            self._fast = None

    def predict(self, row: np.ndarray) -> np.ndarray:
        row = np.ascontiguousarray(row, dtype=np.float64)
        _check(load_lib().LGBM_BoosterPredictForMatSingleRowFast(
            self._fast, row.ctypes.data_as(ctypes.c_void_p),
            ctypes.byref(self._len), self._out_ptr))
        return self._out[: self._len.value].copy()
