"""Exclusive Feature Bundling (EFB).

Role parity with the reference's bundling pipeline
(src/io/dataset.cpp:66-210 FindGroups/FastFeatureBundling,
include/LightGBM/feature_group.h:18): sparse features that are rarely
non-default on the same row are packed into one storage column with
disjoint bin ranges, shrinking both the histogram work and the bin matrix
by the bundle ratio.  The split layer still sees ORIGINAL features — a
bundle's histogram is expanded to per-feature views by static gathers
(ops/bundle.py), mirroring how the reference's FeatureHistogram points
into its group histogram at a bin offset.

Encoding (one uint8/16 value per row per bundle):
  0                     -> every member at its default (zero) bin
  off_f + b - (b > d_f) -> member f at non-default bin b   (d_f skipped)
Singleton bundles keep their feature's raw bins (identity encoding), so
dense features cost nothing.  Rows where two members collide keep the
later-written member — bounded by the conflict budget, the same
approximation the reference accepts (max_conflict_rate).
"""
from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence

import numpy as np

from ..utils.log import Log


class BundleInfo(NamedTuple):
    """Host-side bundle description attached to a BinnedDataset."""
    groups: List[List[int]]      # member feature ids per bundle
    f_group: np.ndarray          # [F] i32 bundle id of each feature
    f_offset: np.ndarray         # [F] i32 bin offset inside the bundle
    f_identity: np.ndarray       # [F] bool raw-bin passthrough (singleton)
    group_num_bin: np.ndarray    # [G] i32 total bins of each bundle
    max_group_bin: int
    #: [G] realized full-data conflict rate per bundle (0 for singletons);
    #: None when the layout was built without counting (validation reuse,
    #: caches saved before the field existed)
    conflict_rates: Optional[np.ndarray] = None


def find_bundles(nonzero: List[np.ndarray], num_rows: int,
                 num_bins: Sequence[int], default_bins: Sequence[int],
                 bundleable: Sequence[bool], *, max_conflict_rate: float,
                 max_bundle_bins: int, rng: np.random.Generator):
    """Greedy conflict-bounded grouping (reference FindGroups,
    src/io/dataset.cpp:66-153).

    nonzero: per-feature sorted row indices with a non-default bin (on the
    bundling sample).  Features are visited in random order like the
    reference (it shuffles feature order before grouping); each tries every
    existing bundle and joins the first whose accumulated conflict count
    and bin budget both fit.
    """
    F = len(nonzero)
    max_conflicts = int(max_conflict_rate * num_rows)
    order = [f for f in rng.permutation(F) if bundleable[f]]

    groups: List[List[int]] = []
    group_rows: List[np.ndarray] = []     # sorted nonzero rows per bundle
    group_conflicts: List[int] = []
    group_bins: List[int] = []            # 1 + sum(nb_f - 1) so far

    for f in order:
        rows_f = nonzero[f]
        extra_bins = int(num_bins[f]) - 1
        placed = False
        for gi in range(len(groups)):
            if group_bins[gi] + extra_bins > max_bundle_bins:
                continue
            cnt = np.intersect1d(group_rows[gi], rows_f,
                                 assume_unique=True).size
            if group_conflicts[gi] + cnt <= max_conflicts:
                groups[gi].append(f)
                group_rows[gi] = np.union1d(group_rows[gi], rows_f)
                group_conflicts[gi] += cnt
                group_bins[gi] += extra_bins
                placed = True
                break
        if not placed:
            groups.append([f])
            group_rows.append(rows_f)
            group_conflicts.append(0)
            group_bins.append(1 + extra_bins)
    return groups


def realized_conflict_rates(bins: np.ndarray, info: BundleInfo,
                            default_bins: Sequence[int]) -> np.ndarray:
    """Per-bundle fraction of rows where two or more members are
    non-default on the FULL data (the rows whose later-written member
    overwrote another).  The reference bounds this on the bundling sample
    (dataset.cpp:66-153, max_conflict_rate); reporting the realized rate
    tells the user how lossy their bundling actually was."""
    N = bins.shape[1]
    rates = np.zeros(len(info.groups), np.float64)
    for gi, feats in enumerate(info.groups):
        if len(feats) <= 1:
            continue
        nd = np.zeros(N, np.int32)
        for f in feats:
            nd += bins[f] != default_bins[f]
        rates[gi] = float(np.count_nonzero(nd > 1)) / max(N, 1)
    return rates


def apply_bundles(bins: np.ndarray, info: BundleInfo,
                  num_bins: Sequence[int],
                  default_bins: Sequence[int],
                  count_conflicts: bool = False):
    """Re-encode a binned matrix with an EXISTING bundle layout (validation
    sets reuse the training dataset's bundling, Dataset::CreateValid).
    With count_conflicts, also returns the per-bundle realized conflict
    rates (reusing the member non-default masks this pass computes
    anyway)."""
    G = len(info.groups)
    N = bins.shape[1]
    dtype = np.uint8 if info.max_group_bin <= 256 else np.uint16
    bundled = np.zeros((G, N), dtype)
    rates = np.zeros(G, np.float64) if count_conflicts else None
    for gi, feats in enumerate(info.groups):
        if len(feats) == 1 and info.f_identity[feats[0]]:
            bundled[gi] = bins[feats[0]].astype(dtype)
            continue
        nd_count = np.zeros(N, np.int32) if count_conflicts else None
        for f in feats:
            b = bins[f].astype(np.int32)
            d = int(default_bins[f])
            nd = b != d
            if count_conflicts:
                nd_count += nd
            enc = info.f_offset[f] + b - (b > d)
            bundled[gi, nd] = enc[nd].astype(dtype)
        if count_conflicts:
            rates[gi] = float(np.count_nonzero(nd_count > 1)) / max(N, 1)
    return (bundled, rates) if count_conflicts else bundled


def bundle_features(bins: np.ndarray, num_bins: Sequence[int],
                    default_bins: Sequence[int], bundleable: Sequence[bool],
                    num_data: int, *, max_conflict_rate: float = 0.0,
                    max_bundle_bins: int = 255,
                    sample_cnt: int = 200000,
                    seed: int = 1) -> Optional[tuple]:
    """Bundle the binned matrix.  Returns (bundled_bins [G, N], BundleInfo)
    or None when bundling would not help (fewer than 2 bundleable sparse
    features, or no bundle gained a second member)."""
    F, N = bins.shape
    rng = np.random.default_rng(seed)
    sample_n = min(num_data, sample_cnt)
    sample = (np.sort(rng.choice(num_data, sample_n, replace=False))
              if sample_n < num_data else np.arange(num_data))

    nonzero = []
    for f in range(F):
        col = bins[f, sample]
        nonzero.append(np.flatnonzero(col != default_bins[f]).astype(np.int64))

    groups = find_bundles(nonzero, sample_n, num_bins, default_bins,
                          bundleable, max_conflict_rate=max_conflict_rate,
                          max_bundle_bins=max_bundle_bins, rng=rng)
    # features the grouping skipped (non-bundleable) become singletons
    grouped = {f for g in groups for f in g}
    for f in range(F):
        if f not in grouped:
            groups.append([f])
    if not any(len(g) > 1 for g in groups):
        return None
    # deterministic layout: order bundles by smallest member id
    groups.sort(key=lambda g: min(g))

    G = len(groups)
    f_group = np.zeros(F, np.int32)
    f_offset = np.zeros(F, np.int32)
    f_identity = np.zeros(F, bool)
    group_num_bin = np.zeros(G, np.int32)
    for gi, feats in enumerate(groups):
        if len(feats) == 1:
            f = feats[0]
            f_group[f] = gi
            f_identity[f] = True
            group_num_bin[gi] = num_bins[f]
            continue
        off = 1
        for f in sorted(feats):
            f_group[f] = gi
            f_offset[f] = off
            off += int(num_bins[f]) - 1
        group_num_bin[gi] = off
    groups = [sorted(g) for g in groups]

    info = BundleInfo(groups=groups, f_group=f_group, f_offset=f_offset,
                      f_identity=f_identity, group_num_bin=group_num_bin,
                      max_group_bin=int(group_num_bin.max()))
    bundled, rates = apply_bundles(bins, info, num_bins, default_bins,
                                   count_conflicts=True)
    # the encode pass covers padded rows (all-default, conflict-free);
    # report rates over the real rows
    rates = rates * (N / max(num_data, 1))

    n_multi = sum(1 for g in groups if len(g) > 1)
    info = info._replace(conflict_rates=rates)
    Log.info("EFB: bundled %d features into %d columns "
             "(%d multi-feature bundles, max %d bins); realized conflict "
             "rate on full data: max %.4f, mean %.4f",
             F, G, n_multi, int(group_num_bin.max()),
             float(rates.max()) if len(rates) else 0.0,
             float(rates.mean()) if len(rates) else 0.0)
    if len(rates) and rates.max() > max(max_conflict_rate, 1e-12):
        Log.warning("EFB: realized conflict rate %.4f exceeds the "
                    "max_conflict_rate budget %.4f (the budget is enforced "
                    "on the bundling sample); colliding rows keep the "
                    "later-written member's bin", float(rates.max()),
                    max_conflict_rate)
    return bundled, info
