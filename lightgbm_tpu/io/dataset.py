"""Binned dataset: the training matrix as a packed integer array in HBM.

Role parity with the reference Dataset/DatasetLoader/Metadata
(include/LightGBM/dataset.h:282-618, src/io/dataset.cpp Construct:212-322,
src/io/dataset_loader.cpp CostructFromSampleData:501+, src/io/metadata.cpp).

TPU-first redesign: instead of per-feature-group Bin objects with push
iterators, the dataset is one [num_features, num_rows] integer matrix (uint8
for <=256 bins) padded to the histogram row-chunk, shipped once to device
memory, plus small per-feature metadata arrays (bin counts, missing types,
default bins) consumed by the split finder.  Exclusive Feature Bundling
arrives with M3 and only changes how columns are packed.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..utils.log import Log
from ..utils.random import Random
from .binning import (BIN_TYPE_CATEGORICAL, BIN_TYPE_NUMERICAL, BinMapper)


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


class Metadata:
    """Labels / weights / query boundaries / init scores (src/io/metadata.cpp)."""

    def __init__(self, num_data: int):
        self.num_data = num_data
        self.label: Optional[np.ndarray] = None
        self.weight: Optional[np.ndarray] = None
        self.init_score: Optional[np.ndarray] = None
        self.query_boundaries: Optional[np.ndarray] = None

    def set_label(self, label) -> None:
        label = np.ascontiguousarray(label, dtype=np.float32).reshape(-1)
        if len(label) != self.num_data:
            Log.fatal("Length of label is not same with #data")
        self.label = label

    def set_weight(self, weight) -> None:
        if weight is None:
            self.weight = None
            return
        weight = np.ascontiguousarray(weight, dtype=np.float32).reshape(-1)
        if len(weight) != self.num_data:
            Log.fatal("Length of weight is not same with #data")
        self.weight = weight

    def set_init_score(self, init_score) -> None:
        if init_score is None:
            self.init_score = None
            return
        self.init_score = np.ascontiguousarray(init_score, dtype=np.float64)

    def set_query(self, group) -> None:
        if group is None:
            self.query_boundaries = None
            return
        group = np.ascontiguousarray(group, dtype=np.int64).reshape(-1)
        if group.sum() != self.num_data:
            Log.fatal("Sum of query counts is not same with #data")
        self.query_boundaries = np.concatenate([[0], np.cumsum(group)])


class BinnedDataset:
    """Host-side binned training matrix + per-feature metadata."""

    def __init__(self):
        self.num_data = 0
        self.num_total_features = 0
        self.bin_mappers: List[BinMapper] = []
        self.bins: Optional[np.ndarray] = None  # [F, N_pad] uint8/uint16
        self.num_data_padded = 0
        self.max_num_bin = 0
        self.metadata: Optional[Metadata] = None
        self.feature_names: List[str] = []
        self.monotone_constraints: Optional[np.ndarray] = None
        self.feature_penalty: Optional[np.ndarray] = None

    # -- construction --------------------------------------------------------
    @classmethod
    def from_matrix(cls, X: np.ndarray, config, *, bin_mappers: Optional[List[BinMapper]] = None,
                    feature_names: Optional[Sequence[str]] = None,
                    categorical_feature: Sequence[int] = (),
                    row_chunk: int = 16384) -> "BinnedDataset":
        """Bin a raw [N, F] float matrix.  When bin_mappers is given (validation
        sets), reuse the training mappers (reference Dataset::CreateValid)."""
        X = np.asarray(X)
        if X.ndim != 2:
            Log.fatal("Data should be 2 dimensional")
        n, f = X.shape
        ds = cls()
        ds.num_data = n
        ds.num_total_features = f
        ds.feature_names = list(feature_names) if feature_names \
            else ["Column_%d" % i for i in range(f)]

        if bin_mappers is None:
            bin_mappers = cls._find_bin_mappers(X, config, categorical_feature)
        ds.bin_mappers = bin_mappers
        ds.max_num_bin = max((m.num_bin for m in bin_mappers), default=1)

        n_pad = _round_up(n, row_chunk) if n > row_chunk else _round_up(max(n, 1), 128)
        dtype = np.uint8 if ds.max_num_bin <= 256 else np.uint16
        bins = np.zeros((f, n_pad), dtype=dtype)
        for j, mapper in enumerate(bin_mappers):
            if mapper.is_trivial:
                continue
            bins[j, :n] = mapper.values_to_bins(X[:, j].astype(np.float64))
        ds.bins = bins
        ds.num_data_padded = n_pad
        ds.metadata = Metadata(n)

        mono = getattr(config, "monotone_constraints", None) or []
        ds.monotone_constraints = np.zeros(f, dtype=np.int32)
        ds.monotone_constraints[: len(mono)] = np.asarray(mono, dtype=np.int32)[:f]
        pen = getattr(config, "feature_contri", None) or []
        ds.feature_penalty = np.ones(f, dtype=np.float32)
        ds.feature_penalty[: len(pen)] = np.asarray(pen, dtype=np.float32)[:f]
        return ds

    @staticmethod
    def _find_bin_mappers(X: np.ndarray, config,
                          categorical_feature: Sequence[int]) -> List[BinMapper]:
        n, f = X.shape
        sample_cnt = min(int(getattr(config, "bin_construct_sample_cnt", 200000)), n)
        rng = Random(int(getattr(config, "data_random_seed", 1)))
        sample_idx = rng.sample(n, sample_cnt)
        cat = set(int(c) for c in categorical_feature)
        mappers: List[BinMapper] = []
        max_bin = int(getattr(config, "max_bin", 255))
        min_data_in_bin = int(getattr(config, "min_data_in_bin", 3))
        use_missing = bool(getattr(config, "use_missing", True))
        zero_as_missing = bool(getattr(config, "zero_as_missing", False))
        for j in range(f):
            m = BinMapper()
            values = X[sample_idx, j].astype(np.float64)
            bin_type = BIN_TYPE_CATEGORICAL if j in cat else BIN_TYPE_NUMERICAL
            m.find_bin(values, len(sample_idx), max_bin,
                       min_data_in_bin=min_data_in_bin, bin_type=bin_type,
                       use_missing=use_missing, zero_as_missing=zero_as_missing)
            mappers.append(m)
        num_trivial = sum(1 for m in mappers if m.is_trivial)
        if num_trivial:
            Log.info("%d features are ignored (constant value)", num_trivial)
        Log.info("Total bins: %d over %d features",
                 sum(m.num_bin for m in mappers), f - num_trivial)
        return mappers

    # -- accessors -----------------------------------------------------------
    @property
    def num_features(self) -> int:
        return self.num_total_features

    def feature_infos(self) -> List[str]:
        return [m.feature_info() for m in self.bin_mappers]

    def real_threshold(self, feature: int, bin_idx: int) -> float:
        """Bin threshold → double threshold for the model file
        (Dataset::RealThreshold)."""
        return self.bin_mappers[feature].bin_to_value(bin_idx)

    def valid_row_mask(self) -> np.ndarray:
        mask = np.zeros(self.num_data_padded, dtype=np.float32)
        mask[: self.num_data] = 1.0
        return mask

    def padded(self, arr: Optional[np.ndarray], fill: float = 0.0,
               dtype=np.float32) -> np.ndarray:
        """Pad a per-row array to the padded row count."""
        out = np.full(self.num_data_padded, fill, dtype=dtype)
        if arr is not None:
            out[: self.num_data] = arr
        return out
