"""Binned dataset: the training matrix as a packed integer array in HBM.

Role parity with the reference Dataset/DatasetLoader/Metadata
(include/LightGBM/dataset.h:282-618, src/io/dataset.cpp Construct:212-322,
src/io/dataset_loader.cpp CostructFromSampleData:501+, src/io/metadata.cpp).

TPU-first redesign: instead of per-feature-group Bin objects with push
iterators, the dataset is one [num_features, num_rows] integer matrix (uint8
for <=256 bins) padded to the histogram row-chunk, shipped once to device
memory, plus small per-feature metadata arrays (bin counts, missing types,
default bins) consumed by the split finder.  Exclusive Feature Bundling
arrives with M3 and only changes how columns are packed.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..utils.log import Log
from ..utils.random import Random
from .binning import (BIN_TYPE_CATEGORICAL, BIN_TYPE_NUMERICAL, BinMapper)
from .bundling import BundleInfo, bundle_features


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


class Metadata:
    """Labels / weights / query boundaries / init scores (src/io/metadata.cpp)."""

    def __init__(self, num_data: int):
        self.num_data = num_data
        self.label: Optional[np.ndarray] = None
        self.weight: Optional[np.ndarray] = None
        self.init_score: Optional[np.ndarray] = None
        self.query_boundaries: Optional[np.ndarray] = None

    def set_label(self, label) -> None:
        label = np.ascontiguousarray(label, dtype=np.float32).reshape(-1)
        if len(label) != self.num_data:
            Log.fatal("Length of label is not same with #data")
        self.label = label

    def set_weight(self, weight) -> None:
        if weight is None:
            self.weight = None
            return
        weight = np.ascontiguousarray(weight, dtype=np.float32).reshape(-1)
        if len(weight) != self.num_data:
            Log.fatal("Length of weight is not same with #data")
        self.weight = weight

    def set_init_score(self, init_score) -> None:
        if init_score is None:
            self.init_score = None
            return
        self.init_score = np.ascontiguousarray(init_score, dtype=np.float64)

    def set_query(self, group) -> None:
        if group is None:
            self.query_boundaries = None
            return
        group = np.ascontiguousarray(group, dtype=np.int64).reshape(-1)
        if group.sum() != self.num_data:
            Log.fatal("Sum of query counts is not same with #data")
        self.query_boundaries = np.concatenate([[0], np.cumsum(group)])


class BinnedDataset:
    """Host-side binned training matrix + per-feature metadata."""

    def __init__(self):
        self.num_data = 0
        self.num_total_features = 0
        self.bin_mappers: List[BinMapper] = []
        self.bins: Optional[np.ndarray] = None  # [G, N_pad] uint8/uint16
        self.bundle_info: Optional[BundleInfo] = None  # EFB grouping (G<F)
        self.num_data_padded = 0
        self.max_num_bin = 0
        self.metadata: Optional[Metadata] = None
        self.feature_names: List[str] = []
        self.monotone_constraints: Optional[np.ndarray] = None
        self.feature_penalty: Optional[np.ndarray] = None

    # -- construction --------------------------------------------------------
    @classmethod
    def from_matrix(cls, X: np.ndarray, config, *, bin_mappers: Optional[List[BinMapper]] = None,
                    feature_names: Optional[Sequence[str]] = None,
                    categorical_feature: Sequence[int] = (),
                    row_chunk: int = 16384,
                    reference_bundle: Optional[BundleInfo] = None) -> "BinnedDataset":
        """Bin a raw [N, F] float matrix.  When bin_mappers is given (validation
        sets), reuse the training mappers (reference Dataset::CreateValid) and
        the training bundling (reference_bundle)."""
        X = np.asarray(X)
        if X.ndim != 2:
            Log.fatal("Data should be 2 dimensional")
        n, f = X.shape
        ds = cls()
        ds.num_data = n
        ds.num_total_features = f
        ds.feature_names = list(feature_names) if feature_names \
            else ["Column_%d" % i for i in range(f)]

        if bin_mappers is None:
            bin_mappers = cls._find_bin_mappers(X, config, categorical_feature)
        ds.bin_mappers = bin_mappers
        ds.max_num_bin = max((m.num_bin for m in bin_mappers), default=1)

        n_pad = _round_up(n, row_chunk) if n > row_chunk else _round_up(max(n, 1), 128)
        dtype = np.uint8 if ds.max_num_bin <= 256 else np.uint16
        bins = np.zeros((f, n_pad), dtype=dtype)
        # native OpenMP ValueToBin over the whole matrix (cpp/ingest.cc)
        # when every non-trivial feature is numerical; otherwise (or with
        # no native library) the per-feature Python path
        from .native import encode_bins
        if not encode_bins(X, bin_mappers, bins):
            for j, mapper in enumerate(bin_mappers):
                if mapper.is_trivial:
                    continue
                bins[j, :n] = mapper.values_to_bins(X[:, j].astype(np.float64))

        # Exclusive Feature Bundling (reference dataset.cpp:66-210): pack
        # mutually-exclusive sparse features into shared storage columns.
        # Validation sets reuse the training layout.  Row-sharded parallel
        # learners (data/voting) train bundled on the mesh fast path;
        # feature-parallel keeps unbundled storage (its feature sharding
        # predates bundles).
        num_bins_arr = [m.num_bin for m in bin_mappers]
        default_bins_arr = [m.default_bin for m in bin_mappers]
        if reference_bundle is not None:
            from .bundling import apply_bundles
            ds.bundle_info = reference_bundle
            bins = apply_bundles(bins, reference_bundle, num_bins_arr,
                                 default_bins_arr)
        elif (bool(getattr(config, "enable_bundle", True))
              and str(getattr(config, "tree_learner", "serial"))
              in ("serial", "data", "voting")
              and f >= 2):
            # features mostly at their zero bin are bundling candidates;
            # denser ones isolate themselves anyway via the conflict budget
            # but would make conflict counting quadratic-expensive
            bundleable = [
                (not m.is_trivial) and m.sparse_rate >= 0.5
                and m.num_bin >= 2 for m in bin_mappers]
            if sum(bundleable) >= 2:
                out = bundle_features(
                    bins, num_bins_arr, default_bins_arr, bundleable, n,
                    max_conflict_rate=float(
                        getattr(config, "max_conflict_rate", 0.0) or 0.0),
                    max_bundle_bins=max(ds.max_num_bin, 255),
                    sample_cnt=int(getattr(config,
                                           "bin_construct_sample_cnt",
                                           200000)),
                    seed=int(getattr(config, "data_random_seed", 1)))
                if out is not None:
                    bins, ds.bundle_info = out
        if ds.bundle_info is not None:
            ds.max_num_bin = max(ds.max_num_bin,
                                 ds.bundle_info.max_group_bin)
        ds.bins = bins
        ds.num_data_padded = n_pad
        ds.metadata = Metadata(n)

        mono = getattr(config, "monotone_constraints", None) or []
        ds.monotone_constraints = np.zeros(f, dtype=np.int32)
        ds.monotone_constraints[: len(mono)] = np.asarray(mono, dtype=np.int32)[:f]
        pen = getattr(config, "feature_contri", None) or []
        ds.feature_penalty = np.ones(f, dtype=np.float32)
        ds.feature_penalty[: len(pen)] = np.asarray(pen, dtype=np.float32)[:f]
        return ds


    # -- binary dataset cache (reference save_binary / DatasetLoader::
    #    LoadFromBinFile, src/io/dataset_loader.cpp:267+) -------------------
    BINARY_MAGIC = "lightgbm_tpu.dataset.v1"
    #: cache-format version stamp (ISSUE 8 satellite): bumped whenever the
    #: on-disk layout or the binning semantics it froze change, so a stale
    #: cache REFUSES to load with a clear rebuild instruction instead of
    #: silently training on bins a newer build would not have produced.
    #: v2 = the first stamped format (v1 files predate the stamp).
    BINARY_FORMAT_VERSION = 2

    def save_binary(self, path: str) -> None:
        """Serialize the fully-constructed dataset (bins, mappers, bundles,
        metadata) so later runs skip parsing + find-bin + bundling."""
        import io as _io
        import json as _json
        header = {
            "magic": self.BINARY_MAGIC,
            "format_version": self.BINARY_FORMAT_VERSION,
            "num_data": self.num_data,
            "num_total_features": self.num_total_features,
            "num_data_padded": self.num_data_padded,
            "max_num_bin": self.max_num_bin,
            "feature_names": self.feature_names,
            "num_columns": int(self.bins.shape[0]),
        }
        from .nbits import get_packed, should_pack
        if should_pack(self):
            # dense_nbits_bin parity at the storage boundary: <=16-bin
            # columns cache at two per byte
            header["nbits4"] = True
            arrays = {"bins": get_packed(self)}
        else:
            arrays = {"bins": self.bins}
        arrays.update({"monotone": self.monotone_constraints,
                       "penalty": self.feature_penalty})
        for i, m in enumerate(self.bin_mappers):
            ma = m.to_arrays()
            header.setdefault("mappers", []).append(
                {k: v for k, v in ma.items()
                 if not isinstance(v, np.ndarray)})
            arrays["mapper%d_upper" % i] = ma["bin_upper_bound"]
            arrays["mapper%d_cats" % i] = ma["bin_2_categorical"]
        if self.bundle_info is not None:
            bi = self.bundle_info
            header["bundle_groups"] = [list(map(int, g)) for g in bi.groups]
            arrays["bundle_f_group"] = bi.f_group
            arrays["bundle_f_offset"] = bi.f_offset
            arrays["bundle_f_identity"] = bi.f_identity
            arrays["bundle_group_num_bin"] = bi.group_num_bin
            if bi.conflict_rates is not None:
                arrays["bundle_conflict_rates"] = bi.conflict_rates
        md = self.metadata
        if md is not None:
            for name in ("label", "weight", "init_score", "query_boundaries"):
                v = getattr(md, name)
                if v is not None:
                    arrays["md_" + name] = v
        arrays["header"] = np.frombuffer(
            _json.dumps(header).encode(), dtype=np.uint8)
        with open(path, "wb") as fh:
            np.savez_compressed(fh, **arrays)
        Log.info("Saved binary dataset cache to %s", path)

    @staticmethod
    def is_binary_file(path: str) -> bool:
        try:
            with np.load(path, allow_pickle=False) as z:
                if "header" not in z.files:
                    return False
                import json as _json
                header = _json.loads(bytes(z["header"].tobytes()).decode())
                return header.get("magic") == BinnedDataset.BINARY_MAGIC
        except Exception:
            return False

    @classmethod
    def load_binary(cls, path: str) -> "BinnedDataset":
        import json as _json
        from .bundling import BundleInfo
        with np.load(path, allow_pickle=False) as z:
            header = _json.loads(bytes(z["header"].tobytes()).decode())
            if header.get("magic") != cls.BINARY_MAGIC:
                Log.fatal("%s is not a lightgbm_tpu binary dataset", path)
            version = int(header.get("format_version", 1))
            if version != cls.BINARY_FORMAT_VERSION:
                Log.fatal(
                    "binary dataset cache %s has format version %d but "
                    "this build reads version %d; the cache is stale — "
                    "delete it and rebuild with save_binary "
                    "(or save_binary=true)", path, version,
                    cls.BINARY_FORMAT_VERSION)
            ds = cls()
            ds.num_data = int(header["num_data"])
            ds.num_total_features = int(header["num_total_features"])
            ds.num_data_padded = int(header["num_data_padded"])
            ds.max_num_bin = int(header["max_num_bin"])
            ds.feature_names = list(header["feature_names"])
            if header.get("nbits4"):
                from .nbits import unpack_nibbles
                packed = z["bins"]
                ds.bins = unpack_nibbles(packed, int(header["num_columns"]))
                ds._bins_packed = packed  # skip the re-pack at upload time
            else:
                ds.bins = z["bins"]
            ds.monotone_constraints = z["monotone"]
            ds.feature_penalty = z["penalty"]
            for i, mh in enumerate(header["mappers"]):
                d = dict(mh)
                d["bin_upper_bound"] = z["mapper%d_upper" % i]
                d["bin_2_categorical"] = z["mapper%d_cats" % i]
                ds.bin_mappers.append(BinMapper.from_arrays(d))
            if "bundle_groups" in header:
                ds.bundle_info = BundleInfo(
                    groups=[list(g) for g in header["bundle_groups"]],
                    f_group=z["bundle_f_group"],
                    f_offset=z["bundle_f_offset"],
                    f_identity=z["bundle_f_identity"],
                    group_num_bin=z["bundle_group_num_bin"],
                    max_group_bin=int(z["bundle_group_num_bin"].max()),
                    conflict_rates=z["bundle_conflict_rates"]
                    if "bundle_conflict_rates" in z.files else None)
            ds.metadata = Metadata(ds.num_data)
            for name in ("label", "weight", "init_score", "query_boundaries"):
                if "md_" + name in z.files:
                    setattr(ds.metadata, name, z["md_" + name])
        Log.info("Loaded binary dataset cache from %s (%d rows, %d features)",
                 path, ds.num_data, ds.num_total_features)
        return ds

    @staticmethod
    def _find_bin_mappers(X: np.ndarray, config,
                          categorical_feature: Sequence[int]) -> List[BinMapper]:
        n, f = X.shape
        sample_cnt = min(int(getattr(config, "bin_construct_sample_cnt", 200000)), n)
        rng = Random(int(getattr(config, "data_random_seed", 1)))
        sample_idx = rng.sample(n, sample_cnt)
        cat = set(int(c) for c in categorical_feature)
        mappers: List[BinMapper] = []
        max_bin = int(getattr(config, "max_bin", 255))
        min_data_in_bin = int(getattr(config, "min_data_in_bin", 3))
        use_missing = bool(getattr(config, "use_missing", True))
        zero_as_missing = bool(getattr(config, "zero_as_missing", False))
        def find_one(j: int) -> BinMapper:
            m = BinMapper()
            values = X[sample_idx, j].astype(np.float64)
            bin_type = BIN_TYPE_CATEGORICAL if j in cat else BIN_TYPE_NUMERICAL
            m.find_bin(values, len(sample_idx), max_bin,
                       min_data_in_bin=min_data_in_bin, bin_type=bin_type,
                       use_missing=use_missing, zero_as_missing=zero_as_missing)
            return m

        # feature-sharded find-bin (reference ParallelFindBin /
        # is_parallel_find_bin, src/io/dataset_loader.cpp:842-924: each rank
        # bins a feature slice and the mappers are allgathered; here the
        # shards are host worker threads, and the "allgather" is the shared
        # result list — one process owns all device shards)
        if bool(getattr(config, "is_parallel_find_bin", True)) and f > 8:
            import concurrent.futures as cf
            import os as _os
            nt = int(getattr(config, "num_threads", 0) or 0)
            workers = nt if nt > 0 else min(16, _os.cpu_count() or 1)
            with cf.ThreadPoolExecutor(workers) as pool:
                mappers = list(pool.map(find_one, range(f)))
        else:
            mappers = [find_one(j) for j in range(f)]
        num_trivial = sum(1 for m in mappers if m.is_trivial)
        if num_trivial:
            Log.info("%d features are ignored (constant value)", num_trivial)
        Log.info("Total bins: %d over %d features",
                 sum(m.num_bin for m in mappers), f - num_trivial)
        return mappers

    # -- row subsetting (reference Dataset::CopySubrow via
    #    LGBM_DatasetGetSubset): gather BINNED rows directly, sharing the
    #    mappers/bundles — no raw data needed, so it also serves datasets
    #    built from a stream whose raw chunks were dropped ------------------
    def subset(self, used_indices) -> "BinnedDataset":
        idx = np.asarray(used_indices, dtype=np.int64).reshape(-1)
        if idx.size == 0:
            Log.fatal("used_indices must not be empty")
        if idx.min() < 0 or idx.max() >= self.num_data:
            Log.fatal("used_indices out of range [0, %d)", self.num_data)
        if np.any(np.diff(idx) <= 0):
            Log.fatal("used_indices must be sorted ascending and unique "
                      "(the reference GetSubset contract)")
        k = int(idx.size)
        ds = BinnedDataset()
        ds.num_data = k
        ds.num_total_features = self.num_total_features
        ds.bin_mappers = list(self.bin_mappers)
        ds.max_num_bin = self.max_num_bin
        ds.bundle_info = self.bundle_info
        n_pad = _round_up(k, 16384) if k > 16384 else _round_up(k, 128)
        bins = np.zeros((self.bins.shape[0], n_pad), dtype=self.bins.dtype)
        bins[:, :k] = self.bins[:, idx]
        ds.bins = bins
        ds.num_data_padded = n_pad
        ds.feature_names = list(self.feature_names)
        ds.monotone_constraints = self.monotone_constraints
        ds.feature_penalty = self.feature_penalty
        md = Metadata(k)
        src = self.metadata
        if src is not None:
            if src.query_boundaries is not None:
                # Ranking subset (ISSUE 11): slice the query structure
                # along with the rows.  Each kept row maps to its source
                # query; since idx is sorted ascending, rows of one query
                # stay contiguous, so the subset's boundaries are the
                # run lengths of that mapping.  Whole kept groups keep
                # their size; partially-kept groups shrink (the
                # rolling-window trainer cuts on group boundaries, so in
                # that path groups are always whole).
                qb = src.query_boundaries
                row_query = np.searchsorted(qb, idx, side="right") - 1
                starts = np.flatnonzero(np.diff(row_query)) + 1
                counts = np.diff(np.concatenate([[0], starts, [k]]))
                md.set_query(counts)
            if src.label is not None:
                md.set_label(src.label[idx])
            if src.weight is not None:
                md.set_weight(src.weight[idx])
            if src.init_score is not None:
                if len(src.init_score) != self.num_data:
                    Log.fatal("cannot subset a multi-class init_score "
                              "through GetSubset")
                md.set_init_score(src.init_score[idx])
        ds.metadata = md
        return ds

    # -- accessors -----------------------------------------------------------
    @property
    def num_features(self) -> int:
        return self.num_total_features

    def feature_infos(self) -> List[str]:
        return [m.feature_info() for m in self.bin_mappers]

    def real_threshold(self, feature: int, bin_idx: int) -> float:
        """Bin threshold → double threshold for the model file
        (Dataset::RealThreshold)."""
        return self.bin_mappers[feature].bin_to_value(bin_idx)

    def storage_num_bins(self) -> np.ndarray:
        """[G] bin count of each STORAGE column (bundle width when EFB is
        active, the feature's own bins otherwise)."""
        if self.bundle_info is not None:
            return np.asarray(self.bundle_info.group_num_bin)
        return np.asarray([m.num_bin for m in self.bin_mappers])

    def valid_row_mask(self) -> np.ndarray:
        mask = np.zeros(self.num_data_padded, dtype=np.float32)
        mask[: self.num_data] = 1.0
        return mask

    def padded(self, arr: Optional[np.ndarray], fill: float = 0.0,
               dtype=np.float32) -> np.ndarray:
        """Pad a per-row array to the padded row count."""
        out = np.full(self.num_data_padded, fill, dtype=dtype)
        if arr is not None:
            out[: self.num_data] = arr
        return out
