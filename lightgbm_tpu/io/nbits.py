"""4-bit bin storage (dense_nbits_bin.hpp:37 role, redesigned for TPU).

The reference keeps <=16-bin features nibble-packed in RAM because its
histogram kernel reads the bin array directly, so 4-bit storage halves its
working-set bandwidth.  This engine's training working set is the f32
payload matrix (lane-padded to 128 on TPU — see docs/STORAGE.md for the
measured argument), so packing pays off at the STORAGE/TRANSFER boundary
instead: the binary dataset cache and the host->device upload are halved
for <=16-bin datasets, with the nibbles unpacked on device where the
unpack is free relative to the transfer.  Host RAM keeps the unpacked
matrix (every host consumer reads it repeatedly; docs/STORAGE.md).

Layout: storage column pairs (2k, 2k+1) share one uint8 row; column 2k in
the high nibble.  An odd trailing column packs alone (low nibble zero-pad
in the high slot semantics kept simple: stored as the high nibble).
"""
from __future__ import annotations

import functools

import numpy as np


def packable(group_num_bins) -> bool:
    """True when every storage column fits in a nibble and packing saves."""
    arr = np.asarray(group_num_bins)
    return arr.size >= 2 and bool((arr <= 16).all())


def should_pack(ds) -> bool:
    """The one gate both boundaries (binary cache, H2D upload) share."""
    return ds.bins.dtype == np.uint8 and packable(ds.storage_num_bins())


def get_packed(ds) -> np.ndarray:
    """The dataset's nibble-packed matrix, computed once and cached."""
    packed = getattr(ds, "_bins_packed", None)
    if packed is None:
        packed = pack_nibbles(ds.bins)
        ds._bins_packed = packed
    return packed


def pack_nibbles(bins: np.ndarray) -> np.ndarray:
    """[G, N] uint8 (values < 16) -> [ceil(G/2), N] uint8."""
    assert bins.dtype == np.uint8 and bins.max(initial=0) < 16
    G, N = bins.shape
    Gp = (G + 1) // 2
    out = np.zeros((Gp, N), np.uint8)
    out[: G // 2] = (bins[0::2][: G // 2] << 4) | bins[1::2]
    if G % 2:
        out[-1] = bins[-1] << 4
    return out


def unpack_nibbles(packed: np.ndarray, num_columns: int) -> np.ndarray:
    """Inverse of pack_nibbles."""
    Gp, N = packed.shape
    out = np.empty((num_columns, N), np.uint8)
    out[0::2] = packed[: (num_columns + 1) // 2] >> 4
    out[1::2] = packed[: num_columns // 2] & 0x0F
    return out


def unpack_nibbles_device(packed_host: np.ndarray, num_columns: int):
    """Upload the PACKED matrix (half the H2D bytes) and unpack on device."""
    import jax  # noqa: F401 — platform bind happens here
    import jax.numpy as jnp

    from ..runtime import xla_obs

    @functools.partial(xla_obs.jit, site="nbits.unpack_device")
    def unpack(p):
        hi = (p >> 4).astype(jnp.uint8)
        lo = (p & 0x0F).astype(jnp.uint8)
        inter = jnp.stack([hi, lo], axis=1).reshape(-1, p.shape[1])
        return inter[:num_columns]

    return unpack(jnp.asarray(packed_host))
