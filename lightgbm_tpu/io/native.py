"""ctypes wrappers for the native ingest fast paths (cpp/ingest.cc).

The reference's loader is native code end to end (dataset_loader.cpp +
parser.cpp + bin.h ValueToBin); these wrappers give the Python loader the
same native parse and bin-encode stages.  Every entry returns None on any
problem so callers fall back to the tolerant Python implementations.
"""
from __future__ import annotations

import ctypes
from typing import List, Optional, Tuple

import numpy as np

_lib = None
_lib_failed = False


def _load():
    """The ingest symbols live in the same shared library as the
    prediction C API; reuse its build-and-load machinery."""
    global _lib, _lib_failed
    if _lib is None and not _lib_failed:
        try:
            from ..capi import load_lib
            lib = load_lib()
            lib.LGBMT_CountRows.restype = ctypes.c_longlong
            lib.LGBMT_CountRows.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                            ctypes.c_char]
            lib.LGBMT_ParseDense.restype = ctypes.c_int
            lib.LGBMT_ParseDense.argtypes = [
                ctypes.c_char_p, ctypes.c_char, ctypes.c_int,
                ctypes.c_longlong, ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_double)]
            lib.LGBMT_EncodeBins.restype = ctypes.c_int
            lib.LGBMT_EncodeBins.argtypes = [
                ctypes.POINTER(ctypes.c_double), ctypes.c_longlong,
                ctypes.c_int, ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_longlong),
                ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_ubyte), ctypes.c_longlong]
            _lib = lib
        except Exception:
            _lib_failed = True
    return _lib


def parse_dense(path: str, sep: str, label_column: int, has_header: bool,
                n_cols: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """mmap + OpenMP parse of a numeric CSV/TSV -> (X [n, n_cols-1], y [n]).
    None when the native library is unavailable or the parse fails."""
    lib = _load()
    if lib is None or n_cols < 2 or not (0 <= label_column < n_cols):
        return None
    try:
        pathb = path.encode()
        n = lib.LGBMT_CountRows(pathb, int(has_header), sep.encode()[:1])
        if n <= 0:
            return None
        X = np.empty((n, n_cols - 1), dtype=np.float64)
        # NaN-filled: short lines that end before the label column leave
        # y rows unwritten (the C side NaN-fills only the feature row)
        y = np.full(n, np.nan, dtype=np.float64)
        rc = lib.LGBMT_ParseDense(
            pathb, sep.encode()[:1], int(has_header),
            ctypes.c_longlong(n), n_cols, label_column,
            X.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            y.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
        if rc != 0:
            return None
        return X, y
    except Exception:
        return None


def encode_bins(X: np.ndarray, mappers: List,
                bins_out: np.ndarray) -> bool:
    """Native ValueToBin over the whole matrix into the feature-major
    uint8 storage (bins_out [F, n_stride]).  Handles numerical features
    only — returns False (caller keeps the Python path) when any
    non-trivial feature is categorical, >256 bins, or the library is
    missing.  Trivial features are skipped (their storage stays zeros),
    matching the Python loop."""
    from .binning import BIN_TYPE_CATEGORICAL
    lib = _load()
    if lib is None or bins_out.dtype != np.uint8:
        return False
    n, F = X.shape
    if F != len(mappers) or bins_out.shape[0] != F or bins_out.shape[1] < n:
        return False
    offs = np.zeros(F, dtype=np.int64)
    cnts = np.zeros(F, dtype=np.int32)
    miss = np.zeros(F, dtype=np.int32)
    nbin = np.zeros(F, dtype=np.int32)
    triv = np.zeros(F, dtype=np.int32)
    chunks = []
    off = 0
    for f, m in enumerate(mappers):
        if m.is_trivial:
            triv[f] = 1
            continue
        if m.bin_type == BIN_TYPE_CATEGORICAL or m.num_bin > 256:
            return False
        b = np.asarray(m.bin_upper_bound, dtype=np.float64)
        offs[f] = off
        cnts[f] = len(b)
        miss[f] = int(m.missing_type)
        nbin[f] = int(m.num_bin)
        chunks.append(b)
        off += len(b)
    bounds = (np.concatenate(chunks) if chunks
              else np.zeros(1, dtype=np.float64))
    # chunk the f64 conversion: a whole-matrix ascontiguousarray of a
    # float32 Higgs-scale X would be a multi-GB transient
    already = (X.dtype == np.float64 and X.flags.c_contiguous)
    block = n if already else max(1, (1 << 24) // max(F, 1))
    for b0 in range(0, n, block):
        b1 = min(b0 + block, n)
        Xc = np.ascontiguousarray(X[b0:b1], dtype=np.float64)
        rc = lib.LGBMT_EncodeBins(
            Xc.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            ctypes.c_longlong(b1 - b0), F,
            bounds.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            offs.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
            cnts.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            miss.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            nbin.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            triv.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            bins_out[:, b0:].ctypes.data_as(
                ctypes.POINTER(ctypes.c_ubyte)),
            ctypes.c_longlong(bins_out.shape[1]))
        if rc != 0:
            return False
    return True
