"""Feature binning: raw values → small-integer bins.

Role parity with the reference BinMapper (include/LightGBM/bin.h:61-209,
src/io/bin.cpp): greedy equal-frequency bin boundaries (GreedyFindBin,
bin.cpp:74-148), a dedicated zero bin (FindBinWithZeroAsOneBin,
bin.cpp:150-207), missing-value modes None/Zero/NaN (FindBin,
bin.cpp:208-300), and count-sorted categorical bins (bin.cpp:303-360).

Host-side (numpy): binning is a one-time ingest step; the result is a packed
integer matrix shipped to TPU HBM.  The algorithms are re-implemented from the
observed semantics, vectorized where possible.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..utils.log import Log

K_ZERO_THRESHOLD = 1e-35

MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2

BIN_TYPE_NUMERICAL = 0
BIN_TYPE_CATEGORICAL = 1


def _double_up(v: float) -> float:
    """Next representable double — boundaries are exclusive upper bounds that
    must still satisfy `value <= bound` for the boundary value itself."""
    return float(np.nextafter(v, np.inf))


def greedy_find_bin(distinct_values: np.ndarray, counts: np.ndarray,
                    max_bin: int, total_cnt: int, min_data_in_bin: int) -> List[float]:
    """Equal-frequency boundaries over (distinct value, count) pairs.

    Heavily-repeated values (count >= mean bin size) are pinned to their own
    bin; remaining budget is re-spread over the rest (bin.cpp:74-148).
    """
    n = len(distinct_values)
    bounds: List[float] = []
    if n == 0:
        return [np.inf]
    if n <= max_bin:
        cur = 0
        for i in range(n - 1):
            cur += int(counts[i])
            if cur >= min_data_in_bin:
                val = _double_up((distinct_values[i] + distinct_values[i + 1]) / 2.0)
                if not bounds or val > bounds[-1]:
                    bounds.append(val)
                    cur = 0
        bounds.append(np.inf)
        return bounds

    if min_data_in_bin > 0:
        max_bin = max(1, min(max_bin, total_cnt // min_data_in_bin))
    mean_bin_size = total_cnt / max_bin
    is_big = counts >= mean_bin_size
    rest_bin_cnt = max_bin - int(is_big.sum())
    rest_sample_cnt = total_cnt - int(counts[is_big].sum())
    mean_bin_size = rest_sample_cnt / max(rest_bin_cnt, 1)

    uppers: List[float] = []
    lowers: List[float] = [float(distinct_values[0])]
    cur = 0
    for i in range(n - 1):
        if not is_big[i]:
            rest_sample_cnt -= int(counts[i])
        cur += int(counts[i])
        if is_big[i] or cur >= mean_bin_size or \
                (is_big[i + 1] and cur >= max(1.0, mean_bin_size * 0.5)):
            uppers.append(float(distinct_values[i]))
            lowers.append(float(distinct_values[i + 1]))
            if len(uppers) >= max_bin - 1:
                break
            cur = 0
            if not is_big[i]:
                rest_bin_cnt -= 1
                mean_bin_size = rest_sample_cnt / max(rest_bin_cnt, 1)
    for i in range(len(uppers)):
        val = _double_up((uppers[i] + lowers[i + 1]) / 2.0)
        if not bounds or val > bounds[-1]:
            bounds.append(val)
    bounds.append(np.inf)
    return bounds


def find_bin_with_zero_as_one_bin(distinct_values: np.ndarray, counts: np.ndarray,
                                  max_bin: int, total_cnt: int,
                                  min_data_in_bin: int) -> List[float]:
    """Split the value range at zero so bin(0.0) is exact (bin.cpp:150-207)."""
    left_mask = distinct_values <= -K_ZERO_THRESHOLD
    right_mask = distinct_values > K_ZERO_THRESHOLD
    zero_mask = ~left_mask & ~right_mask
    left_cnt_data = int(counts[left_mask].sum())
    cnt_zero = int(counts[zero_mask].sum())
    right_cnt_data = int(counts[right_mask].sum())

    left_cnt = int(left_mask.sum())
    bounds: List[float] = []
    if left_cnt > 0:
        denom = max(total_cnt - cnt_zero, 1)
        left_max_bin = max(1, int(left_cnt_data / denom * (max_bin - 1)))
        bounds = greedy_find_bin(distinct_values[:left_cnt], counts[:left_cnt],
                                 left_max_bin, left_cnt_data, min_data_in_bin)
        bounds[-1] = -K_ZERO_THRESHOLD
    if right_cnt_data > 0 or right_mask.any():
        right_start = np.argmax(right_mask) if right_mask.any() else -1
    else:
        right_start = -1
    if right_start >= 0:
        right_max_bin = max_bin - 1 - len(bounds)
        right_bounds = greedy_find_bin(distinct_values[right_start:], counts[right_start:],
                                       right_max_bin, right_cnt_data, min_data_in_bin)
        bounds.append(K_ZERO_THRESHOLD)
        bounds.extend(right_bounds)
    else:
        bounds.append(np.inf)
    return bounds


class BinMapper:
    """Per-feature raw-value ↔ bin mapping."""

    def __init__(self):
        self.num_bin = 1
        self.missing_type = MISSING_NONE
        self.is_trivial = True
        self.sparse_rate = 0.0
        self.bin_type = BIN_TYPE_NUMERICAL
        self.bin_upper_bound: np.ndarray = np.array([np.inf])
        self.bin_2_categorical: List[int] = []
        self.categorical_2_bin: dict = {}
        self.min_val = 0.0
        self.max_val = 0.0
        self.default_bin = 0  # bin of raw value 0.0

    # -- construction (bin.cpp FindBin:208-360) ------------------------------
    def find_bin(self, values: np.ndarray, total_sample_cnt: int, max_bin: int,
                 min_data_in_bin: int = 3, min_split_data: int = 0,
                 bin_type: int = BIN_TYPE_NUMERICAL, use_missing: bool = True,
                 zero_as_missing: bool = False) -> None:
        values = np.asarray(values, dtype=np.float64)
        na_mask = np.isnan(values)
        non_na = values[~na_mask]
        na_cnt = int(na_mask.sum())
        if not use_missing:
            self.missing_type = MISSING_NONE
        elif zero_as_missing:
            self.missing_type = MISSING_ZERO
        else:
            self.missing_type = MISSING_NAN if na_cnt > 0 else MISSING_NONE

        # implicit zeros: rows not present in the sample (sparse ingest)
        zero_cnt = int(total_sample_cnt - len(non_na) - na_cnt)
        distinct, counts = self._distinct_with_zero(non_na, zero_cnt)
        if len(distinct) == 0:
            distinct = np.array([0.0])
            counts = np.array([max(zero_cnt, 1)])
        self.min_val, self.max_val = float(distinct[0]), float(distinct[-1])
        self.bin_type = bin_type

        if bin_type == BIN_TYPE_NUMERICAL:
            if self.missing_type == MISSING_ZERO:
                bounds = find_bin_with_zero_as_one_bin(distinct, counts, max_bin,
                                                       total_sample_cnt, min_data_in_bin)
                if len(bounds) == 2:
                    self.missing_type = MISSING_NONE
            elif self.missing_type == MISSING_NONE:
                bounds = find_bin_with_zero_as_one_bin(distinct, counts, max_bin,
                                                       total_sample_cnt, min_data_in_bin)
            else:  # NaN: reserve the last bin for NaN
                bounds = find_bin_with_zero_as_one_bin(distinct, counts, max_bin - 1,
                                                       total_sample_cnt - na_cnt,
                                                       min_data_in_bin)
                bounds.append(np.nan)
            self.bin_upper_bound = np.array(bounds)
            self.num_bin = len(bounds)
            self.default_bin = self.value_to_bin(0.0)
        else:
            self._find_bin_categorical(distinct, counts, max_bin, total_sample_cnt,
                                       na_cnt, min_data_in_bin)

        self.is_trivial = self.num_bin <= 1
        counts_per_bin = self._cnt_in_bin(distinct, counts, na_cnt)
        if self.num_bin > 0 and len(counts_per_bin):
            self.sparse_rate = float(counts_per_bin[self.default_bin]) / max(total_sample_cnt, 1)

    @staticmethod
    def _distinct_with_zero(non_na: np.ndarray, zero_cnt: int) -> Tuple[np.ndarray, np.ndarray]:
        """Distinct sorted values with the implicit-zero count merged in."""
        if len(non_na) == 0:
            if zero_cnt > 0:
                return np.array([0.0]), np.array([zero_cnt])
            return np.array([]), np.array([], dtype=np.int64)
        vals = np.sort(non_na)
        distinct, counts = np.unique(vals, return_counts=True)
        if zero_cnt > 0:
            zero_pos = np.searchsorted(distinct, 0.0)
            if zero_pos < len(distinct) and distinct[zero_pos] == 0.0:
                counts = counts.copy()
                counts[zero_pos] += zero_cnt
            else:
                distinct = np.insert(distinct, zero_pos, 0.0)
                counts = np.insert(counts, zero_pos, zero_cnt)
        return distinct, counts

    def _find_bin_categorical(self, distinct: np.ndarray, counts: np.ndarray,
                              max_bin: int, total_cnt: int, na_cnt: int,
                              min_data_in_bin: int = 3) -> None:
        """Count-sorted categorical bins (bin.cpp:303-371): most frequent
        category ↔ bin 0 (but never category 0, which stays off bin 0 for the
        sparse default), rare tail / negatives / NaN fold to the LAST bin,
        which split finding excludes unless missing_type is None."""
        ints = distinct.astype(np.int64)
        neg = ints < 0
        if neg.any():
            Log.warning("Met negative value in categorical features, will convert it to NaN")
            na_cnt += int(np.asarray(counts)[neg].sum())
        ints, counts = ints[~neg], np.asarray(counts)[~neg]
        agg: dict = {}
        for v, c in zip(ints, counts):
            agg[int(v)] = agg.get(int(v), 0) + int(c)
        vals = sorted(agg, key=lambda v: -agg[v])
        cnts = [agg[v] for v in vals]
        rest_cnt = total_cnt - na_cnt
        self.num_bin = 0
        self.bin_2_categorical = []
        self.categorical_2_bin = {}
        if rest_cnt > 0 and vals:
            # avoid first bin being category zero (bin.cpp:325-333)
            if vals[0] == 0:
                if len(vals) == 1:
                    vals.append(1)
                    cnts.append(0)
                vals[0], vals[1] = vals[1], vals[0]
                cnts[0], cnts[1] = cnts[1], cnts[0]
            cut_cnt = int(rest_cnt * 0.99)
            max_bin_eff = min(len(vals), max_bin)
            used = 0
            cur = 0
            while cur < len(vals) and (used < cut_cnt or self.num_bin < max_bin_eff):
                if cnts[cur] < min_data_in_bin and cur > 1:
                    break
                self.bin_2_categorical.append(vals[cur])
                self.categorical_2_bin[vals[cur]] = self.num_bin
                used += cnts[cur]
                self.num_bin += 1
                cur += 1
            if cur == len(vals) and na_cnt > 0:
                # dedicated NaN bin, category -1 (bin.cpp:354-360)
                self.bin_2_categorical.append(-1)
                self.categorical_2_bin[-1] = self.num_bin
                self.num_bin += 1
            if cur == len(vals) and na_cnt == 0:
                self.missing_type = MISSING_NONE
            elif na_cnt == 0:
                self.missing_type = MISSING_ZERO
            else:
                self.missing_type = MISSING_NAN
        # ValueToBin(0): category 0's bin, or the overflow (last) bin
        self.default_bin = self.categorical_2_bin.get(0, max(self.num_bin - 1, 0))

    def _cnt_in_bin(self, distinct: np.ndarray, counts: np.ndarray, na_cnt: int) -> np.ndarray:
        out = np.zeros(max(self.num_bin, 1), dtype=np.int64)
        if self.bin_type == BIN_TYPE_NUMERICAL:
            if len(distinct):
                idx = np.searchsorted(self.bin_upper_bound[:-1], distinct, side="left")
                np.add.at(out, np.minimum(idx, self.num_bin - 1), counts)
            if self.missing_type == MISSING_NAN and self.num_bin >= 1:
                out[self.num_bin - 1] = na_cnt
        else:
            for v, c in zip(distinct.astype(np.int64), counts):
                b = self.categorical_2_bin.get(int(v))
                if b is not None:
                    out[b] += int(c)
        return out

    # -- mapping (bin.h ValueToBin:452-488) ----------------------------------
    def value_to_bin(self, value) -> int:
        return int(self.values_to_bins(np.array([value]))[0])

    def values_to_bins(self, values: np.ndarray) -> np.ndarray:
        """Vectorized raw value → bin index."""
        values = np.asarray(values, dtype=np.float64)
        if self.bin_type == BIN_TYPE_CATEGORICAL:
            # negative / unseen -> last bin; NaN -> last bin when
            # missing_type is NaN, else treated as category 0
            # (bin.h ValueToBin:452-487)
            last = max(self.num_bin - 1, 0)
            out = np.full(len(values), last, dtype=np.int32)
            for i, v in enumerate(values):
                if np.isnan(v):
                    if self.missing_type != MISSING_NAN:
                        out[i] = self.categorical_2_bin.get(0, last)
                elif int(v) >= 0:
                    out[i] = self.categorical_2_bin.get(int(v), last)
            return out
        nan_mask = np.isnan(values)
        if self.missing_type == MISSING_NAN:
            # non-NaN values bin over bounds[:-2] (last numeric bin), NaN → last bin
            search_bounds = self.bin_upper_bound[:-2] if self.num_bin >= 2 else self.bin_upper_bound[:0]
            vals = np.where(nan_mask, 0.0, values)
            idx = np.searchsorted(search_bounds, vals, side="left")
            idx = np.where(nan_mask, self.num_bin - 1, idx)
        else:
            vals = np.where(nan_mask, 0.0, values)  # NaN treated as zero
            idx = np.searchsorted(self.bin_upper_bound[:-1], vals, side="left")
        return idx.astype(np.int32)

    def bin_to_value(self, bin_idx: int) -> float:
        """Representative threshold for saving models (upper bound of the bin)."""
        if self.bin_type == BIN_TYPE_CATEGORICAL:
            return float(self.bin_2_categorical[bin_idx])
        return float(self.bin_upper_bound[bin_idx])

    def feature_info(self) -> str:
        """Model-file feature_infos entry: `[min:max]` or category list."""
        if self.is_trivial:
            return "none"
        if self.bin_type == BIN_TYPE_NUMERICAL:
            return "[%s:%s]" % (repr(self.min_val), repr(self.max_val))
        # bin order, not sorted (bin.h bin_info:176-185)
        return ":".join(str(c) for c in self.bin_2_categorical)

    # -- serialization for distributed find-bin ------------------------------
    def to_arrays(self):
        return {
            "num_bin": self.num_bin, "missing_type": self.missing_type,
            "is_trivial": self.is_trivial, "sparse_rate": self.sparse_rate,
            "bin_type": self.bin_type,
            "bin_upper_bound": np.asarray(self.bin_upper_bound, dtype=np.float64),
            "bin_2_categorical": np.asarray(self.bin_2_categorical, dtype=np.int64),
            "min_val": self.min_val, "max_val": self.max_val,
            "default_bin": self.default_bin,
        }

    @classmethod
    def from_arrays(cls, d) -> "BinMapper":
        m = cls()
        m.num_bin = int(d["num_bin"]); m.missing_type = int(d["missing_type"])
        m.is_trivial = bool(d["is_trivial"]); m.sparse_rate = float(d["sparse_rate"])
        m.bin_type = int(d["bin_type"])
        m.bin_upper_bound = np.asarray(d["bin_upper_bound"])
        m.bin_2_categorical = [int(v) for v in d["bin_2_categorical"]]
        m.categorical_2_bin = {v: i for i, v in enumerate(m.bin_2_categorical)}
        m.min_val = float(d["min_val"]); m.max_val = float(d["max_val"])
        m.default_bin = int(d["default_bin"])
        return m
