"""Text data parsers: CSV / TSV / LibSVM with auto-detection.

Role parity with the reference Parser (src/io/parser.cpp:169 CreateParser,
include/LightGBM/dataset.h:252-277): sniff the format from sample lines,
parse label + features into a dense matrix.  Host-side ingest (numpy); the
result feeds BinnedDataset.from_matrix.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ..utils.log import Log


def _is_libsvm_pair(tok: str) -> bool:
    """True only for `<int>:<number>` — a colon inside a timestamp or URL
    must not flip the whole file to libsvm."""
    k, sep, v = tok.partition(":")
    if not sep:
        return False
    try:
        int(k)
        float(v)
        return True
    except ValueError:
        return False


def detect_format(sample_lines) -> str:
    """'libsvm' | 'tsv' | 'csv' (parser.cpp GetDataType semantics: index:value
    pairs -> libsvm, tabs -> tsv, commas -> csv)."""
    for line in sample_lines:
        line = line.strip()
        if not line:
            continue
        tokens = line.replace("\t", " ").replace(",", " ").split()
        if any(_is_libsvm_pair(t) for t in tokens[1:]):
            return "libsvm"
        if "\t" in line:
            return "tsv"
        if "," in line:
            return "csv"
    return "tsv"


def _is_number(tok: str) -> bool:
    try:
        float(tok)
        return True
    except ValueError:
        return False


def sniff(path: str, has_header: Optional[bool] = None):
    """Format/header sniff shared by parse_file and the incremental tail
    parser (runtime/continuous.py) -> (fmt, sep, has_header, head_lines).
    sep is None for libsvm.  Reads only the file head — materializing the
    whole file as Python strings would dwarf the chunked fast path."""
    import itertools
    with open(path) as fh:
        head = [l for l in itertools.islice(fh, 200) if l.strip()][:20]
    fmt = detect_format(head)
    if has_header is None:
        first = head[0].strip() if head else ""
        seps = {"csv": ",", "tsv": "\t"}
        toks = first.split(seps[fmt]) if fmt in seps else first.split()
        # a header needs a token that is neither numeric nor a missing marker
        has_header = bool(toks) and not all(
            _is_number(t.split(":")[0]) or t.strip().lower() in _MISSING
            for t in toks)
    return fmt, {"csv": ",", "tsv": "\t"}.get(fmt), bool(has_header), head


def parse_file(path: str, label_column: int = 0, has_header: Optional[bool] = None,
               num_features: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray]:
    """Parse a data file -> (X [n, F], y [n]).  Auto-detects format and
    header; missing values ('', 'na', 'nan', 'null') become NaN."""
    fmt, sep, has_header, head = sniff(path, has_header)
    if fmt != "libsvm":
        # native mmap + OpenMP parser first (cpp/ingest.cc — the role of
        # the reference's native Parser), then the chunked pandas C-engine
        # pipeline, then the tolerant pure-Python parser
        n_cols = len(head[1 if has_header and len(head) > 1 else 0]
                     .rstrip("\n\r").split(sep)) if head else 0
        if n_cols >= 2:
            from .native import parse_dense
            out = parse_dense(path, sep, label_column, has_header, n_cols)
            if out is not None:
                X, y = out
                return _fix_width(X, num_features), y
        out = _parse_delimited_pandas(path, sep, label_column, num_features,
                                      has_header)
        if out is not None:
            return out
    # tolerant pure-Python fallback (and the libsvm path) read fully
    with open(path) as fh:
        lines = [l for l in fh.readlines() if l.strip()]
    body = lines[1:] if has_header else lines
    if fmt == "libsvm":
        return _parse_libsvm(body, num_features)
    return _parse_delimited(body, sep, label_column, num_features)


def _parse_delimited_pandas(path, sep, label_column, num_features,
                            has_header):
    """Chunked two-stage ingest pipeline (role of the reference's
    overlapped TextReader/Parser pipeline, §2.11 item 7): pandas' C parser
    reads+tokenizes the NEXT chunk in a worker thread (the C engine drops
    the GIL) while the main thread converts the PREVIOUS chunk to the
    float matrix.  Falls back to the pure-Python parser on anything the
    fast path can't express (ragged rows, exotic markers)."""
    try:
        import pandas as pd
    except ImportError:
        return None
    import concurrent.futures as cf
    try:
        reader = pd.read_csv(
            path, sep=sep, header=0 if has_header else None,
            na_values=list(_MISSING), comment=None, engine="c",
            dtype=np.float64, chunksize=1_000_000)
        xs, ys = [], []
        with cf.ThreadPoolExecutor(1) as pool:
            def pull():
                try:
                    return next(reader)
                except StopIteration:
                    return None
            fut = pool.submit(pull)
            while True:
                chunk = fut.result()
                if chunk is None:
                    break
                fut = pool.submit(pull)          # overlap next read
                arr = chunk.to_numpy(dtype=np.float64, copy=False)
                # copy the label slice: a view would pin the whole chunk
                # matrix in memory until the final concatenate
                ys.append(arr[:, label_column].copy())
                xs.append(np.delete(arr, label_column, axis=1))
        if not xs:
            return None
        X = np.concatenate(xs) if len(xs) > 1 else xs[0]
        y = np.concatenate(ys) if len(ys) > 1 else ys[0]
        return _fix_width(X, num_features), y
    except Exception:
        return None  # ragged/odd file: the tolerant python parser handles it


def _fix_width(X, num_features):
    """Reconcile a parsed matrix to the requested feature count
    (validation files must align to the training schema)."""
    if num_features is None or X.shape[1] == num_features:
        return X
    fixed = np.full((X.shape[0], num_features), np.nan)
    fixed[:, :min(X.shape[1], num_features)] = X[:, :num_features]
    return fixed


_MISSING = {"", "na", "nan", "null", "n/a", "none", "?"}


def _parse_value(tok: str) -> float:
    tok = tok.strip()
    if tok.lower() in _MISSING:
        return np.nan
    return float(tok)


def _parse_delimited(lines, sep, label_column, num_features):
    rows = []
    labels = []
    for line in lines:
        line = line.rstrip("\n\r")
        if not line.strip():
            continue
        toks = line.split(sep)
        vals = [_parse_value(t) for t in toks]
        labels.append(vals[label_column])
        del vals[label_column]
        rows.append(vals)
    if not rows:
        Log.fatal("Data file is empty or unparseable")
    F = num_features if num_features else max(len(r) for r in rows)
    X = np.full((len(rows), F), np.nan)
    for i, r in enumerate(rows):
        X[i, :min(len(r), F)] = r[:F]
    return X, np.asarray(labels, dtype=np.float64)


def _parse_libsvm(lines, num_features):
    rows = []
    labels = []
    maxf = -1
    for line in lines:
        parts = line.split()
        if not parts:
            continue
        labels.append(float(parts[0]))
        feats = {}
        for tok in parts[1:]:
            if ":" not in tok:
                continue
            k, v = tok.split(":", 1)
            feats[int(k)] = _parse_value(v)
            maxf = max(maxf, int(k))
        rows.append(feats)
    if not rows:
        Log.fatal("Data file is empty or unparseable")
    F = num_features if num_features else maxf + 1
    X = np.zeros((len(rows), F))
    for i, feats in enumerate(rows):
        for k, v in feats.items():
            if k < F:
                X[i, k] = v
    return X, np.asarray(labels, dtype=np.float64)


def load_sidecar(path: str) -> Optional[np.ndarray]:
    """Optional one-value-per-line sidecar (<data>.weight / <data>.query,
    metadata.cpp LoadWeights/LoadQueryBoundaries)."""
    import os
    if not os.path.exists(path):
        return None
    vals = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if line:
                vals.append(float(line))
    return np.asarray(vals, dtype=np.float64) if vals else None
