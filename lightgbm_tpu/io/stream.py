"""Zero-copy streaming dataset ingest: chunked CSR/CSC/dense construction.

Role parity with the reference's dataset-from-memory block (c_api.h:48-232,
src/c_api.cpp Dataset sections): ``LGBM_DatasetCreateFromCSR/CSC/Mat``,
``LGBM_DatasetCreateByReference`` + ``LGBM_DatasetPushRows[ByCSR]``
streaming.  Feature-store pipelines and other-language bindings push
in-memory chunks and get the exact same binned ``BinnedDataset`` the CSV
parser path produces — no file detour, no re-parse.

Two operating modes, mirroring the reference's sample-then-bin flow:

* **buffered** (fresh stream, no reference): pushed chunks are retained by
  reference (zero-copy — dense chunks and CSR triplets are not copied or
  densified at push time) while a BOUNDED reservoir sample, capped at
  ``bin_construct_sample_cnt`` rows, is maintained online for bin
  construction.  ``finalize()`` materializes the matrix once and runs the
  exact ``BinnedDataset.from_matrix`` pipeline.  While the stream fits the
  reservoir (the default 200k-row cap) the bins/bundles/metadata are
  byte-identical to what the file parser path produces on the same rows;
  beyond the cap both paths bin from a size-``sample_cnt`` uniform sample
  and differ only in which indices were drawn (docs/INGEST.md).
* **by-reference** (``LGBM_DatasetCreateByReference`` + push): the
  reference dataset's mappers are fixed up front, packed-integer storage
  is preallocated at the declared row count, and every pushed chunk is
  ENCODED IMMEDIATELY then dropped — memory is bounded by the uint8/uint16
  bin matrix, not the raw float stream.

CSR semantics follow the reference C API: absent entries are 0.0 (so
``zero_as_missing`` applies to them exactly as it does to explicit zeros
from a parsed file).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..utils.log import LightGBMError, Log
from ..utils.random import partition_seed
from .binning import BinMapper
from .dataset import BinnedDataset, Metadata, _round_up

#: partition_seed stream id for the reservoir sampler (disjoint from the
#: bagging/feature/binning streams used elsewhere)
_RESERVOIR_STREAM = 77


def _is_scipy_sparse(data) -> bool:
    return data.__class__.__module__.startswith("scipy.sparse")


class _Chunk:
    """One pushed chunk.  Dense chunks keep the caller's array by
    reference; CSR chunks keep the raw (indptr, indices, values) triplet —
    nothing is densified until finalize."""

    __slots__ = ("start_row", "num_rows", "dense", "csr")

    def __init__(self, start_row: int, num_rows: int, dense=None, csr=None):
        self.start_row = start_row
        self.num_rows = num_rows
        self.dense = dense
        self.csr = csr          # (indptr, indices, values, num_col)

    def rows(self, local_idx: np.ndarray, num_features: int) -> np.ndarray:
        """Densify ONLY the requested local rows (reservoir feed)."""
        if self.dense is not None:
            return np.asarray(self.dense, dtype=np.float64)[local_idx]
        indptr, indices, values, _ = self.csr
        out = np.zeros((len(local_idx), num_features), dtype=np.float64)
        for k, i in enumerate(np.asarray(local_idx)):
            s, e = int(indptr[i]), int(indptr[i + 1])
            out[k, np.asarray(indices[s:e], dtype=np.int64)] = values[s:e]
        return out

    def fill(self, X: np.ndarray, at: int) -> None:
        """Write this chunk's rows into X[at : at+num_rows] (X is zeroed,
        so absent CSR entries stay 0.0 — the reference's CSR contract)."""
        if self.dense is not None:
            X[at:at + self.num_rows] = np.asarray(self.dense,
                                                  dtype=np.float64)
            return
        indptr, indices, values, _ = self.csr
        counts = np.diff(np.asarray(indptr, dtype=np.int64))
        rows = np.repeat(np.arange(self.num_rows, dtype=np.int64), counts)
        X[at + rows, np.asarray(indices, dtype=np.int64)] = \
            np.asarray(values, dtype=np.float64)


class StreamingDatasetBuilder:
    """Chunked dataset builder behind ``lgb.Dataset(data=<iterator>)`` and
    the ``LGBM_Dataset*`` streaming C entry points."""

    def __init__(self, params: Optional[dict] = None,
                 num_features: Optional[int] = None,
                 reference=None, num_total_rows: Optional[int] = None,
                 feature_names: Optional[Sequence[str]] = None,
                 categorical_feature: Sequence[int] = (),
                 quarantine=None):
        """`quarantine` (a `runtime.quality.QuarantineLedger`, or True
        for a fresh one) arms push-time schema validation (ISSUE 12
        firewall stage one): rows with non-finite labels/weights are
        routed to the ledger instead of the dataset.  Default off — a
        quarantine-less build is byte-identical; even armed, a clean
        stream's chunks pass through untouched (same objects, still
        zero-copy)."""
        if quarantine is True:
            from ..runtime.quality import QuarantineLedger
            quarantine = QuarantineLedger()
        self.quarantine = quarantine
        self.params = dict(params or {})
        self.feature_names = list(feature_names) if feature_names else None
        self.categorical_feature = tuple(int(c) for c in categorical_feature)
        self._num_features = int(num_features) if num_features else None
        self._chunks: List[_Chunk] = []
        self._labels: List[Tuple[int, np.ndarray]] = []
        self._weights: List[Tuple[int, np.ndarray]] = []
        self._n = 0                      # rows pushed (append mode)
        self._explicit_rows = False      # any push carried a start_row
        self._finalized: Optional[BinnedDataset] = None

        # bounded reservoir (buffered mode find-bin sample)
        self._sample_cap = max(int(self.params.get(
            "bin_construct_sample_cnt", 200000) or 200000), 1)
        seed = int(self.params.get("data_random_seed", 1) or 1)
        self._res_rng = np.random.Generator(np.random.Philox(
            partition_seed(seed, _RESERVOIR_STREAM)))
        self._res: Optional[np.ndarray] = None
        self._res_seen = 0

        # by-reference streaming mode: mappers fixed, storage preallocated,
        # chunks encoded eagerly and dropped
        self._ref_binned = None
        self._bins: Optional[np.ndarray] = None
        self._covered: Optional[np.ndarray] = None
        self._num_total_rows = None
        if reference is not None:
            binned = getattr(reference, "binned", reference)
            if not isinstance(binned, BinnedDataset):
                raise LightGBMError(
                    "StreamingDatasetBuilder reference must be a Dataset "
                    "or BinnedDataset")
            self._ref_binned = binned
            self._num_features = binned.num_total_features
            if num_total_rows is not None:
                n = int(num_total_rows)
                if n <= 0:
                    raise LightGBMError(
                        "num_total_rows must be positive, got %d" % n)
                self._num_total_rows = n
                n_pad = _round_up(n, 16384) if n > 16384 \
                    else _round_up(max(n, 1), 128)
                max_bin = max((m.num_bin for m in binned.bin_mappers),
                              default=1)
                dtype = np.uint8 if max_bin <= 256 else np.uint16
                self._bins = np.zeros((self._num_features, n_pad),
                                      dtype=dtype)
                self._covered = np.zeros(n, dtype=bool)

    # -- introspection -------------------------------------------------------
    @property
    def num_pushed_rows(self) -> int:
        return self._n

    @property
    def num_features(self) -> Optional[int]:
        return self._num_features

    @property
    def streaming(self) -> bool:
        """True in the bounded-memory by-reference mode (raw chunks are
        encoded eagerly and never retained)."""
        return self._bins is not None

    @property
    def reservoir_rows(self) -> int:
        """Rows currently held by the bounded find-bin reservoir."""
        return 0 if self._res is None else min(self._res_seen,
                                               self._sample_cap)

    def labels(self) -> Optional[np.ndarray]:
        if not self._labels:
            return None
        y = np.empty(self._n, dtype=np.float64)
        for start, part in self._labels:
            y[start:start + len(part)] = part
        return y

    def weights(self) -> Optional[np.ndarray]:
        if not self._weights:
            return None
        w = np.empty(self._n, dtype=np.float64)
        for start, part in self._weights:
            w[start:start + len(part)] = part
        return w

    # -- push API ------------------------------------------------------------
    def push(self, chunk) -> "StreamingDatasetBuilder":
        """Duck-typed push for ``lgb.Dataset(data=<iterator>)`` chunks:
        a 2-D array, an ``(X, y)`` or ``(X, y, w)`` tuple, or a
        scipy.sparse matrix."""
        if isinstance(chunk, tuple):
            if len(chunk) == 2:
                X, y = chunk
                return self.push_dense(X, label=y)
            if len(chunk) == 3:
                X, y, w = chunk
                return self.push_dense(X, label=y, weight=w)
            raise LightGBMError("stream chunks must be X, (X, y) or "
                                "(X, y, w); got a %d-tuple" % len(chunk))
        if _is_scipy_sparse(chunk):
            csr = chunk.tocsr()
            return self.push_csr(csr.indptr, csr.indices, csr.data,
                                 csr.shape[1])
        return self.push_dense(chunk)

    def push_dense(self, X, label=None, weight=None,
                   start_row: int = -1) -> "StreamingDatasetBuilder":
        """Push a dense [m, F] chunk.  The array is kept by reference
        (zero-copy) in buffered mode and encoded immediately in
        by-reference mode; don't mutate it afterwards."""
        if getattr(X, "ndim", None) == 1:
            X = np.asarray(X).reshape(1, -1)
        if getattr(X, "ndim", None) != 2:
            raise LightGBMError("pushed chunks must be 2-dimensional")
        keep = self._quarantine_mask(X.shape[0], label, weight, start_row)
        if keep is not None:
            X = np.asarray(X, dtype=np.float64)[keep]
            label = np.asarray(label, dtype=np.float64).reshape(-1)[keep] \
                if label is not None else None
            weight = np.asarray(weight,
                                dtype=np.float64).reshape(-1)[keep] \
                if weight is not None else None
        m, f = X.shape
        self._check_features(f)
        chunk = _Chunk(start_row, m, dense=X)
        return self._push(chunk, label, weight)

    def push_csr(self, indptr, indices, values, num_col: int,
                 label=None, weight=None,
                 start_row: int = -1) -> "StreamingDatasetBuilder":
        """Push a CSR chunk: indptr [m+1] row offsets, indices [nnz]
        column ids, values [nnz].  Absent entries are 0.0 (the reference
        C-API contract, so zero-as-missing semantics match a parsed
        file's explicit zeros)."""
        indptr = np.asarray(indptr)
        m = len(indptr) - 1
        if m < 0 or int(indptr[0]) != 0:
            raise LightGBMError("CSR indptr must start at 0 and have one "
                                "entry per row plus one")
        nnz = int(indptr[-1])
        if len(indices) < nnz or len(values) < nnz:
            raise LightGBMError("CSR indices/values shorter than indptr[-1]")
        idx = np.asarray(indices)
        if nnz and int(idx[:nnz].max()) >= int(num_col):
            raise LightGBMError("CSR column index %d out of range for "
                                "num_col=%d" % (int(idx[:nnz].max()),
                                                int(num_col)))
        keep = self._quarantine_mask(m, label, weight, start_row)
        if keep is not None:
            ip = np.asarray(indptr, dtype=np.int64)
            counts = np.diff(ip)[keep]
            row_sel = np.repeat(keep, np.diff(ip))
            idx = idx[:nnz][row_sel]
            values = np.asarray(values)[:nnz][row_sel]
            indptr = np.concatenate([[0], np.cumsum(counts)])
            m = int(keep.sum())
            label = np.asarray(label, dtype=np.float64).reshape(-1)[keep] \
                if label is not None else None
            weight = np.asarray(weight,
                                dtype=np.float64).reshape(-1)[keep] \
                if weight is not None else None
        self._check_features(int(num_col))
        chunk = _Chunk(start_row, m, csr=(indptr, idx, values, int(num_col)))
        return self._push(chunk, label, weight)

    def push_csc(self, col_ptr, indices, values, num_row: int,
                 label=None, weight=None) -> "StreamingDatasetBuilder":
        """One-shot CSC push (``LGBM_DatasetCreateFromCSC``): a CSC matrix
        carries whole columns, so it arrives as a single chunk covering
        all ``num_row`` rows; it is transposed to a dense chunk here."""
        col_ptr = np.asarray(col_ptr, dtype=np.int64)
        ncol = len(col_ptr) - 1
        self._check_features(ncol)
        n = int(num_row)
        X = np.zeros((n, ncol), dtype=np.float64)
        idx = np.asarray(indices, dtype=np.int64)
        vals = np.asarray(values, dtype=np.float64)
        for j in range(ncol):
            s, e = int(col_ptr[j]), int(col_ptr[j + 1])
            X[idx[s:e], j] = vals[s:e]
        return self.push_dense(X, label=label, weight=weight)

    # -- internals -----------------------------------------------------------
    def _quarantine_mask(self, n_rows: int, label, weight,
                         start_row: int) -> Optional[np.ndarray]:
        """Push-time schema validation (armed by `quarantine=`): the
        keep-mask when rows must be dropped, None when the chunk is
        clean (or validation is off) so the zero-copy path is untouched.
        Positioned (`start_row`) pushes cannot silently renumber rows —
        there a quarantine hit is a loud error instead."""
        if self.quarantine is None or (label is None and weight is None):
            return None
        from ..runtime.quality import validate_rows
        y = None if label is None \
            else np.asarray(label, dtype=np.float64).reshape(-1)
        w = None if weight is None \
            else np.asarray(weight, dtype=np.float64).reshape(-1)
        # a width-0 X placeholder: validation only reads labels/weights
        # here, so the caller's chunk is never densified on this path
        keep, counts = validate_rows(np.zeros((n_rows, 0)), y, weight=w,
                                     ledger=self.quarantine)
        if keep.all():
            return None
        if start_row >= 0:
            raise LightGBMError(
                "quarantine: positioned push at start_row=%d carries %d "
                "schema-invalid row(s) (%s); by-reference streams cannot "
                "renumber rows — clean the chunk upstream"
                % (start_row, int((~keep).sum()), counts))
        return keep

    def _check_features(self, f: int) -> None:
        if self._finalized is not None:
            raise LightGBMError("cannot push rows into a finalized stream")
        if self._num_features is None:
            self._num_features = int(f)
        elif int(f) != self._num_features:
            raise LightGBMError(
                "pushed chunk has %d features; the stream is %d-wide"
                % (f, self._num_features))

    def _push(self, chunk: _Chunk, label, weight) -> "StreamingDatasetBuilder":
        if chunk.start_row >= 0:
            if self._bins is None and self._n > 0 and not self._explicit_rows:
                raise LightGBMError(
                    "cannot mix positioned (start_row) and appended pushes")
            self._explicit_rows = True
            start = chunk.start_row
        else:
            if self._explicit_rows:
                raise LightGBMError(
                    "cannot mix positioned (start_row) and appended pushes")
            start = self._n
            chunk.start_row = start
        if self._bins is not None:
            end = start + chunk.num_rows
            if end > self._num_total_rows:
                raise LightGBMError(
                    "push of rows [%d, %d) exceeds the declared "
                    "num_total_rows=%d" % (start, end, self._num_total_rows))
            if self._covered[start:end].any():
                raise LightGBMError(
                    "rows [%d, %d) were already pushed" % (start, end))
            self._encode_into(chunk, start)
            self._covered[start:end] = True
            self._n += chunk.num_rows
        else:
            self._feed_reservoir(chunk)
            self._chunks.append(chunk)
            self._n += chunk.num_rows
        if label is not None:
            y = np.asarray(label, dtype=np.float64).reshape(-1)
            if len(y) != chunk.num_rows:
                raise LightGBMError("label chunk length %d != row chunk %d"
                                    % (len(y), chunk.num_rows))
            self._labels.append((start, y))
        if weight is not None:
            w = np.asarray(weight, dtype=np.float64).reshape(-1)
            if len(w) != chunk.num_rows:
                raise LightGBMError("weight chunk length %d != row chunk %d"
                                    % (len(w), chunk.num_rows))
            self._weights.append((start, w))
        return self

    def _encode_into(self, chunk: _Chunk, start: int) -> None:
        """By-reference mode: bin the chunk with the FIXED reference
        mappers straight into the preallocated storage; the raw chunk is
        dropped when this returns."""
        mappers = self._ref_binned.bin_mappers
        m = chunk.num_rows
        Xc = np.zeros((m, self._num_features), dtype=np.float64)
        chunk.fill(Xc, 0)
        tmp = np.zeros((self._num_features, m), dtype=self._bins.dtype)
        from .native import encode_bins
        if not encode_bins(Xc, mappers, tmp):
            for j, mapper in enumerate(mappers):
                if mapper.is_trivial:
                    continue
                tmp[j, :m] = mapper.values_to_bins(Xc[:, j])
        self._bins[:, start:start + m] = tmp

    def _feed_reservoir(self, chunk: _Chunk) -> None:
        """Online bounded reservoir over the pushed stream (uniform,
        deterministic given the seed and push sequence).  Only the rows
        the reservoir actually keeps are densified."""
        cap = self._sample_cap
        F = self._num_features
        m = chunk.num_rows
        t = self._res_seen
        need = min(cap, t + m)
        if self._res is None or len(self._res) < need:
            # grow geometrically toward the cap instead of paying the full
            # cap (default 200k rows) for small streams
            size = max(min(cap, 1024), need)
            if self._res is not None:
                size = min(cap, max(size, 2 * len(self._res)))
            grown = np.empty((size, F), dtype=np.float64)
            if self._res is not None and t > 0:
                grown[:min(t, len(self._res))] = \
                    self._res[:min(t, len(self._res))]
            self._res = grown
        fill = min(max(cap - t, 0), m)
        if fill:
            self._res[t:t + fill] = chunk.rows(np.arange(fill), F)
        rest = m - fill
        if rest > 0:
            # classic reservoir step, vectorized: row with global index g
            # replaces a random slot with probability cap / (g + 1)
            g = np.arange(t + fill, t + m, dtype=np.int64)
            r = self._res_rng.integers(0, g + 1)
            hit = r < cap
            if hit.any():
                local = np.nonzero(hit)[0] + fill
                self._res[r[hit]] = chunk.rows(local, F)
        self._res_seen = t + m

    def _reservoir_mappers(self, config) -> List[BinMapper]:
        """Find bin mappers from the bounded reservoir (only taken when
        the stream outgrew the cap; otherwise the exact offline sampling
        path runs over the full buffered rows)."""
        rows = self._res[:min(self._res_seen, self._sample_cap)]
        Log.info("stream ingest: binning from a %d-row reservoir over a "
                 "%d-row stream", len(rows), self._n)
        # reuse the offline find-bin verbatim with a sample that covers
        # the whole reservoir (Random.sample(n, n) keeps every row)
        import copy as _copy
        cfg = _copy.copy(config)
        try:
            cfg.bin_construct_sample_cnt = len(rows)
        except Exception:
            pass
        return BinnedDataset._find_bin_mappers(
            rows, cfg, self.categorical_feature)

    def _materialize(self) -> np.ndarray:
        """Buffered mode: assemble the full [n, F] float64 matrix exactly
        once (the same materialization the file parser performs)."""
        order = sorted(self._chunks, key=lambda c: c.start_row)
        expect = 0
        for c in order:
            if c.start_row != expect:
                raise LightGBMError(
                    "pushed rows do not tile [0, %d): gap/overlap at row "
                    "%d (next chunk starts at %d)"
                    % (self._n, expect, c.start_row))
            expect += c.num_rows
        X = np.zeros((self._n, self._num_features), dtype=np.float64)
        for c in order:
            c.fill(X, c.start_row)
        return X

    # -- finalize ------------------------------------------------------------
    def finalize(self, config=None, *, bin_mappers=None,
                 reference_bundle=None, feature_names=None,
                 categorical_feature=None) -> BinnedDataset:
        """Produce the binned dataset.  Idempotent — the first call's
        result is cached and returned thereafter."""
        if self._finalized is not None:
            return self._finalized
        if self._n <= 0:
            raise LightGBMError("cannot finalize an empty stream: push at "
                                "least one chunk first")
        if config is None:
            from ..config import Config
            config = Config(self.params)
        names = feature_names or self.feature_names
        cats = categorical_feature if categorical_feature \
            else self.categorical_feature

        if self._bins is not None:
            ds = self._finalize_streaming(config, names)
        else:
            if bin_mappers is None and self._ref_binned is not None:
                bin_mappers = self._ref_binned.bin_mappers
                if reference_bundle is None:
                    reference_bundle = self._ref_binned.bundle_info
            if bin_mappers is None and self._n > self._sample_cap:
                bin_mappers = self._reservoir_mappers(config)
            X = self._materialize()
            ds = BinnedDataset.from_matrix(
                X, config, bin_mappers=bin_mappers, feature_names=names,
                categorical_feature=cats,
                reference_bundle=reference_bundle)
        y = self.labels()
        if y is not None and ds.metadata.label is None:
            ds.metadata.set_label(y)
        w = self.weights()
        if w is not None and ds.metadata.weight is None:
            ds.metadata.set_weight(w)
        self._finalized = ds
        self._chunks = []        # raw chunks are no longer needed
        self._res = None
        return ds

    def _finalize_streaming(self, config, names) -> BinnedDataset:
        """By-reference mode assembly: the bins were encoded at push time;
        here only bundling + metadata remain (mirrors from_matrix's tail
        so the result is byte-identical to binning the same rows through
        from_matrix with the reference mappers)."""
        n = self._num_total_rows
        if not self._covered.all():
            missing = int((~self._covered).sum())
            raise LightGBMError(
                "stream is incomplete: %d of the declared %d rows were "
                "never pushed (first missing row: %d)"
                % (missing, n, int(np.argmax(~self._covered))))
        ref = self._ref_binned
        ds = BinnedDataset()
        ds.num_data = n
        ds.num_total_features = self._num_features
        ds.feature_names = list(names) if names \
            else list(ref.feature_names)
        ds.bin_mappers = list(ref.bin_mappers)
        ds.max_num_bin = max((m.num_bin for m in ds.bin_mappers), default=1)
        bins = self._bins
        if ref.bundle_info is not None:
            from .bundling import apply_bundles
            ds.bundle_info = ref.bundle_info
            bins = apply_bundles(bins, ref.bundle_info,
                                 [m.num_bin for m in ds.bin_mappers],
                                 [m.default_bin for m in ds.bin_mappers])
            ds.max_num_bin = max(ds.max_num_bin,
                                 ds.bundle_info.max_group_bin)
        ds.bins = bins
        ds.num_data_padded = bins.shape[1]
        ds.metadata = Metadata(n)
        f = ds.num_total_features
        mono = getattr(config, "monotone_constraints", None) or []
        ds.monotone_constraints = np.zeros(f, dtype=np.int32)
        ds.monotone_constraints[: len(mono)] = \
            np.asarray(mono, dtype=np.int32)[:f]
        pen = getattr(config, "feature_contri", None) or []
        ds.feature_penalty = np.ones(f, dtype=np.float32)
        ds.feature_penalty[: len(pen)] = \
            np.asarray(pen, dtype=np.float32)[:f]
        self._bins = None
        return ds
