"""Jitted batch prediction on the accelerator (gbdt_prediction.cpp role).

The host predictor (`models/tree.py`) is the exactness reference (f64
thresholds, byte-parity with the reference CLI).  This one trades f32
thresholds for device throughput: all trees are packed into stacked SoA
arrays once, and one jitted program traverses [N] rows x T trees with a
fixed depth loop (num_leaves-1 bounds any path in a leaf-wise tree).

Opt-in via `Booster.predict(..., device=True)`.  Models with categorical
splits fall back to the host path (bitset membership over ragged
category words does not vectorize cleanly; numeric models are the ones
with million-row prediction workloads).
"""
from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_K_ZERO_THRESHOLD = 1e-35
MISSING_NONE, MISSING_ZERO, MISSING_NAN = 0, 1, 2


def packable_model(model) -> bool:
    return all(t.num_cat == 0 for t in model.trees)


def pack_trees(trees, num_leaves_cap: int) -> Dict[str, np.ndarray]:
    """Stack tree SoA arrays to [T, L-1] / [T, L] (inert padding)."""
    T = len(trees)
    L = max(num_leaves_cap, 2)
    feat = np.zeros((T, L - 1), np.int32)
    thr = np.zeros((T, L - 1), np.float32)
    miss = np.zeros((T, L - 1), np.int32)
    dleft = np.zeros((T, L - 1), bool)
    left = np.full((T, L - 1), -1, np.int32)
    right = np.full((T, L - 1), -1, np.int32)
    leaf = np.zeros((T, L), np.float32)
    for i, t in enumerate(trees):
        ni = max(t.num_leaves - 1, 0)
        if ni:
            feat[i, :ni] = t.split_feature[:ni]
            thr[i, :ni] = t.threshold[:ni]
            dt = t.decision_type[:ni]
            miss[i, :ni] = (dt >> 2) & 3
            dleft[i, :ni] = (dt & 2) != 0
            left[i, :ni] = t.left_child[:ni]
            right[i, :ni] = t.right_child[:ni]
        leaf[i, : t.num_leaves] = t.leaf_value[: t.num_leaves]
    return {"feat": feat, "thr": thr, "miss": miss, "dleft": dleft,
            "left": left, "right": right, "leaf": leaf}


@functools.partial(jax.jit, static_argnames=("num_class", "depth_iters"))
def _predict_packed(arrs, X, *, num_class: int, depth_iters: int):
    N = X.shape[0]
    K = num_class

    def per_tree(carry, tree):
        score, t_idx = carry

        def body(_, node):
            active = node >= 0
            nd = jnp.maximum(node, 0)
            f = tree["feat"][nd]                                  # [N]
            fv = jnp.take_along_axis(X, f[:, None], axis=1)[:, 0]
            mt = tree["miss"][nd]
            is_nan = jnp.isnan(fv)
            fv2 = jnp.where(is_nan & (mt != MISSING_NAN), 0.0, fv)
            missing = ((mt == MISSING_ZERO) &
                       (jnp.abs(fv2) <= _K_ZERO_THRESHOLD)) | \
                      ((mt == MISSING_NAN) & is_nan)
            go_left = jnp.where(missing, tree["dleft"][nd],
                                fv2 <= tree["thr"][nd])
            child = jnp.where(go_left, tree["left"][nd], tree["right"][nd])
            return jnp.where(active, child, node)

        node0 = jnp.zeros(N, jnp.int32)
        node = lax.fori_loop(0, depth_iters, body, node0) \
            if depth_iters else node0
        # children encode leaves as ~leaf, so stump/padded trees (whose
        # children are all -1 = ~0) land on leaf 0 with no special case
        leaf_idx = ~jnp.minimum(node, -1)
        vals = tree["leaf"][leaf_idx]                             # [N]
        k = jnp.mod(t_idx, K)
        onehot = (jnp.arange(K) == k).astype(vals.dtype)          # [K]
        return (score + vals[:, None] * onehot[None, :],
                t_idx + 1), None

    score0 = jnp.zeros((N, K), jnp.float32)
    (score, _), _ = lax.scan(per_tree, (score0, jnp.int32(0)), arrs)
    return score


class DevicePredictor:
    """Packs a model once; predicts [N, F] matrices on the accelerator."""

    def __init__(self, model, start_iteration: int = 0,
                 num_iteration: int = -1):
        if not packable_model(model):
            raise ValueError("model has categorical splits; "
                             "use the host predictor")
        k = model.num_tree_per_iteration
        end = model.num_prediction_iterations(start_iteration, num_iteration)
        trees = model.trees[start_iteration * k:
                            (start_iteration + end) * k]
        L = max((t.num_leaves for t in trees), default=2)
        packed = pack_trees(trees, L)
        self._arrs = {kk: jnp.asarray(v) for kk, v in packed.items()}
        self.num_class = k
        self.depth_iters = max(L - 1, 0)
        self.num_features = model.max_feature_idx + 1

    def predict_raw(self, X: np.ndarray) -> np.ndarray:
        X = np.asarray(X, np.float32)
        if X.shape[1] < self.num_features:
            # jit gathers clamp out-of-bounds indices — a narrow matrix
            # would yield silently wrong predictions, not an IndexError
            raise ValueError("input has %d features, model needs %d"
                             % (X.shape[1], self.num_features))
        X = jnp.asarray(X)
        out = _predict_packed(self._arrs, X, num_class=self.num_class,
                              depth_iters=self.depth_iters)
        return np.asarray(out, np.float64)
