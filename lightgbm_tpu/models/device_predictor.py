"""Tree-parallel jitted inference engine (gbdt_prediction.cpp role).

The host predictor (`models/tree.py`) is the exactness reference (f64
thresholds, byte-parity with the reference CLI).  This one trades f32
thresholds for device throughput.  Design (see docs/PERFORMANCE.md
"Inference engine"):

- **Tree-parallel traversal.**  All T trees advance one level per step
  over a `[N, T]` node frontier: every gather is batched over the tree
  axis, so one loop trip touches N x T cells instead of the old
  per-tree `lax.scan` whose T x (L-1) serialized steps dominated wall
  clock.
- **Flattened branchless node table (ISSUE 16).**  Internal nodes and
  leaves live in ONE absolute-index table of `(L-1) + L` slots per
  tree; child pointers are pre-resolved to absolute flat ids at pack
  time and leaves are self-loops, so the traversal body is exactly
  gather -> compare -> pick child — no sign test, no per-step offset
  add, no active-row mask.  Rows that reach a leaf early just keep
  re-gathering their leaf slot; the final value fetch is one gather on
  the already-absolute frontier.
- **Optional int8 leaf values (ISSUE 16, staged).**  With
  `leaf_quant="int8"` the leaf table is stored int8 with a per-tree f32
  scale (PR 2's stochastic rounding, `ops/quantize.py`) and dequantized
  only at the final gather — the value table shrinks 4x.  Staged behind
  `LEAF_QUANT_VALIDATED` (default OFF = byte-identical f32 leaves).
- **Depth-bounded loop.**  The loop runs `max leaf depth` trips — for
  leaf-wise 255-leaf trees typically 20-40, not the worst-case
  `num_leaves - 1 = 254` the scan engine used.  Rows/trees that reach a
  leaf early park on the encoded `~leaf` node id.
- **Categorical splits on device.**  Each node's category bitset is
  packed into fixed-width uint32 words `[T, nodes, W]`; membership is a
  flat gather + shift/mask, so categorical models no longer fall back
  to the host path.
- **Shape-bucketed program cache.**  Row counts are padded up to
  power-of-two buckets (padding rows are discarded after the fact), so
  repeated ragged batch sizes reuse at most log2(N) compiled programs.
- **Row micro-batching + double buffering.**  File-scale matrices are
  cut into device-sized micro-batches; the next batch's host->device
  transfer and the previous batch's fetch overlap the current compute.
- **Device-side prediction early stop.**  Port of the vectorized host
  logic (models/gbdt_model.py predict_raw): per-leaf values for all
  trees are computed in one traversal, then a masked per-iteration
  accumulation stops adding a row's contributions once its margin
  clears the threshold at a check point — same truncated sums as the
  host path.

Opt-in via `Booster.predict(..., device=True)`.
"""
from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..runtime import resilience, xla_obs

_K_ZERO_THRESHOLD = 1e-35
MISSING_NONE, MISSING_ZERO, MISSING_NAN = 0, 1, 2

#: staged flag (ISSUE 16): int8-quantized leaf values in the device
#: predictor.  OFF -> Booster.predict(device=True) is byte-identical to
#: the f32-leaf engine.  ON -> DevicePredictor defaults to
#: leaf_quant="int8": leaves stored int8 with a per-tree scale
#: (ops/quantize.py stochastic rounding), dequantized at the final
#: gather — the leaf table shrinks 4x at a pinned tolerance vs the f64
#: host reference.  Expiry row: docs/PERFORMANCE.md staged-flag table.
LEAF_QUANT_VALIDATED = False

#: bumped once per (re)trace of the tree-parallel program — the shape
#: bucket policy is pinned by asserting how this moves across calls
_TRACE_COUNT = 0


def trace_count() -> int:
    return _TRACE_COUNT


def packable_model(model) -> bool:
    """Every model packs now — categorical bitsets ride fixed-width words
    (kept for API compatibility with the pre-tree-parallel engine)."""
    return True


def _tree_depth(t) -> int:
    """Max leaf depth from child pointers.  Node indices are creation
    order, so an internal child always has a larger index than its
    parent (tree.h Split; our tree.py split()) and one in-order pass
    settles every depth."""
    ni = t.num_leaves - 1
    if ni <= 0:
        return 0
    depth = np.zeros(ni, np.int64)
    max_leaf = 1
    for node in range(ni):
        d = depth[node] + 1
        for child in (int(t.left_child[node]), int(t.right_child[node])):
            if child >= 0:
                if child <= node:   # malformed pointers: keep the safe bound
                    return ni
                depth[child] = d
            else:
                max_leaf = max(max_leaf, d)
    return int(max_leaf)


def pack_trees(trees, num_leaves_cap: int):
    """Stack tree SoA arrays to [T, L-1] / [T, L] (inert padding), plus
    fixed-width categorical bitset words when the slice has categorical
    splits.  Returns (arrays: Dict[str, np.ndarray], max_depth)."""
    T = len(trees)
    L = max(num_leaves_cap, 2)
    feat = np.zeros((T, L - 1), np.int32)
    thr = np.zeros((T, L - 1), np.float32)
    miss = np.zeros((T, L - 1), np.int32)
    dleft = np.zeros((T, L - 1), bool)
    left = np.full((T, L - 1), -1, np.int32)
    right = np.full((T, L - 1), -1, np.int32)
    leaf = np.zeros((T, L), np.float32)
    is_cat = np.zeros((T, L - 1), bool)
    depth = 0
    W = 0
    for t in trees:
        if t.num_cat > 0:
            for node in range(t.num_leaves - 1):
                if t.decision_type[node] & 1:
                    W = max(W, len(t.cat_words_for_node(node)))
    catw = np.zeros((T, L - 1, W), np.uint32) if W else None
    for i, t in enumerate(trees):
        ni = max(t.num_leaves - 1, 0)
        if ni:
            feat[i, :ni] = t.split_feature[:ni]
            thr[i, :ni] = t.threshold[:ni]
            dt = t.decision_type[:ni]
            miss[i, :ni] = (dt >> 2) & 3
            dleft[i, :ni] = (dt & 2) != 0
            left[i, :ni] = t.left_child[:ni]
            right[i, :ni] = t.right_child[:ni]
            if t.num_cat > 0:
                is_cat[i, :ni] = (dt & 1) != 0
                for node in np.nonzero(is_cat[i, :ni])[0]:
                    words = t.cat_words_for_node(int(node))
                    catw[i, node, :len(words)] = words
        leaf[i, : t.num_leaves] = t.leaf_value[: t.num_leaves]
        depth = max(depth, _tree_depth(t))
    out = {"feat": feat, "thr": thr, "miss": miss, "dleft": dleft,
           "left": left, "right": right, "leaf": leaf}
    if W:
        out["is_cat"] = is_cat
        out["catw"] = catw
    return out, depth


def _flatten_packed(packed, leaf_quant: Optional[str] = None):
    """Flatten [T, L-1]/[T, L] packed trees into one branchless node
    table of S = (L-1) + L slots per tree (internal nodes first, then
    leaves).  Child pointers are pre-resolved to ABSOLUTE flat indices
    (internal child c -> base + c, leaf ~c -> base + NI + c) and every
    leaf slot is a self-loop (left = right = itself, threshold +inf),
    so the traversal loop needs no sign test, no offset arithmetic and
    no active-row mask.  With leaf_quant="int8" the value table is
    stored int8 with a per-tree f32 scale (stochastic rounding, same
    max-scaling convention as ops/quantize.quantize_pair)."""
    feat, thr = packed["feat"], packed["thr"]
    T, NI = feat.shape
    L = packed["leaf"].shape[1]
    S = NI + L
    base = (np.arange(T, dtype=np.int32) * S)[:, None]
    out = {"feat": np.zeros((T, S), np.int32),
           "thr": np.full((T, S), np.inf, np.float32),
           "miss": np.zeros((T, S), np.int32),
           "dleft": np.zeros((T, S), bool)}
    out["feat"][:, :NI] = feat
    out["thr"][:, :NI] = thr
    out["miss"][:, :NI] = packed["miss"]
    out["dleft"][:, :NI] = packed["dleft"]
    self_idx = base + np.arange(S, dtype=np.int32)[None, :]
    for name in ("left", "right"):
        dst = self_idx.copy()
        c = packed[name]
        dst[:, :NI] = np.where(c >= 0, c, NI + ~c) + base
        out[name] = dst
    value = np.zeros((T, S), np.float32)
    value[:, NI:] = packed["leaf"]
    if leaf_quant == "int8":
        from ..ops.quantize import stochastic_round
        amax = np.abs(value).max(axis=1)
        # per-tree max-scaling; an all-zero tree gets scale 1 so the
        # division is always finite (quantize_pair's convention)
        scale = (np.where(amax > 0, amax, 127.0) / 127.0).astype(
            np.float32)
        q = stochastic_round(jnp.asarray(value / scale[:, None]),
                             jax.random.PRNGKey(0), -127.0, 127.0)
        out["value_q"] = np.asarray(q, np.int8)
        out["scale"] = scale
    else:
        out["value"] = value
    if "catw" in packed:
        W = packed["catw"].shape[-1]
        is_cat = np.zeros((T, S), bool)
        is_cat[:, :NI] = packed["is_cat"]
        catw = np.zeros((T, S, W), np.uint32)
        catw[:, :NI] = packed["catw"]
        out["is_cat"], out["catw"] = is_cat, catw
    return out


@functools.partial(xla_obs.jit, site="predictor.tree_parallel",
                   static_argnames=("num_class", "depth_iters",
                                    "early_mode", "early_freq"))
def _predict_tree_parallel(arrs, X, margin, *, num_class: int,
                           depth_iters: int, early_mode: Optional[str],
                           early_freq: int):
    global _TRACE_COUNT
    _TRACE_COUNT += 1
    N = X.shape[0]
    T, S = arrs["feat"].shape
    K = num_class

    # flat [T * S] views over the branchless node table: internal nodes
    # and leaves share one absolute index space, child pointers are
    # pre-resolved flat ids and leaves self-loop, so the body is just
    # gather -> compare -> pick child
    feat = arrs["feat"].reshape(-1)
    thr = arrs["thr"].reshape(-1)
    miss = arrs["miss"].reshape(-1)
    dleft = arrs["dleft"].reshape(-1)
    left = arrs["left"].reshape(-1)
    right = arrs["right"].reshape(-1)
    has_cat = "catw" in arrs
    if has_cat:
        is_cat = arrs["is_cat"].reshape(-1)
        W = arrs["catw"].shape[-1]
        catw = arrs["catw"].reshape(-1)          # [T * S * W]

    def body(_, node):                           # node: [N, T] flat ids
        f = feat[node]
        fv = jnp.take_along_axis(X, f, axis=1)   # [N, T]
        mt = miss[node]
        is_nan = jnp.isnan(fv)
        fv2 = jnp.where(is_nan & (mt != MISSING_NAN), 0.0, fv)
        missing = ((mt == MISSING_ZERO) &
                   (jnp.abs(fv2) <= _K_ZERO_THRESHOLD)) | \
                  ((mt == MISSING_NAN) & is_nan)
        go_left = jnp.where(missing, dleft[node], fv2 <= thr[node])
        if has_cat:
            # tree.h CategoricalDecision: NaN -> right (missing NaN) or
            # category 0; negative / beyond the node's bitset -> right
            iv = jnp.where(is_nan,
                           jnp.where(mt == MISSING_NAN, -1.0, 0.0), fv)
            in_range = jnp.isfinite(iv) & (iv >= 0) & (iv < W * 32.0)
            v = jnp.clip(iv, 0.0, W * 32.0 - 1.0).astype(jnp.int32)
            word = catw[node * W + (v >> 5)]
            bit = (word >> (v & 31).astype(jnp.uint32)) & jnp.uint32(1)
            go_left = jnp.where(is_cat[node],
                                in_range & (bit == 1), go_left)
        return jnp.where(go_left, left[node], right[node])

    # roots are each tree's internal slot 0; one trip minimum so a
    # single-leaf tree (root's children point at its leaf 0 slot) still
    # lands on a value slot
    roots = (jnp.arange(T, dtype=jnp.int32) * S)[None, :]
    node = lax.fori_loop(0, max(depth_iters, 1), body,
                         jnp.broadcast_to(roots, (N, T)))
    if "value_q" in arrs:
        # staged int8 leaves: dequantize at the final gather only (one
        # int8 gather + a per-tree scale multiply)
        vals = (arrs["value_q"].reshape(-1)[node].astype(jnp.float32)
                * arrs["scale"][None, :])
    else:
        vals = arrs["value"].reshape(-1)[node]   # [N, T]

    # per-class reduction: trees are iteration-major, tree t -> class t%K
    iters = T // K
    vals_k = vals.reshape(N, iters, K)
    if early_mode is None:
        return vals_k.sum(axis=1)

    # prediction early stop (prediction_early_stop.cpp, vectorized): add
    # per iteration, check the margin every early_freq iterations, and
    # stop accumulating the rows that cleared it
    def step(carry, v):                                      # v: [N, K]
        out, active, since = carry
        out = out + v * active[:, None]
        since = since + 1
        if early_mode == "binary":
            m = 2.0 * jnp.abs(out[:, 0])
        else:
            top2 = lax.top_k(out, 2)[0]
            m = top2[:, 0] - top2[:, 1]
        check = since >= early_freq
        active = jnp.where(check, active & ~(m > margin), active)
        since = jnp.where(check, 0, since)
        return (out, active, since), None

    out0 = jnp.zeros((N, K), jnp.float32)
    active0 = jnp.ones(N, bool)
    (score, _, _), _ = lax.scan(step, (out0, active0, jnp.int32(0)),
                                jnp.moveaxis(vals_k, 1, 0))
    return score


@functools.partial(xla_obs.jit, site="predictor.packed_scan",
                   static_argnames=("num_class", "depth_iters"))
def _predict_packed_scan(arrs, X, *, num_class: int, depth_iters: int):
    """Pre-tree-parallel engine (sequential lax.scan over trees), kept as
    the A/B reference for BENCH_PREDICT and the equivalence tests.
    Numeric splits only."""
    N = X.shape[0]
    K = num_class

    def per_tree(carry, tree):
        score, t_idx = carry

        def body(_, node):
            active = node >= 0
            nd = jnp.maximum(node, 0)
            f = tree["feat"][nd]                                  # [N]
            fv = jnp.take_along_axis(X, f[:, None], axis=1)[:, 0]
            mt = tree["miss"][nd]
            is_nan = jnp.isnan(fv)
            fv2 = jnp.where(is_nan & (mt != MISSING_NAN), 0.0, fv)
            missing = ((mt == MISSING_ZERO) &
                       (jnp.abs(fv2) <= _K_ZERO_THRESHOLD)) | \
                      ((mt == MISSING_NAN) & is_nan)
            go_left = jnp.where(missing, tree["dleft"][nd],
                                fv2 <= tree["thr"][nd])
            child = jnp.where(go_left, tree["left"][nd], tree["right"][nd])
            return jnp.where(active, child, node)

        node0 = jnp.zeros(N, jnp.int32)
        node = lax.fori_loop(0, depth_iters, body, node0) \
            if depth_iters else node0
        leaf_idx = ~jnp.minimum(node, -1)
        vals = tree["leaf"][leaf_idx]                             # [N]
        k = jnp.mod(t_idx, K)
        onehot = (jnp.arange(K) == k).astype(vals.dtype)          # [K]
        return (score + vals[:, None] * onehot[None, :],
                t_idx + 1), None

    score0 = jnp.zeros((N, K), jnp.float32)
    (score, _), _ = lax.scan(per_tree, (score0, jnp.int32(0)), arrs)
    return score


def _bucket_rows(n: int) -> int:
    """Pad a row count up to its power-of-two bucket so ragged batches
    share compiled programs (min bucket 16)."""
    return max(16, 1 << (max(n - 1, 1)).bit_length())


def _default_batch_rows(num_trees: int) -> int:
    """Micro-batch so the [N, T] traversal buffers stay device-sized:
    ~2^24 cells per buffer, power-of-two rows, capped at 2^20."""
    rows = max((1 << 24) // max(num_trees, 1), 256)
    return min(1 << (rows.bit_length() - 1), 1 << 20)


class DevicePredictor:
    """Packs a model once; predicts [N, F] matrices on the accelerator."""

    def __init__(self, model, start_iteration: int = 0,
                 num_iteration: int = -1,
                 batch_rows: Optional[int] = None,
                 leaf_quant: Optional[str] = None):
        if leaf_quant not in (None, "int8"):
            raise ValueError("leaf_quant must be None or 'int8', got %r"
                             % (leaf_quant,))
        k = model.num_tree_per_iteration
        end = model.num_prediction_iterations(start_iteration, num_iteration)
        trees = model.trees[start_iteration * k:
                            (start_iteration + end) * k]
        L = max((t.num_leaves for t in trees), default=2)
        packed, depth = pack_trees(trees, L)
        self.leaf_quant = leaf_quant
        # host copy of the per-tree layout for the legacy scan engine
        # (A/B reference); the device holds only the flat table
        self._packed = packed
        flat = _flatten_packed(packed, leaf_quant)
        self._arrs = {kk: jnp.asarray(v) for kk, v in flat.items()}
        self.num_class = k
        self.depth_iters = depth
        self.num_trees = len(trees)
        self.num_features = model.max_feature_idx + 1
        self.batch_rows = batch_rows or _default_batch_rows(self.num_trees)
        # legacy-scan bound: num_leaves-1 covers any path
        self._scan_depth_iters = max(L - 1, 0)

    # -- internals -----------------------------------------------------------
    def _check_width(self, X: np.ndarray) -> np.ndarray:
        X = np.asarray(X, np.float32)
        if X.shape[1] < self.num_features:
            # jit gathers clamp out-of-bounds indices — a narrow matrix
            # would yield silently wrong predictions, not an IndexError
            raise ValueError("input has %d features, model needs %d"
                             % (X.shape[1], self.num_features))
        return X

    def _pad_rows(self, X: np.ndarray) -> np.ndarray:
        n = X.shape[0]
        bucket = _bucket_rows(n)
        if bucket == n:
            return X
        pad = np.zeros((bucket - n, X.shape[1]), X.dtype)
        return np.concatenate([X, pad])

    def _run(self, X_dev, early_mode, early_freq, margin):
        return _predict_tree_parallel(
            self._arrs, X_dev, jnp.float32(margin),
            num_class=self.num_class, depth_iters=self.depth_iters,
            early_mode=early_mode, early_freq=early_freq)

    # -- public --------------------------------------------------------------
    def predict_raw(self, X: np.ndarray, early_stop: Optional[str] = None,
                    early_stop_freq: int = 10,
                    early_stop_margin: float = 10.0,
                    batch_hook: Optional[Callable[[int, int], None]] = None,
                    out_dtype=np.float64) -> np.ndarray:
        """Raw margin scores [N, num_class].  early_stop: None, 'binary'
        or 'multiclass' (same truncated-sum semantics as the host
        predictor's vectorized early stop).

        `out_dtype=np.float32` fetches the device result without the
        f64 upcast — half the D2H bytes (ISSUE 16's serving fast path).
        The engine computes in f32 either way, so the f32 surface equals
        the f64 surface `.astype(float32)` exactly.

        `batch_hook(i, n_batches)` fires before each micro-batch dispatch
        — the batch-boundary seam the serving runtime builds on: faults
        (`LGBM_TPU_FAULT=die_at_predict|slow_predict`) land HERE, between
        micro-batches, never mid-dispatch, and a model swap observed at
        this boundary still finishes the in-flight call on the predictor
        it started with (the packed arrays are immutable per instance).
        Per-row outputs are batch-composition invariant (pinned), so
        micro-batching and serving batch assembly never change results.
        """
        X = self._check_width(X)
        N = X.shape[0]
        freq = max(int(early_stop_freq), 1)
        if early_stop not in ("binary", "multiclass"):
            early_stop = None
        out = np.empty((N, self.num_class), out_dtype)

        bs = self.batch_rows
        slices = [(s, min(s + bs, N)) for s in range(0, N, bs)] or [(0, 0)]
        # double buffering: enqueue batch i+1's host->device transfer and
        # fetch batch i-1's result while batch i computes
        dev_next = jax.device_put(self._pad_rows(X[slices[0][0]:slices[0][1]]))
        pending = None
        for i, (s, e) in enumerate(slices):
            if batch_hook is not None:
                batch_hook(i, len(slices))
            resilience.maybe_fail_predict()   # serving fault seam
            xb = dev_next
            if i + 1 < len(slices):
                ns, ne = slices[i + 1]
                dev_next = jax.device_put(self._pad_rows(X[ns:ne]))
            yb = self._run(xb, early_stop, freq, early_stop_margin)
            if pending is not None:
                (ps, pe), py = pending
                out[ps:pe] = np.asarray(py, out_dtype)[: pe - ps]
            pending = ((s, e), yb)
        (ps, pe), py = pending
        out[ps:pe] = np.asarray(py, out_dtype)[: pe - ps]
        return out

    def predict_raw_scan(self, X: np.ndarray) -> np.ndarray:
        """The pre-PR scan engine, for A/B benchmarking only (numeric
        models; no bucketing, no micro-batching — the old behavior)."""
        if "catw" in self._packed:
            raise ValueError("the legacy scan engine has no categorical "
                             "support")
        X = jnp.asarray(self._check_width(X))
        arrs = {kk: jnp.asarray(self._packed[kk]) for kk in
                ("feat", "thr", "miss", "dleft", "left", "right", "leaf")}
        out = _predict_packed_scan(arrs, X, num_class=self.num_class,
                                   depth_iters=self._scan_depth_iters)
        return np.asarray(out, np.float64)
