"""Flat-array (SoA) decision tree model.

Role parity with the reference's include/LightGBM/tree.h:20-518 and
src/io/tree.cpp (Split/SplitCategorical, Predict*, ToString/ToJSON,
parse-from-string at tree.cpp:475).  Redesigned TPU-first: a tree is a bundle
of flat numpy/jnp arrays (structure-of-arrays) so prediction is a vectorized
gather traversal that jits cleanly, and training emits array slices rather
than mutating a pointer graph.

Node index conventions follow the reference text format exactly so model files
interchange: internal nodes are numbered 0..num_leaves-2; child pointers are
`>= 0` for internal children and `~leaf_index` (negative) for leaves.
decision_type bit layout (tree.h:14-15, 195-202): bit0 = categorical,
bit1 = default_left, bits 2-3 = missing type (0 none, 1 zero, 2 nan).
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional

import numpy as np

_K_CATEGORICAL_MASK = 1
_K_DEFAULT_LEFT_MASK = 2

MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2

_K_ZERO_THRESHOLD = 1e-35  # reference meta.h kZeroThreshold


def _fmt_double(v: float) -> str:
    return repr(float(v))


def _join_arr(arr, fmt=str) -> str:
    return " ".join(fmt(x) for x in arr)


class Tree:
    """One decision tree with num_leaves leaves stored as flat arrays."""

    def __init__(self, max_leaves: int):
        self.max_leaves = max_leaves
        self.num_leaves = 1
        self.num_cat = 0
        n = max(max_leaves - 1, 1)
        self.left_child = np.zeros(n, dtype=np.int32)
        self.right_child = np.zeros(n, dtype=np.int32)
        self.split_feature = np.zeros(n, dtype=np.int32)
        self.threshold_in_bin = np.zeros(n, dtype=np.int32)
        self.threshold = np.zeros(n, dtype=np.float64)
        self.decision_type = np.zeros(n, dtype=np.int8)
        self.split_gain = np.zeros(n, dtype=np.float32)
        self.internal_value = np.zeros(n, dtype=np.float64)
        self.internal_count = np.zeros(n, dtype=np.int32)
        self.leaf_value = np.zeros(max_leaves, dtype=np.float64)
        self.leaf_count = np.zeros(max_leaves, dtype=np.int32)
        self.leaf_parent = np.full(max_leaves, -1, dtype=np.int32)
        self.leaf_depth = np.zeros(max_leaves, dtype=np.int32)
        self.cat_boundaries: List[int] = [0]
        self.cat_threshold: List[int] = []
        # inner (bin-id) bitsets, training-side only — not serialized
        # (tree.h cat_boundaries_inner_/cat_threshold_inner_)
        self.cat_boundaries_inner: List[int] = [0]
        self.cat_threshold_inner: List[int] = []
        self.shrinkage = 1.0

    # -- training-side mutation ---------------------------------------------
    def split(self, leaf: int, feature: int, threshold_bin: int,
              threshold_double: float, left_value: float, right_value: float,
              left_cnt: int, right_cnt: int, gain: float,
              missing_type: int, default_left: bool) -> int:
        """Split `leaf`; the left child keeps index `leaf`, the right child
        becomes leaf `num_leaves`.  Returns the new internal node index."""
        node = self.num_leaves - 1
        parent = self.leaf_parent[leaf]
        if parent >= 0:
            if self.left_child[parent] == ~leaf:
                self.left_child[parent] = node
            else:
                self.right_child[parent] = node
        self.split_feature[node] = feature
        self.threshold_in_bin[node] = threshold_bin
        self.threshold[node] = threshold_double
        dt = 0
        if default_left:
            dt |= _K_DEFAULT_LEFT_MASK
        dt |= (missing_type & 3) << 2
        self.decision_type[node] = dt
        self.split_gain[node] = gain
        self.left_child[node] = ~leaf
        new_leaf = self.num_leaves
        self.right_child[node] = ~new_leaf
        # reference stores the pre-split leaf output as the internal value (tree.h Split)
        self.internal_value[node] = self.leaf_value[leaf]
        self.internal_count[node] = left_cnt + right_cnt
        self.leaf_value[leaf] = left_value if left_value == left_value else 0.0
        self.leaf_count[leaf] = left_cnt
        self.leaf_value[new_leaf] = right_value if right_value == right_value else 0.0
        self.leaf_count[new_leaf] = right_cnt
        self.leaf_parent[leaf] = node
        self.leaf_parent[new_leaf] = node
        self.leaf_depth[new_leaf] = self.leaf_depth[leaf] + 1
        self.leaf_depth[leaf] += 1
        self.num_leaves += 1
        return node

    def split_categorical(self, leaf: int, feature: int, threshold_bin_bitset: List[int],
                          threshold_cat_bitset: List[int], left_value: float,
                          right_value: float, left_cnt: int, right_cnt: int,
                          gain: float, missing_type: int) -> int:
        node = self.split(leaf, feature, 0, 0.0, left_value, right_value,
                          left_cnt, right_cnt, gain, missing_type, False)
        self.decision_type[node] &= ~_K_DEFAULT_LEFT_MASK
        self.decision_type[node] |= _K_CATEGORICAL_MASK
        self.threshold_in_bin[node] = self.num_cat
        self.threshold[node] = self.num_cat
        self.num_cat += 1
        self.cat_threshold.extend(threshold_cat_bitset)
        self.cat_boundaries.append(len(self.cat_threshold))
        self.cat_threshold_inner.extend(threshold_bin_bitset)
        self.cat_boundaries_inner.append(len(self.cat_threshold_inner))
        return node

    def apply_shrinkage(self, rate: float) -> None:
        self.leaf_value[: self.num_leaves] *= rate
        self.internal_value[: self.num_leaves - 1] *= rate
        self.shrinkage *= rate

    # -- prediction ----------------------------------------------------------
    def predict(self, X: np.ndarray) -> np.ndarray:
        """Vectorized traversal over raw feature values [n, num_features]."""
        return self._traverse(X, leaf_index=False)

    def predict_leaf_index(self, X: np.ndarray) -> np.ndarray:
        return self._traverse(X, leaf_index=True)

    def _traverse(self, X: np.ndarray, leaf_index: bool) -> np.ndarray:
        n = X.shape[0]
        if self.num_leaves <= 1:
            out = np.zeros(n) if leaf_index else np.full(n, self.leaf_value[0])
            return out
        node = np.zeros(n, dtype=np.int32)
        active = np.ones(n, dtype=bool)
        # num_leaves-1 is the max depth of any path
        for _ in range(self.num_leaves):
            if not active.any():
                break
            nd = node[active]
            fval = X[active, self.split_feature[nd]].astype(np.float64)
            dt = self.decision_type[nd]
            is_cat = (dt & _K_CATEGORICAL_MASK) != 0
            missing_type = (dt >> 2) & 3
            default_left = (dt & _K_DEFAULT_LEFT_MASK) != 0
            go_left = np.zeros(len(nd), dtype=bool)

            # numerical decision (tree.h:212-232)
            num_mask = ~is_cat
            if num_mask.any():
                fv = fval[num_mask]
                mt = missing_type[num_mask]
                nan_mask = np.isnan(fv)
                fv = np.where(nan_mask & (mt != MISSING_NAN), 0.0, fv)
                is_missing = ((mt == MISSING_ZERO) & (np.abs(fv) <= _K_ZERO_THRESHOLD)) | \
                             ((mt == MISSING_NAN) & nan_mask)
                left = np.where(is_missing, default_left[num_mask],
                                fv <= self.threshold[nd[num_mask]])
                go_left[num_mask] = left
            # categorical decision (tree.h:251-268)
            if is_cat.any():
                fv = fval[is_cat]
                mt = missing_type[is_cat]
                # NaN goes right when missing_type==NaN, else is treated as category 0
                int_val = np.where(np.isnan(fv),
                                   np.where(mt == MISSING_NAN, -1.0, 0.0), fv)
                cat_idx = self.threshold_in_bin[nd[is_cat]]
                inb = np.zeros(int(is_cat.sum()), dtype=bool)
                for j in range(len(inb)):
                    v = int_val[j]
                    if not np.isfinite(v) or v < 0:
                        continue
                    v = int(v)
                    ci = int(cat_idx[j])
                    lo, hi = self.cat_boundaries[ci], self.cat_boundaries[ci + 1]
                    i1, i2 = v // 32, v % 32
                    if lo + i1 < hi and (self.cat_threshold[lo + i1] >> i2) & 1:
                        inb[j] = True
                go_left[is_cat] = inb

            child = np.where(go_left, self.left_child[nd], self.right_child[nd])
            node[active] = child
            reached_leaf = child < 0
            # store leaves as encoded negatives; deactivate
            idx = np.where(active)[0]
            active[idx[reached_leaf]] = False
        leaf = ~node  # node holds ~leaf_index for finished rows
        if leaf_index:
            return leaf.astype(np.float64)
        return self.leaf_value[leaf]

    def cat_words_for_node(self, node: int) -> np.ndarray:
        """The raw-category membership bitset of a categorical split node
        as uint32 words (word i covers categories 32*i .. 32*i+31) — the
        export format the device predictor packs into its fixed-width
        [T, nodes, W] word tensor."""
        if not (self.decision_type[node] & _K_CATEGORICAL_MASK):
            return np.zeros(0, np.uint32)
        ci = int(self.threshold_in_bin[node])
        lo, hi = self.cat_boundaries[ci], self.cat_boundaries[ci + 1]
        return np.asarray(self.cat_threshold[lo:hi], np.uint32)

    def set_bin_thresholds(self, bin_mappers) -> None:
        """Map double thresholds back to bin thresholds against a training
        dataset's mappers so a loaded model can be replayed on binned data
        (continued training / validation replay).  Inverse of RealThreshold:
        the stored double threshold is the upper bound of its bin, so
        values_to_bins maps it exactly onto that bin."""
        ni = self.num_leaves - 1
        self.cat_boundaries_inner = [0]
        self.cat_threshold_inner = []
        for node in range(ni):
            f = int(self.split_feature[node])
            mapper = bin_mappers[f]
            dt = int(self.decision_type[node])
            if dt & _K_CATEGORICAL_MASK:
                ci = int(self.threshold[node])
                self.threshold_in_bin[node] = ci
                lo, hi = self.cat_boundaries[ci], self.cat_boundaries[ci + 1]
                cats = [(i - lo) * 32 + j for i in range(lo, hi) for j in range(32)
                        if (self.cat_threshold[i] >> j) & 1]
                bins = sorted(mapper.categorical_2_bin[c] for c in cats
                              if c in mapper.categorical_2_bin)
                words = [0] * ((max(bins) // 32 + 1) if bins else 0)
                for b in bins:
                    words[b // 32] |= 1 << (b % 32)
                self.cat_threshold_inner.extend(words)
                self.cat_boundaries_inner.append(len(self.cat_threshold_inner))
            else:
                self.threshold_in_bin[node] = int(
                    mapper.values_to_bins(np.array([self.threshold[node]]))[0])

    # -- SHAP contributions (tree.cpp TreeSHAP:591-698, arXiv:1706.06060) ----
    def _decide_one(self, node: int, fval: float) -> int:
        """Single-value decision -> child node id (Tree::Decision)."""
        dt = int(self.decision_type[node])
        if dt & _K_CATEGORICAL_MASK:
            mt = (dt >> 2) & 3
            if np.isnan(fval):
                v = -1 if mt == MISSING_NAN else 0
            else:
                v = int(fval) if np.isfinite(fval) else -1
            go_left = False
            if v >= 0:
                ci = int(self.threshold_in_bin[node])
                lo, hi = self.cat_boundaries[ci], self.cat_boundaries[ci + 1]
                i1, i2 = v // 32, v % 32
                go_left = lo + i1 < hi and bool((self.cat_threshold[lo + i1] >> i2) & 1)
        else:
            mt = (dt >> 2) & 3
            default_left = bool(dt & _K_DEFAULT_LEFT_MASK)
            if np.isnan(fval) and mt != MISSING_NAN:
                fval = 0.0
            if (mt == MISSING_ZERO and abs(fval) <= _K_ZERO_THRESHOLD) or \
                    (mt == MISSING_NAN and np.isnan(fval)):
                go_left = default_left
            else:
                go_left = fval <= self.threshold[node]
        return int(self.left_child[node] if go_left else self.right_child[node])

    def _data_count(self, node: int) -> float:
        if node < 0:
            return float(self.leaf_count[~node])
        return float(self.internal_count[node])

    def predict_contrib(self, X: np.ndarray, num_features: int) -> np.ndarray:
        """Per-feature SHAP contributions [n, num_features + 1]; the last
        column accumulates the expected value (Tree::PredictContrib,
        tree.h:466-475)."""
        n = X.shape[0]
        phi = np.zeros((n, num_features + 1))
        phi[:, num_features] += self.expected_value()
        if self.num_leaves > 1:
            for row in range(n):
                self._tree_shap(X[row], phi[row], 0, 0, [], 1.0, 1.0, -1)
        return phi

    def _tree_shap(self, x, phi, node, unique_depth, parent_path,
                   parent_zero_fraction, parent_one_fraction,
                   parent_feature_index) -> None:
        # each frame owns a copy of the path prefix (reference keeps one big
        # buffer with std::copy per level)
        path = [list(el) for el in parent_path[:unique_depth]]
        path.append([parent_feature_index, parent_zero_fraction,
                     parent_one_fraction, 1.0 if unique_depth == 0 else 0.0])
        for i in range(unique_depth - 1, -1, -1):
            path[i + 1][3] += parent_one_fraction * path[i][3] * (i + 1) / (unique_depth + 1.0)
            path[i][3] = parent_zero_fraction * path[i][3] * (unique_depth - i) / (unique_depth + 1.0)

        if node < 0:
            for i in range(1, unique_depth + 1):
                w = _unwound_path_sum(path, unique_depth, i)
                fi, one_f, zero_f = path[i][0], path[i][2], path[i][1]
                phi[fi] += w * (one_f - zero_f) * self.leaf_value[~node]
            return

        f = int(self.split_feature[node])
        hot_index = self._decide_one(node, float(x[f]))
        cold_index = int(self.right_child[node]) if hot_index == int(self.left_child[node]) \
            else int(self.left_child[node])
        w = self._data_count(node)
        hot_zero_fraction = self._data_count(hot_index) / w
        cold_zero_fraction = self._data_count(cold_index) / w
        incoming_zero_fraction = 1.0
        incoming_one_fraction = 1.0

        path_index = 0
        while path_index <= unique_depth:
            if path[path_index][0] == f:
                break
            path_index += 1
        if path_index != unique_depth + 1:
            incoming_zero_fraction = path[path_index][1]
            incoming_one_fraction = path[path_index][2]
            _unwind_path(path, unique_depth, path_index)
            unique_depth -= 1

        self._tree_shap(x, phi, hot_index, unique_depth + 1, path,
                        hot_zero_fraction * incoming_zero_fraction,
                        incoming_one_fraction, f)
        self._tree_shap(x, phi, cold_index, unique_depth + 1, path,
                        cold_zero_fraction * incoming_zero_fraction, 0.0, f)

    def expected_value(self) -> float:
        if self.num_leaves == 1:
            return float(self.leaf_value[0])
        total = float(self.internal_count[0])
        return float(np.sum(self.leaf_value[:self.num_leaves] *
                            self.leaf_count[:self.num_leaves]) / max(total, 1.0))

    # -- serialization (reference text format, tree.cpp:209-244) -------------
    def to_string(self) -> str:
        nl = self.num_leaves
        lines = ["num_leaves=%d" % nl, "num_cat=%d" % self.num_cat]
        if nl > 1:
            ni = nl - 1
            lines.append("split_feature=" + _join_arr(self.split_feature[:ni]))
            lines.append("split_gain=" + _join_arr(self.split_gain[:ni], lambda v: _fmt_float32(v)))
            lines.append("threshold=" + _join_arr(self.threshold[:ni], _fmt_double))
            lines.append("decision_type=" + _join_arr(self.decision_type[:ni]))
            lines.append("left_child=" + _join_arr(self.left_child[:ni]))
            lines.append("right_child=" + _join_arr(self.right_child[:ni]))
            lines.append("leaf_value=" + _join_arr(self.leaf_value[:nl], _fmt_double))
            lines.append("leaf_count=" + _join_arr(self.leaf_count[:nl]))
            lines.append("internal_value=" + _join_arr(self.internal_value[:ni], _fmt_double))
            lines.append("internal_count=" + _join_arr(self.internal_count[:ni]))
            if self.num_cat > 0:
                lines.append("cat_boundaries=" + _join_arr(self.cat_boundaries))
                lines.append("cat_threshold=" + _join_arr(self.cat_threshold))
        else:
            lines.append("leaf_value=" + _fmt_double(self.leaf_value[0]))
        lines.append("shrinkage=%s" % _fmt_double(self.shrinkage))
        lines.append("")
        return "\n".join(lines) + "\n"

    @classmethod
    def from_string(cls, text: str) -> "Tree":
        kv: Dict[str, str] = {}
        for line in text.split("\n"):
            line = line.strip()
            if "=" in line:
                k, v = line.split("=", 1)
                kv[k] = v
        num_leaves = int(kv["num_leaves"])
        tree = cls(max(num_leaves, 2))
        tree.num_leaves = num_leaves
        tree.num_cat = int(kv.get("num_cat", "0"))
        tree.shrinkage = float(kv.get("shrinkage", "1"))
        if num_leaves <= 1:
            if "leaf_value" in kv:
                tree.leaf_value[0] = float(kv["leaf_value"].split()[0])
            return tree

        def arr(key, dtype, n):
            if key not in kv or kv[key] == "":
                return np.zeros(n, dtype=dtype)
            vals = np.array(kv[key].split(), dtype=np.float64)
            return vals.astype(dtype)

        ni = num_leaves - 1
        tree.split_feature[:ni] = arr("split_feature", np.int32, ni)
        tree.split_gain[:ni] = arr("split_gain", np.float32, ni)
        tree.threshold[:ni] = arr("threshold", np.float64, ni)
        tree.decision_type[:ni] = arr("decision_type", np.int8, ni)
        tree.threshold_in_bin[:ni] = tree.threshold[:ni].astype(np.int32)
        tree.left_child[:ni] = arr("left_child", np.int32, ni)
        tree.right_child[:ni] = arr("right_child", np.int32, ni)
        tree.leaf_value[:num_leaves] = arr("leaf_value", np.float64, num_leaves)
        tree.leaf_count[:num_leaves] = arr("leaf_count", np.int32, num_leaves)
        tree.internal_value[:ni] = arr("internal_value", np.float64, ni)
        tree.internal_count[:ni] = arr("internal_count", np.int32, ni)
        if tree.num_cat > 0:
            tree.cat_boundaries = [int(x) for x in kv["cat_boundaries"].split()]
            tree.cat_threshold = [int(x) for x in kv["cat_threshold"].split()]
        # recompute leaf parents/depths from child pointers
        tree.leaf_parent[:] = -1
        for node in range(ni):
            for child in (tree.left_child[node], tree.right_child[node]):
                if child < 0:
                    tree.leaf_parent[~child] = node
        return tree

    def to_json(self) -> Dict:
        if self.num_leaves == 1:
            structure = {"leaf_value": float(self.leaf_value[0])}
        else:
            structure = self._node_to_json(0)
        return {"num_leaves": int(self.num_leaves), "num_cat": int(self.num_cat),
                "shrinkage": float(self.shrinkage), "tree_structure": structure}

    def _node_to_json(self, index: int) -> Dict:
        if index >= 0:
            dt = int(self.decision_type[index])
            is_cat = bool(dt & _K_CATEGORICAL_MASK)
            node = {
                "split_index": int(index),
                "split_feature": int(self.split_feature[index]),
                "split_gain": float(self.split_gain[index]),
                "missing_type": ["None", "Zero", "NaN"][(dt >> 2) & 3],
                "default_left": bool(dt & _K_DEFAULT_LEFT_MASK),
                "internal_value": float(self.internal_value[index]),
                "internal_count": int(self.internal_count[index]),
                "left_child": self._node_to_json(int(self.left_child[index])),
                "right_child": self._node_to_json(int(self.right_child[index])),
            }
            if is_cat:
                ci = int(self.threshold_in_bin[index])
                lo, hi = self.cat_boundaries[ci], self.cat_boundaries[ci + 1]
                cats = []
                for i in range(lo, hi):
                    for j in range(32):
                        if (self.cat_threshold[i] >> j) & 1:
                            cats.append((i - lo) * 32 + j)
                node["decision_type"] = "=="
                node["threshold"] = "||".join(str(c) for c in cats)
            else:
                node["decision_type"] = "<="
                node["threshold"] = float(self.threshold[index])
            return node
        leaf = ~index
        return {"leaf_index": int(leaf), "leaf_value": float(self.leaf_value[leaf]),
                "leaf_count": int(self.leaf_count[leaf])}


def _fmt_float32(v) -> str:
    return repr(round(float(v), 6)) if v == v else "nan"


def _unwind_path(path, unique_depth, path_index) -> None:
    """Tree::UnwindPath (tree.cpp:605-628)."""
    one_fraction = path[path_index][2]
    zero_fraction = path[path_index][1]
    next_one_portion = path[unique_depth][3]
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0:
            tmp = path[i][3]
            path[i][3] = next_one_portion * (unique_depth + 1.0) / ((i + 1) * one_fraction)
            next_one_portion = tmp - path[i][3] * zero_fraction * (unique_depth - i) / (unique_depth + 1.0)
        else:
            path[i][3] = path[i][3] * (unique_depth + 1.0) / (zero_fraction * (unique_depth - i))
    for i in range(path_index, unique_depth):
        path[i][0] = path[i + 1][0]
        path[i][1] = path[i + 1][1]
        path[i][2] = path[i + 1][2]


def _unwound_path_sum(path, unique_depth, path_index) -> float:
    """Tree::UnwoundPathSum (tree.cpp:630-649)."""
    one_fraction = path[path_index][2]
    zero_fraction = path[path_index][1]
    next_one_portion = path[unique_depth][3]
    total = 0.0
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0:
            tmp = next_one_portion * (unique_depth + 1.0) / ((i + 1) * one_fraction)
            total += tmp
            next_one_portion = path[i][3] - tmp * zero_fraction * \
                ((unique_depth - i) / (unique_depth + 1.0))
        else:
            total += (path[i][3] / zero_fraction) / \
                ((unique_depth - i) / (unique_depth + 1.0))
    return total
