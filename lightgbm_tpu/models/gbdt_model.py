"""Model container: an ordered list of trees + metadata, text-format compatible.

Role parity with the reference's src/boosting/gbdt_model_text.cpp
(SaveModelToString at :240-326, LoadModelFromString, DumpModel JSON at :15-54)
so model files interchange with the reference: a model trained here loads in
the reference CLI and vice versa.
"""
from __future__ import annotations

import json
import re
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..utils.log import Log
from .tree import Tree

_MODEL_VERSION = "v2"


class GBDTModel:
    """Trees + the header metadata the reference stores in its model file."""

    def __init__(self):
        self.trees: List[Tree] = []
        self.num_class = 1
        self.num_tree_per_iteration = 1
        self.label_index = 0
        self.max_feature_idx = 0
        self.objective_str: str = "regression"
        self.average_output = False
        self.feature_names: List[str] = []
        self.feature_infos: List[str] = []
        self.loaded_parameters: str = ""
        self.sub_model_name = "tree"

    # -- iteration bookkeeping ----------------------------------------------
    @property
    def num_total_trees(self) -> int:
        return len(self.trees)

    @property
    def current_iteration(self) -> int:
        return len(self.trees) // self.num_tree_per_iteration

    # -- prediction ----------------------------------------------------------
    def predict_raw(self, X: np.ndarray, start_iteration: int = 0,
                    num_iteration: int = -1, early_stop: Optional[str] = None,
                    early_stop_freq: int = 10,
                    early_stop_margin: float = 10.0) -> np.ndarray:
        """Raw margin scores [n, num_tree_per_iteration] by summing trees.

        early_stop: None/'none', 'binary' (stop a row once 2*|margin| exceeds
        early_stop_margin) or 'multiclass' (top1-top2 gap) — vectorized form
        of src/boosting/prediction_early_stop.cpp, checked every
        early_stop_freq iterations per row."""
        n = X.shape[0]
        k = self.num_tree_per_iteration
        out = np.zeros((n, k), dtype=np.float64)
        end = self._resolve_end_iteration(start_iteration, num_iteration)
        use_early = early_stop in ("binary", "multiclass")
        if use_early and early_stop == "multiclass" and k < 2:
            Log.fatal("Multiclass early stopping needs predictions of length >= 2")
        if use_early and early_stop == "binary" and k != 1:
            Log.fatal("Binary early stopping needs predictions of length one")
        active = np.ones(n, dtype=bool)
        all_active = True  # avoid per-iteration fancy-index copies until a row stops
        rounds_since_check = 0
        for it in range(start_iteration, end):
            if use_early and not all_active:
                rows = X[active]
                if rows.shape[0] == 0:
                    break
            else:
                rows = X
            for j in range(k):
                pred = self.trees[it * k + j].predict(rows)
                if use_early and not all_active:
                    out[active, j] += pred
                else:
                    out[:, j] += pred
            if use_early:
                rounds_since_check += 1
                if rounds_since_check == early_stop_freq:
                    rounds_since_check = 0
                    if early_stop == "binary":
                        margin = 2.0 * np.abs(out[:, 0])
                    else:
                        part = np.partition(out, k - 2, axis=1)
                        margin = part[:, k - 1] - part[:, k - 2]
                    active &= ~(margin > early_stop_margin)
                    all_active = bool(active.all())
        return out

    def early_stop_mode(self, requested: bool) -> Optional[str]:
        """None / 'binary' / 'multiclass' — the reference gates prediction
        early stop on NeedAccuratePrediction: only binary / multiclass /
        ranking objectives tolerate truncated sums (predictor.hpp:46-52,
        objective NeedAccuratePrediction overrides).  Shared by the host
        and device predict paths so both truncate identically."""
        if not requested or self.average_output:
            return None
        obj_kind = str(self.objective_str).split()[0] \
            if self.objective_str else ""
        if obj_kind not in ("binary", "multiclass", "multiclassova",
                            "lambdarank"):
            return None
        return "multiclass" if self.num_tree_per_iteration > 1 else "binary"

    def num_prediction_iterations(self, start_iteration: int = 0,
                                  num_iteration: int = -1) -> int:
        return max(self._resolve_end_iteration(start_iteration, num_iteration)
                   - start_iteration, 1)

    def _resolve_end_iteration(self, start_iteration: int, num_iteration) -> int:
        """'<= 0 means all' + clamp rule shared by every prediction entry."""
        total_iter = self.current_iteration
        if num_iteration is None or num_iteration <= 0:
            num_iteration = total_iter
        return min(start_iteration + num_iteration, total_iter)

    def predict_contrib(self, X: np.ndarray, num_iteration: int = -1) -> np.ndarray:
        """SHAP feature contributions summed over trees: [n, F+1] for one
        model per iteration, [n, K*(F+1)] for multiclass (c_api predict
        CONTRIB layout)."""
        n = X.shape[0]
        F = self.max_feature_idx + 1
        k = self.num_tree_per_iteration
        end = self._resolve_end_iteration(0, num_iteration)
        out = np.zeros((n, k, F + 1))
        for it in range(end):
            for j in range(k):
                out[:, j, :] += self.trees[it * k + j].predict_contrib(X, F)
        return out[:, 0, :] if k == 1 else out.reshape(n, k * (F + 1))

    def predict_leaf_index(self, X: np.ndarray, num_iteration: int = -1) -> np.ndarray:
        end = self._resolve_end_iteration(0, num_iteration) * self.num_tree_per_iteration
        outs = [self.trees[i].predict_leaf_index(X) for i in range(end)]
        return np.stack(outs, axis=1) if outs else np.zeros((X.shape[0], 0))

    # -- serialization -------------------------------------------------------
    def save_model_to_string(self, start_iteration: int = 0, num_iteration: int = -1,
                             feature_importance_type: str = "split",
                             parameters: str = "") -> str:
        lines = [self.sub_model_name, "version=%s" % _MODEL_VERSION,
                 "num_class=%d" % self.num_class,
                 "num_tree_per_iteration=%d" % self.num_tree_per_iteration,
                 "label_index=%d" % self.label_index,
                 "max_feature_idx=%d" % self.max_feature_idx,
                 "objective=%s" % self.objective_str]
        if self.average_output:
            lines.append("average_output")
        fnames = self.feature_names
        if len(fnames) <= self.max_feature_idx:
            fnames = ["Column_%d" % i for i in range(self.max_feature_idx + 1)]
        lines.append("feature_names=" + " ".join(fnames))
        lines.append("feature_infos=" + " ".join(self.feature_infos))

        total_iter = self.current_iteration
        start_iteration = max(0, min(start_iteration, total_iter))
        if num_iteration is None or num_iteration <= 0:
            end_model = self.num_total_trees
        else:
            end_model = min((start_iteration + num_iteration) * self.num_tree_per_iteration,
                            self.num_total_trees)
        start_model = start_iteration * self.num_tree_per_iteration

        tree_strs = []
        for i in range(start_model, end_model):
            s = "Tree=%d\n" % (i - start_model) + self.trees[i].to_string() + "\n"
            tree_strs.append(s)
        lines.append("tree_sizes=" + " ".join(str(len(s)) for s in tree_strs))
        lines.append("")
        body = "\n".join(lines) + "\n" + "".join(tree_strs) + "end of trees\n"

        imp = self.feature_importance(num_iteration, feature_importance_type)
        pairs = sorted([(int(v), fnames[i]) for i, v in enumerate(imp) if v > 0],
                       key=lambda p: -p[0])
        body += "\nfeature importances:\n"
        body += "".join("%s=%d\n" % (nm, v) for v, nm in pairs)
        if parameters:
            body += "\nparameters:\n" + parameters + "\nend of parameters\n"
        return body

    def save_model(self, filename: str, start_iteration: int = 0,
                   num_iteration: int = -1, parameters: str = "") -> None:
        with open(filename, "w") as f:
            f.write(self.save_model_to_string(start_iteration, num_iteration,
                                              parameters=parameters))

    @classmethod
    def load_model_from_string(cls, text: str) -> "GBDTModel":
        model = cls()
        header, _, rest = text.partition("Tree=0")
        kv: Dict[str, str] = {}
        for line in header.split("\n"):
            line = line.strip()
            if line == "average_output":
                model.average_output = True
            elif "=" in line:
                k, v = line.split("=", 1)
                kv[k] = v
        model.num_class = int(kv.get("num_class", "1"))
        model.num_tree_per_iteration = int(kv.get("num_tree_per_iteration", str(model.num_class)))
        model.label_index = int(kv.get("label_index", "0"))
        model.max_feature_idx = int(kv.get("max_feature_idx", "0"))
        model.objective_str = kv.get("objective", "regression")
        model.feature_names = kv.get("feature_names", "").split()
        model.feature_infos = kv.get("feature_infos", "").split()
        if not rest:
            return model
        tree_part, _, tail = ("Tree=0" + rest).partition("end of trees")
        blocks = re.split(r"Tree=\d+\n", tree_part)
        for block in blocks:
            if "num_leaves" in block:
                model.trees.append(Tree.from_string(block))
        m = re.search(r"parameters:\n(.*?)\nend of parameters", tail, re.S)
        if m:
            model.loaded_parameters = m.group(1)
        return model

    @classmethod
    def load_model(cls, filename: str) -> "GBDTModel":
        with open(filename) as f:
            return cls.load_model_from_string(f.read())

    def dump_model(self, num_iteration: int = -1) -> Dict:
        total_iter = self.current_iteration
        if num_iteration is None or num_iteration <= 0:
            num_iteration = total_iter
        end = min(num_iteration, total_iter) * self.num_tree_per_iteration
        return {
            "name": self.sub_model_name,
            "version": _MODEL_VERSION,
            "num_class": self.num_class,
            "num_tree_per_iteration": self.num_tree_per_iteration,
            "label_index": self.label_index,
            "max_feature_idx": self.max_feature_idx,
            "objective": self.objective_str,
            "average_output": self.average_output,
            "feature_names": list(self.feature_names),
            "tree_info": [t.to_json() for t in self.trees[:end]],
        }

    # -- importance (gbdt.cpp FeatureImportance) ----------------------------
    def feature_importance(self, num_iteration: int = -1,
                           importance_type: str = "split") -> np.ndarray:
        num_feat = self.max_feature_idx + 1
        imp = np.zeros(num_feat, dtype=np.float64)
        total_iter = self.current_iteration
        if num_iteration is None or num_iteration <= 0:
            num_iteration = total_iter
        end = min(num_iteration, total_iter) * self.num_tree_per_iteration
        for tree in self.trees[:end]:
            ni = tree.num_leaves - 1
            for node in range(ni):
                f = tree.split_feature[node]
                if importance_type == "split":
                    imp[f] += 1
                else:
                    imp[f] += max(tree.split_gain[node], 0.0)
        return imp
