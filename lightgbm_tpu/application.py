"""CLI application: task=train / predict / convert_model / refit.

Role parity with the reference src/application/application.cpp and main.cpp:
parameters from `k=v` argv entries plus a `config=<file>` of `key = value`
lines (argv wins, application.cpp:48-81); training loads data (+ optional
<data>.weight / <data>.query sidecars), runs the engine, saves the model and
periodic snapshots (gbdt.cpp:330-334); prediction writes one converted score
per row (src/application/predictor.hpp); convert_model emits the model as
C++ if-else code (gbdt_model_text.cpp ModelToIfElse).
"""
from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Optional

import numpy as np

from .basic import Booster, Dataset
from .callback import record_evaluation
from .config import Config
from .engine import train as engine_train
from .io.parser import load_sidecar, parse_file
from .models.gbdt_model import GBDTModel
from .runtime import resilience, telemetry
from .utils.log import LightGBMError, Log

#: per-stage deadline for the CLI's ingest/save stages (seconds; 0
#: disables).  Training itself is legitimately unbounded, so only the
#: bounded stages are watchdogged by default — a hung parse or a stuck
#: filesystem dies loudly with a faulthandler dump instead of stalling
#: the whole task (LGBM_TPU_STAGE_TIMEOUT overrides).
_INGEST_STAGE_TIMEOUT = int(os.environ.get("LGBM_TPU_STAGE_TIMEOUT", "3600"))


def parse_parameters(argv: List[str]) -> Dict[str, str]:
    """argv `k=v` pairs > config file lines (application.cpp LoadParameters)."""
    cli: Dict[str, str] = {}
    for arg in argv:
        if "=" not in arg:
            continue
        k, v = arg.split("=", 1)
        cli[k.strip()] = v.strip()
    params: Dict[str, str] = {}
    config_path = cli.get("config", cli.get("config_file"))
    if config_path:
        with open(config_path) as fh:
            for line in fh:
                line = line.split("#", 1)[0].strip()
                if not line or "=" not in line:
                    continue
                k, v = line.split("=", 1)
                params[k.strip()] = v.strip()
    params.update(cli)
    params.pop("config", None)
    params.pop("config_file", None)
    return params


class Application:
    def __init__(self, argv: List[str]):
        self.raw_params = parse_parameters(argv)
        self.task = self.raw_params.pop("task", "train")
        # distributed-tracing knobs (ISSUE 14): trace_dir= arms the
        # atexit flight-recorder dump (same as $LGBM_TPU_TRACE_DIR —
        # subprocesses inherit the env form), trace=false disables the
        # recorder entirely (the <1% disabled-path pin covers the cost)
        from .runtime import tracing
        trace_dir = self.raw_params.pop("trace_dir", None)
        if trace_dir:
            os.environ[tracing.TRACE_DIR_ENV] = trace_dir
        if str(self.raw_params.pop("trace", "")).lower() in ("false", "0"):
            tracing.set_enabled(False)
        tracing.set_context(self.task)
        tracing.maybe_autostart()
        # persistent-compile-cache seam (ISSUE 15): compile_cache_dir=
        # (same as $LGBM_TPU_COMPILE_CACHE) wires jax's persistent
        # compilation cache to a fingerprinted subdirectory before any
        # task compiles; zero-cost (no jax import) when neither is set
        from .runtime import warmup
        cache_dir = self.raw_params.pop("compile_cache_dir", None)
        if cache_dir:
            warmup.enable_compile_cache(cache_dir)
        else:
            warmup.maybe_enable_from_env()

    def run(self) -> None:
        if self.task in ("train", "refit"):
            # reference parity: Network::Init runs inside InitTrain only
            # (application.cpp:168-171) — predict/convert stay local even
            # when the conf still carries the cluster's machine list
            self._maybe_init_network()
        try:
            if self.task == "train":
                self.train()
            elif self.task == "train_online":
                self.train_online()
            elif self.task == "serve":
                self.serve()
            elif self.task in ("predict", "prediction", "test"):
                self.predict()
            elif self.task == "convert_model":
                self.convert_model()
            elif self.task == "refit":
                self.refit()
            elif self.task == "doctor":
                self.doctor()
            else:
                Log.fatal("Unknown task type %s", self.task)
        except (SystemExit, KeyboardInterrupt):
            raise
        except BaseException:
            # crash path: ship the evidence before dying.  The bundle is
            # the same artifact task=doctor builds (probe skipped — the
            # crash may BE a wedged platform); LGBM_TPU_DOCTOR_ON_CRASH=0
            # opts out, LGBM_TPU_DOCTOR_DIR redirects it.
            self._crash_bundle()
            raise

    def _crash_bundle(self) -> None:
        if os.environ.get("LGBM_TPU_DOCTOR_ON_CRASH", "1") == "0" \
                or self.task == "doctor":
            return
        try:
            import tempfile

            from .runtime.doctor import collect_debug_bundle
            out_dir = os.environ.get("LGBM_TPU_DOCTOR_DIR",
                                     tempfile.gettempdir())
            import traceback
            rec = collect_debug_bundle(
                out_dir=out_dir, tag="crash_%s" % self.task,
                config=self.raw_params, probe=False,
                note=traceback.format_exc(limit=20))
            sys.stderr.write("doctor: crash bundle written to %s "
                             "(%d members)\n"
                             % (rec["path"],
                                len(rec["manifest"]["members"])))
        except BaseException:       # noqa: BLE001 — never mask the crash
            pass

    def _maybe_init_network(self) -> None:
        """Reference CLI parity: a training task with a cluster config
        brings the network up first (application.cpp Network::Init) —
        here that is jax.distributed over the same machine list."""
        from .parallel.launch import maybe_init_distributed
        maybe_init_distributed({Config.resolve_alias(k): v
                                for k, v in self.raw_params.items()})

    # -- data loading --------------------------------------------------------
    def _load(self, path: str, num_features: Optional[int] = None):
        params = self.raw_params
        label_column = 0
        lc = params.get("label_column", params.get("label", ""))
        if lc.startswith("name:"):
            Log.fatal("label_column by name requires a header; use an index")
        elif lc:
            label_column = int(lc)
        has_header = None
        if params.get("has_header", params.get("header", "")).lower() in ("true", "1"):
            has_header = True
        X, y = parse_file(path, label_column=label_column, has_header=has_header,
                          num_features=num_features)
        weight = load_sidecar(path + ".weight")
        query = load_sidecar(path + ".query")
        return X, y, weight, query

    # -- tasks ---------------------------------------------------------------
    def train(self) -> None:
        params = dict(self.raw_params)
        data_path = params.pop("data", params.pop("train_data", None))
        if not data_path:
            Log.fatal("No training data, set data=<file>")
        valid_paths = [p for p in
                       params.pop("valid", params.pop("valid_data", "")).split(",") if p]
        output_model = params.pop("output_model", "LightGBM_model.txt")
        input_model = params.pop("input_model", None)
        from .engine import _rounds_from_params
        num_rounds, early_stopping = _rounds_from_params(params, 100, 0)
        num_rounds, early_stopping = int(num_rounds), int(early_stopping or 0)
        snapshot_freq = int(params.pop("snapshot_freq", -1))
        # keep-last-K snapshot cleanup; <= 0 keeps everything
        snapshot_retention = int(params.pop("snapshot_retention", -1))
        resume = str(params.pop("resume", "")).lower() in ("true", "1")

        # resume=true: scan for the newest VALID snapshot (checksummed
        # footer; corrupt/truncated ones are skipped with a warning) and
        # continue from it to a model byte-identical to an uninterrupted
        # run (runtime/resilience.py restores scores, payload row order
        # and RNG streams past the trees themselves)
        resume_state = None
        if resume:
            snap_path, resume_state = resilience.find_resume_snapshot(
                output_model, log=Log)
            if snap_path is None:
                Log.warning("resume=true but no valid snapshot found for "
                            "%s; training from scratch", output_model)
            else:
                Log.info("Resuming from snapshot %s (iteration %d)",
                         snap_path, resume_state["total_iter"])
                input_model = snap_path
                if resume_state["total_iter"] >= num_rounds:
                    Log.info("Snapshot already has %d >= %d iterations; "
                             "saving it as the final model",
                             resume_state["total_iter"], num_rounds)
                    GBDTModel.load_model(snap_path).save_model(output_model)
                    return

        # $LGBM_TPU_METRICS_FILE: periodic atomic JSON-lines snapshots of
        # the metrics registry (per-iteration timing, sync audit, spans)
        # for batch runs that have no scrape endpoint (ISSUE 9)
        telemetry.maybe_start_file_export("cli_train")

        wd = resilience.Watchdog(_INGEST_STAGE_TIMEOUT, hard=False,
                                 label="cli stage")
        from .io.dataset import BinnedDataset
        resolved = {Config.resolve_alias(k): v for k, v in params.items()}
        with wd.stage_scope("ingest train data (%s)" % data_path):
            import time as _time
            t_ingest = _time.perf_counter()
            if BinnedDataset.is_binary_file(data_path):
                # version-stamped cache: a stale format_version refuses
                # here with a clear delete-and-rebuild error
                train_set = Dataset(data_path, params=params)
                train_set.construct(Config(params))
                dt = _time.perf_counter() - t_ingest
                wd.annotate("ingest", {
                    "mode": "binary_cache",
                    "rows": int(train_set.num_data()),
                    "rows_per_sec": round(train_set.num_data() / dt, 1)
                    if dt > 0 else None})
            else:
                X, y, weight, query = self._load(data_path)
                dt = _time.perf_counter() - t_ingest
                wd.annotate("ingest", {
                    "mode": "file_parse", "rows": int(X.shape[0]),
                    "rows_per_sec": round(X.shape[0] / dt, 1)
                    if dt > 0 else None})
                group = None
                if query is not None:
                    group = query.astype(np.int64)
                train_set = Dataset(X, label=y, weight=weight, group=group,
                                    params=params)
                if str(resolved.get("save_binary", "")).lower() in ("true", "1"):
                    train_set.construct(Config(params))
                    train_set.save_binary(data_path + ".bin")
        valid_sets = []
        valid_names = []
        num_features = train_set.binned.num_features
        for i, vp in enumerate(valid_paths):
            with wd.stage_scope("ingest valid data (%s)" % vp):
                vX, vy, vweight, vquery = self._load(vp,
                                                     num_features=num_features)
                vgroup = vquery.astype(np.int64) if vquery is not None else None
                valid_sets.append(train_set.create_valid(
                    vX, label=vy, weight=vweight, group=vgroup))
                valid_names.append(os.path.basename(vp))
        wd.done()

        callbacks = []
        if resume_state is not None:
            callbacks.append(resilience.make_resume_callback(resume_state,
                                                             log=Log))
        if snapshot_freq > 0:
            def snapshot(env):
                # absolute iteration clock (model.current_iteration), so a
                # resumed run writes the SAME snapshot schedule and names
                # as an uninterrupted one
                total = int(env.model.current_iteration())
                if total % snapshot_freq == 0:
                    resilience.write_snapshot(env.model, output_model,
                                              total_iter=total,
                                              retention=snapshot_retention,
                                              log=Log)
            callbacks.append(snapshot)
        evals: Dict = {}
        callbacks.append(record_evaluation(evals))

        # preemption guard: SIGTERM/SIGINT write a final checksummed
        # snapshot at the next iteration boundary, then exit cleanly
        guard = resilience.PreemptionGuard(output_model,
                                           retention=snapshot_retention,
                                           log=Log)
        callbacks.append(guard.callback)
        remaining = num_rounds - (resume_state["total_iter"]
                                  if resume_state is not None else 0)
        try:
            with guard:
                booster = engine_train(
                    params, train_set, num_boost_round=remaining,
                    valid_sets=valid_sets or None,
                    valid_names=valid_names or None,
                    init_model=input_model, callbacks=callbacks,
                    early_stopping_rounds=early_stopping
                    if early_stopping > 0 else None,
                    verbose_eval=int(params.get("metric_freq", 1)))
        except resilience.TrainingPreempted as e:
            Log.warning("Training preempted by signal %d at iteration %d; "
                        "snapshot %s written — rerun with resume=true to "
                        "continue", e.signum, e.iteration, e.snapshot)
            telemetry.write_snapshot_now("cli_train_preempted")
            return
        with wd.stage_scope("save model (%s)" % output_model):
            booster.save_model(output_model)
        wd.done()
        telemetry.write_snapshot_now("cli_train")
        Log.info("Finished training, model saved to %s", output_model)

    def train_online(self) -> None:
        """Continuous-training service (runtime/continuous.py): a
        rolling-window trainer that boosts or refits on an absolute-clock
        schedule, survives preemption mid-cycle, and publishes every
        cycle atomically to `publish_dir` for subscribers (the serving
        layer's contract).  Key params: `online_interval` (seconds
        between cycles), `online_cycles` (total generations; 0 = run
        forever), `online_rounds`, `online_mode=boost|refit`,
        `online_window_rows`, `publish_retention`/`publish_grace`,
        `snapshot_retention`/`snapshot_grace`, `metrics_port` (live
        GET /metrics endpoint — docs/OBSERVABILITY.md), and the
        quality-firewall knobs `online_quarantine_limit`,
        `publish_gate_tolerance` (inf = gate off),
        `publish_gate_holdout`, `publish_gate_metric`.  See
        docs/RESILIENCE.md for the runbook."""
        from .runtime.continuous import ContinuousTrainer
        rc = ContinuousTrainer(dict(self.raw_params), log=Log).run()
        if rc != 0:
            sys.exit(rc)

    def serve(self) -> None:
        """Fault-tolerant serving service (runtime/serving.py): a
        long-lived JSON-lines TCP server that micro-batches concurrent
        predict requests into the tree-parallel device engine, sheds
        overload with explicit retryable rejections, degrades to the
        host predictor when the device path fails or hangs, and
        hot-swaps models from a `publish_dir` (the task=train_online
        publish directory) without dropping a request.  Key params:
        `publish_dir=` or `input_model=`, `serve_port` (0 = ephemeral,
        printed on stdout), `serve_host`, `serve_queue`,
        `serve_batch_rows`, `serve_batch_window`, `serve_deadline`,
        `predict_deadline`, `serve_poll_interval`, `breaker_cooldown`,
        `serve_raw_score`, `metrics_port` (GET /metrics Prometheus
        endpoint; 0 = ephemeral, printed on stdout — see
        docs/OBSERVABILITY.md), the ISSUE-16 binary data-plane knobs
        `serve_wire_port` / `serve_wire_uds` / `serve_response_dtype`
        plus the ISSUE-20 `serve_wire_shm` shared-memory-ring toggle
        (docs/SERVING.md wire-protocol section), and the ISSUE-12
        canary knobs
        `canary_fraction` (0 = off) with `canary_min_samples`,
        `canary_patience`, `canary_error_ratio`, `canary_error_margin`,
        `canary_latency_ratio`, `canary_promote_after`
        (docs/RESILIENCE.md quality-firewall runbook).  SIGTERM/SIGINT
        stop cleanly with the final stats on stderr.  See
        docs/SERVING.md for the runbook."""
        import signal as _signal
        import threading as _threading

        from .runtime.serving import ServingRuntime, ServingServer
        params = dict(self.raw_params)
        publish_dir = params.pop("publish_dir", None)
        input_model = params.pop("input_model", None)
        host = params.pop("serve_host", "127.0.0.1")
        port = int(params.pop("serve_port", 0) or 0)
        metrics_port = params.pop("metrics_port", None)
        # ISSUE 16 binary data plane: serve_wire_port (0 = ephemeral)
        # opens the length-prefixed binary frame protocol beside the
        # JSON front end; serve_wire_uds=/path serves the same frames
        # over a Unix-domain socket; serve_response_dtype=float32 halves
        # the response payloads (exact downcast of the f64 surface)
        wire_port = params.pop("serve_wire_port", None)
        wire_uds = params.pop("serve_wire_uds", None)
        # ISSUE 20: any UDS wire connection may upgrade itself to a
        # per-client shared-memory ring (syscall-free steady state);
        # serve_wire_shm=false pins the socket-only data plane
        wire_shm = bool(params.pop("serve_wire_shm", True))
        response_dtype = params.pop("serve_response_dtype", None) or None
        # ISSUE 12 canary knobs: canary_fraction=F routes F of batches
        # to each newly published generation until the CanaryPolicy
        # promotes it or rolls the fleet back (docs/RESILIENCE.md)
        canary_fraction = float(params.pop("canary_fraction", 0.0) or 0.0)
        canary_policy = None
        if canary_fraction > 0:
            from .runtime.policy import CanaryPolicy
            canary_policy = CanaryPolicy(
                min_samples=int(params.pop("canary_min_samples", 8)),
                patience=int(params.pop("canary_patience", 3)),
                error_ratio=float(params.pop("canary_error_ratio", 1.5)),
                error_margin=float(params.pop("canary_error_margin",
                                              0.02)),
                latency_ratio=float(params.pop("canary_latency_ratio",
                                               5.0)),
                promote_after=int(params.pop("canary_promote_after", 64)))
        runtime = ServingRuntime(
            canary_fraction=canary_fraction, canary_policy=canary_policy,
            metrics_port=int(metrics_port) if metrics_port is not None
            else None,
            publish_dir=publish_dir, model_file=input_model,
            params=params, response_dtype=response_dtype,
            raw_score=str(params.pop("serve_raw_score", "")).lower()
            in ("true", "1"),
            max_queue=int(params.pop("serve_queue", 256)),
            max_batch_rows=int(params.pop("serve_batch_rows", 4096)),
            batch_window_s=float(params.pop("serve_batch_window", 0.002)),
            default_deadline_s=float(params.pop("serve_deadline", 10.0)),
            predict_deadline_s=float(params.pop("predict_deadline", 30.0)),
            poll_interval_s=float(params.pop("serve_poll_interval", 0.2)),
            breaker_cooldown_s=float(params.pop("breaker_cooldown", 2.0)),
            probe_platform_on_start=True, log=Log)
        runtime.start()
        server = ServingServer(runtime, host=host, port=port)
        wire_servers = []
        if wire_port is not None:
            from .runtime.wire import WireTCPServer
            wire_servers.append(WireTCPServer(runtime, host=host,
                                              port=int(wire_port or 0)))
        if wire_uds:
            from .runtime.wire import WireUnixServer
            wire_servers.append(WireUnixServer(runtime, path=str(wire_uds),
                                               enable_shm=wire_shm))
        stop_evt = _threading.Event()

        def _stop(signum, frame):
            Log.warning("serve: signal %d received; draining and "
                        "shutting down", signum)
            if stop_evt.is_set():
                return
            stop_evt.set()
            # shutdown() blocks until serve_forever exits — and this
            # handler RUNS on the serve_forever thread, so it must be
            # issued from a helper thread or it deadlocks
            _threading.Thread(target=server.shutdown, daemon=True).start()

        for sig in (_signal.SIGTERM, _signal.SIGINT):
            _signal.signal(sig, _stop)
        # the port on stdout is the machine-readable contract for
        # supervisors that asked for an ephemeral port
        print("serving %s on %s:%d" % (publish_dir or input_model,
                                       host, server.port), flush=True)
        for wsrv in wire_servers:
            _threading.Thread(target=wsrv.serve_forever,
                              daemon=True).start()
            if getattr(wsrv, "wire_path_label", "") == "uds":
                print("wire (uds) on %s" % wire_uds, flush=True)
            else:
                print("wire (tcp) on %s:%d" % (host, wsrv.port),
                      flush=True)
        if runtime.metrics_port is not None:
            print("metrics on %s:%d" % (host, runtime.metrics_port),
                  flush=True)
        try:
            server.serve_forever(poll_interval=0.2)
        finally:
            for wsrv in wire_servers:
                wsrv.shutdown()
                wsrv.server_close()
            server.server_close()
            runtime.stop()
            sys.stderr.write("serve: final stats: %s\n"
                             % json.dumps(runtime.stats()))

    def predict(self) -> None:
        params = dict(self.raw_params)
        data_path = params.pop("data", None)
        input_model = params.pop("input_model", None)
        output_result = params.pop("output_result", "LightGBM_predict_result.txt")
        # predict_device=true routes file-scale prediction through the
        # tree-parallel device engine (f32 thresholds, micro-batched
        # streaming transfer); the default stays the exact f64 host
        # traversal whose output files are the byte-parity reference for
        # the C ABI's LGBM_BoosterPredictForFile
        use_device = params.pop("predict_device",
                                params.pop("device", "")).lower() \
            in ("true", "1")
        if not data_path or not input_model:
            Log.fatal("Prediction needs data=<file> and input_model=<file>")
        booster = Booster(params=params, model_file=input_model)
        num_feat = booster._model.max_feature_idx + 1
        X, _, _, _ = self._load(data_path, num_features=num_feat)
        raw_score = params.get("predict_raw_score", "").lower() in ("true", "1")
        pred_leaf = params.get("predict_leaf_index", "").lower() in ("true", "1")
        pred_contrib = params.get("predict_contrib", "").lower() in ("true", "1")
        num_iter = int(params.get("num_iteration_predict", -1))
        early = params.get("pred_early_stop", "").lower() in ("true", "1")
        if use_device and (pred_leaf or pred_contrib):
            Log.warning("predict_device supports normal/raw prediction "
                        "only; using the host predictor")
            use_device = False
        out = booster.predict(
            X, raw_score=raw_score, pred_leaf=pred_leaf,
            pred_contrib=pred_contrib, num_iteration=num_iter,
            pred_early_stop=early, device=use_device,
            pred_early_stop_freq=int(params.get("pred_early_stop_freq", 10)),
            pred_early_stop_margin=float(
                params.get("pred_early_stop_margin", 10.0)))
        out = np.asarray(out)
        with open(output_result, "w") as fh:
            if out.ndim == 1:
                for v in out:
                    fh.write("%.18g\n" % v)
            else:
                for row in out:
                    fh.write("\t".join("%.18g" % v for v in row) + "\n")
        Log.info("Finished prediction, results saved to %s", output_result)

    def convert_model(self) -> None:
        params = dict(self.raw_params)
        input_model = params.pop("input_model", None)
        out_path = params.pop("convert_model_file",
                              params.pop("output_model", "gbdt_prediction.cpp"))
        if not input_model:
            Log.fatal("convert_model needs input_model=<file>")
        model = GBDTModel.load_model(input_model)
        with open(out_path, "w") as fh:
            fh.write(model_to_ifelse(model))
        Log.info("Finished converting model, saved to %s", out_path)

    def doctor(self) -> None:
        """One-command debug bundle (runtime/doctor.py): platform probe,
        env/config fingerprint, stage trails, metrics snapshot, compile
        ledger and the newest BENCH/CHAOS/MULTICHIP artifacts in one
        atomic checksummed tar.  Params: `output_dir=` (default .),
        `probe=false` skips the platform probe, `probe_deadline=S`,
        `artifact_dir=` overrides where artifacts are collected from.
        See docs/OBSERVABILITY.md for the runbook."""
        from .runtime.doctor import collect_debug_bundle
        params = dict(self.raw_params)
        out_dir = params.pop("output_dir", params.pop("out_dir", "."))
        probe = str(params.pop("probe", "true")).lower() not in ("false",
                                                                 "0")
        deadline = float(params.pop("probe_deadline", 10.0))
        artifact_dir = params.pop("artifact_dir", None)
        rec = collect_debug_bundle(out_dir=out_dir, tag=None,
                                   config=params, probe=probe,
                                   probe_deadline=deadline,
                                   artifact_dir=artifact_dir)
        # the path on stdout is the machine contract (exp scripts commit
        # the manifest next to the round's artifacts)
        print("doctor bundle %s" % rec["path"], flush=True)
        for m in rec["manifest"]["members"]:
            Log.info("doctor:   %-28s %7d bytes  sha256=%s...",
                     m["name"], m["bytes"], m["sha256"][:12])
        if rec["manifest"].get("errors"):
            Log.warning("doctor: some members could not be gathered: %s",
                        rec["manifest"]["errors"])

    def refit(self) -> None:
        params = dict(self.raw_params)
        data_path = params.pop("data", None)
        input_model = params.pop("input_model", None)
        output_model = params.pop("output_model", "LightGBM_model.txt")
        if not data_path or not input_model:
            Log.fatal("Refit needs data=<file> and input_model=<file>")
        booster = Booster(params=params, model_file=input_model)
        num_feat = booster._model.max_feature_idx + 1
        X, y, weight, query = self._load(data_path, num_features=num_feat)
        group = query.astype(np.int64) if query is not None else None
        new_booster = booster.refit(X, y, weight=weight, group=group)
        new_booster.save_model(output_model)
        Log.info("Finished refit, model saved to %s", output_model)


def model_to_ifelse(model: GBDTModel) -> str:
    """C++ codegen of the model (gbdt_model_text.cpp ModelToIfElse:240+):
    one PredictTreeN function per tree plus a summing Predict entry."""
    lines = ["#include <cmath>", "#include <cstdio>", "", "namespace {", ""]

    def node_code(tree, node: int, depth: int) -> List[str]:
        pad = "  " * (depth + 1)
        if node < 0:
            return ["%sreturn %.17g;" % (pad, tree.leaf_value[~node])]
        dt = int(tree.decision_type[node])
        f = int(tree.split_feature[node])
        out = []
        if dt & 1:  # categorical
            ci = int(tree.threshold_in_bin[node])
            lo, hi = tree.cat_boundaries[ci], tree.cat_boundaries[ci + 1]
            cats = [(i - lo) * 32 + j for i in range(lo, hi) for j in range(32)
                    if (tree.cat_threshold[i] >> j) & 1]
            cond = " || ".join("static_cast<int>(arr[%d]) == %d" % (f, c)
                               for c in cats) or "false"
            out.append("%sif (%s) {" % (pad, cond))
        else:
            missing_type = (dt >> 2) & 3
            default_left = bool(dt & 2)
            thr = "%.17g" % tree.threshold[node]
            if missing_type == 2:  # NaN
                if default_left:
                    cond = "(std::isnan(arr[%d]) || arr[%d] <= %s)" % (f, f, thr)
                else:
                    cond = "(!std::isnan(arr[%d]) && arr[%d] <= %s)" % (f, f, thr)
            elif missing_type == 1:  # Zero
                if default_left:
                    cond = "(std::fabs(arr[%d]) <= 1e-35 || arr[%d] <= %s)" % (f, f, thr)
                else:
                    cond = "(std::fabs(arr[%d]) > 1e-35 && arr[%d] <= %s)" % (f, f, thr)
            else:
                cond = "(arr[%d] <= %s)" % (f, thr)
            out.append("%sif %s {" % (pad, cond))
        out.extend(node_code(tree, int(tree.left_child[node]), depth + 1))
        out.append("%s} else {" % pad)
        out.extend(node_code(tree, int(tree.right_child[node]), depth + 1))
        out.append("%s}" % pad)
        return out

    for i, tree in enumerate(model.trees):
        lines.append("double PredictTree%d(const double* arr) {" % i)
        if tree.num_leaves <= 1:
            lines.append("  return %.17g;" % tree.leaf_value[0])
        else:
            lines.extend(node_code(tree, 0, 0))
        lines.append("}")
        lines.append("")
    lines.append("}  // namespace")
    lines.append("")
    lines.append("double Predict(const double* arr) {")
    lines.append("  double sum = 0.0;")
    for i in range(len(model.trees)):
        lines.append("  sum += PredictTree%d(arr);" % i)
    if model.average_output and model.trees:
        lines.append("  sum /= %d.0;" % model.current_iteration)
    lines.append("  return sum;")
    lines.append("}")
    return "\n".join(lines) + "\n"


def main(argv: Optional[List[str]] = None) -> None:
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print("usage: python -m lightgbm_tpu task=<train|train_online|serve|"
              "predict|convert_model|refit|doctor> [config=<file>] "
              "[key=value ...]")
        return
    Application(argv).run()
