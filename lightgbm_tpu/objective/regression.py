"""Regression objective family.

Role parity with the reference src/objective/regression_objective.hpp:
RegressionL2loss (:64-170), RegressionL1loss (:175-256), RegressionHuberLoss
(:261-319), RegressionFairLoss (:323-365), RegressionPoissonLoss (:371-450),
RegressionQuantileloss (:452-545), RegressionMAPELOSS (:551-645),
RegressionGammaLoss (:652-684), RegressionTweedieLoss (:689-725).

Gradient/hessian math runs on device (jnp, f32); BoostFromScore and the
percentile-based leaf renewal (IsRenewTreeOutput objectives: L1, quantile,
MAPE) run on host over the leaf partition fetched once per tree.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..utils.log import Log
from .base import ObjectiveFunction


def percentile(data: np.ndarray, alpha: float) -> float:
    """PercentileFun (regression_objective.hpp:11-36): descending-rank
    percentile with linear interpolation; pos<1 -> max, pos>=cnt -> min."""
    cnt = len(data)
    if cnt == 0:
        return 0.0
    a = np.sort(np.asarray(data, dtype=np.float64))
    float_pos = (1.0 - alpha) * cnt
    pos = int(float_pos)
    if pos < 1:
        return float(a[-1])
    if pos >= cnt:
        return float(a[0])
    bias = float_pos - pos
    v1 = a[cnt - pos]       # pos-1 -th largest
    v2 = a[cnt - 1 - pos]   # pos   -th largest
    return float(v1 - (v1 - v2) * bias)


def weighted_percentile(data: np.ndarray, weights: np.ndarray, alpha: float) -> float:
    """WeightedPercentileFun (regression_objective.hpp:38-59): weighted CDF
    inversion.  The interpolation uses the [cdf[pos-1], cdf[pos]] step (the
    reference's off-by-one there reads past the CDF end for the final step;
    we keep the clearly intended in-bounds form)."""
    cnt = len(data)
    if cnt == 0:
        return 0.0
    data = np.asarray(data, dtype=np.float64)
    weights = np.asarray(weights, dtype=np.float64)
    order = np.argsort(data, kind="stable")
    d = data[order]
    cdf = np.cumsum(weights[order])
    threshold = cdf[-1] * alpha
    pos = int(np.searchsorted(cdf, threshold, side="right"))
    if pos == 0:
        return float(d[0])
    if pos >= cnt:
        return float(d[-1])
    v1, v2 = d[pos - 1], d[pos]
    denom = cdf[pos] - cdf[pos - 1]
    if denom <= 0:
        return float(v1)
    return float(v1 + (threshold - cdf[pos - 1]) / denom * (v2 - v1))


class RegressionL2(ObjectiveFunction):
    name = "regression"
    is_constant_hessian = True  # when unweighted

    def __init__(self, config):
        super().__init__(config)
        self.sqrt = bool(getattr(config, "reg_sqrt", False))

    def init(self, label, weight, query_boundaries=None):
        super().init(label, weight, query_boundaries)
        if self.sqrt:
            self.label = np.sign(self.label) * np.sqrt(np.abs(self.label))
        self.is_constant_hessian = weight is None

    def _trans_label(self, label):
        """Device-side label transform matching host init (sqrt mode)."""
        if self.sqrt:
            return jnp.sign(label) * jnp.sqrt(jnp.abs(label))
        return label

    def get_gradients(self, score, label, weight):
        label = self._trans_label(label)
        grad = ((score - label) * weight).astype(jnp.float32)
        hess = weight.astype(jnp.float32)
        return grad, hess

    def boost_from_score(self) -> float:
        if self.weight is not None:
            return float(np.sum(self.label * self.weight) / np.sum(self.weight))
        return float(np.mean(self.label))

    def convert_output(self, raw: np.ndarray) -> np.ndarray:
        if self.sqrt:
            return np.sign(raw) * raw * raw
        return raw

    def to_string(self) -> str:
        return "regression sqrt" if self.sqrt else "regression"


class RegressionL1(RegressionL2):
    name = "regression_l1"

    def get_gradients(self, score, label, weight):
        label = self._trans_label(label)
        diff = score - label
        grad = (jnp.sign(diff) * weight).astype(jnp.float32)
        hess = weight.astype(jnp.float32)
        return grad, hess

    def boost_from_score(self) -> float:
        if self.weight is not None:
            return weighted_percentile(self.label, self.weight, 0.5)
        return percentile(self.label, 0.5)

    def renew_tree_output_required(self) -> bool:
        return True

    def _renew_alpha(self) -> float:
        return 0.5

    def _renew_weights(self):
        return self.weight

    def renew_leaf_values(self, leaf_values: np.ndarray, leaf_ids: np.ndarray,
                          pred: np.ndarray, in_bag: np.ndarray) -> np.ndarray:
        """RenewTreeOutput (regression_objective.hpp:221-251): per-leaf
        percentile of residuals (label - pred) over the bagged rows.  Rows are
        bucketed by leaf with one argsort (the reference's data_partition_
        gives it contiguous leaf slices the same way) instead of per-leaf
        masks."""
        alpha = self._renew_alpha()
        w = self._renew_weights()
        out = leaf_values.copy()
        n = self.num_data
        residual = self.label - pred[:n]
        lid = leaf_ids[:n]
        rows = np.nonzero(in_bag[:n])[0]
        order = rows[np.argsort(lid[rows], kind="stable")]
        sorted_lid = lid[order]
        leaf_range = np.arange(len(leaf_values))
        starts = np.searchsorted(sorted_lid, leaf_range, side="left")
        ends = np.searchsorted(sorted_lid, leaf_range, side="right")
        for l in leaf_range:
            seg = order[starts[l]: ends[l]]
            if len(seg) == 0:
                continue
            if w is None:
                out[l] = percentile(residual[seg], alpha)
            else:
                out[l] = weighted_percentile(residual[seg], w[seg], alpha)
        return out

    def to_string(self) -> str:
        return self.name


class RegressionHuber(RegressionL2):
    name = "huber"
    is_constant_hessian = False

    def __init__(self, config):
        super().__init__(config)
        self.alpha = float(getattr(config, "alpha", 0.9))
        if self.sqrt:
            Log.warning("Cannot use sqrt transform in %s Regression, will auto disable it", self.name)
            self.sqrt = False

    def init(self, label, weight, query_boundaries=None):
        super().init(label, weight, query_boundaries)
        self.is_constant_hessian = False

    def get_gradients(self, score, label, weight):
        diff = score - label
        clipped = jnp.clip(diff, -self.alpha, self.alpha)
        grad = (clipped * weight).astype(jnp.float32)
        hess = weight.astype(jnp.float32)
        return grad, hess

    def to_string(self) -> str:
        return self.name


class RegressionFair(RegressionL2):
    name = "fair"
    is_constant_hessian = False

    def __init__(self, config):
        super().__init__(config)
        self.c = float(getattr(config, "fair_c", 1.0))

    def init(self, label, weight, query_boundaries=None):
        super().init(label, weight, query_boundaries)
        self.is_constant_hessian = False

    def get_gradients(self, score, label, weight):
        x = score - self._trans_label(label)
        denom = jnp.abs(x) + self.c
        grad = (self.c * x / denom * weight).astype(jnp.float32)
        hess = (self.c * self.c / (denom * denom) * weight).astype(jnp.float32)
        return grad, hess

    def to_string(self) -> str:
        return self.name


class RegressionPoisson(RegressionL2):
    """loss = exp(f) - label * f;  output = exp(f)
    (regression_objective.hpp:405-429)."""
    name = "poisson"
    is_constant_hessian = False

    def __init__(self, config):
        super().__init__(config)
        self.max_delta_step = float(getattr(config, "poisson_max_delta_step", 0.7))
        if self.sqrt:
            Log.warning("Cannot use sqrt transform in %s Regression, will auto disable it", self.name)
            self.sqrt = False

    def check_label(self) -> None:
        if np.min(self.label) < 0.0:
            Log.fatal("[%s]: at least one target label is negative", self.name)
        if np.sum(self.label) == 0.0:
            Log.fatal("[%s]: sum of labels is zero", self.name)

    def init(self, label, weight, query_boundaries=None):
        super().init(label, weight, query_boundaries)
        self.is_constant_hessian = False

    def get_gradients(self, score, label, weight):
        exp_s = jnp.exp(score)
        grad = ((exp_s - label) * weight).astype(jnp.float32)
        hess = (jnp.exp(score + self.max_delta_step) * weight).astype(jnp.float32)
        return grad, hess

    def boost_from_score(self) -> float:
        return float(np.log(super().boost_from_score()))

    def convert_output(self, raw: np.ndarray) -> np.ndarray:
        return np.exp(raw)

    def to_string(self) -> str:
        return self.name


class RegressionQuantile(RegressionL1):
    name = "quantile"

    def __init__(self, config):
        super().__init__(config)
        self.alpha = float(getattr(config, "alpha", 0.9))
        if not (0.0 < self.alpha < 1.0):
            Log.fatal("alpha should be in (0, 1) for quantile objective")

    def get_gradients(self, score, label, weight):
        label = self._trans_label(label)
        delta = score - label
        grad = (jnp.where(delta >= 0, 1.0 - self.alpha, -self.alpha)
                * weight).astype(jnp.float32)
        hess = weight.astype(jnp.float32)
        return grad, hess

    def boost_from_score(self) -> float:
        if self.weight is not None:
            return weighted_percentile(self.label, self.weight, self.alpha)
        return percentile(self.label, self.alpha)

    def _renew_alpha(self) -> float:
        return self.alpha


class RegressionMAPE(RegressionL1):
    """Gradient weight 1/max(1,|label|) folded into grad only; leaf renewal
    uses the same label weights (regression_objective.hpp:551-645)."""
    name = "mape"
    is_constant_hessian = True

    def init(self, label, weight, query_boundaries=None):
        super().init(label, weight, query_boundaries)
        if np.any(np.abs(self.label) < 1):
            Log.warning("Met 'abs(label) < 1', will convert them to '1' in MAPE objective and metric")
        lw = 1.0 / np.maximum(1.0, np.abs(self.label))
        self.label_weight = lw if self.weight is None else lw * self.weight
        self.is_constant_hessian = True

    def get_gradients(self, score, label, weight):
        label = self._trans_label(label)
        diff = score - label
        lw = 1.0 / jnp.maximum(1.0, jnp.abs(label))
        lw = lw * weight if self.weight is not None else lw
        grad = (jnp.sign(diff) * lw).astype(jnp.float32)
        hess = weight.astype(jnp.float32)
        return grad, hess

    def boost_from_score(self) -> float:
        return weighted_percentile(self.label, self.label_weight, 0.5)

    def _renew_weights(self):
        # always weighted (by label_weight), even without sample weights
        return self.label_weight


class RegressionGamma(RegressionPoisson):
    name = "gamma"

    def get_gradients(self, score, label, weight):
        exp_ns = jnp.exp(-score)
        grad = ((1.0 - label * exp_ns) * weight).astype(jnp.float32)
        hess = (label * exp_ns * weight).astype(jnp.float32)
        return grad, hess


class RegressionTweedie(RegressionPoisson):
    name = "tweedie"

    def __init__(self, config):
        super().__init__(config)
        self.rho = float(getattr(config, "tweedie_variance_power", 1.5))

    def get_gradients(self, score, label, weight):
        rho = self.rho
        e1 = jnp.exp((1.0 - rho) * score)
        e2 = jnp.exp((2.0 - rho) * score)
        grad = ((-label * e1 + e2) * weight).astype(jnp.float32)
        hess = ((-label * (1.0 - rho) * e1 + (2.0 - rho) * e2) * weight).astype(jnp.float32)
        return grad, hess
