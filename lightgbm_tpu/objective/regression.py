"""Regression objectives: L2 first; the full family lands with M2.

Role parity with the reference src/objective/regression_objective.hpp
(RegressionL2loss at :15-100, BoostFromScore at :142).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .base import ObjectiveFunction


class RegressionL2(ObjectiveFunction):
    name = "regression"
    is_constant_hessian = True  # when unweighted

    def __init__(self, config):
        super().__init__(config)
        self.sqrt = bool(getattr(config, "reg_sqrt", False))

    def init(self, label, weight, query_boundaries=None):
        super().init(label, weight, query_boundaries)
        if self.sqrt:
            self.label = np.sign(label) * np.sqrt(np.abs(label))
        self.is_constant_hessian = weight is None

    def get_gradients(self, score, label, weight):
        grad = ((score - label) * weight).astype(jnp.float32)
        hess = weight.astype(jnp.float32)
        return grad, hess

    def boost_from_score(self) -> float:
        if self.weight is not None:
            return float(np.sum(self.label * self.weight) / np.sum(self.weight))
        return float(np.mean(self.label))

    def convert_output(self, raw: np.ndarray) -> np.ndarray:
        if self.sqrt:
            return np.sign(raw) * raw * raw
        return raw

    def to_string(self) -> str:
        return "regression"
