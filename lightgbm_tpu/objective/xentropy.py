"""Cross-entropy objectives for continuous labels in [0, 1].

Role parity with the reference src/objective/xentropy_objective.hpp:
CrossEntropy ("xentropy", :38-135) — loss on p = sigmoid(f), optional linear
weights; CrossEntropyLambda ("xentlambda", :140-268) — alternative
parameterization p = 1 - exp(-w * log(1 + exp(f))), whose ConvertToOutput is
the positive "intensity" lambda = log1p(exp(f)), not a probability.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..utils.log import Log
from .base import ObjectiveFunction


def _check_unit_interval(label: np.ndarray, name: str) -> None:
    if np.any(label < 0.0) or np.any(label > 1.0):
        Log.fatal("[%s]: label must be in the interval [0, 1]", name)


class CrossEntropy(ObjectiveFunction):
    name = "xentropy"

    def check_label(self) -> None:
        _check_unit_interval(self.label, self.name)
        if self.weight is not None:
            if np.min(self.weight) < 0.0:
                Log.fatal("[%s]: at least one weight is negative", self.name)
            if np.sum(self.weight) == 0.0:
                Log.fatal("[%s]: sum of weights is zero", self.name)

    def get_gradients(self, score, label, weight):
        z = 1.0 / (1.0 + jnp.exp(-score))
        grad = ((z - label) * weight).astype(jnp.float32)
        hess = (z * (1.0 - z) * weight).astype(jnp.float32)
        return grad, hess

    def boost_from_score(self) -> float:
        if self.weight is not None:
            pavg = float(np.sum(self.label * self.weight) / np.sum(self.weight))
        else:
            pavg = float(np.mean(self.label))
        pavg = min(max(pavg, 1e-15), 1.0 - 1e-15)
        init = float(np.log(pavg / (1.0 - pavg)))
        Log.info("[%s:BoostFromScore]: pavg = %f -> initscore = %f",
                 self.name, pavg, init)
        return init

    def convert_output(self, raw: np.ndarray) -> np.ndarray:
        return 1.0 / (1.0 + np.exp(-raw))


class CrossEntropyLambda(ObjectiveFunction):
    name = "xentlambda"

    def check_label(self) -> None:
        _check_unit_interval(self.label, self.name)
        if self.weight is not None:
            if np.min(self.weight) <= 0.0:
                Log.fatal("[%s]: at least one weight is non-positive", self.name)
        self._has_weight = self.weight is not None

    def get_gradients(self, score, label, weight):
        if not self._has_weight:
            # unit weights: identical to CrossEntropy (xentropy_objective.hpp:185-193);
            # the weight vector is all-ones here except padded rows (0), which it zeroes
            z = 1.0 / (1.0 + jnp.exp(-score))
            return (((z - label) * weight).astype(jnp.float32),
                    (z * (1.0 - z) * weight).astype(jnp.float32))
        # padded rows carry w = 0, which drives z -> 0 and c -> 1 and turns the
        # closed form into 0/0; compute with w = 1 there and zero the result
        # (real rows have w > 0, checked in init)
        valid = weight > 0.0
        w = jnp.where(valid, weight, 1.0)
        y = label
        epf = jnp.exp(score)
        hhat = jnp.log1p(epf)
        z = 1.0 - jnp.exp(-w * hhat)
        enf = 1.0 / epf
        grad = (1.0 - y / z) * w / (1.0 + enf)
        c = 1.0 / (1.0 - z)
        d = 1.0 + epf
        a = w * epf / (d * d)
        d2 = c - 1.0
        b = (c / (d2 * d2)) * (1.0 + w * epf - c)
        hess = a * (1.0 + y * b)
        grad = jnp.where(valid, grad, 0.0)
        hess = jnp.where(valid, hess, 0.0)
        return grad.astype(jnp.float32), hess.astype(jnp.float32)

    def boost_from_score(self) -> float:
        if self.weight is not None:
            havg = float(np.sum(self.label * self.weight) / np.sum(self.weight))
        else:
            havg = float(np.mean(self.label))
        init = float(np.log(np.expm1(max(havg, 1e-15))))
        Log.info("[%s:BoostFromScore]: havg = %f -> initscore = %f",
                 self.name, havg, init)
        return init

    def convert_output(self, raw: np.ndarray) -> np.ndarray:
        # the "normalized exponential parameter" lambda > 0, NOT a probability
        return np.log1p(np.exp(raw))
