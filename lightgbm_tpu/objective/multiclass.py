"""Multiclass objectives: softmax (K trees/iteration) and one-vs-all.

Role parity with the reference src/objective/multiclass_objective.hpp
(MulticlassSoftmax :16-137, MulticlassOVA :139-225).  The K per-class
gradient planes are computed in one vectorized [K, N] device op instead of
the reference's per-row softmax loop.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.log import Log
from .base import ObjectiveFunction


class MulticlassSoftmax(ObjectiveFunction):
    name = "multiclass"

    def __init__(self, config):
        super().__init__(config)
        self.num_class = int(getattr(config, "num_class", 1))
        if self.num_class <= 1:
            Log.fatal("num_class must be > 1 for multiclass objective")

    @property
    def num_model_per_iteration(self) -> int:
        return self.num_class

    def check_label(self) -> None:
        li = self.label.astype(np.int64)
        if np.any(li < 0) or np.any(li >= self.num_class) or \
                np.any(li != self.label):
            Log.fatal("Label must be in [0, %d) for multiclass objective", self.num_class)

    def get_gradients_multi(self, score, label, weight):
        """score [K, N] -> (grad [K, N], hess [K, N]);
        hess = 2 p (1-p) like the reference (multiclass_objective.hpp:73)."""
        p = jax.nn.softmax(score, axis=0)
        onehot = (label[None, :].astype(jnp.int32) ==
                  jnp.arange(self.num_class, dtype=jnp.int32)[:, None])
        grad = ((p - onehot.astype(p.dtype)) * weight[None, :]).astype(jnp.float32)
        hess = (2.0 * p * (1.0 - p) * weight[None, :]).astype(jnp.float32)
        return grad, hess

    def convert_output(self, raw: np.ndarray) -> np.ndarray:
        """Row-wise softmax; raw is [N, K] (or [K] for one row)."""
        raw = np.asarray(raw, dtype=np.float64)
        m = raw - np.max(raw, axis=-1, keepdims=True)
        e = np.exp(m)
        return e / np.sum(e, axis=-1, keepdims=True)

    def to_string(self) -> str:
        return "multiclass num_class:%d" % self.num_class


class MulticlassOVA(ObjectiveFunction):
    """One-vs-all: K independent sigmoid binary objectives
    (multiclass_objective.hpp:139-225; per-class BinaryLogloss with an
    indicator label)."""
    name = "multiclassova"

    def __init__(self, config):
        super().__init__(config)
        self.num_class = int(getattr(config, "num_class", 1))
        self.sigmoid = float(getattr(config, "sigmoid", 1.0))
        self.is_unbalance = bool(getattr(config, "is_unbalance", False))
        self.scale_pos_weight = float(getattr(config, "scale_pos_weight", 1.0))
        if self.num_class <= 1:
            Log.fatal("num_class must be > 1 for multiclassova objective")
        if self.sigmoid <= 0.0:
            Log.fatal("Sigmoid parameter %f should be greater than zero", self.sigmoid)
        # per-class (neg_weight, pos_weight), filled by check_label
        self.label_weights = np.ones((self.num_class, 2), dtype=np.float64)

    @property
    def num_model_per_iteration(self) -> int:
        return self.num_class

    def check_label(self) -> None:
        li = self.label.astype(np.int64)
        if np.any(li < 0) or np.any(li >= self.num_class) or np.any(li != self.label):
            Log.fatal("Label must be in [0, %d) for multiclassova objective", self.num_class)
        # per-class pos/neg weighting, as the reference gets by composing one
        # BinaryLogloss per class with an indicator label
        # (multiclass_objective.hpp:145, binary_objective.hpp CheckLabel)
        for k in range(self.num_class):
            cnt_pos = float(np.sum(li == k))
            cnt_neg = float(len(li) - cnt_pos)
            if self.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
                if cnt_pos > cnt_neg:
                    self.label_weights[k] = (cnt_pos / cnt_neg, 1.0)
                else:
                    self.label_weights[k] = (1.0, cnt_neg / cnt_pos)
            elif self.scale_pos_weight != 1.0:
                self.label_weights[k] = (1.0, self.scale_pos_weight)

    def get_gradients_multi(self, score, label, weight):
        # y_k in {-1, +1} per class plane; binary logloss math per plane
        # (binary_objective.hpp GetGradients with indicator labels)
        onehot = (label[None, :].astype(jnp.int32) ==
                  jnp.arange(self.num_class, dtype=jnp.int32)[:, None])
        lw = jnp.asarray(self.label_weights, jnp.float32)
        w = weight[None, :] * jnp.where(onehot, lw[:, 1:2], lw[:, 0:1])
        y = jnp.where(onehot, 1.0, -1.0)
        response = -y * self.sigmoid / (1.0 + jnp.exp(y * self.sigmoid * score))
        abs_r = jnp.abs(response)
        grad = (response * w).astype(jnp.float32)
        hess = (abs_r * (self.sigmoid - abs_r) * w).astype(jnp.float32)
        return grad, hess

    def convert_output(self, raw: np.ndarray) -> np.ndarray:
        return 1.0 / (1.0 + np.exp(-self.sigmoid * np.asarray(raw, dtype=np.float64)))

    def to_string(self) -> str:
        return "multiclassova num_class:%d sigmoid:%g" % (self.num_class, self.sigmoid)
