"""Objective function interface.

Role parity with the reference include/LightGBM/objective_function.h and the
factory src/objective/objective_function.cpp:10-47.  Gradients/hessians are
computed on-device by a jitted function of the raw score; host-side helpers
provide init-score boosting and output transforms.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..utils.log import Log


class ObjectiveFunction:
    name = "custom"
    is_constant_hessian = False

    def __init__(self, config):
        self.config = config
        self.num_class = getattr(config, "num_class", 1)
        self.label: Optional[np.ndarray] = None
        self.weight: Optional[np.ndarray] = None
        self.num_data = 0

    @property
    def num_model_per_iteration(self) -> int:
        return 1

    def init(self, label: np.ndarray, weight: Optional[np.ndarray],
             query_boundaries: Optional[np.ndarray] = None) -> None:
        self.label = np.asarray(label, dtype=np.float64)
        self.weight = None if weight is None else np.asarray(weight, dtype=np.float64)
        self.num_data = len(self.label)
        self.check_label()

    def check_label(self) -> None:
        pass

    def get_gradients(self, score, label, weight):
        """Device computation: (grad, hess) from raw scores. score/label/weight
        are padded jnp arrays; weight is all-ones when unweighted."""
        raise NotImplementedError

    def boost_from_score(self) -> float:
        """Initial raw score (BoostFromScore in the reference objectives)."""
        return 0.0

    def convert_output(self, raw: np.ndarray) -> np.ndarray:
        return raw

    def renew_tree_output_required(self) -> bool:
        return False

    def renew_tree_output(self, leaf_value, leaf_index_per_row, score, label, weight,
                          leaf_count) -> np.ndarray:
        return leaf_value

    def to_string(self) -> str:
        return self.name
