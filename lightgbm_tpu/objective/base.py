"""Objective function interface.

Role parity with the reference include/LightGBM/objective_function.h and the
factory src/objective/objective_function.cpp:10-47.  Gradients/hessians are
computed on-device by a jitted function of the raw score; host-side helpers
provide init-score boosting and output transforms.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..utils.log import Log


class ObjectiveFunction:
    name = "custom"
    is_constant_hessian = False
    # gradients depend only on each row's own (score, label, weight) — lets
    # the trainer compute them in any row order (partitioned fast path);
    # query-grouped objectives (ranking) set this False
    is_rowwise = True

    def __init__(self, config):
        self.config = config
        self.num_class = getattr(config, "num_class", 1)
        self.label: Optional[np.ndarray] = None
        self.weight: Optional[np.ndarray] = None
        self.num_data = 0

    @property
    def num_model_per_iteration(self) -> int:
        return 1

    def init(self, label: np.ndarray, weight: Optional[np.ndarray],
             query_boundaries: Optional[np.ndarray] = None) -> None:
        self.label = np.asarray(label, dtype=np.float64)
        self.weight = None if weight is None else np.asarray(weight, dtype=np.float64)
        self.num_data = len(self.label)
        self.check_label()

    def check_label(self) -> None:
        pass

    def get_gradients(self, score, label, weight):
        """Device computation: (grad, hess) from raw scores. score/label/weight
        are padded jnp arrays; weight is all-ones when unweighted."""
        raise NotImplementedError

    def get_gradients_multi(self, score, label, weight):
        """Device computation over the full [K, N] score matrix.  Single-model
        objectives wrap get_gradients on the one score plane; multiclass
        objectives override with a vectorized softmax/OVA computation."""
        grad, hess = self.get_gradients(score[0], label, weight)
        return grad[None, :], hess[None, :]

    def boost_from_score(self) -> float:
        """Initial raw score (BoostFromScore in the reference objectives)."""
        return 0.0

    def convert_output(self, raw: np.ndarray) -> np.ndarray:
        return raw

    def renew_tree_output_required(self) -> bool:
        """IsRenewTreeOutput (objective_function.h): objectives that replace
        leaf outputs with a robust statistic after the tree is grown."""
        return False

    def renew_leaf_values(self, leaf_values: np.ndarray, leaf_ids: np.ndarray,
                          pred: np.ndarray, in_bag: np.ndarray) -> np.ndarray:
        """RenewTreeOutput: leaf_values [L] (unshrunk), leaf_ids [N_pad] row →
        leaf assignment, pred [N_pad] raw scores before this tree, in_bag [N_pad]
        bagging mask.  Returns renewed leaf values."""
        return leaf_values

    def to_string(self) -> str:
        return self.name
