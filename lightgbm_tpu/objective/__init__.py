"""Objective factory — reference src/objective/objective_function.cpp:10-47."""
from __future__ import annotations

from ..utils.log import Log
from .base import ObjectiveFunction
from .binary import BinaryLogloss
from .multiclass import MulticlassOVA, MulticlassSoftmax
from .rank import LambdarankNDCG
from .xentropy import CrossEntropy, CrossEntropyLambda
from .regression import (RegressionFair, RegressionGamma, RegressionHuber,
                         RegressionL1, RegressionL2, RegressionMAPE,
                         RegressionPoisson, RegressionQuantile,
                         RegressionTweedie)

_REGISTRY = {
    "regression": RegressionL2,
    "regression_l1": RegressionL1,
    "huber": RegressionHuber,
    "fair": RegressionFair,
    "poisson": RegressionPoisson,
    "quantile": RegressionQuantile,
    "mape": RegressionMAPE,
    "gamma": RegressionGamma,
    "tweedie": RegressionTweedie,
    "binary": BinaryLogloss,
    "multiclass": MulticlassSoftmax,
    "multiclassova": MulticlassOVA,
    "lambdarank": LambdarankNDCG,
    "xentropy": CrossEntropy,
    "xentlambda": CrossEntropyLambda,
}


def create_objective(name: str, config) -> ObjectiveFunction:
    if name in _REGISTRY:
        return _REGISTRY[name](config)
    if name == "none":
        return None
    Log.fatal("Unknown objective type name: %s", name)


def create_objective_from_model_string(objective_str: str, config):
    """Parse 'binary sigmoid:1'-style objective strings from model files."""
    parts = objective_str.split()
    name = parts[0] if parts else "regression"
    for tok in parts[1:]:
        if ":" in tok:
            k, v = tok.split(":", 1)
            try:
                setattr(config, k, int(v))
            except ValueError:
                try:
                    setattr(config, k, float(v))
                except ValueError:
                    setattr(config, k, v)
        elif tok == "sqrt":
            config.reg_sqrt = True
    return create_objective(name, config)
