"""Binary log-loss objective.

Role parity with the reference src/objective/binary_objective.hpp (sigmoid
parameter, label weighting via is_unbalance / scale_pos_weight, BoostFromScore
at :119-140).  Gradient math on device in f32.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..utils.log import Log
from .base import ObjectiveFunction


class BinaryLogloss(ObjectiveFunction):
    name = "binary"

    def __init__(self, config):
        super().__init__(config)
        self.sigmoid = float(getattr(config, "sigmoid", 1.0))
        self.is_unbalance = bool(getattr(config, "is_unbalance", False))
        self.scale_pos_weight = float(getattr(config, "scale_pos_weight", 1.0))
        if self.sigmoid <= 0.0:
            Log.fatal("Sigmoid parameter %f should be greater than zero", self.sigmoid)
        self.label_weights = (1.0, 1.0)

    def check_label(self) -> None:
        unique = np.unique(self.label)
        if not np.all(np.isin(unique, (0.0, 1.0))):
            Log.fatal("Binary objective requires labels in {0, 1}")
        cnt_pos = float(np.sum(self.label == 1))
        cnt_neg = float(np.sum(self.label == 0))
        if cnt_neg == 0 or cnt_pos == 0:
            Log.warning("Contains only one class")
        if self.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            if cnt_pos > cnt_neg:
                self.label_weights = (1.0, cnt_pos / cnt_neg)
            else:
                self.label_weights = (cnt_neg / cnt_pos, 1.0)
        elif self.scale_pos_weight != 1.0:
            self.label_weights = (1.0, self.scale_pos_weight)

    def get_gradients(self, score, label, weight):
        # y in {-1, +1}; response = -y*sig / (1 + exp(y*sig*score))
        y = jnp.where(label > 0, 1.0, -1.0)
        w_label = jnp.where(label > 0, self.label_weights[1], self.label_weights[0])
        w = weight * w_label
        response = -y * self.sigmoid / (1.0 + jnp.exp(y * self.sigmoid * score))
        abs_r = jnp.abs(response)
        grad = (response * w).astype(jnp.float32)
        hess = (abs_r * (self.sigmoid - abs_r) * w).astype(jnp.float32)
        return grad, hess

    def boost_from_score(self) -> float:
        if self.weight is not None:
            suml = float(np.sum(self.label * self.weight))
            sumw = float(np.sum(self.weight))
        else:
            suml = float(np.sum(self.label))
            sumw = float(self.num_data)
        pavg = min(max(suml / max(sumw, 1e-300), 1e-15), 1.0 - 1e-15)
        init = float(np.log(pavg / (1.0 - pavg)) / self.sigmoid)
        Log.info("[%s:BoostFromScore]: pavg=%.6f -> initscore=%.6f", self.name, pavg, init)
        return init

    def convert_output(self, raw: np.ndarray) -> np.ndarray:
        return 1.0 / (1.0 + np.exp(-self.sigmoid * raw))

    def to_string(self) -> str:
        return "binary sigmoid:%g" % self.sigmoid
