"""LambdaRank (NDCG) objective.

Role parity with the reference src/objective/rank_objective.hpp
(LambdarankNDCG: Init at :43-71, GetGradientsForOneQuery at :82-168,
sigmoid table at :172-197) and src/metric/dcg_calculator.cpp (label gains,
position discounts, CalMaxDCGAtK at :52-74).

TPU-first redesign: the reference runs a per-query O(n^2) pairwise loop under
OpenMP with a precomputed sigmoid lookup table.  Here queries are padded into
a dense [Q, S] layout (S = longest query) and the pairwise lambda computation
is one vectorized [q_chunk, S, S] tensor program per query chunk, scanned with
`lax.map` to bound the transient memory.  The sigmoid table becomes the exact
expression (transcendentals are cheap on the VPU; the table is a CPU trick).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..utils.log import Log
from .base import ObjectiveFunction

# reference dcg_calculator.cpp:30-38 — label_gain[i] = 2^i - 1, 31 levels
_MAX_LABEL = 31


def default_label_gain() -> np.ndarray:
    return np.array([(1 << i) - 1 for i in range(_MAX_LABEL)], dtype=np.float64)


def position_discounts(n: int) -> np.ndarray:
    """discount[i] = 1/log2(2+i) (dcg_calculator.cpp:44-48)."""
    return 1.0 / np.log2(2.0 + np.arange(n, dtype=np.float64))


def max_dcg_at_k(k: int, labels: np.ndarray, label_gain: np.ndarray) -> float:
    """Ideal DCG@k: labels sorted descending (CalMaxDCGAtK)."""
    k = min(k, len(labels))
    top = np.sort(labels.astype(np.int64))[::-1][:k]
    disc = position_discounts(k)
    return float(np.sum(label_gain[top] * disc))


def check_rank_label(label: np.ndarray, num_levels: int) -> None:
    """DCGCalculator::CheckLabel semantics."""
    if np.any(np.abs(label - np.round(label)) > 1e-15):
        Log.fatal("label should be int type for ranking task")
    if np.any(label < 0) or np.any(label >= num_levels):
        Log.fatal("label exceeds the max range of label_gain")


class LambdarankNDCG(ObjectiveFunction):
    is_rowwise = False  # pairwise within query groups
    name = "lambdarank"
    is_constant_hessian = False

    def __init__(self, config):
        super().__init__(config)
        self.sigmoid = float(getattr(config, "sigmoid", 1.0))
        if self.sigmoid <= 0.0:
            Log.fatal("Sigmoid param %f should be greater than zero", self.sigmoid)
        gains = list(getattr(config, "label_gain", ()) or ())
        self.label_gain = np.asarray(gains, np.float64) if gains else default_label_gain()
        self.optimize_pos_at = int(getattr(config, "max_position", 20))

    def init(self, label, weight, query_boundaries=None) -> None:
        super().init(label, weight, query_boundaries)
        if query_boundaries is None:
            Log.fatal("Lambdarank tasks require query information")
        qb = np.asarray(query_boundaries, dtype=np.int64)
        check_rank_label(self.label, len(self.label_gain))
        Q = len(qb) - 1
        sizes = np.diff(qb)
        S = int(sizes.max())

        # padded [Q, S] layout; padding slots index row 0 but carry mask 0
        doc_idx = np.zeros((Q, S), dtype=np.int32)
        mask = np.zeros((Q, S), dtype=np.float32)
        label_mat = np.zeros((Q, S), dtype=np.float32)
        inv_max_dcg = np.zeros(Q, dtype=np.float32)
        for qi in range(Q):
            lo, hi = int(qb[qi]), int(qb[qi + 1])
            cnt = hi - lo
            doc_idx[qi, :cnt] = np.arange(lo, hi)
            mask[qi, :cnt] = 1.0
            label_mat[qi, :cnt] = self.label[lo:hi]
            mdcg = max_dcg_at_k(self.optimize_pos_at, self.label[lo:hi], self.label_gain)
            inv_max_dcg[qi] = 1.0 / mdcg if mdcg > 0.0 else 0.0

        # chunk so a [q_chunk, S, S] f32 transient stays ~64 MB; pad Q up to a
        # chunk multiple with zero-mask dummy queries rather than shrinking the
        # chunk (a prime Q would otherwise serialize the lax.map)
        q_chunk = min(max(1, (1 << 24) // max(S * S, 1)), Q)
        q_pad = -Q % q_chunk
        if q_pad:
            doc_idx = np.concatenate([doc_idx, np.zeros((q_pad, S), np.int32)])
            mask = np.concatenate([mask, np.zeros((q_pad, S), np.float32)])
            label_mat = np.concatenate([label_mat, np.zeros((q_pad, S), np.float32)])
            inv_max_dcg = np.concatenate([inv_max_dcg, np.zeros(q_pad, np.float32)])
        self._q_chunk = q_chunk
        self.doc_idx = jnp.asarray(doc_idx)
        self.qmask = jnp.asarray(mask)
        self.label_mat = jnp.asarray(label_mat)
        self.inv_max_dcg = jnp.asarray(inv_max_dcg)
        self.gain_of_label = jnp.asarray(self.label_gain, jnp.float32)
        self.discounts = jnp.asarray(position_discounts(S), jnp.float32)

    def get_gradients(self, score, label, weight):
        Q, S = self.doc_idx.shape
        sigma = self.sigmoid
        disc_tab = self.discounts
        gain_tab = self.gain_of_label

        def one_chunk(args):
            s, lbl, msk, imd = args  # [Qc,S], [Qc,S], [Qc,S], [Qc]
            neg_inf = jnp.float32(-1e30)
            s_m = jnp.where(msk > 0, s, neg_inf)
            # rank of every slot in its query's descending-score order
            order = jnp.argsort(-s_m, axis=1)
            ranks = jnp.argsort(order, axis=1)  # [Qc, S] position of each slot
            disc = disc_tab[ranks] * (msk > 0)
            gain = gain_tab[lbl.astype(jnp.int32)]
            best = jnp.max(s_m, axis=1, keepdims=True)
            worst = jnp.min(jnp.where(msk > 0, s, -neg_inf), axis=1, keepdims=True)
            has_range = (best != worst)[:, :, None]

            ds = s[:, :, None] - s[:, None, :]            # delta_score (i=high, j=low)
            valid = (msk[:, :, None] > 0) & (msk[:, None, :] > 0) & \
                    (lbl[:, :, None] > lbl[:, None, :])
            dcg_gap = gain[:, :, None] - gain[:, None, :]
            paired_disc = jnp.abs(disc[:, :, None] - disc[:, None, :])
            delta_ndcg = dcg_gap * paired_disc * imd[:, None, None]
            delta_ndcg = jnp.where(has_range,
                                   delta_ndcg / (0.01 + jnp.abs(ds)), delta_ndcg)
            sig = 2.0 / (1.0 + jnp.exp(2.0 * sigma * ds))
            p_lambda = jnp.where(valid, -delta_ndcg * sig, 0.0)
            p_hess = jnp.where(valid, 2.0 * delta_ndcg * sig * (2.0 - sig), 0.0)
            # pair (i=high, j=low): lambda_i += p, lambda_j -= p; hess both += h
            g = jnp.sum(p_lambda, axis=2) - jnp.sum(p_lambda, axis=1)
            h = jnp.sum(p_hess, axis=2) + jnp.sum(p_hess, axis=1)
            return g, h

        nchunk = Q // self._q_chunk
        s_all = score[self.doc_idx]
        args = (s_all.reshape(nchunk, self._q_chunk, S),
                self.label_mat.reshape(nchunk, self._q_chunk, S),
                self.qmask.reshape(nchunk, self._q_chunk, S),
                self.inv_max_dcg.reshape(nchunk, self._q_chunk))
        g, h = lax.map(one_chunk, args)
        g = (g.reshape(Q, S) * self.qmask).reshape(-1)
        h = (h.reshape(Q, S) * self.qmask).reshape(-1)
        flat_idx = self.doc_idx.reshape(-1)
        grad = jnp.zeros_like(score).at[flat_idx].add(g)
        hess = jnp.zeros_like(score).at[flat_idx].add(h)
        # per-doc weights multiply at the end (rank_objective.hpp:162-167)
        grad = grad * weight
        hess = hess * weight
        return grad.astype(jnp.float32), hess.astype(jnp.float32)

    def to_string(self) -> str:
        return self.name
