"""Atomic model publish/subscribe seam (the serving layer's contract).

The continuous trainer (runtime/continuous.py) must hand freshly trained
models to consumers — the future serving service (ROADMAP item 3), a
`NativeBooster`, a plain file watcher — such that a consumer can NEVER
observe a torn, partial, or checksum-invalid model, no matter when the
publisher process dies.  The seam is a directory of immutable generation
files plus a manifest pointer:

* ``gen_<N>.txt`` — the FULL model text (a loadable model file) followed
  by two footer lines past ``end of trees``: ``!publish_meta=`` (b64 of
  zlib of JSON: generation, wallclock, training provenance) and
  ``!publish_checksum=sha256:`` over everything above it.  Written
  atomically (tmp + fsync + rename) — a generation file either does not
  exist or is complete and self-validating.
* ``MANIFEST.json`` — atomic pointer to the newest generation with its
  full-file sha256.  The manifest is a CACHE: subscribers that find it
  stale, torn, or missing fall back to a directory scan, so the
  publisher dying between the generation rename and the manifest write
  (`LGBM_TPU_FAULT=die_at_publish`) costs freshness, never correctness.

Retention is keep-last-K **plus a grace window**: a generation beyond the
K newest is only unlinked once it is also older than `grace_s`, so a
subscriber that just resolved a path cannot have the file deleted out
from under it between resolve and read (`ModelSubscriber.resolve`
additionally reads-then-validates in one pass, so even a lost race
surfaces as "skip and fall back", never as a corrupt observation).

No jax / numpy at module scope — subscribers (serving hosts, test
pollers) must be able to use this without binding a platform.
"""
from __future__ import annotations

import base64
import contextlib
import hashlib
import json
import os
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

from . import resilience, tracing

__all__ = ["ModelPublisher", "ModelSubscriber", "PublishedModel",
           "NoValidGeneration", "generation_paths", "validate_generation",
           "read_rollback_marker", "mark_rollback", "rejection_paths",
           "WARMUP_MANIFEST"]

_META_PREFIX = "!publish_meta="
_CHECKSUM_PREFIX = "!publish_checksum=sha256:"
_GEN_PREFIX = "gen_"
_GEN_SUFFIX = ".txt"
_REJECT_PREFIX = "rejected_"
MANIFEST = "MANIFEST.json"
#: durable quality-rollback marker (ISSUE 12 stage three).  A single
#: atomic JSON file in the publish dir naming the generations the canary
#: condemned — it is not a gen_ file, so pruning never touches it, a
#: relaunched subscriber reads it before its first resolve, and
#: concurrent readers all see one consistent bad-set.
ROLLBACK_MARKER = "ROLLBACK.json"
#: checksummed shape manifest published alongside the generations
#: (ISSUE 15, runtime/warmup.py): what shape buckets and jit sites this
#: lineage's producers/consumers actually compiled.  Like the rollback
#: marker it is its own atomic non-generation file — pruning never
#: touches it and concurrent readers can never observe it torn.
WARMUP_MANIFEST = "warmup.json"


class NoValidGeneration(RuntimeError):
    """No valid published generation could be resolved (after retries)."""


def _gen_name(generation: int) -> str:
    return "%s%08d%s" % (_GEN_PREFIX, generation, _GEN_SUFFIX)


def generation_paths(pub_dir: str) -> List[Tuple[int, str]]:
    """Existing generation files, newest first (by generation number —
    publication order, not mtime, which a relaunch's republish rewrites)."""
    out: List[Tuple[int, str]] = []
    try:
        names = os.listdir(pub_dir)
    except OSError:
        return []
    for name in names:
        if name.startswith(_GEN_PREFIX) and name.endswith(_GEN_SUFFIX):
            digits = name[len(_GEN_PREFIX):-len(_GEN_SUFFIX)]
            if digits.isdigit():
                out.append((int(digits), os.path.join(pub_dir, name)))
    out.sort(reverse=True)
    return out


def read_rollback_marker(pub_dir: str) -> Dict[str, Any]:
    """The durable rollback record: ``{"bad_generations": [...],
    "pinned": [...], "events": [...]}`` (empty dict when no rollback has
    ever happened).  A torn/unreadable marker reads as empty — the
    marker is written atomically, so that only happens when it does not
    exist."""
    try:
        with open(os.path.join(pub_dir, ROLLBACK_MARKER)) as fh:
            rec = json.load(fh)
        if isinstance(rec, dict):
            return rec
    except (OSError, ValueError):
        pass
    return {}


def mark_rollback(pub_dir: str, bad_generation: int,
                  pinned_generation: Optional[int] = None,
                  reason: str = "", evidence: Optional[Dict] = None
                  ) -> Dict[str, Any]:
    """Condemn `bad_generation` fleet-wide: merge it into the publish
    dir's ROLLBACK marker (read-merge-atomic-write, so concurrent
    replicas condemning independently both land) and record the
    generation the fleet is rolled back to.  Every subscriber —
    including ones launched AFTER this call — skips condemned
    generations during resolution; the marker survives pruning and
    relaunch because it is its own atomic non-generation file."""
    rec = read_rollback_marker(pub_dir)
    bad = set(int(g) for g in rec.get("bad_generations", []))
    bad.add(int(bad_generation))
    pinned = set(int(g) for g in rec.get("pinned", []))
    if pinned_generation is not None:
        pinned.add(int(pinned_generation))
    events = list(rec.get("events", []))
    events.append({"bad_generation": int(bad_generation),
                   "pinned_generation": pinned_generation,
                   "reason": reason, "evidence": evidence,
                   "wallclock": resilience.wallclock()})
    out = {"bad_generations": sorted(bad), "pinned": sorted(pinned),
           "events": events[-64:]}
    resilience.atomic_write(os.path.join(pub_dir, ROLLBACK_MARKER),
                            json.dumps(out, indent=1))
    return out


def rejection_paths(pub_dir: str) -> List[Tuple[int, str]]:
    """Persisted gate rejections (``rejected_<N>.txt``), newest first."""
    out: List[Tuple[int, str]] = []
    try:
        names = os.listdir(pub_dir)
    except OSError:
        return []
    for name in names:
        if name.startswith(_REJECT_PREFIX) and name.endswith(_GEN_SUFFIX):
            digits = name[len(_REJECT_PREFIX):-len(_GEN_SUFFIX)]
            if digits.isdigit():
                out.append((int(digits), os.path.join(pub_dir, name)))
    out.sort(reverse=True)
    return out


def _with_publish_footer(model_text: str, meta: Dict[str, Any]) -> str:
    body = model_text
    if not body.endswith("\n"):
        body += "\n"
    blob = base64.b64encode(zlib.compress(json.dumps(meta).encode())).decode()
    body += _META_PREFIX + blob + "\n"
    digest = hashlib.sha256(body.encode()).hexdigest()
    return body + _CHECKSUM_PREFIX + digest + "\n"


def _split_validate(text: str) -> Optional[Tuple[str, Dict[str, Any]]]:
    """(model_text, meta) from a generation file's full text, or None if
    the file is torn/corrupt/not-a-publication.  Validation runs on the
    bytes ALREADY READ — there is no second open, so a file pruned or
    rewritten mid-observation can only ever look invalid, never torn."""
    if not text.endswith("\n"):
        return None                      # a complete publish ends in \n
    lines = text.rstrip("\n").split("\n")
    if len(lines) < 2 or not lines[-1].startswith(_CHECKSUM_PREFIX):
        return None
    digest = lines[-1][len(_CHECKSUM_PREFIX):].strip()
    body = text[: text.rfind(_CHECKSUM_PREFIX)]
    if hashlib.sha256(body.encode()).hexdigest() != digest:
        return None
    if not lines[-2].startswith(_META_PREFIX):
        return None
    try:
        meta = json.loads(zlib.decompress(
            base64.b64decode(lines[-2][len(_META_PREFIX):])).decode())
    except (ValueError, zlib.error):
        return None
    model_text = text[: text.rfind(_META_PREFIX)]
    return model_text, meta


def validate_generation(path: str) -> Tuple[bool, str]:
    """(ok, reason) for a generation file on disk."""
    try:
        with open(path, "rb") as fh:
            text = fh.read().decode("utf-8", "replace")
    except OSError as e:
        return False, "unreadable: %s" % e
    if _split_validate(text) is None:
        return False, "torn or checksum-invalid"
    return True, "ok"


class PublishedModel:
    """One resolved generation: the validated bytes travel WITH the
    resolution (no re-open between validate and use)."""

    __slots__ = ("generation", "path", "model_text", "meta")

    def __init__(self, generation: int, path: str, model_text: str,
                 meta: Dict[str, Any]):
        self.generation = generation
        self.path = path
        self.model_text = model_text
        self.meta = meta


class ModelPublisher:
    """Single-writer publication endpoint for one model lineage.

    ``publish(model_text, meta)`` assigns the next generation number
    (resuming past whatever a dead predecessor left on disk), writes the
    generation file atomically, updates the manifest, and prunes old
    generations under the keep-last-K + grace-window rule.
    """

    def __init__(self, pub_dir: str, keep_last: int = 8,
                 grace_s: float = 30.0):
        self.pub_dir = pub_dir
        self.keep_last = int(keep_last)
        self.grace_s = float(grace_s)
        os.makedirs(pub_dir, exist_ok=True)
        self._publish_count = 0          # this process, 1-based after ++

    # -- state on disk -------------------------------------------------------
    def latest_valid(self) -> Optional[PublishedModel]:
        """Newest VALID generation by directory scan (the truth a
        relaunch reconciles against; the manifest may be stale)."""
        for gen, path in generation_paths(self.pub_dir):
            try:
                with open(path, "rb") as fh:
                    text = fh.read().decode("utf-8", "replace")
            except OSError:
                continue
            split = _split_validate(text)
            if split is not None:
                return PublishedModel(gen, path, split[0], split[1])
        return None

    def next_generation(self) -> int:
        gens = generation_paths(self.pub_dir)
        return (gens[0][0] + 1) if gens else 1

    # -- publication ---------------------------------------------------------
    def publish(self, model_text: str, meta: Optional[Dict[str, Any]] = None,
                generation: Optional[int] = None) -> PublishedModel:
        """Atomically publish one generation; returns the record.

        `generation` overrides the auto-assigned number — the continuous
        trainer uses this to REPUBLISH a cycle whose original publish was
        torn or never landed (overwriting a torn file of the same number
        is safe: the replacement rename is atomic and carries the same
        bytes an uninterrupted run would have published).
        """
        gen = int(generation) if generation is not None \
            else self.next_generation()
        full_meta = dict(meta or {})
        full_meta.setdefault("generation", gen)
        full_meta.setdefault("published_at", resilience.wallclock())
        # machine-usable publish stamp (ISSUE 11): subscribers measure
        # model staleness against this without parsing the wallclock
        full_meta.setdefault("published_unix", round(time.time(), 3))
        body = _with_publish_footer(model_text, full_meta)
        path = os.path.join(self.pub_dir, _gen_name(gen))
        self._publish_count += 1
        # fault seam: a torn non-atomic write + abrupt death (the write
        # discipline this publisher exists to make impossible) …
        resilience.maybe_torn_publish(path, body, self._publish_count)
        resilience.atomic_write(path, body)
        # … and an abrupt death in the rename→manifest window
        resilience.maybe_die_at_publish(self._publish_count)
        self._write_manifest(gen, path, body)
        self._prune()
        # source end of the publish→subscriber flow arrow (ISSUE 14):
        # the flow id is derived from what BOTH ends read out of the
        # meta footer, so a subscriber in another process computes the
        # same id at swap-in and the merged timeline draws the link
        tracing.flow_start(
            "publish gen=%d" % gen,
            tracing.flow_id(full_meta.get("trace") or "no-trace", gen),
            generation=gen, trace=full_meta.get("trace"))
        return PublishedModel(gen, path, model_text, full_meta)

    def _write_manifest(self, gen: int, path: str, body: str) -> None:
        manifest = {
            "latest": gen,
            "file": os.path.basename(path),
            "sha256": hashlib.sha256(body.encode()).hexdigest(),
            "published_at": resilience.wallclock(),
            "keep_last": self.keep_last,
            "grace_s": self.grace_s,
        }
        resilience.atomic_write(os.path.join(self.pub_dir, MANIFEST),
                                json.dumps(manifest, indent=1))

    def publish_manifest(self, kind: str, section: Dict[str, Any]) -> str:
        """Publish one role's warm-start shape manifest alongside the
        generations (ISSUE 15): `runtime/warmup.py` merges the section
        into the dir's checksummed ``warmup.json`` atomically, so a
        fresh consumer can precompile the lineage's real shapes before
        admitting traffic.  Returns the manifest path."""
        from . import warmup
        return warmup.write_manifest(self.pub_dir, kind, section)

    def record_rejection(self, model_text: str, gate: Dict[str, Any],
                         cycle: int) -> str:
        """Persist a gate-REJECTED candidate for the audit trail (ISSUE
        12 stage two): ``rejected_<cycle>.txt`` carries the full rejected
        model text with the same checksummed footer a publication gets —
        the meta holds the gate record (candidate metrics, incumbent
        metrics, tolerance, verdict) — but the ``rejected_`` name keeps
        it invisible to every subscriber.  Returns the path."""
        meta = {"rejected": True, "cycle": int(cycle), "gate": gate,
                "rejected_at": resilience.wallclock()}
        body = _with_publish_footer(model_text, meta)
        path = os.path.join(self.pub_dir,
                            "%s%08d%s" % (_REJECT_PREFIX, cycle,
                                          _GEN_SUFFIX))
        resilience.atomic_write(path, body)
        return path

    def _prune(self) -> None:
        """keep-last-K AND older-than-grace: both conditions must hold
        before a generation is unlinked (satellite pin: a subscriber that
        just resolved a path must get to read it).  A generation the
        rollback marker names as a PIN TARGET is never pruned — after a
        quality rollback the whole fleet is serving it, however old it
        is."""
        if self.keep_last <= 0:
            return
        cutoff = time.time() - max(self.grace_s, 0.0)
        protected = set(read_rollback_marker(self.pub_dir).get("pinned", []))
        for gen, old in generation_paths(self.pub_dir)[self.keep_last:]:
            if gen in protected:
                continue
            with contextlib.suppress(OSError):
                if self.grace_s <= 0 or os.path.getmtime(old) < cutoff:
                    os.unlink(old)


class ModelSubscriber:
    """Read-side resolution of the newest valid generation.

    ``resolve()`` returns a `PublishedModel` whose bytes were validated
    in the same pass that read them; torn/corrupt/vanished generations
    are skipped (and counted in ``skipped_invalid`` — the chaos soak's
    corruption ledger is exactly this counter staying at the number of
    faults injected, with ``corrupt_observed`` at zero).  When NOTHING
    valid exists yet (subscriber raced the very first publish), it
    retries with bounded jittered backoff before raising
    `NoValidGeneration`.
    """

    def __init__(self, pub_dir: str, attempts: int = 4,
                 backoff_base: float = 0.05, backoff_cap: float = 0.5,
                 seed: int = 0):
        self.pub_dir = pub_dir
        self.attempts = max(int(attempts), 1)
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        self.seed = seed
        self.skipped_invalid = 0         # torn/corrupt files stepped past
        self.skipped_rolled_back = 0     # marker-condemned gens stepped past
        self.resolved_count = 0
        self._pin: Optional[Tuple[int, Optional[int]]] = None

    # -- quality rollback (ISSUE 12 stage three) ----------------------------
    def pin_generation(self, generation: int,
                       release_above: Optional[int] = None) -> None:
        """Roll this subscriber back: resolve `generation` (and only it)
        until either the pin is released (`unpin`) or — when
        `release_above` is given — a generation NEWER than
        `release_above` lands, i.e. the trainer has published a fresh
        candidate that deserves its own canary window.  The pin is
        runtime-local and immediate; the durable fleet-wide counterpart
        is the publish dir's ROLLBACK marker (`mark_rollback`), which
        every resolve consults."""
        self._pin = (int(generation), release_above)

    def unpin(self) -> None:
        self._pin = None

    def read_warmup(self, kind: str):
        """(warm-start manifest section, reason) for this publish dir —
        the consumer half of the ISSUE 15 seam (see
        `ModelPublisher.publish_manifest`)."""
        from . import warmup
        return warmup.read_manifest(self.pub_dir, kind)

    @property
    def pinned_generation(self) -> Optional[int]:
        return self._pin[0] if self._pin is not None else None

    def _bad_generations(self) -> set:
        return set(read_rollback_marker(self.pub_dir).get(
            "bad_generations", []))

    def _candidates(self) -> List[Tuple[int, str]]:
        """Generation candidates newest-first: the manifest pointer is
        tried first (one read instead of a directory scan in the common
        case), then the scan — a stale or torn manifest only costs the
        fallback, and a manifest pointing at a BETTER generation than the
        scan can see cannot happen (the generation file is renamed into
        place before the manifest names it)."""
        cands: List[Tuple[int, str]] = []
        try:
            with open(os.path.join(self.pub_dir, MANIFEST)) as fh:
                m = json.load(fh)
            cands.append((int(m["latest"]),
                          os.path.join(self.pub_dir, str(m["file"]))))
        except (OSError, ValueError, KeyError, TypeError):
            pass
        seen = {c[0] for c in cands}
        cands.extend((g, p) for g, p in generation_paths(self.pub_dir)
                     if g not in seen)
        cands.sort(reverse=True)
        return cands

    def resolve_once(self) -> Optional[PublishedModel]:
        """One resolution attempt (no retry).  Never raises on torn or
        vanishing files — those are skipped, as are generations the
        publish dir's ROLLBACK marker condemns.  While a pin is active
        the pinned generation is resolved instead of the newest one —
        until a candidate newer than the pin's `release_above` bound
        appears, which releases the pin (the fresh candidate gets its
        own canary judgment)."""
        bad = self._bad_generations()
        cands = self._candidates()
        if self._pin is not None:
            pin_gen, release_above = self._pin
            newest_ok = max((g for g, _ in cands
                             if g not in bad), default=None)
            if release_above is not None and newest_ok is not None \
                    and newest_ok > release_above:
                self._pin = None
            else:
                cands = [(g, p) for g, p in cands if g == pin_gen] or cands
        for gen, path in cands:
            if gen in bad:
                self.skipped_rolled_back += 1
                continue
            try:
                with open(path, "rb") as fh:
                    text = fh.read().decode("utf-8", "replace")
            except OSError:
                continue                 # pruned between listing and open
            split = _split_validate(text)
            if split is None:
                self.skipped_invalid += 1
                continue
            self.resolved_count += 1
            return PublishedModel(gen, path, split[0], split[1])
        return None

    def resolve(self) -> PublishedModel:
        delays = resilience.backoff_delays(self.attempts,
                                           base=self.backoff_base,
                                           cap=self.backoff_cap,
                                           seed=self.seed)
        for a in range(self.attempts):
            rec = self.resolve_once()
            if rec is not None:
                return rec
            if a < len(delays):
                time.sleep(delays[a])
        raise NoValidGeneration(
            "no valid published generation in %r after %d attempts"
            % (self.pub_dir, self.attempts))
