"""One-command debug bundle (``task=doctor`` / `collect_debug_bundle`).

The artifact a failed hardware window ships home (ISSUE 10).  Five
rounds of red MULTICHIP artifacts proved that ad-hoc evidence gathering
loses exactly the file that mattered; this module packages EVERYTHING a
post-mortem needs into one atomic tar with a checksummed manifest:

* **platform probe** — `resilience.probe_platform` in a short-deadline
  subprocess (a dead tunnel is recorded, never waited on);
* **environment / config fingerprint** — python/jax/numpy versions,
  platform, argv, and every ``LGBM_* / JAX_* / XLA_* / BENCH_*`` env
  var, plus the CLI's resolved parameters when available;
* **stage trails** — ``$LGBM_TPU_STAGE_REPORT`` /
  ``$LGBM_TPU_SERVE_REPORT`` and any explicitly passed trail files
  (read through the tolerant `read_stage_report`, so a torn trail
  degrades to its raw bytes instead of being dropped);
* **metrics snapshot** — the PR 9 registry (the merged {host}-labeled
  mesh view when the process is part of a multi-host run);
* **compile ledger** — `xla_obs.LEDGER.to_json()`: per-site compiles,
  wall time, last shapes, steady-state retraces;
* **trace ring** — the ISSUE 14 flight recorder's bounded event ring as
  Perfetto-loadable Chrome trace JSON (``trace.json``): the causal
  timeline of the last moments before the crash;
* **recent artifacts** — the newest ``BENCH_* / CHAOS* / MULTICHIP*``
  JSONs found next to the repo (size-capped).

The bundle is written tmp+fsync+rename (one atomic file); the manifest
inside it carries a sha256 per member and `verify_bundle` re-checks
them — the round-trip is test-pinned.  Collection must never crash the
crashing process: every member is gathered under its own guard, and a
member that cannot be gathered becomes an ``errors`` entry in the
manifest instead of an exception.
"""
from __future__ import annotations

import glob
import hashlib
import io
import json
import os
import platform
import sys
import tarfile
import time
from typing import Any, Dict, List, Optional

from . import resilience, telemetry, tracing, warmup, xla_obs

__all__ = ["collect_debug_bundle", "verify_bundle", "env_fingerprint"]

#: newest-first artifact globs bundled from the artifact directory
ARTIFACT_GLOBS = ("BENCH_r*.json", "BENCH_local*.json", "CHAOS*.json",
                  "MULTICHIP*.json")

#: per-member size cap — a bundle must stay shippable over a bad link
MAX_MEMBER_BYTES = 1 << 20

#: artifacts bundled at most (newest by mtime)
MAX_ARTIFACTS = 8


def env_fingerprint(config: Optional[Dict[str, Any]] = None
                    ) -> Dict[str, Any]:
    """Everything about WHERE this ran that a post-mortem asks first."""
    env_keys = sorted(k for k in os.environ
                      if k.startswith(("LGBM_", "JAX_", "XLA_", "BENCH_",
                                       "NDEV", "TPU_")))
    fp: Dict[str, Any] = {
        "wallclock": resilience.wallclock(),
        "pid": os.getpid(),
        "argv": list(sys.argv),
        "cwd": os.getcwd(),
        "python": sys.version.split()[0],
        "platform": platform.platform(),
        "env": {k: os.environ[k] for k in env_keys},
    }
    jax = sys.modules.get("jax")      # never INITIALIZE a platform here
    if jax is not None:
        fp["jax_version"] = getattr(jax, "__version__", "?")
    np = sys.modules.get("numpy")
    if np is not None:
        fp["numpy_version"] = getattr(np, "__version__", "?")
    if config:
        fp["config"] = {str(k): str(v) for k, v in config.items()}
    return fp


def _stage_trail_members(extra: Optional[List[str]]) -> Dict[str, bytes]:
    out: Dict[str, bytes] = {}
    paths: List[str] = []
    for env_key in ("LGBM_TPU_STAGE_REPORT", "LGBM_TPU_SERVE_REPORT"):
        p = os.environ.get(env_key)
        if p:
            paths.append(p)
    paths.extend(extra or [])
    for i, p in enumerate(paths):
        if not os.path.exists(p):
            continue
        name = "trails/%d_%s" % (i, os.path.basename(p))
        rep = resilience.read_stage_report(p)
        if rep is not None:
            out[name] = (json.dumps(rep, indent=1) + "\n").encode("utf-8")
        else:
            with open(p, "rb") as fh:        # torn: raw bytes beat nothing
                out[name] = fh.read(MAX_MEMBER_BYTES)
    return out


def _artifact_members(artifact_dir: str) -> Dict[str, bytes]:
    found: List[str] = []
    for pat in ARTIFACT_GLOBS:
        found.extend(glob.glob(os.path.join(artifact_dir, pat)))
    found = sorted(set(found), key=os.path.getmtime, reverse=True)
    out: Dict[str, bytes] = {}
    for p in found[:MAX_ARTIFACTS]:
        with open(p, "rb") as fh:
            out["artifacts/" + os.path.basename(p)] = \
                fh.read(MAX_MEMBER_BYTES)
    return out


def _metrics_member() -> bytes:
    snap: Dict[str, Any]
    try:
        if telemetry.mesh_process_count() > 1:
            snap = telemetry.mesh_snapshot("doctor")
        else:
            snap = telemetry.snapshot("doctor")
    except Exception:    # noqa: BLE001 — platform query may be wedged
        snap = telemetry.snapshot("doctor")
    return (json.dumps(snap) + "\n").encode("utf-8")


def collect_debug_bundle(out_dir: str = ".",
                         tag: Optional[str] = None,
                         config: Optional[Dict[str, Any]] = None,
                         probe: bool = True,
                         probe_deadline: float = 10.0,
                         stage_reports: Optional[List[str]] = None,
                         artifact_dir: Optional[str] = None,
                         note: Optional[str] = None) -> Dict[str, Any]:
    """Collect everything into ``<out_dir>/lgbm_debug_<stamp>.tar.gz``
    atomically.  Returns ``{"path": ..., "manifest": {...}}``; the same
    manifest (with per-member sha256) rides INSIDE the tar as
    ``manifest.json``."""
    stamp = time.strftime("%Y%m%d_%H%M%S")
    name = "lgbm_debug_%s%s_%d" % (("%s_" % tag) if tag else "", stamp,
                                   os.getpid())
    members: Dict[str, bytes] = {}
    errors: Dict[str, str] = {}

    def gather(member: str, fn) -> None:
        try:
            v = fn()
            if isinstance(v, dict):
                v = (json.dumps(v, indent=1) + "\n").encode("utf-8")
            if v:
                members[member] = v[:MAX_MEMBER_BYTES] \
                    if isinstance(v, bytes) else v
        except Exception as e:   # noqa: BLE001 — collection must not crash
            errors[member] = "%s: %s" % (type(e).__name__, e)

    gather("env.json", lambda: env_fingerprint(config))
    if probe:
        gather("probe.json",
               lambda: resilience.probe_platform(deadline=probe_deadline))
    gather("metrics.json", _metrics_member)
    gather("xla_ledger.json", lambda: xla_obs.LEDGER.to_json())
    # warm-start state (ISSUE 15): persistent compile-cache dir /
    # fingerprint / hit-miss-evict counts — the first question a slow
    # cold start gets asked
    gather("warmup_status.json", warmup.cache_status)
    # the trace flight recorder's ring (ISSUE 14): the causal timeline
    # of the process's last TRACE_RING_EVENTS events, Perfetto-loadable
    # straight out of the bundle
    gather("trace.json", lambda: tracing.export_chrome(
        context_name="doctor"))

    def _trails() -> None:
        members.update(_stage_trail_members(stage_reports))
    try:
        _trails()
    except Exception as e:       # noqa: BLE001
        errors["trails"] = "%s: %s" % (type(e).__name__, e)

    try:
        members.update(_artifact_members(
            artifact_dir if artifact_dir is not None else os.getcwd()))
    except Exception as e:       # noqa: BLE001
        errors["artifacts"] = "%s: %s" % (type(e).__name__, e)

    manifest: Dict[str, Any] = {
        "bundle": name,
        "created": resilience.wallclock(),
        "members": [
            {"name": m, "sha256": hashlib.sha256(members[m]).hexdigest(),
             "bytes": len(members[m])}
            for m in sorted(members)],
    }
    if note:
        manifest["note"] = note
    if errors:
        manifest["errors"] = errors

    out_path = os.path.join(out_dir, name + ".tar.gz")
    tmp = out_path + ".tmp.%d" % os.getpid()
    with tarfile.open(tmp, "w:gz") as tar:
        def add(member_name: str, data: bytes) -> None:
            info = tarfile.TarInfo(name + "/" + member_name)
            info.size = len(data)
            info.mtime = int(time.time())
            tar.addfile(info, io.BytesIO(data))
        add("manifest.json",
            (json.dumps(manifest, indent=1) + "\n").encode("utf-8"))
        for m in sorted(members):
            add(m, members[m])
    with open(tmp, "rb") as fh:           # fsync before the atomic rename
        os.fsync(fh.fileno())
    os.replace(tmp, out_path)
    return {"path": out_path, "manifest": manifest}


def verify_bundle(path: str) -> Dict[str, Any]:
    """Re-read a bundle and re-hash every member against its manifest.
    Returns {"ok": bool, "members": N, "mismatches": [...]}."""
    with tarfile.open(path, "r:gz") as tar:
        by_name = {}
        root = None
        for info in tar.getmembers():
            parts = info.name.split("/", 1)
            if len(parts) != 2:
                continue
            root = parts[0]
            by_name[parts[1]] = tar.extractfile(info).read()
        manifest = json.loads(by_name.pop("manifest.json").decode("utf-8"))
    mismatches: List[str] = []
    for entry in manifest["members"]:
        data = by_name.get(entry["name"])
        if data is None:
            mismatches.append("%s: missing from tar" % entry["name"])
        elif hashlib.sha256(data).hexdigest() != entry["sha256"]:
            mismatches.append("%s: sha256 mismatch" % entry["name"])
    for extra in sorted(set(by_name) - {e["name"]
                                        for e in manifest["members"]}):
        mismatches.append("%s: in tar but not in manifest" % extra)
    return {"ok": not mismatches, "bundle": root,
            "members": len(manifest["members"]), "mismatches": mismatches}
