"""Deterministic open-loop load generator with a verifying client pool.

ISSUE 11: the "millions of users" side of the closed-loop production
sim.  Three properties matter and all are deliberate:

* **Open loop.**  Arrival times are drawn from a seedable inhomogeneous
  Poisson process over a traffic *shape* (diurnal / bursty / step) and
  walked on an ABSOLUTE clock: a slow server does not slow the offered
  load down — exactly the property that makes overload visible.  The
  submitting thread never blocks on a response; completions are awaited
  by a separate client pool.
* **Deterministic.**  Same seed, same shape, same duration -> the same
  arrival offsets, the same class assignment, the same probe rows.  A
  sim run is reproducible load for a nondeterministic system.
* **Verifying.**  Every completed response is checked BYTE-FOR-BYTE
  against the offline predictor for the generation it reports, through
  the path it reports (host responses against the exact f64 host
  predictor, device responses against the device path — per-row device
  outputs are batch-composition invariant, pinned in
  tests/test_serving.py).  The chaos-soak correctness bar (ISSUE 7)
  becomes a continuous property of every sim.

Offered load and verification verdicts land in the metrics registry
(``lgbm_loadgen_offered_total{cls}``,
``lgbm_loadgen_verified_total{result}``), so the sim artifact's
shed-rate and zero-wrong-generation numbers are registry-scraped like
everything else.

Only numpy at module scope; the model stack loads lazily inside the
verifier (first generation resolution).
"""
from __future__ import annotations

import math
import os
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from . import publish, telemetry, tracing
from .serving import ServeRejected


def _bucket_width_at(value: float) -> float:
    """Width of the fixed-layout latency bucket `value` falls in — the
    tolerance the stage-sum-vs-latency pin is allowed (one bucket)."""
    b = telemetry.LATENCY_BUCKETS_S
    i = 0
    while value > b[i]:
        i += 1
    if math.isinf(b[i]):
        i = len(b) - 2
    return b[i] - (b[i - 1] if i > 0 else 0.0)

__all__ = ["TrafficShape", "RequestClass", "ResponseVerifier",
           "LoadGenerator", "poisson_arrivals"]


class TrafficShape:
    """A named offered-load curve: ``rate(t)`` in requests/second at
    offset ``t`` from the run start, plus its peak (the thinning
    envelope)."""

    def __init__(self, name: str, rate_fn: Callable[[float], float],
                 peak_rps: float):
        self.name = name
        self._rate_fn = rate_fn
        self.peak_rps = float(peak_rps)

    def rate(self, t: float) -> float:
        return max(float(self._rate_fn(t)), 0.0)

    # -- the three canonical shapes ------------------------------------------
    @classmethod
    def diurnal(cls, base_rps: float, peak_rps: float,
                period_s: float) -> "TrafficShape":
        """A day compressed to `period_s`: sinusoid from base (trough)
        to peak, starting at the trough."""
        amp = (peak_rps - base_rps) / 2.0
        mid = base_rps + amp

        def rate(t: float) -> float:
            return mid - amp * math.cos(2.0 * math.pi * t / period_s)

        return cls("diurnal", rate, peak_rps)

    @classmethod
    def bursty(cls, base_rps: float, burst_rps: float, period_s: float,
               burst_len_s: float) -> "TrafficShape":
        """Flat base load with a square-wave burst of `burst_len_s`
        at the start of every `period_s` window."""

        def rate(t: float) -> float:
            return burst_rps if (t % period_s) < burst_len_s else base_rps

        return cls("bursty", rate, max(base_rps, burst_rps))

    @classmethod
    def step(cls, levels: List) -> "TrafficShape":
        """Piecewise-constant: ``levels`` is [(duration_s, rps), ...];
        past the last level the last rps holds."""
        levels = [(float(d), float(r)) for d, r in levels]

        def rate(t: float) -> float:
            acc = 0.0
            for dur, rps in levels:
                acc += dur
                if t < acc:
                    return rps
            return levels[-1][1]

        return cls("step", rate, max(r for _, r in levels))


def poisson_arrivals(shape: TrafficShape, duration_s: float,
                     seed: int = 0) -> np.ndarray:
    """Sorted arrival offsets (seconds) of an inhomogeneous Poisson
    process with rate ``shape.rate(t)``, by thinning a homogeneous
    process at ``peak_rps``.  Deterministic per (shape, duration, seed)."""
    rng = np.random.default_rng(seed)
    peak = max(shape.peak_rps, 1e-9)
    n = int(rng.poisson(peak * duration_s))
    t = np.sort(rng.uniform(0.0, duration_s, size=n))
    keep = rng.uniform(0.0, 1.0, size=n) * peak < \
        np.array([shape.rate(x) for x in t])
    return t[keep]


class RequestClass:
    """One slice of the request mix: a priority class hitting one model
    with `rows` feature rows per request, drawn with probability
    proportional to `weight`."""

    __slots__ = ("name", "priority", "model_id", "weight", "rows")

    def __init__(self, name: str, priority: int = 0,
                 model_id: str = "default", weight: float = 1.0,
                 rows: int = 1):
        self.name = name
        self.priority = int(priority)
        self.model_id = model_id
        self.weight = float(weight)
        self.rows = int(rows)


class ResponseVerifier:
    """Byte-identity oracle: offline `Booster.predict` references per
    (generation, served_by path), computed over the FIXED probe matrix so
    verifying a response is pure indexing.

    Generation texts resolve from `texts` (a {generation: model_text}
    map) first, then from the publish directory (the validated
    generation file — publish retention must cover the run, which the
    sim harness configures).  A generation that resolves nowhere is a
    ``wrong_generation`` verdict: the response names a model that was
    never validly published."""

    def __init__(self, probe: np.ndarray, pub_dir: Optional[str] = None,
                 texts: Optional[Dict[int, str]] = None,
                 params: Optional[Dict[str, Any]] = None,
                 raw_score: bool = False,
                 value_dtype: Optional[type] = None):
        """`value_dtype=np.float32` verifies a float32 serving surface
        (the binary wire plane / ``response_dtype="float32"``): the
        reference stays the exact f64 offline predict, narrowed with the
        SAME deterministic cast the server applies — still byte-identity,
        just in the narrower lane (ISSUE 16/17)."""
        self.probe = np.asarray(probe, dtype=np.float64)
        self.pub_dir = pub_dir
        self.texts: Dict[int, str] = dict(texts or {})
        self.params = dict(params or {})
        self.raw_score = bool(raw_score)
        self.value_dtype = value_dtype
        self._refs: Dict[int, Dict[str, np.ndarray]] = {}
        self._lock = threading.Lock()

    def _resolve_text(self, generation: int) -> Optional[str]:
        text = self.texts.get(generation)
        if text is not None:
            return text
        if self.pub_dir is None:
            return None
        path = os.path.join(self.pub_dir,
                            publish._gen_name(generation))  # noqa: SLF001
        try:
            with open(path, "rb") as fh:
                raw = fh.read().decode("utf-8", "replace")
        except OSError:
            return None
        split = publish._split_validate(raw)                # noqa: SLF001
        return split[0] if split is not None else None

    def refs(self, generation: int) -> Optional[Dict[str, np.ndarray]]:
        with self._lock:
            cached = self._refs.get(generation)
        if cached is not None:
            return cached
        text = self._resolve_text(generation)
        if text is None:
            return None
        from ..basic import Booster
        bst = Booster(params=dict(self.params), model_str=text)
        # the reference DEVICE predict runs in the same process as the
        # sim's LGBM_TPU_FAULT churn, so a kill window can take it down
        # too — retry through the window (faults are transient by
        # design; the cache makes this a once-per-generation cost)
        entry: Optional[Dict[str, np.ndarray]] = None
        for _ in range(40):
            try:
                entry = {
                    "host": np.asarray(bst.predict(self.probe,
                                                   raw_score=self.raw_score,
                                                   device=False)),
                    "device": np.asarray(bst.predict(self.probe,
                                                     raw_score=self.raw_score,
                                                     device=True)),
                }
                break
            except BaseException:            # noqa: BLE001 — fault window
                time.sleep(0.25)
        if entry is None:
            raise RuntimeError("reference predict for generation %d kept "
                               "failing (fault window never closed?)"
                               % generation)
        with self._lock:
            self._refs.setdefault(generation, entry)
        return entry

    def verify(self, result, idx: np.ndarray) -> str:
        """Verdict for one `ServeResult` served over probe rows `idx`:
        ok / wrong_generation / mismatch / unverifiable (the reference
        itself could not be computed — never silently dropped)."""
        try:
            refs = self.refs(result.generation)
        except BaseException:                # noqa: BLE001 — verdict below
            return "unverifiable"
        if refs is None:
            return "wrong_generation"
        ref = refs.get(result.served_by)
        if ref is None:
            return "mismatch"
        expect = ref[idx]
        if self.value_dtype is not None:
            expect = expect.astype(self.value_dtype)
        if not np.array_equal(np.asarray(result.values), expect):
            return "mismatch"
        return "ok"


class LoadGenerator:
    """Drive one `ServingRuntime` with a shaped, classed, verified
    open-loop request stream.  `run()` blocks for `duration_s` and
    returns the machine-readable ledger."""

    def __init__(self, runtime, classes: List[RequestClass],
                 shape: TrafficShape, duration_s: float,
                 probe: np.ndarray, seed: int = 0,
                 verifier: Optional[ResponseVerifier] = None,
                 deadline_s: float = 2.0, waiters: int = 8,
                 trace_every: int = 0):
        """`trace_every=K` (ISSUE 14) traces every K-th offered request:
        a fresh trace id travels to the server as the submit's
        traceparent (the server records queue/gather/device/drain slices
        under it), the client-side wait is recorded as the root span,
        and the response's stage decomposition is collected into the
        ledger's ``trace`` section with the stage-sum-vs-client-latency
        error accounted per sample.  0 disables sampling."""
        if not classes:
            raise ValueError("LoadGenerator needs at least one RequestClass")
        self.runtime = runtime
        self.classes = list(classes)
        self.shape = shape
        self.duration_s = float(duration_s)
        self.probe = np.asarray(probe, dtype=np.float64)
        self.seed = int(seed)
        self.verifier = verifier
        self.deadline_s = float(deadline_s)
        self.waiters = max(int(waiters), 1)
        self.trace_every = max(int(trace_every), 0)
        self.trace_samples: List[Dict[str, Any]] = []
        self._trace_lock = threading.Lock()
        self._ledger_lock = threading.Lock()

        self.offered: Dict[str, int] = {c.name: 0 for c in self.classes}
        self.completed: Dict[str, int] = {c.name: 0 for c in self.classes}
        self.shed: Dict[str, Dict[str, int]] = {c.name: {}
                                                for c in self.classes}
        self.verify_counts: Dict[str, int] = {}
        self.served_by: Dict[str, int] = {}
        self.bad_rejections = 0
        self.hard_errors: List[str] = []
        self.max_lag_s = 0.0

    # -- the verifying client pool -------------------------------------------
    def _waiter(self, q: "queue.Queue") -> None:
        verified = telemetry.counter("lgbm_loadgen_verified_total")
        while True:
            item = q.get()
            if item is None:
                return
            req, idx, cls = item
            try:
                try:
                    rec = req.wait(timeout=self.deadline_s
                                   + self.runtime.predict_deadline_s + 10.0)
                except ServeRejected as e:
                    self._record_shed(cls, e)
                    continue
                verdict = (self.verifier.verify(rec, idx)
                           if self.verifier is not None else None)
                # one lock around the ledger counters: the waiters'
                # unlocked read-modify-writes used to lose updates under
                # preemption, so verified_total could drift from
                # completed — an equality validate_sim_artifact rejects
                with self._ledger_lock:
                    self.completed[cls.name] += 1
                    self.served_by[rec.served_by] = \
                        self.served_by.get(rec.served_by, 0) + 1
                    if verdict is not None:
                        self.verify_counts[verdict] = \
                            self.verify_counts.get(verdict, 0) + 1
                if verdict is not None:
                    verified.inc(result=verdict)
            except BaseException as e:       # noqa: BLE001 — a waiter
                # must NEVER die silently: a dead waiter would strand its
                # queue share and undercount verification
                self.hard_errors.append("%s: %s" % (type(e).__name__, e))

    def _trace_waiter(self, req, cls: RequestClass, ctx,
                      t_submit: float) -> None:
        """Dedicated waiter for ONE sampled request: the client clock
        must stop when the response ARRIVES, so a sampled request never
        sits behind head-of-line peers in the shared waiter pool's FIFO
        (that queueing is loadgen overhead, not observed latency)."""
        try:
            rec = req.wait(timeout=self.deadline_s
                           + self.runtime.predict_deadline_s + 10.0)
        except BaseException:       # noqa: BLE001 — sheds/errors are the
            return                  # shared pool's ledger, not a sample
        self._record_trace_sample(rec, req, cls, ctx, t_submit)

    def _record_trace_sample(self, rec, req, cls: RequestClass,
                             ctx, t_submit: float) -> None:
        """Close one sampled request's client-side root span and account
        its server stage decomposition against the CLIENT-observed
        latency (the acceptance pin: stage sum within one bucket width).

        Client-observed latency = submit call to response READY, both on
        the client's own clock reads: ``t_submit`` is taken before the
        submit call, and readiness is the request's completion stamp
        (``enqueued + latency_s`` on the same monotonic clock — what a
        TCP client's socket read would see modulo the wire).  The
        further gap until this waiter thread actually WAKES is recorded
        separately as ``delivery_s``: on an oversubscribed host (the
        1-core CI box) the scheduler's wake-up delay is real, but it is
        client-runtime noise, not server time — folding it into the pin
        would make the gate flake exactly where the decomposition is
        most precise (sub-10 ms requests)."""
        t_wake = time.monotonic()
        t_ready = req.enqueued + rec.latency_s
        client_latency = max(t_ready - t_submit, 0.0)
        tracing.record("client request %s" % cls.name,
                       int(t_submit * 1e9),
                       int(client_latency * 1e9),
                       trace=ctx[0], span_id=ctx[1],
                       cls=cls.name, served_by=rec.served_by,
                       generation=rec.generation,
                       model_trace=rec.model_trace)
        stage_sum = round(sum(rec.stages.values()), 6) if rec.stages \
            else None
        sample = {
            "cls": cls.name,
            "client_latency_s": round(client_latency, 6),
            "server_latency_s": rec.latency_s,
            "delivery_s": round(max(t_wake - t_ready, 0.0), 6),
            "stages": dict(rec.stages),
            "stage_sum_s": stage_sum,
            "stage_sum_err_s": round(abs(stage_sum - client_latency), 6)
            if stage_sum is not None else None,
            "bucket_width_s": _bucket_width_at(client_latency),
            "served_by": rec.served_by,
            "generation": rec.generation,
            "trace": tracing.make_traceparent(*ctx),
            "model_trace": rec.model_trace,
        }
        with self._trace_lock:
            if len(self.trace_samples) < 512:
                self.trace_samples.append(sample)

    def _record_shed(self, cls: RequestClass, e: ServeRejected) -> None:
        with self._ledger_lock:
            reasons = self.shed[cls.name]
            reasons[e.reason] = reasons.get(e.reason, 0) + 1
        d = e.to_dict()
        # the machine-readability contract: retryable flag, a reason,
        # and (ISSUE 11) the priority class the shed applied to
        if not (d.get("error") == "rejected" and d.get("reason")
                and "retryable" in d
                and d.get("priority") == cls.priority):
            self.bad_rejections += 1

    # -- the open loop -------------------------------------------------------
    def run(self) -> Dict[str, Any]:
        arrivals = poisson_arrivals(self.shape, self.duration_s, self.seed)
        rng = np.random.default_rng(self.seed + 1)
        weights = np.asarray([c.weight for c in self.classes], np.float64)
        weights = weights / weights.sum()
        cls_idx = rng.choice(len(self.classes), size=len(arrivals),
                             p=weights)
        row_idx = [rng.integers(0, len(self.probe),
                                size=self.classes[c].rows)
                   for c in cls_idx]

        q: "queue.Queue" = queue.Queue()
        pool = [threading.Thread(target=self._waiter, args=(q,),
                                 name="loadgen-waiter-%d" % i, daemon=True)
                for i in range(self.waiters)]
        for t in pool:
            t.start()
        trace_threads: List[threading.Thread] = []
        offered = telemetry.counter("lgbm_loadgen_offered_total")
        t0 = time.monotonic()
        for i, (off, ci, idx) in enumerate(zip(arrivals, cls_idx, row_idx)):
            cls = self.classes[ci]
            now = time.monotonic() - t0
            if off > now:
                time.sleep(off - now)
            else:
                # open loop: late arrivals submit immediately; the lag is
                # recorded, the offered schedule is never thinned
                self.max_lag_s = max(self.max_lag_s, now - off)
            self.offered[cls.name] += 1
            offered.inc(cls=cls.name)
            # sampled tracing (ISSUE 14): every K-th request gets a
            # fresh trace id that travels to the server as traceparent
            ctx = None
            tp = None
            if self.trace_every and i % self.trace_every == 0 \
                    and tracing.enabled():
                trace_threads = [t for t in trace_threads if t.is_alive()]
                if len(trace_threads) < 256:   # bound the waiter spawn
                    ctx = (tracing.new_trace_id(), tracing.new_span_id())
                    tp = tracing.make_traceparent(*ctx)
            t_submit = time.monotonic()
            try:
                req = self.runtime.submit(self.probe[idx],
                                          deadline_s=self.deadline_s,
                                          model_id=cls.model_id,
                                          priority=cls.priority,
                                          traceparent=tp)
            except ServeRejected as e:
                self._record_shed(cls, e)
                continue
            if ctx is not None:
                # a dedicated waiter per sampled request: its client
                # clock stops at response arrival, not at its turn in
                # the shared pool's FIFO
                tt = threading.Thread(target=self._trace_waiter,
                                      args=(req, cls, ctx, t_submit),
                                      name="loadgen-trace", daemon=True)
                tt.start()
                trace_threads.append(tt)
            q.put((req, idx, cls))
        for _ in pool:
            q.put(None)
        for t in pool:
            t.join(timeout=60)
        for t in trace_threads:
            t.join(timeout=60)
        return self.ledger()

    def ledger(self) -> Dict[str, Any]:
        total_offered = sum(self.offered.values())
        out: Dict[str, Any] = {
            "shape": self.shape.name,
            "duration_s": self.duration_s,
            "seed": self.seed,
            "offered_total": total_offered,
            "offered_rps_mean": round(total_offered
                                      / max(self.duration_s, 1e-9), 2),
            "max_lag_s": round(self.max_lag_s, 4),
            "served_by": dict(self.served_by),
            "verification": dict(self.verify_counts),
            "non_machine_readable_rejections": self.bad_rejections,
            "hard_errors": self.hard_errors[:10],
            "classes": {},
        }
        if self.trace_every:
            with self._trace_lock:
                samples = list(self.trace_samples)
            errs = [s["stage_sum_err_s"] for s in samples
                    if s["stage_sum_err_s"] is not None]
            within = [s for s in samples
                      if s["stage_sum_err_s"] is not None
                      and s["stage_sum_err_s"] <= s["bucket_width_s"]]
            out["trace"] = {
                "trace_every": self.trace_every,
                "sampled": len(samples),
                "with_stages": len(errs),
                "stage_sum_within_bucket": len(within),
                "stage_sum_max_err_s": round(max(errs), 6) if errs
                else None,
                # the acceptance pin: EVERY sampled request's stage sum
                # lands within one bucket width of its client latency
                "ok": bool(samples) and len(within) == len(errs) > 0,
                "samples": samples[:64],
            }
        for c in self.classes:
            shed = sum(self.shed[c.name].values())
            out["classes"][c.name] = {
                "priority": c.priority,
                "model": c.model_id,
                "offered": self.offered[c.name],
                "completed": self.completed[c.name],
                "shed": shed,
                "shed_rate": round(shed / self.offered[c.name], 4)
                if self.offered[c.name] else 0.0,
                "reasons": dict(self.shed[c.name]),
            }
        return out
