"""End-to-end distributed tracing: the causal half of observability
(ISSUE 14).

PR 9/10 made the system *measurable* — ``lgbm_span_seconds`` and the
compile ledger say how long each stage kind takes ON AVERAGE — but not
*traceable*: when one request's p99 spikes or one cycle stalls, the
histograms have already aggregated the causality away.  This module is
an always-on, bounded ring-buffer **flight recorder** of structured
trace events, plus the propagation plumbing that lets one request or
one training cycle be followed across threads (serving batcher, PR 5
assembler worker, watchdog stages) and across processes (TCP requests,
publish/subscribe, subprocess launches):

* **Events** carry ``trace_id``/``span_id``/``parent_id`` (W3C-sized
  hex ids), a monotonic-ns timestamp, the recording thread, and free
  labels.  The ring is bounded (`TRACE_RING_EVENTS`); overflow drops
  the OLDEST events and counts them — the recorder can run for days and
  always holds the most recent window, exactly a flight recorder.
* **Context propagation.**  A thread-local span stack provides the
  ambient parent; `context()` captures it for another thread and
  `attach(ctx)` / `bind(fn, ...)` restore it there (the assembler
  worker and the serving batcher use this).  Across processes the
  context travels as a ``traceparent`` string
  (``00-<trace>-<span>-01``): TCP serve requests carry a
  ``traceparent`` field, publish meta carries the producing cycle's
  context, and ``$LGBM_TPU_TRACEPARENT`` seeds a subprocess's root
  context (prod_sim / dryrun passthrough).
* **Exporters.**  `export_chrome()` renders the ring as Chrome
  trace-event JSON (Perfetto-loadable: one process track per pid, one
  row per thread, flow arrows for publish→subscribe links), timestamps
  mapped onto the ABSOLUTE unix clock through a per-process
  (unix_ns, monotonic_ns) anchor pair — the same absolute-clock seam
  the online scheduler rides — so `merge_traces()` can fuse N
  replica/trainer/loadgen files into ONE timeline with ``{host,pid}``
  track names and no per-file clock fixups.  ``$LGBM_TPU_TRACE_DIR``
  arms an atexit dump (``trace_<host>_<pid>.json``) in every process
  that imports the runtime, so a fleet run collects itself.

The hot-loop contract matches PR 9's: every recording call checks the
module enable flag first, so with tracing disabled each site costs one
global read + an early return (the BENCH ``telemetry`` section asserts
the combined disabled path stays under 1% of an iteration —
``LGBM_TPU_TRACE=0`` is the kill switch).

No jax / numpy at module scope — the hermetic dryrun bootstrap and
platform-free subscribers must be able to import this.
"""
from __future__ import annotations

import atexit
import collections
import contextlib
import json
import os
import socket
import struct
import threading
import time
import zlib
from typing import Any, Dict, Iterable, List, Optional, Tuple

from .resilience import atomic_write

__all__ = [
    "TRACE_RING_EVENTS", "TRACE_DIR_ENV", "TRACEPARENT_ENV",
    "TRACE_ENABLED_ENV",
    "set_enabled", "enabled", "reset", "set_context",
    "span", "instant", "record", "counter_event",
    "current", "current_traceparent", "context", "attach", "bind",
    "make_traceparent", "parse_traceparent", "process_root",
    "flow_id", "flow_start", "flow_end",
    "export_chrome", "export_to_dir", "merge_traces", "ring_summary",
    "maybe_autostart",
]

#: ring capacity (events per process).  ~200 bytes/event in memory: the
#: default bounds the recorder near 12 MB however long the process runs.
TRACE_RING_EVENTS = int(os.environ.get("LGBM_TPU_TRACE_RING", "65536"))

#: directory the atexit exporter dumps this process's ring into
#: (``trace_<host>_<pid>.json``); unset = no automatic dump.
TRACE_DIR_ENV = "LGBM_TPU_TRACE_DIR"

#: cross-process context seed: a child launched with this env var set
#: parents its root spans under the caller's span.
TRACEPARENT_ENV = "LGBM_TPU_TRACEPARENT"

#: kill switch: "0" disables every recording call at the one-global-read
#: cost (the <1% disabled-path pin covers this path).
TRACE_ENABLED_ENV = "LGBM_TPU_TRACE"

#: hard cap on label values embedded in events (they become export JSON)
_LABEL_MAX_CHARS = 200

# ---------------------------------------------------------------------------
# enable flag + clock anchor
# ---------------------------------------------------------------------------

_enabled = os.environ.get(TRACE_ENABLED_ENV, "1") != "0"

#: the absolute-clock anchor: every event timestamp is monotonic ns, and
#: export maps it to unix ns through this pair — so traces from
#: different processes (or hosts sharing wall clocks) merge onto one
#: timeline without negotiation.
_ANCHOR_MONO_NS = time.monotonic_ns()
_ANCHOR_UNIX_NS = time.time_ns()


def set_enabled(on: bool) -> bool:
    """Flip the recorder; returns the previous state.  Disabled, every
    recording call is one global read + an early return."""
    global _enabled
    prev = _enabled
    _enabled = bool(on)
    return prev


def enabled() -> bool:
    return _enabled


def mono_to_unix_ns(t_ns: int) -> int:
    return _ANCHOR_UNIX_NS + (t_ns - _ANCHOR_MONO_NS)


# ---------------------------------------------------------------------------
# ids + traceparent
# ---------------------------------------------------------------------------

_id_lock = threading.Lock()
_id_state = struct.unpack("<Q", os.urandom(8))[0] | 1


def _next_id64() -> int:
    """Cheap process-unique 64-bit id stream (splitmix64): one lock'd
    integer step beats an os.urandom syscall on the request path."""
    global _id_state
    with _id_lock:
        _id_state = (_id_state + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
        z = _id_state
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    return (z ^ (z >> 31)) or 1


def new_span_id() -> str:
    return "%016x" % _next_id64()


def new_trace_id() -> str:
    return "%016x%016x" % (_next_id64(), _next_id64())


def make_traceparent(trace_id: str, span_id: str) -> str:
    """W3C-shaped header value: ``00-<32 hex>-<16 hex>-01``."""
    return "00-%s-%s-01" % (trace_id, span_id)


def parse_traceparent(value: Any) -> Optional[Tuple[str, str]]:
    """(trace_id, span_id) from a traceparent string, or None when the
    value is absent/malformed — a bad header is dropped, never raised."""
    if not isinstance(value, str):
        return None
    parts = value.strip().lower().split("-")
    if len(parts) < 3:
        return None
    trace_id, span_id = parts[1], parts[2]
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    if int(trace_id, 16) == 0 or int(span_id, 16) == 0:
        return None
    return trace_id, span_id


# ---------------------------------------------------------------------------
# the ring
# ---------------------------------------------------------------------------

class _Ring:
    """Bounded event store.  Each event is appended as ONE fully-built
    dict (deque.append is atomic under the GIL), so concurrent writers
    can never tear an event; ordering is restored at export time by a
    sort on the monotonic timestamp.  `dropped` counts overflow."""

    def __init__(self, maxlen: int):
        self._events: "collections.deque[dict]" = collections.deque(
            maxlen=maxlen)
        self.maxlen = maxlen
        self.total = 0          # events ever recorded (bench events/iter)
        self._lock = threading.Lock()

    def append(self, ev: dict) -> None:
        # total is advisory (bench denominator) — the append itself must
        # stay a single atomic deque op on the hot path
        self._events.append(ev)
        self.total += 1

    @property
    def dropped(self) -> int:
        return max(self.total - len(self._events), 0)

    def snapshot(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.total = 0


_RING = _Ring(TRACE_RING_EVENTS)

# thread bookkeeping: tid -> thread name at first event (export metadata)
_thread_names: Dict[int, str] = {}

#: synthetic track registry: track name -> stable synthetic tid (export
#: emits a thread_name metadata row per track).  Used for events that
#: should render on their own Perfetto row (the xla compile track)
#: rather than on the recording thread's.
_tracks: Dict[str, int] = {}
_tracks_lock = threading.Lock()


def _track_tid(name: str) -> int:
    tid = _tracks.get(name)
    if tid is None:
        with _tracks_lock:
            tid = _tracks.get(name)
            if tid is None:
                tid = 0x7FFF0000 + len(_tracks)
                _tracks[name] = tid
    return tid


def _tid() -> int:
    t = threading.current_thread()
    tid = t.ident or 0
    if tid not in _thread_names:
        _thread_names[tid] = t.name
    return tid


def _clean_labels(labels: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in labels.items():
        if isinstance(v, (int, float, bool)) or v is None:
            out[k] = v
        else:
            out[k] = str(v)[:_LABEL_MAX_CHARS]
    return out


# ---------------------------------------------------------------------------
# context
# ---------------------------------------------------------------------------

_tls = threading.local()

_proc_root: Optional[Tuple[str, str]] = None
_proc_root_read = False


def process_root() -> Optional[Tuple[str, str]]:
    """The context ``$LGBM_TPU_TRACEPARENT`` seeded this process with
    (None when unset/malformed): the ambient parent of any root span
    opened before an explicit context exists — a subprocess's first
    spans link back to the launcher that set the env var."""
    global _proc_root, _proc_root_read
    if not _proc_root_read:
        _proc_root = parse_traceparent(os.environ.get(TRACEPARENT_ENV))
        _proc_root_read = True
    return _proc_root


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current() -> Optional[Tuple[str, str]]:
    """(trace_id, span_id) of the innermost open span on this thread —
    falling back to an attached context, then the process root."""
    st = _stack()
    if st:
        return st[-1]
    return process_root()


def current_traceparent() -> Optional[str]:
    ctx = current()
    return make_traceparent(*ctx) if ctx is not None else None


def context() -> Optional[Tuple[str, str]]:
    """Capture the current context for hand-off to another thread."""
    return current()


def thread_context() -> Optional[Tuple[str, str]]:
    """The innermost OPEN span on this thread only — no process-root
    fallback.  Per-item consumers (the serving per-request tracer) use
    this so an ambient ``$LGBM_TPU_TRACEPARENT`` umbrella does not turn
    every request into a traced one."""
    st = _stack()
    return st[-1] if st else None


@contextlib.contextmanager
def attach(ctx: Optional[Tuple[str, str]]):
    """Adopt a captured (or parsed-traceparent) context as this thread's
    ambient parent for the scope.  ``attach(None)`` is a no-op scope."""
    if ctx is None:
        yield
        return
    st = _stack()
    st.append((ctx[0], ctx[1]))
    try:
        yield
    finally:
        st.pop()


def bind(fn, name: Optional[str] = None, **labels):
    """Wrap `fn` so it runs under THIS thread's current context when
    invoked later on another thread (the assembler hand-off seam).  With
    a `name`, the invocation is additionally recorded as a span.
    Disabled, returns `fn` unchanged — zero indirection on the off
    path."""
    if not _enabled:
        return fn
    ctx = context()
    if ctx is None and name is None:
        return fn

    def bound(*a, **k):
        with attach(ctx):
            if name is not None:
                with span(name, **labels):
                    return fn(*a, **k)
            return fn(*a, **k)
    return bound


# ---------------------------------------------------------------------------
# recording
# ---------------------------------------------------------------------------

def record(name: str, t0_ns: int, dur_ns: int, *,
           trace: Optional[str] = None, span_id: Optional[str] = None,
           parent: Optional[str] = None, status: str = "ok",
           track: Optional[str] = None, **labels) -> None:
    """Retro-record one COMPLETED span (watchdog stage closes and xla
    compiles arrive after the fact, with a duration already in hand).
    Context defaults to the thread's current context; `track` renders
    the event on a named synthetic Perfetto row instead of the recording
    thread's."""
    if not _enabled:
        return
    ctx = current()
    if trace is None:
        trace = ctx[0] if ctx is not None else None
    if parent is None and ctx is not None:
        parent = ctx[1]
    ev: Dict[str, Any] = {
        "ph": "X", "name": str(name)[:_LABEL_MAX_CHARS],
        "t_ns": int(t0_ns), "dur_ns": max(int(dur_ns), 0),
        "tid": _track_tid(track) if track else _tid(),
    }
    if trace:
        ev["trace"] = trace
    ev["span"] = span_id or new_span_id()
    if parent:
        ev["parent"] = parent
    if status != "ok":
        ev["status"] = status
    if labels:
        ev["args"] = _clean_labels(labels)
    _RING.append(ev)


@contextlib.contextmanager
def span(name: str, **labels):
    """Open a live span: a child of the current context (or a fresh
    trace root when there is none), ambient for everything recorded in
    the scope, one 'X' event at close carrying ok/error status."""
    if not _enabled:
        yield None
        return
    ctx = current()
    trace = ctx[0] if ctx is not None else new_trace_id()
    parent = ctx[1] if ctx is not None else None
    sid = new_span_id()
    st = _stack()
    st.append((trace, sid))
    t0 = time.monotonic_ns()
    status = "ok"
    try:
        yield (trace, sid)
    except BaseException:
        status = "error"
        raise
    finally:
        st.pop()
        record(name, t0, time.monotonic_ns() - t0, trace=trace,
               span_id=sid, parent=parent, status=status, **labels)


def instant(name: str, track: Optional[str] = None, **labels) -> None:
    """One point-in-time event under the current context."""
    if not _enabled:
        return
    ctx = current()
    ev: Dict[str, Any] = {
        "ph": "i", "name": str(name)[:_LABEL_MAX_CHARS],
        "t_ns": time.monotonic_ns(),
        "tid": _track_tid(track) if track else _tid(),
    }
    if ctx is not None:
        ev["trace"], ev["parent"] = ctx
    if labels:
        ev["args"] = _clean_labels(labels)
    _RING.append(ev)


def counter_event(name: str, value: float, track: str = "counters") -> None:
    """One Perfetto counter sample (renders as a little graph row)."""
    if not _enabled:
        return
    _RING.append({"ph": "C", "name": str(name)[:_LABEL_MAX_CHARS],
                  "t_ns": time.monotonic_ns(),
                  "tid": _track_tid(track), "value": float(value)})


# -- flow links (publish -> subscriber arrows) ------------------------------

def flow_id(*parts: Any) -> int:
    """Stable flow id from the parts both ends of a link know (e.g. the
    publishing cycle's traceparent + the generation number)."""
    return zlib.crc32("|".join(str(p) for p in parts).encode("utf-8"))


def flow_start(name: str, fid: int, **labels) -> None:
    """Source end of a Perfetto flow arrow (the publish side)."""
    if not _enabled:
        return
    ctx = current()
    ev: Dict[str, Any] = {"ph": "s", "name": str(name)[:_LABEL_MAX_CHARS],
                          "t_ns": time.monotonic_ns(), "tid": _tid(),
                          "flow": int(fid)}
    if ctx is not None:
        ev["trace"], ev["parent"] = ctx
    if labels:
        ev["args"] = _clean_labels(labels)
    _RING.append(ev)


def flow_end(name: str, fid: int, **labels) -> None:
    """Sink end of a flow arrow (the subscriber swap-in side)."""
    if not _enabled:
        return
    ctx = current()
    ev: Dict[str, Any] = {"ph": "f", "name": str(name)[:_LABEL_MAX_CHARS],
                          "t_ns": time.monotonic_ns(), "tid": _tid(),
                          "flow": int(fid)}
    if ctx is not None:
        ev["trace"], ev["parent"] = ctx
    if labels:
        ev["args"] = _clean_labels(labels)
    _RING.append(ev)


# ---------------------------------------------------------------------------
# export
# ---------------------------------------------------------------------------

_context_name: Optional[str] = None


def set_context(name: str) -> None:
    """Name this process's role ("train_online", "replica_binary") for
    export headers and {host,pid} track labels — the atexit dump uses it
    when no explicit context is passed."""
    global _context_name
    _context_name = str(name)


def ring_summary() -> Dict[str, Any]:
    evs = _RING.snapshot()
    return {"events": len(evs), "recorded_total": _RING.total,
            "dropped": _RING.dropped, "capacity": _RING.maxlen,
            "threads": len({e["tid"] for e in evs}),
            "traces": len({e.get("trace") for e in evs} - {None})}


def export_chrome(path: Optional[str] = None,
                  context_name: Optional[str] = None) -> Dict[str, Any]:
    """The ring as Chrome trace-event JSON (Perfetto's legacy-JSON
    loader).  Timestamps are ABSOLUTE unix microseconds via the anchor
    pair, so per-process files merge by concatenation; `merge_traces`
    only has to relabel tracks.  With `path`, the JSON is also written
    atomically."""
    pid = os.getpid()
    host = socket.gethostname()
    if context_name is None:
        context_name = _context_name
    events: List[Dict[str, Any]] = []
    proc_label = "%s pid=%d%s" % (host, pid,
                                  " (%s)" % context_name if context_name
                                  else "")
    events.append({"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                   "args": {"name": proc_label}})
    raw = sorted(_RING.snapshot(), key=lambda e: e["t_ns"])
    tids = {e["tid"] for e in raw}
    track_by_tid = {tid: name for name, tid in _tracks.items()}
    for tid in sorted(tids):
        tname = track_by_tid.get(tid) or _thread_names.get(tid) \
            or "thread-%d" % tid
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": tid, "args": {"name": tname}})
    for e in raw:
        ts_us = mono_to_unix_ns(e["t_ns"]) / 1000.0
        out: Dict[str, Any] = {"ph": e["ph"], "name": e["name"],
                               "pid": pid, "tid": e["tid"],
                               "ts": round(ts_us, 3)}
        if e["ph"] == "X":
            out["dur"] = round(e["dur_ns"] / 1000.0, 3)
        if e["ph"] == "i":
            out["s"] = "t"
        if e["ph"] in ("s", "f"):
            out["id"] = "0x%x" % e["flow"]
            out["cat"] = "link"
            if e["ph"] == "f":
                out["bp"] = "e"
        if e["ph"] == "C":
            out["args"] = {"value": e["value"]}
        else:
            args = dict(e.get("args", {}))
            for key in ("trace", "span", "parent", "status"):
                if key in e:
                    args[key] = e[key]
            if args:
                out["args"] = args
        events.append(out)
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "host": host, "pid": pid,
            "anchor_unix_ns": _ANCHOR_UNIX_NS,
            "recorded_total": _RING.total,
            "dropped": _RING.dropped,
            "traceparent_env": os.environ.get(TRACEPARENT_ENV),
        },
    }
    if context_name:
        doc["otherData"]["context"] = context_name
    if path:
        atomic_write(path, json.dumps(doc) + "\n")
    return doc


def export_to_dir(trace_dir: Optional[str] = None,
                  context_name: Optional[str] = None) -> Optional[str]:
    """Dump this process's ring into `trace_dir` (default: the
    ``$LGBM_TPU_TRACE_DIR`` env) as ``trace_<host>_<pid>.json``; returns
    the path, or None when no directory is configured."""
    trace_dir = trace_dir or os.environ.get(TRACE_DIR_ENV)
    if not trace_dir:
        return None
    try:
        os.makedirs(trace_dir, exist_ok=True)
        path = os.path.join(trace_dir, "trace_%s_%d.json"
                            % (socket.gethostname(), os.getpid()))
        export_chrome(path, context_name=context_name)
        return path
    except OSError:
        return None                      # diagnostics must never crash exit


def merge_traces(paths: Iterable[str], out_path: Optional[str] = None,
                 max_events: Optional[int] = None) -> Dict[str, Any]:
    """Fuse N per-process Chrome trace files into ONE timeline.

    Every input already carries absolute-unix timestamps (the anchor
    seam), so fusing is: re-key each file onto a unique pid slot (two
    replicas on one host can share a real pid across time), keep its
    ``{host,pid}`` process_name, concatenate, and sort.  `max_events`
    (slices, newest kept) bounds a committed artifact's size — the cut
    is recorded in otherData, never silent."""
    merged: List[Dict[str, Any]] = []
    sources: List[Dict[str, Any]] = []
    for slot, path in enumerate(sorted(paths)):
        with open(path) as fh:
            doc = json.load(fh)
        other = doc.get("otherData", {})
        sources.append({"file": os.path.basename(path),
                        "host": other.get("host"),
                        "pid": other.get("pid"),
                        "dropped": other.get("dropped", 0),
                        "context": other.get("context")})
        for ev in doc.get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = slot + 1
            merged.append(ev)
    meta = [e for e in merged if e.get("ph") == "M"]
    body = sorted((e for e in merged if e.get("ph") != "M"),
                  key=lambda e: e.get("ts", 0.0))
    truncated = 0
    if max_events is not None and len(body) > max_events:
        truncated = len(body) - max_events
        body = body[-max_events:]
    doc = {"traceEvents": meta + body, "displayTimeUnit": "ms",
           "otherData": {"merged_from": sources,
                         "events": len(body),
                         "truncated_oldest": truncated}}
    if out_path:
        atomic_write(out_path, json.dumps(doc) + "\n")
    return doc


def reset() -> None:
    """Test seam: drop every recorded event and forget thread/track
    names (context stacks and the enable flag are untouched)."""
    global _proc_root_read
    _RING.clear()
    _thread_names.clear()
    _proc_root_read = False


# ---------------------------------------------------------------------------
# autostart (the fleet self-collection seam)
# ---------------------------------------------------------------------------

_atexit_armed = False


def maybe_autostart() -> bool:
    """Arm the atexit ring dump when ``$LGBM_TPU_TRACE_DIR`` is set.
    Idempotent; returns whether the dump is armed.  Called at import
    from the telemetry module, so every process of a fleet (trainer,
    replicas, bench, dryrun children) self-collects without per-caller
    wiring."""
    global _atexit_armed
    if _atexit_armed:
        return True
    if not os.environ.get(TRACE_DIR_ENV):
        return False
    atexit.register(export_to_dir)
    _atexit_armed = True
    return True


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m lightgbm_tpu.runtime.tracing merge out.json in*.json``
    — the standalone merge tool the Perfetto runbook names."""
    import sys
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) < 3 or argv[0] != "merge":
        print("usage: python -m lightgbm_tpu.runtime.tracing merge "
              "<out.json> <trace1.json> [trace2.json ...]")
        return 2
    doc = merge_traces(argv[2:], out_path=argv[1])
    print("merged %d events from %d files -> %s"
          % (doc["otherData"]["events"],
             len(doc["otherData"]["merged_from"]), argv[1]))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
