"""Unified observability subsystem (ISSUE 9): metrics, spans, exporters.

Before this module the runtime's telemetry was five disconnected ad-hoc
surfaces — resilience stage trails, the sync-audit counters, serving
degradation events, bench phase telemetry and the chaos-soak ledgers —
none of which could be scraped from a live ``task=serve`` or
``task=train_online`` process.  This module is the one instrument panel
they all now feed:

* **Metrics registry** (`MetricsRegistry` / the process-global
  `REGISTRY`): counters, gauges, and bounded-memory streaming histograms
  with p50/p95/p99 exact to within one bucket of the FIXED bucket layout
  (`Histogram.quantile`).  Label cardinality is bounded per family: past
  `max_label_sets` distinct label sets, new ones land in an explicit
  ``__overflow__`` bucket instead of growing without bound.  Every
  product metric must be declared in `METRIC_TABLE` — the single source
  of truth the docs/OBSERVABILITY.md catalog is test-pinned against
  (same pattern as `resilience.FAULT_TABLE`).

* **Span tracing** (`span` / `record_span`): named wall-clock spans
  recorded into ``lgbm_span_seconds{span=...}`` /
  ``lgbm_spans_total{span=...,status=...}``.  The PR 4 stage-trail
  watchdog is a CLIENT of this API — every stage close lands here too
  (digit runs normalized to ``N`` so per-cycle/per-batch stage names do
  not explode cardinality), so stages, spans and metrics share one
  clock (`resilience.wallclock`) and one naming scheme.

* **Exporters** — three ways out of the process:
  1. `MetricsServer` / ``metrics_port=``: a Prometheus text-exposition
     HTTP endpoint (``GET /metrics``; ``/metrics.json`` returns the JSON
     snapshot; ``/healthz``) served from `ServingRuntime` and the
     continuous trainer.
  2. ``$LGBM_TPU_METRICS_FILE``: a periodic ATOMIC JSON-lines snapshot
     file for batch CLI/bench runs (each flush rewrites the whole file
     tmp+fsync+rename, so a scraper never reads a torn line).
  3. ``LGBM_TPU_PROFILE=<dir>``: wraps the first N training iterations
     or M serving batches in a ``jax.profiler`` trace
     (`profile_hook`), N/M via ``LGBM_TPU_PROFILE_ITERS`` /
     ``LGBM_TPU_PROFILE_BATCHES``.

The hot-loop contract: every instrument checks the module-level enable
flag first, so with `set_enabled(False)` the whole subsystem costs one
global read + a returned call per site (the BENCH ``telemetry`` section
asserts the disabled path stays under 1% of an iteration).

No jax / numpy at module scope — the hermetic dryrun bootstrap, the CLI
entry and platform-free subscribers must be able to import this.
"""
from __future__ import annotations

import collections
import contextlib
import json
import math
import os
import re
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from . import tracing
from .resilience import atomic_write, wallclock

# every process that touches the metrics registry also arms the trace
# flight-recorder's atexit dump when $LGBM_TPU_TRACE_DIR is set — the
# fleet self-collects (ISSUE 14)
tracing.maybe_autostart()

__all__ = [
    "METRIC_TABLE", "LATENCY_BUCKETS_S", "OVERFLOW_LABEL",
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
    "set_enabled", "enabled", "counter", "gauge", "histogram",
    "span", "record_span", "normalize_span_name", "SPAN_KEEP_KEYS",
    "count_sync",
    "MetricsServer", "start_http_server",
    "MetricsFileWriter", "maybe_start_file_export", "write_snapshot_now",
    "snapshot", "render_prometheus", "profile_hook", "reset",
    "gather_host_snapshots", "merge_host_snapshots", "mesh_snapshot",
    "render_prometheus_from_snapshot", "mesh_process_count",
]

#: the fixed latency/duration bucket layout (seconds).  Quantiles read
#: from these histograms are exact to within one bucket width — the
#: serving acceptance gate compares them against client-side wall-clock
#: measurements at exactly that tolerance.
LATENCY_BUCKETS_S: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, math.inf)

#: every label of an over-cardinality label set is rewritten to this
#: value — overload is visible as an explicit bucket, never as silent
#: unbounded growth or a dropped sample.
OVERFLOW_LABEL = "__overflow__"

#: THE metric registry: every product metric, its type, its label names
#: and its one-line meaning.  docs/OBSERVABILITY.md's catalog table is
#: pinned row-for-row against this dict (tests/test_telemetry.py), so
#: the docs and the registry cannot drift — the FAULT_TABLE pattern.
METRIC_TABLE: Dict[str, Dict[str, Any]] = {
    "lgbm_train_iterations_total": {
        "type": "counter", "labels": (),
        "help": "Completed Booster.update calls (all boosting variants)"},
    "lgbm_train_iteration_seconds": {
        "type": "histogram", "labels": (),
        "help": "Wall time of one boosting iteration (dispatch-side; at "
                "pipeline_depth>0 host assembly drains off this clock)"},
    "lgbm_train_host_syncs_per_iter": {
        "type": "gauge", "labels": ("path",),
        "help": "Blocking host fetches recorded during the last "
                "iteration, path=total/critical (sync-audit seam)"},
    "lgbm_host_syncs_total": {
        "type": "counter", "labels": ("label",),
        "help": "Blocking device->host syncs through runtime/syncs.py, "
                "by call-site label"},
    "lgbm_host_syncs_critical_total": {
        "type": "counter", "labels": ("label",),
        "help": "Sync-audit events recorded ON the tree->tree critical "
                "path (pinned 0 at pipeline_depth=1 fused fast path)"},
    "lgbm_pipeline_queue_depth": {
        "type": "gauge", "labels": (),
        "help": "Host halves pending-or-running in the async tree "
                "assembler (bounded at pipeline_depth)"},
    "lgbm_pipeline_drain_seconds": {
        "type": "histogram", "labels": (),
        "help": "Dispatch-to-append latency of one tree's deferred host "
                "half (queue wait + packed fetch + Tree assembly)"},
    "lgbm_window_iterations_total": {
        "type": "counter", "labels": (),
        "help": "Boosting iterations trained inside fused boost_window "
                "scan dispatches (J iterations per device program)"},
    "lgbm_window_truncations_total": {
        "type": "counter", "labels": (),
        "help": "Open boosting windows settled mid-window at an "
                "observation point (eval/snapshot/rollback) by exact "
                "snapshot replay"},
    "lgbm_ingest_rows_total": {
        "type": "counter", "labels": ("mode",),
        "help": "Rows parsed by ingest, mode=full_parse/tail_append/"
                "binary_cache/file_parse"},
    "lgbm_ingest_seconds": {
        "type": "histogram", "labels": (),
        "help": "Wall time of one ingest pass (parse or cache load)"},
    "lgbm_ingest_window_rows": {
        "type": "gauge", "labels": (),
        "help": "Rows currently staged in the online rolling window"},
    "lgbm_online_cycles_total": {
        "type": "counter", "labels": ("status",),
        "help": "Continuous-training cycles, status=ok/timeout/"
                "quarantine/gate_reject"},
    "lgbm_online_publish_seconds": {
        "type": "histogram", "labels": (),
        "help": "Atomic model publish latency per cycle"},
    "lgbm_serve_latency_seconds": {
        "type": "histogram", "labels": ("model",),
        "help": "Per-request serving latency, admission to completion "
                "(drives BENCH_SERVE's p50/p99)"},
    "lgbm_serve_requests_total": {
        "type": "counter", "labels": ("outcome",),
        "help": "Serving requests by outcome: completed, or the shed "
                "reason (queue_full/deadline_exceeded/no_model/shutdown)"},
    "lgbm_serve_rows_total": {
        "type": "counter", "labels": (),
        "help": "Feature rows served (completed requests only)"},
    "lgbm_serve_batches_total": {
        "type": "counter", "labels": ("path",),
        "help": "Micro-batches served, path=device/host (host = degraded)"},
    "lgbm_serve_queue_depth": {
        "type": "gauge", "labels": (),
        "help": "Admission queue depth sampled at the last submit/batch"},
    "lgbm_serve_swaps_total": {
        "type": "counter", "labels": (),
        "help": "Hot model swaps (new generation loaded + prewarmed)"},
    "lgbm_serve_degradations_total": {
        "type": "counter", "labels": (),
        "help": "Circuit-breaker trips device->host"},
    "lgbm_serve_recoveries_total": {
        "type": "counter", "labels": (),
        "help": "Probe-based recoveries host->device"},
    "lgbm_serve_bytes_total": {
        "type": "counter", "labels": ("path", "dir"),
        "help": "Binary wire-plane bytes moved (headers + payloads), "
                "path=tcp/uds/shm, dir=rx/tx"},
    "lgbm_shm_sessions_total": {
        "type": "counter", "labels": ("event",),
        "help": "SHM ring sessions by lifecycle event: ready/closed/"
                "reclaimed (peer died with work in flight)/torn "
                "(protocol violation)/rejected_setup/leaked"},
    "lgbm_shm_frames_total": {
        "type": "counter", "labels": ("outcome",),
        "help": "SHM ring frames by outcome: completed/rejected/"
                "bad_crc (rejected in place, counters stay in sync)"},
    "lgbm_shm_doorbell_syscalls_total": {
        "type": "counter", "labels": ("op",),
        "help": "Every syscall the ring doorbell makes, op=ring (wake "
                "peer)/wait (poll)/drain (eventfd read) — zero in the "
                "spin-hot steady state, which BENCH_WIRE measures"},
    "lgbm_serve_frames_total": {
        "type": "counter", "labels": ("outcome",),
        "help": "Binary wire frames by outcome: completed/rejected or "
                "the torn-frame class (truncated_header/short_payload/"
                "bad_crc/bad_magic/bad_version/bad_dtype/oversized)"},
    "lgbm_span_seconds": {
        "type": "histogram", "labels": ("span",),
        "help": "Named span durations (watchdog stage closes land here; "
                "digit runs in names normalized to N)"},
    "lgbm_spans_total": {
        "type": "counter", "labels": ("span", "status"),
        "help": "Span completions by status=ok/error/timeout"},
    "lgbm_xla_compiles_total": {
        "type": "counter", "labels": ("site",), "max_label_sets": 256,
        "help": "XLA traces/compiles per registered jit site "
                "(runtime/xla_obs.py ledger)"},
    "lgbm_xla_compile_seconds": {
        "type": "histogram", "labels": ("site",), "max_label_sets": 256,
        "help": "Wall time of the call that triggered each trace "
                "(trace + compile + first run)"},
    "lgbm_xla_retraces_total": {
        "type": "counter", "labels": ("site", "delta"),
        "max_label_sets": 256,
        "help": "Steady-state retraces (after xla_obs.mark_steady), "
                "labeled with the shape delta that triggered them"},
    "lgbm_program_cache_events_total": {
        "type": "counter", "labels": ("site", "event"),
        "max_label_sets": 256,
        "help": "Program-cache traffic per site: event=hit/compile for "
                "jit sites, hit/miss/evict for the python-side caches"},
    "lgbm_serve_class_requests_total": {
        "type": "counter", "labels": ("cls", "outcome"),
        "help": "Serving requests by priority class (cls=p0 highest..pN "
                "lowest) and outcome: completed or the machine-readable "
                "shed reason (queue_full/load_shed/quota_exceeded/...)"},
    "lgbm_serve_staleness_seconds": {
        "type": "histogram", "labels": ("model",),
        "help": "Age of the serving generation at batch completion "
                "(now minus its publish stamp) - the model-staleness "
                "distribution the production sim reports"},
    "lgbm_policy_decisions_total": {
        "type": "counter", "labels": ("action",),
        "help": "Autoscale/shed policy transitions, action=widen/narrow/"
                "shed_on/shed_off (runtime/policy.py hysteresis "
                "controller)"},
    "lgbm_policy_window_seconds": {
        "type": "gauge", "labels": (),
        "help": "Current micro-batch gather window the policy controller "
                "has set on the serving runtime"},
    "lgbm_policy_shed_active": {
        "type": "gauge", "labels": (),
        "help": "1 while the policy holds the lowest priority class in "
                "load-shed mode, else 0"},
    "lgbm_loadgen_offered_total": {
        "type": "counter", "labels": ("cls",),
        "help": "Requests the load generator offered (open-loop "
                "arrivals), by priority class - the shed-rate "
                "denominator the sim artifact scrapes"},
    "lgbm_loadgen_verified_total": {
        "type": "counter", "labels": ("result",),
        "help": "Load-generator response verifications, result=ok/"
                "wrong_generation/mismatch/unverifiable (byte-identity "
                "vs the offline predictor for the reported generation)"},
    "lgbm_ingest_quarantined_total": {
        "type": "counter", "labels": ("reason",),
        "help": "Rows the ingest quarantine dropped before they could "
                "reach a training window, reason=nonfinite_label/"
                "nonfinite_weight/bad_query_id/column_drift "
                "(runtime/quality.py firewall stage one)"},
    "lgbm_publish_gate_total": {
        "type": "counter", "labels": ("verdict",),
        "help": "Pre-publish eval-gate decisions per cycle, verdict="
                "pass/reject/no_incumbent/no_metric/disabled (firewall "
                "stage two; a reject persists the rejected model next "
                "to the publish dir)"},
    "lgbm_canary_events_total": {
        "type": "counter", "labels": ("event",),
        "help": "Canary lifecycle events, event=start/promote/rollback "
                "(runtime/policy.CanaryPolicy; rollback also writes the "
                "durable ROLLBACK marker in the publish dir)"},
    "lgbm_canary_batches_total": {
        "type": "counter", "labels": ("kind",),
        "help": "Serving micro-batches routed while a canary window is "
                "open, kind=canary/incumbent (the canary-fraction "
                "accounting the chaos artifact scrapes)"},
    "lgbm_warmup_total": {
        "type": "counter", "labels": ("kind", "outcome"),
        "help": "Prewarm attempts by role (kind=serving/train_online) "
                "and outcome: manifest_ok, or the degradation to the "
                "legacy prewarm (manifest_missing/manifest_torn/"
                "manifest_stale/manifest_invalid/shape_mismatch/error) "
                "(runtime/warmup.py)"},
    "lgbm_warmup_seconds": {
        "type": "histogram", "labels": ("kind",),
        "help": "Wall time of one prewarm pass (manifest read + bucket "
                "precompiles before readiness opens)"},
    "lgbm_compile_cache_events_total": {
        "type": "counter", "labels": ("event",),
        "help": "Persistent XLA compilation-cache traffic, event=hit "
                "(compile loaded from disk)/miss (fresh compile wrote an "
                "entry)/evict (LRU sweep past the size budget) "
                "(runtime/warmup.py seam over jax_compilation_cache_dir)"},
    "lgbm_fleet_replicas": {
        "type": "gauge", "labels": ("state",),
        "help": "Serving replica processes as the fleet controller sees "
                "them, state=target/alive/ready (runtime/fleet.py "
                "control loop)"},
    "lgbm_fleet_scale_events_total": {
        "type": "counter", "labels": ("action",),
        "help": "Fleet controller actions applied, action=spawn/retire/"
                "relaunch/shed_on/shed_off (scale decisions come from "
                "runtime/policy.FleetScalePolicy)"},
    "lgbm_fleet_reaction_seconds": {
        "type": "histogram", "labels": (),
        "help": "Scale-up reaction time: first SLO-breach sample of a "
                "pressure streak to the first scrape with windowed p99 "
                "back under the SLO (the ISSUE 17 acceptance number)"},
    "lgbm_serve_resident_models": {
        "type": "gauge", "labels": (),
        "help": "Model entries currently loaded in this serving runtime "
                "(bounded by max_resident when the model-zoo residency "
                "manager is on)"},
    "lgbm_serve_residency_events_total": {
        "type": "counter", "labels": ("event",),
        "help": "Model-zoo residency transitions, event=page_in (tenant "
                "loaded on demand)/evict (LRU victim dropped, manifest "
                "exported)/defer (every resident model busy; page-in "
                "retries next poll)"},
}

# ---------------------------------------------------------------------------
# enable flag (the hot-loop gate)
# ---------------------------------------------------------------------------

_enabled = True


def set_enabled(on: bool) -> bool:
    """Flip the whole subsystem; returns the previous state.  Disabled,
    every instrument call is one global read + an early return."""
    global _enabled
    prev = _enabled
    _enabled = bool(on)
    return prev


def enabled() -> bool:
    return _enabled


# ---------------------------------------------------------------------------
# instruments
# ---------------------------------------------------------------------------

class _Family:
    """One metric family: name + label names + children per label set.
    Children are created lazily under the lock; past `max_label_sets`
    distinct sets, the overflow child absorbs new ones."""

    kind = "untyped"

    def __init__(self, name: str, help_: str, labels: Tuple[str, ...],
                 max_label_sets: int, registry: "MetricsRegistry",
                 buckets: Tuple[float, ...] = LATENCY_BUCKETS_S):
        self.name = name
        self.help = help_
        self.label_names = tuple(labels)
        self.max_label_sets = max_label_sets
        self._registry = registry
        self._buckets = buckets
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], Any] = {}

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        if set(labels) != set(self.label_names):
            raise ValueError(
                "metric %s takes labels %r, got %r"
                % (self.name, self.label_names, tuple(labels)))
        return tuple(str(labels[n]) for n in self.label_names)

    def _child(self, labels: Dict[str, str]):
        key = self._key(labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                if (len(self._children) >= self.max_label_sets
                        and self.label_names):
                    key = (OVERFLOW_LABEL,) * len(self.label_names)
                    child = self._children.get(key)
                    if child is not None:
                        return child
                child = self._new_child()
                self._children[key] = child
            return child

    def _new_child(self):
        raise NotImplementedError

    def items(self) -> List[Tuple[Tuple[str, ...], Any]]:
        with self._lock:
            return sorted(self._children.items())

    def clear(self) -> None:
        with self._lock:
            self._children.clear()


class _CounterChild:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0


class Counter(_Family):
    kind = "counter"

    def _new_child(self) -> _CounterChild:
        return _CounterChild()

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        if not _enabled:
            return
        child = self._child(labels)
        with self._lock:
            child.value += amount
            self._registry.ops += 1

    def value(self, **labels: str) -> float:
        child = self._children.get(self._key(labels))
        return child.value if child is not None else 0.0

    def total(self) -> float:
        with self._lock:
            return sum(c.value for c in self._children.values())


class _GaugeChild:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0


class Gauge(_Family):
    kind = "gauge"

    def _new_child(self) -> _GaugeChild:
        return _GaugeChild()

    def set(self, value: float, **labels: str) -> None:
        if not _enabled:
            return
        child = self._child(labels)
        with self._lock:
            child.value = float(value)
            self._registry.ops += 1

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        if not _enabled:
            return
        child = self._child(labels)
        with self._lock:
            child.value += amount
            self._registry.ops += 1

    def value(self, **labels: str) -> float:
        child = self._children.get(self._key(labels))
        return child.value if child is not None else 0.0


class _HistChild:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets     # per-bucket (non-cumulative)
        self.sum = 0.0
        self.count = 0


class Histogram(_Family):
    """Bounded-memory streaming histogram: one int per fixed bucket plus
    sum/count.  `quantile(q)` is exact to within one bucket width —
    inside the resolved bucket it interpolates linearly (the Prometheus
    ``histogram_quantile`` rule), and values past the largest finite
    edge report that edge."""

    kind = "histogram"

    def _new_child(self) -> _HistChild:
        return _HistChild(len(self._buckets))

    @property
    def buckets(self) -> Tuple[float, ...]:
        return self._buckets

    def observe(self, value: float, **labels: str) -> None:
        if not _enabled:
            return
        child = self._child(labels)
        i = 0
        b = self._buckets
        while value > b[i]:               # last bucket is +Inf: always stops
            i += 1
        with self._lock:
            child.counts[i] += 1
            child.sum += value
            child.count += 1
            self._registry.ops += 1

    # -- read side -----------------------------------------------------------
    def state(self, **labels: str) -> Dict[str, Any]:
        """Aggregated (counts, sum, count) — over ALL label sets when no
        labels are given.  A copyable snapshot: diff two of these to
        scope quantiles to a measurement window (bench does)."""
        with self._lock:
            if labels:
                child = self._children.get(self._key(labels))
                children = [child] if child is not None else []
            else:
                children = list(self._children.values())
            counts = [0] * len(self._buckets)
            total, cnt = 0.0, 0
            for c in children:
                for i, v in enumerate(c.counts):
                    counts[i] += v
                total += c.sum
                cnt += c.count
        return {"buckets": list(self._buckets), "counts": counts,
                "sum": total, "count": cnt}

    def quantile(self, q: float, state: Optional[Dict[str, Any]] = None,
                 **labels: str) -> Optional[float]:
        st = state if state is not None else self.state(**labels)
        return quantile_from_state(st, q)

    def bucket_width_at(self, value: float) -> float:
        """Width of the bucket `value` falls in — the quantile error
        bound at that point (the +Inf bucket reports the last finite
        width)."""
        b = self._buckets
        i = 0
        while value > b[i]:
            i += 1
        if math.isinf(b[i]):
            i = len(b) - 2
        lo = b[i - 1] if i > 0 else 0.0
        return b[i] - lo


def state_delta(after: Dict[str, Any], before: Dict[str, Any]
                ) -> Dict[str, Any]:
    """Histogram movement between two `Histogram.state()` snapshots."""
    return {
        "buckets": list(after["buckets"]),
        "counts": [a - b for a, b in zip(after["counts"], before["counts"])],
        "sum": after["sum"] - before["sum"],
        "count": after["count"] - before["count"],
    }


def quantile_from_state(state: Dict[str, Any], q: float) -> Optional[float]:
    """The q-quantile of a histogram state (None when empty): resolve
    the bucket holding rank q*count, interpolate linearly inside it."""
    count = state["count"]
    if count <= 0:
        return None
    rank = q * count
    b = state["buckets"]
    seen = 0
    for i, c in enumerate(state["counts"]):
        if seen + c >= rank and c > 0:
            lo = b[i - 1] if i > 0 else 0.0
            hi = b[i]
            if math.isinf(hi):
                return lo if i > 0 else None
            frac = (rank - seen) / c
            return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
        seen += c
    # rank beyond the recorded mass (q=1.0 edge): largest finite edge hit
    for i in range(len(b) - 1, -1, -1):
        if state["counts"][i] > 0:
            return b[i] if not math.isinf(b[i]) else b[i - 1]
    return None


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

class MetricsRegistry:
    """Name -> instrument map over a declaration table.  Undeclared
    names raise — the docs drift lint is only complete if every product
    metric is table-declared."""

    _KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}

    def __init__(self, table: Optional[Dict[str, Dict[str, Any]]] = None,
                 max_label_sets: int = 64):
        self.table = METRIC_TABLE if table is None else table
        self.max_label_sets = int(max_label_sets)
        self.ops = 0                       # recorded-op count (bench A/B)
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    def _family(self, name: str, kind: str) -> _Family:
        fam = self._families.get(name)
        if fam is not None:
            if fam.kind != kind:
                raise ValueError("metric %s is a %s, not a %s"
                                 % (name, fam.kind, kind))
            return fam
        decl = self.table.get(name)
        if decl is None:
            raise KeyError(
                "metric %r is not declared in METRIC_TABLE — declare it "
                "(and document it in docs/OBSERVABILITY.md) first" % name)
        if decl["type"] != kind:
            raise ValueError("metric %s is declared as a %s, not a %s"
                             % (name, decl["type"], kind))
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._KINDS[kind](
                    name, decl["help"], tuple(decl["labels"]),
                    # per-family override: the xla ledger families carry
                    # one label set per jit site x event, more than the
                    # default bound
                    int(decl.get("max_label_sets", self.max_label_sets)),
                    self,
                    buckets=tuple(decl.get("buckets", LATENCY_BUCKETS_S)))
                self._families[name] = fam
        return fam

    def counter(self, name: str) -> Counter:
        return self._family(name, "counter")            # type: ignore

    def gauge(self, name: str) -> Gauge:
        return self._family(name, "gauge")              # type: ignore

    def histogram(self, name: str) -> Histogram:
        return self._family(name, "histogram")          # type: ignore

    def families(self) -> List[_Family]:
        with self._lock:
            return [self._families[n] for n in sorted(self._families)]

    def reset(self) -> None:
        """Drop every recorded value (tests / bench sections).  The
        declaration table is untouched."""
        with self._lock:
            fams = list(self._families.values())
            self.ops = 0
        for fam in fams:
            fam.clear()

    # -- export --------------------------------------------------------------
    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        out: List[str] = []
        for fam in self.families():
            out.append("# HELP %s %s" % (fam.name, _esc_help(fam.help)))
            out.append("# TYPE %s %s" % (fam.name, fam.kind))
            for key, child in fam.items():
                lbl = _label_str(fam.label_names, key)
                if fam.kind == "histogram":
                    cum = 0
                    for i, edge in enumerate(fam.buckets):   # type: ignore
                        cum += child.counts[i]
                        le = "+Inf" if math.isinf(edge) else _fmt(edge)
                        out.append('%s_bucket%s %d' % (
                            fam.name,
                            _label_str(fam.label_names + ("le",),
                                       key + (le,), raw_last=True), cum))
                    out.append("%s_sum%s %s" % (fam.name, lbl,
                                                _fmt(child.sum)))
                    out.append("%s_count%s %d" % (fam.name, lbl,
                                                  child.count))
                else:
                    out.append("%s%s %s" % (fam.name, lbl,
                                            _fmt(child.value)))
        return "\n".join(out) + "\n"

    def snapshot(self, context: Optional[str] = None) -> Dict[str, Any]:
        """JSON-able dump of everything recorded (one snapshot-file line)."""
        metrics: Dict[str, Any] = {}
        for fam in self.families():
            series = []
            for key, child in fam.items():
                entry: Dict[str, Any] = {
                    "labels": dict(zip(fam.label_names, key))}
                if fam.kind == "histogram":
                    entry.update({
                        "count": child.count, "sum": round(child.sum, 9),
                        "counts": list(child.counts)})
                    for qn, q in (("p50", 0.5), ("p95", 0.95),
                                  ("p99", 0.99)):
                        v = quantile_from_state(
                            {"buckets": fam.buckets,      # type: ignore
                             "counts": child.counts, "sum": child.sum,
                             "count": child.count}, q)
                        entry[qn] = None if v is None else round(v, 9)
                else:
                    entry["value"] = child.value
                series.append(entry)
            metrics[fam.name] = {"type": fam.kind, "series": series}
        snap = {"wallclock": wallclock(), "pid": os.getpid(),
                "metrics": metrics}
        if context:
            snap["context"] = context
        return snap


def _esc_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _esc_label(s: str) -> str:
    return (s.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _label_str(names: Tuple[str, ...], values: Tuple[str, ...],
               raw_last: bool = False) -> str:
    if not names:
        return ""
    parts = []
    for i, (n, v) in enumerate(zip(names, values)):
        if raw_last and i == len(names) - 1:
            parts.append('%s="%s"' % (n, v))
        else:
            parts.append('%s="%s"' % (n, _esc_label(v)))
    return "{%s}" % ",".join(parts)


#: the process-global registry every product instrument records into
REGISTRY = MetricsRegistry()


def counter(name: str) -> Counter:
    return REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return REGISTRY.gauge(name)


def histogram(name: str) -> Histogram:
    return REGISTRY.histogram(name)


def snapshot(context: Optional[str] = None) -> Dict[str, Any]:
    return REGISTRY.snapshot(context)


def render_prometheus() -> str:
    return REGISTRY.render_prometheus()


def reset() -> None:
    REGISTRY.reset()


# ---------------------------------------------------------------------------
# mesh-wide aggregation (ISSUE 10): per-process registries gather to
# process 0 over the jax collective seam; merged series carry a {host}
# label so a multi-host scrape/snapshot attributes every number
# ---------------------------------------------------------------------------

def mesh_process_count() -> int:
    """Process count of the multi-host run this process is part of —
    WITHOUT ever initializing a backend.  `jax.process_count()` binds
    the platform when called on an un-initialized jax, which on a dead
    accelerator tunnel hangs the caller (a metrics flush must never be
    the thing that wedges a run); multi-host runs always bring
    `jax.distributed` up first, so its client state is the safe probe."""
    jax = sys.modules.get("jax")
    if jax is None:
        return 1
    try:
        from jax._src import distributed
        state = distributed.global_state
        if getattr(state, "client", None) is None:
            return 1
        return max(int(getattr(state, "num_processes", 1) or 1), 1)
    except Exception:    # noqa: BLE001 — jax internals moved: stay local
        return 1


def gather_host_snapshots(context: Optional[str] = None,
                          registry: Optional[MetricsRegistry] = None
                          ) -> Dict[str, Dict[str, Any]]:
    """{host_index: snapshot} across every process of a multi-host run.

    Single-process (or jax not distributed-initialized — this function
    must never INITIALIZE a platform, see `mesh_process_count`) degrades
    to the local snapshot under host "0".  Multi-process, snapshots
    travel as length-prefixed JSON blobs through `process_allgather` —
    the same collective seam the mesh learners ride — so every process
    returns the full map and process 0 can export it."""
    reg = registry if registry is not None else REGISTRY
    local = reg.snapshot(context)
    if mesh_process_count() <= 1:
        return {"0": local}
    jax = sys.modules.get("jax")
    try:
        nproc = jax.process_count()
        if nproc <= 1:
            return {str(jax.process_index()): local}
        import numpy as np
        from jax.experimental import multihost_utils as mhu
        blob = np.frombuffer(json.dumps(local).encode("utf-8"), np.uint8)
        lens = np.asarray(mhu.process_allgather(
            np.array([blob.size], np.int32))).reshape(-1)
        buf = np.zeros(int(lens.max()), np.uint8)
        buf[:blob.size] = blob
        gathered = np.asarray(mhu.process_allgather(buf))
        out: Dict[str, Dict[str, Any]] = {}
        for p in range(nproc):
            raw = bytes(gathered[p][:int(lens[p])])
            out[str(p)] = json.loads(raw.decode("utf-8"))
        return out
    except Exception:   # noqa: BLE001 — observability must not take it down
        return {str(getattr(jax, "process_index", lambda: 0)()): local}


def merge_host_snapshots(hosts: Dict[str, Dict[str, Any]]
                         ) -> Dict[str, Any]:
    """One combined snapshot: every series of every host, with a
    ``host`` label prepended — the artifact a multi-host dryrun ships
    and the view a process-0 /metrics scrape serves."""
    merged_metrics: Dict[str, Any] = {}
    for host in sorted(hosts, key=lambda h: (len(h), h)):
        snap = hosts[host]
        for name, fam in snap.get("metrics", {}).items():
            slot = merged_metrics.setdefault(
                name, {"type": fam["type"], "series": []})
            for entry in fam["series"]:
                e = dict(entry)
                e["labels"] = dict({"host": host}, **entry.get("labels", {}))
                slot["series"].append(e)
    return {"wallclock": wallclock(), "hosts": sorted(hosts),
            "metrics": merged_metrics}


def mesh_snapshot(context: Optional[str] = None,
                  registry: Optional[MetricsRegistry] = None
                  ) -> Dict[str, Any]:
    """Gather + merge in one call (every process gets the merged view)."""
    return merge_host_snapshots(gather_host_snapshots(context, registry))


def render_prometheus_from_snapshot(snap: Dict[str, Any],
                                    table: Optional[Dict[str, Any]] = None
                                    ) -> str:
    """Prometheus text exposition from a (possibly merged, {host}-
    labeled) snapshot dict.  Histogram bucket edges come from the
    METRIC_TABLE declaration (all product histograms ride the one fixed
    layout); unknown names fall back to `LATENCY_BUCKETS_S`."""
    table = METRIC_TABLE if table is None else table
    out: List[str] = []
    for name in sorted(snap.get("metrics", {})):
        fam = snap["metrics"][name]
        decl = table.get(name, {})
        out.append("# HELP %s %s" % (name, _esc_help(
            decl.get("help", "(undeclared)"))))
        out.append("# TYPE %s %s" % (name, fam["type"]))
        for entry in fam["series"]:
            labels = entry.get("labels", {})
            names = tuple(labels)
            values = tuple(str(labels[k]) for k in names)
            lbl = _label_str(names, values)
            if fam["type"] == "histogram":
                edges = tuple(decl.get("buckets", LATENCY_BUCKETS_S))
                cum = 0
                for i, edge in enumerate(edges):
                    cum += entry["counts"][i] \
                        if i < len(entry.get("counts", [])) else 0
                    le = "+Inf" if math.isinf(edge) else _fmt(edge)
                    out.append("%s_bucket%s %d" % (
                        name, _label_str(names + ("le",), values + (le,),
                                         raw_last=True), cum))
                out.append("%s_sum%s %s" % (name, lbl, _fmt(entry["sum"])))
                out.append("%s_count%s %d" % (name, lbl, entry["count"]))
            else:
                out.append("%s%s %s" % (name, lbl, _fmt(entry["value"])))
    return "\n".join(out) + "\n"


def count_sync(label: str, critical: bool) -> None:
    """Sync-audit bridge (called by runtime/syncs.record for every
    blocking host fetch)."""
    if not _enabled:
        return
    REGISTRY.counter("lgbm_host_syncs_total").inc(label=label)
    if critical:
        REGISTRY.counter("lgbm_host_syncs_critical_total").inc(label=label)


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

_DIGITS = re.compile(r"\d+")

#: ``key=<digits>`` pairs whose digits SURVIVE normalization: these are
#: bounded product parameters (the boost-window length, the pipeline
#: depth) whose value IS the series identity — collapsing them merged
#: e.g. the J=2 and J=4 window-dispatch stages into one metric series
#: (ISSUE 14 satellite).  Unbounded identifiers (cycle/gen/rows counts)
#: stay normalized: only keys listed here escape, so cardinality stays
#: bounded by the small set of legal values those knobs take.
SPAN_KEEP_KEYS: Tuple[str, ...] = ("J", "depth", "window", "K")

#: one alternation, tried left to right: a ``key=value`` token for an
#: allowlisted key is consumed whole (and kept verbatim); any other
#: digit run collapses to ``N``.
_NORM = re.compile(r"\b(?:%s)=\d{1,4}\b|\d+" % "|".join(SPAN_KEEP_KEYS))


def normalize_span_name(name: str, max_len: int = 80) -> str:
    """Digit runs -> ``N`` and a hard length cap, so per-cycle /
    per-batch stage names ("cycle 17: train", "batch ... rows=512")
    collapse to a bounded family of span names — EXCEPT ``key=value``
    digits for the `SPAN_KEEP_KEYS` product parameters, which stay
    distinguishable ("window dispatch J=4" vs "J=2" are different
    stages, not two samples of one)."""
    return _NORM.sub(lambda m: m.group(0) if "=" in m.group(0) else "N",
                     name)[:max_len]


def record_span(name: str, dur_s: float, status: str = "ok",
                trace: bool = True) -> None:
    """One completed span on the shared clock.  The stage-trail watchdog
    calls this at every stage close.  The RAW name also lands in the
    trace flight recorder (`trace=False` for callers that already
    recorded the trace event themselves — the `span` context manager)."""
    if not _enabled:
        return
    key = normalize_span_name(name)
    REGISTRY.histogram("lgbm_span_seconds").observe(max(dur_s, 0.0),
                                                    span=key)
    REGISTRY.counter("lgbm_spans_total").inc(span=key, status=status)
    if trace:
        now = time.monotonic_ns()
        dur_ns = int(max(dur_s, 0.0) * 1e9)
        tracing.record(name, now - dur_ns, dur_ns, status=status)


@contextlib.contextmanager
def span(name: str):
    """Context-manager span: records duration + ok/error status into the
    registry AND opens a causal trace span (children recorded inside the
    scope parent under it; ISSUE 14)."""
    t0 = time.monotonic()
    try:
        with tracing.span(name):
            yield
    except BaseException:
        record_span(name, time.monotonic() - t0, status="error",
                    trace=False)
        raise
    record_span(name, time.monotonic() - t0, status="ok", trace=False)


# ---------------------------------------------------------------------------
# per-iteration training instrumentation (the Booster.update seam)
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def train_iteration():
    """Wraps one boosting iteration: wall time into the iteration
    histogram, the iteration counter, the per-iteration sync-audit
    gauges (total + critical path), and the training profiler hook."""
    if not _enabled:
        yield
        return
    from . import syncs
    profile_hook("train").tick()
    s0 = syncs.snapshot()
    t0 = time.monotonic()
    # one causal slice per boosting iteration: dispatch marks and the
    # assembler drain hand-off recorded inside parent under it
    with tracing.span("train/iteration"):
        yield
    dt = time.monotonic() - t0
    d = syncs.delta(s0)
    REGISTRY.histogram("lgbm_train_iteration_seconds").observe(dt)
    REGISTRY.counter("lgbm_train_iterations_total").inc()
    g = REGISTRY.gauge("lgbm_train_host_syncs_per_iter")
    g.set(d["total"], path="total")
    g.set(d["critical_path"], path="critical")


# ---------------------------------------------------------------------------
# HTTP exporter (GET /metrics)
# ---------------------------------------------------------------------------

class MetricsServer:
    """Prometheus scrape endpoint over the stdlib HTTP server.  Serves
    ``/metrics`` (text exposition), ``/metrics.json`` (snapshot) and
    ``/healthz``; runs on a daemon thread, `stop()` shuts it down."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 registry: Optional[MetricsRegistry] = None,
                 snapshot_provider: Optional[Any] = None,
                 health_provider: Optional[Any] = None):
        """`snapshot_provider`: optional zero-arg callable returning a
        snapshot dict (e.g. `mesh_snapshot` on process 0 of a multi-host
        run) — when given, /metrics and /metrics.json serve ITS view
        (with {host} labels) instead of the local registry.

        `health_provider`: optional zero-arg callable; while it returns
        falsy, ``/healthz`` answers 503 ``warming`` instead of 200
        ``ok`` — the serving runtime's prewarm-before-admit readiness
        gate (ISSUE 15): a load balancer never routes to a replica that
        would pay a compile on its first real batch."""
        import http.server

        reg = registry if registry is not None else REGISTRY

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self) -> None:            # noqa: N802 — stdlib API
                path = self.path.split("?", 1)[0]
                status = 200
                if path == "/metrics":
                    if snapshot_provider is not None:
                        body = render_prometheus_from_snapshot(
                            snapshot_provider()).encode("utf-8")
                    else:
                        body = reg.render_prometheus().encode("utf-8")
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif path == "/metrics.json":
                    snap = (snapshot_provider() if snapshot_provider
                            is not None else reg.snapshot())
                    body = (json.dumps(snap) + "\n").encode("utf-8")
                    ctype = "application/json"
                elif path == "/healthz":
                    healthy = True
                    if health_provider is not None:
                        try:
                            healthy = bool(health_provider())
                        except Exception:   # noqa: BLE001 — gate, not crash
                            healthy = False
                    body = b"ok\n" if healthy else b"warming\n"
                    status = 200 if healthy else 503
                    ctype = "text/plain"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args) -> None:
                pass                              # scrapes are not stderr news

        class _Server(http.server.ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self.registry = reg
        self._httpd = _Server((host, int(port)), _Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.2},
            name="lgbm-metrics-http", daemon=True)
        self._thread.start()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


def start_http_server(port: int = 0, host: str = "127.0.0.1",
                      registry: Optional[MetricsRegistry] = None,
                      health_provider: Optional[Any] = None
                      ) -> MetricsServer:
    return MetricsServer(port=port, host=host, registry=registry,
                         health_provider=health_provider)


# ---------------------------------------------------------------------------
# JSON-lines snapshot file ($LGBM_TPU_METRICS_FILE)
# ---------------------------------------------------------------------------

METRICS_FILE_ENV = "LGBM_TPU_METRICS_FILE"
METRICS_INTERVAL_ENV = "LGBM_TPU_METRICS_INTERVAL"

#: snapshot lines kept per file (the file is a rolling window, not an
#: unbounded log; each flush rewrites it atomically)
SNAPSHOT_KEEP_LAST = 256


class MetricsFileWriter:
    """Periodic atomic JSON-lines snapshots for batch runs.  Every flush
    rewrites the WHOLE file via tmp+fsync+rename (`atomic_write`), so a
    concurrent scraper reads either the previous window or the new one,
    never a torn line — plain append could tear mid-line."""

    def __init__(self, path: str, interval_s: float = 30.0,
                 context: Optional[str] = None,
                 registry: Optional[MetricsRegistry] = None):
        self.path = path
        self.interval_s = float(interval_s)
        self.context = context
        self.registry = registry if registry is not None else REGISTRY
        self._lines: "collections.deque[str]" = collections.deque(
            maxlen=SNAPSHOT_KEEP_LAST)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if self.interval_s > 0:
            self._thread = threading.Thread(target=self._loop,
                                            name="lgbm-metrics-file",
                                            daemon=True)
            self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.write_now()
            except OSError:
                pass                    # export must never take the run down

    def write_now(self, context: Optional[str] = None) -> None:
        """Append one snapshot line and atomically rewrite the file.  On
        a multi-host run (jax already up, process_count > 1) the line is
        the MERGED mesh snapshot with {host}-labeled series — process 0
        ships the whole mesh's numbers in its file."""
        if mesh_process_count() > 1:
            snap = mesh_snapshot(context or self.context, self.registry)
        else:
            snap = self.registry.snapshot(context or self.context)
        with self._lock:
            self._lines.append(json.dumps(snap))
            atomic_write(self.path, "\n".join(self._lines) + "\n")

    def stop(self, final_flush: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if final_flush:
            try:
                self.write_now()
            except OSError:
                pass


_file_writer: Optional[MetricsFileWriter] = None
_file_writer_lock = threading.Lock()


def maybe_start_file_export(context: Optional[str] = None
                            ) -> Optional[MetricsFileWriter]:
    """Start (once per process) the periodic snapshot writer when
    ``$LGBM_TPU_METRICS_FILE`` is set; interval from
    ``$LGBM_TPU_METRICS_INTERVAL`` (seconds, default 30).  Returns the
    writer, or None when the env var is unset."""
    global _file_writer
    path = os.environ.get(METRICS_FILE_ENV)
    if not path:
        return None
    with _file_writer_lock:
        if _file_writer is None or _file_writer.path != path:
            interval = float(os.environ.get(METRICS_INTERVAL_ENV, "30"))
            _file_writer = MetricsFileWriter(path, interval_s=interval,
                                             context=context)
    return _file_writer


def write_snapshot_now(context: Optional[str] = None) -> Optional[str]:
    """One-shot snapshot flush (CLI/bench exit paths): writes through
    the active writer, creating one (interval 0 = no background thread)
    if the env var is set and none exists.  Returns the path written."""
    writer = maybe_start_file_export(context)
    if writer is None:
        return None
    writer.write_now(context)
    return writer.path


# ---------------------------------------------------------------------------
# device-profiler hook (LGBM_TPU_PROFILE=<dir>)
# ---------------------------------------------------------------------------

PROFILE_ENV = "LGBM_TPU_PROFILE"
PROFILE_ITERS_ENV = "LGBM_TPU_PROFILE_ITERS"
PROFILE_BATCHES_ENV = "LGBM_TPU_PROFILE_BATCHES"


class _ProfilerHook:
    """Wraps the first N ticks (training iterations or serving batches)
    of the process in ONE ``jax.profiler`` trace written under
    ``$LGBM_TPU_PROFILE/<kind>``.  One-shot per kind per process;
    anything raising inside the profiler disables the hook with a
    warning — profiling is diagnostics, never a crash source."""

    def __init__(self, kind: str, limit_env: str, default_limit: int):
        self.kind = kind
        self.dir = os.environ.get(PROFILE_ENV) or None
        self.limit = int(os.environ.get(limit_env, default_limit)) \
            if self.dir else 0
        self.ticks = 0
        self.active = False
        self.done = self.dir is None
        self._lock = threading.Lock()

    def tick(self) -> None:
        if self.done:
            return
        with self._lock:
            if self.done:
                return
            try:
                if not self.active:
                    import jax
                    out = os.path.join(self.dir, self.kind)
                    os.makedirs(out, exist_ok=True)
                    jax.profiler.start_trace(out)
                    self.active = True
                    sys.stderr.write(
                        "[%s] telemetry: jax.profiler trace started for "
                        "%d %s ticks -> %s\n"
                        % (wallclock(), self.limit, self.kind, out))
                self.ticks += 1
                if self.ticks >= self.limit:
                    import jax
                    jax.profiler.stop_trace()
                    self.active = False
                    self.done = True
                    sys.stderr.write(
                        "[%s] telemetry: jax.profiler trace closed after "
                        "%d %s ticks\n" % (wallclock(), self.ticks,
                                           self.kind))
            except Exception as e:       # noqa: BLE001 — diagnostics only
                self.done = True
                self.active = False
                sys.stderr.write(
                    "[%s] telemetry WARNING: profiler hook disabled "
                    "(%s: %s)\n" % (wallclock(), type(e).__name__, e))


_hooks: Dict[str, _ProfilerHook] = {}
_hooks_lock = threading.Lock()


def profile_hook(kind: str) -> _ProfilerHook:
    """The per-process profiler hook for `kind` ("train" ticks per
    boosting iteration, "serve" per device micro-batch)."""
    hook = _hooks.get(kind)
    if hook is None:
        with _hooks_lock:
            hook = _hooks.get(kind)
            if hook is None:
                env, dflt = ((PROFILE_ITERS_ENV, 5) if kind == "train"
                             else (PROFILE_BATCHES_ENV, 20))
                hook = _ProfilerHook(kind, env, dflt)
                _hooks[kind] = hook
    return hook


def _reset_profile_hooks() -> None:
    """Test seam: re-read the profiler environment."""
    with _hooks_lock:
        _hooks.clear()
