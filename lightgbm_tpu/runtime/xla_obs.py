"""Compile/retrace ledger: the XLA-side half of observability (ISSUE 10).

PR 9's telemetry sees host wall-clocks and sync counts but is blind to
where device time actually goes — XLA compiles, silent retraces and
program-cache misses are invisible, and on a tunneled TPU a single
unplanned retrace costs more than a whole training iteration.  This
module is the ONE seam every jit entry point in the codebase registers
through:

* **`xla_obs.jit(fn, site=..., **jax_jit_kwargs)`** — a drop-in
  replacement for ``jax.jit`` (same semantics: ``donate_argnums``,
  ``static_argnames``, ``__wrapped__`` exposing the unjitted function
  for inlining into outer traces).  Every call is classified as a
  program-cache *hit* or a *compile* (a trace of the wrapped function
  fired during the call), and every compile records its wall time, the
  triggering abstract shapes, and — after `mark_steady()` — the shape
  DELTA vs the site's previous trace, so a steady-state retrace names
  both the site and what changed.  ``helper/check_xla_sites.py`` lints
  that no raw ``jax.jit`` bypasses this seam.

* **`cache_event(site, event)`** — the same ledger for the python-side
  program caches (`_PACK_CACHE`, `_GROWER_CACHE`, the predictor's shape
  buckets): hit/miss/evict land in
  ``lgbm_program_cache_events_total{site,event}``.

* **The steady-state zero-retrace pin** — `snapshot()` / `delta()` let
  a test (or BENCH_ATTRIB) assert that after warmup, N further training
  iterations and M further serving batches compile NOTHING; a violation
  is a named `retraces` entry carrying site + shape delta
  (``lgbm_xla_retraces_total{site,delta}``).

* **Cost capture** (`set_cost_capture(True)`, opt-in: it lowers and
  compiles once more per new shape signature) — per-site
  ``cost_analysis()`` (FLOPs / bytes accessed) captured at compile
  time, folded into BENCH_ATTRIB and the doctor bundle.

Metrics ride the PR 9 registry (`lgbm_xla_compiles_total{site}`,
``lgbm_xla_compile_seconds{site}``, the cache/retrace families above);
the ledger itself is pure host bookkeeping — with telemetry disabled
the per-call cost is two clock reads and a list check.

No jax / numpy at module scope — jax loads lazily inside `jit()`, so
the hermetic dryrun bootstrap can import this.
"""
from __future__ import annotations

import collections
import functools
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import telemetry, tracing
from .resilience import wallclock

__all__ = [
    "jit", "LEDGER", "CompileLedger", "cache_event", "mark_steady",
    "set_cost_capture", "snapshot", "delta", "total_compiles", "reset",
    "calls_snapshot", "calls_delta", "total_calls",
    "set_compile_observer",
]

#: compile-history entries kept per site (bounded: the ledger lives for
#: the whole process)
HISTORY_PER_SITE = 32

#: hard cap on shape-signature / delta strings (they become metric label
#: values and bundle JSON)
SIG_MAX_CHARS = 160


def _aval_str(x: Any) -> str:
    """Compact dtype[shape] of one argument leaf; static/python values
    render as their type name (their CHANGE still shows in the delta)."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        short = str(dtype)
        short = {"float32": "f32", "float64": "f64", "int32": "i32",
                 "int64": "i64", "uint8": "u8", "uint16": "u16",
                 "uint32": "u32", "int8": "i8", "int16": "i16",
                 "bool": "b1", "bfloat16": "bf16"}.get(short, short)
        return "%s[%s]" % (short, ",".join(str(d) for d in shape))
    if isinstance(x, (bool, int, float, str)):
        return repr(x)[:24]
    return type(x).__name__


def _signature(args: tuple, kwargs: dict) -> Tuple[str, ...]:
    """Abstract signature of a call: one entry per argument leaf.  Dicts
    (the grower's tree pytrees) are summarized by sorted keys to keep
    signatures short and stable."""
    out: List[str] = []
    for a in args:
        if isinstance(a, dict):
            out.append("{%s}" % ",".join(
                "%s:%s" % (k, _aval_str(a[k])) for k in sorted(a)[:8]))
        elif isinstance(a, (list, tuple)):
            out.append("(%s)" % ",".join(_aval_str(v) for v in a[:8]))
        else:
            out.append(_aval_str(a))
    for k in sorted(kwargs):
        out.append("%s=%s" % (k, _aval_str(kwargs[k])))
    return tuple(out)


def sig_delta(old: Optional[Tuple[str, ...]],
              new: Tuple[str, ...]) -> str:
    """Human-readable diff of two signatures: only the argument slots
    that changed, ``argN:old->new``.  This is what a steady-state
    retrace reports in its metric label."""
    if old is None:
        return "first_trace"
    parts = []
    for i in range(max(len(old), len(new))):
        o = old[i] if i < len(old) else "<absent>"
        n = new[i] if i < len(new) else "<absent>"
        if o != n:
            parts.append("arg%d:%s->%s" % (i, o, n))
    return (";".join(parts) or "identical_signature")[:SIG_MAX_CHARS]


class _Site:
    """Per-site ledger record."""

    __slots__ = ("name", "compiles", "calls", "cache_hits", "cache_misses",
                 "last_sig", "compile_seconds", "history", "cost",
                 "cost_seen")

    def __init__(self, name: str):
        self.name = name
        self.compiles = 0
        self.calls = 0
        self.cache_hits = 0          # python-side cache hits (cache_event)
        self.cache_misses = 0
        self.last_sig: Optional[Tuple[str, ...]] = None
        self.compile_seconds = 0.0
        self.history: "collections.deque" = collections.deque(
            maxlen=HISTORY_PER_SITE)
        self.cost: Dict[str, Any] = {}          # last cost_analysis()
        self.cost_seen: set = set()

    def to_json(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "compiles": self.compiles, "calls": self.calls,
            "compile_seconds": round(self.compile_seconds, 6),
            "last_signature": list(self.last_sig or ()),
            "history": list(self.history),
        }
        if self.cache_hits or self.cache_misses:
            d["cache_hits"] = self.cache_hits
            d["cache_misses"] = self.cache_misses
        if self.cost:
            d["cost_analysis"] = self.cost
        return d


class CompileLedger:
    """Process-wide compile/retrace ledger (one instance: `LEDGER`)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._sites: Dict[str, _Site] = {}
        self._tls = threading.local()
        self._steady = False
        self._cost_capture = False
        #: post-compile observer (runtime/warmup.py's persistent-cache
        #: hit/miss classifier registers here — warmup imports this
        #: module, never the reverse)
        self._observer: Optional[Callable[[str, float], None]] = None
        #: steady-state violations: {site, delta, wallclock, wall_s}
        self.retraces: List[Dict[str, Any]] = []

    # -- registration --------------------------------------------------------
    def register(self, site: str) -> _Site:
        rec = self._sites.get(site)
        if rec is None:
            with self._lock:
                rec = self._sites.get(site)
                if rec is None:
                    rec = _Site(site)
                    self._sites[site] = rec
        return rec

    def site_names(self) -> List[str]:
        with self._lock:
            return sorted(self._sites)

    # -- trace plumbing (called from inside jax tracing) ---------------------
    def _notes(self) -> list:
        lst = getattr(self._tls, "notes", None)
        if lst is None:
            lst = self._tls.notes = []
        return lst

    def _note_trace(self, rec: _Site, args: tuple, kwargs: dict) -> None:
        """Runs AT TRACE TIME (host code executed while jax traces the
        wrapped function) — traces are rare, so the signature is computed
        here, never on the cached-call fast path."""
        self._notes().append((rec, _signature(args, kwargs)))

    def _record_compile(self, rec: _Site, wall_s: float,
                        sig: Tuple[str, ...]) -> None:
        prev = rec.last_sig
        with self._lock:
            rec.compiles += 1
            rec.compile_seconds += wall_s
            rec.last_sig = sig
            rec.history.append({
                "wallclock": wallclock(), "wall_s": round(wall_s, 6),
                "signature": list(sig)[:16],
                "delta": sig_delta(prev, sig),
            })
        telemetry.counter("lgbm_xla_compiles_total").inc(site=rec.name)
        telemetry.histogram("lgbm_xla_compile_seconds").observe(
            wall_s, site=rec.name)
        telemetry.counter("lgbm_program_cache_events_total").inc(
            site=rec.name, event="compile")
        # the compile as a slice on its own Perfetto row (ISSUE 14): the
        # flight recorder's merged timeline shows WHICH request/cycle was
        # stalled behind which site's trace+compile
        now_ns = time.monotonic_ns()
        dur_ns = int(wall_s * 1e9)
        tracing.record("xla compile %s" % rec.name, now_ns - dur_ns,
                       dur_ns, track="xla compile", site=rec.name,
                       delta=sig_delta(prev, sig))
        if self._steady:
            delta_s = sig_delta(prev, sig)
            event = {"site": rec.name, "delta": delta_s,
                     "wall_s": round(wall_s, 6), "wallclock": wallclock()}
            with self._lock:
                self.retraces.append(event)
            telemetry.counter("lgbm_xla_retraces_total").inc(
                site=rec.name, delta=delta_s)
            tracing.instant("xla RETRACE %s" % rec.name,
                            track="xla compile", site=rec.name,
                            delta=delta_s)
        obs = self._observer
        if obs is not None:
            try:
                obs(rec.name, wall_s)
            except Exception:    # noqa: BLE001 — never the compile's problem
                pass

    # -- python-side cache events --------------------------------------------
    def cache_event(self, site: str, event: str, n: int = 1) -> None:
        """hit / miss / evict for an explicit program cache (the grower
        caches, `_PACK_CACHE`, the predictor's shape buckets)."""
        rec = self.register(site)
        with self._lock:
            if event == "hit":
                rec.cache_hits += n
            elif event == "miss":
                rec.cache_misses += n
        telemetry.counter("lgbm_program_cache_events_total").inc(
            n, site=site, event=event)

    # -- steady-state pin ----------------------------------------------------
    def mark_steady(self, on: bool = True) -> None:
        """After warmup: any further trace at any site is a RETRACE,
        recorded with the site and the shape delta that triggered it."""
        self._steady = bool(on)

    @property
    def steady(self) -> bool:
        return self._steady

    def set_cost_capture(self, on: bool) -> bool:
        prev = self._cost_capture
        self._cost_capture = bool(on)
        return prev

    def set_compile_observer(self, fn: Optional[Callable[[str, float],
                                                         None]]) -> None:
        """Register the post-compile observer (one per process; None
        unregisters).  Called with (site, wall_s) AFTER each compile is
        recorded; an observer exception is swallowed."""
        self._observer = fn

    # -- read side -----------------------------------------------------------
    def total_compiles(self) -> int:
        with self._lock:
            return sum(s.compiles for s in self._sites.values())

    def snapshot(self) -> Dict[str, int]:
        """{site: compile count} — diff two of these to pin a window."""
        with self._lock:
            return {name: s.compiles for name, s in self._sites.items()}

    def calls_snapshot(self) -> Dict[str, int]:
        """{site: DISPATCH count} — every LedgeredJit invocation is one
        device-program launch (inlined ``__wrapped__`` calls are part of
        their outer program and do not count).  Diff two of these for a
        dispatches-per-iteration attribution (BENCH_ATTRIB)."""
        with self._lock:
            return {name: s.calls for name, s in self._sites.items()}

    def calls_delta(self, before: Dict[str, int]) -> Dict[str, int]:
        """Per-site dispatches since `before` (only non-zero entries)."""
        now = self.calls_snapshot()
        out = {}
        for name, n in now.items():
            d = n - before.get(name, 0)
            if d:
                out[name] = d
        return out

    def total_calls(self) -> int:
        with self._lock:
            return sum(s.calls for s in self._sites.values())

    def delta(self, before: Dict[str, int]) -> Dict[str, int]:
        """Per-site compiles since `before` (only non-zero entries)."""
        now = self.snapshot()
        out = {}
        for name, n in now.items():
            d = n - before.get(name, 0)
            if d:
                out[name] = d
        return out

    def to_json(self) -> Dict[str, Any]:
        with self._lock:
            sites = {name: s.to_json()
                     for name, s in sorted(self._sites.items())}
            retraces = list(self.retraces)
        return {"wallclock": wallclock(), "steady": self._steady,
                "total_compiles": sum(s["compiles"]
                                      for s in sites.values()),
                "sites": sites, "retraces": retraces}

    def reset(self) -> None:
        """Test seam: forget every recorded event (registered wrapper
        objects keep working; their site records are re-created)."""
        with self._lock:
            self._sites.clear()
            self.retraces.clear()
        self._steady = False


#: THE ledger every `xla_obs.jit` site records into
LEDGER = CompileLedger()


class LedgeredJit:
    """`jax.jit` with the compile ledger wired in.  Calls behave exactly
    like the plain jitted function; `__wrapped__` is the traced (but
    unjitted) function so callers that inline into an outer trace (the
    fused-step pattern in gbdt.py) keep working — and their inlined
    traces still note the site."""

    def __init__(self, fn: Callable, site: str, jit_kwargs: Dict[str, Any]):
        import jax
        self.site = site
        self._rec = LEDGER.register(site)
        rec = self._rec

        @functools.wraps(fn)
        def marked(*a, **k):
            LEDGER._note_trace(rec, a, k)
            return fn(*a, **k)

        self._jitted = jax.jit(marked, **jit_kwargs)
        functools.update_wrapper(self, fn, updated=())
        # AFTER update_wrapper (which points __wrapped__ at fn): inlining
        # callers get the MARKED function, so an inlined trace still
        # notes the site inside the outer program's compile
        self.__wrapped__ = marked

    def __call__(self, *args, **kwargs):
        rec = self._rec
        rec.calls += 1
        notes = LEDGER._notes()
        n0 = len(notes)
        if LEDGER._cost_capture:
            self._maybe_capture_cost(args, kwargs)
        t0 = time.perf_counter()
        out = self._jitted(*args, **kwargs)
        dt = time.perf_counter() - t0
        if len(notes) > n0:
            mine = [sig for r, sig in notes[n0:] if r is rec]
            del notes[n0:]
            if mine:
                LEDGER._record_compile(rec, dt, mine[-1])
                return out
        telemetry.counter("lgbm_program_cache_events_total").inc(
            site=rec.name, event="hit")
        return out

    def _maybe_capture_cost(self, args, kwargs) -> None:
        """Opt-in FLOPs/bytes capture: lower+compile once per new shape
        signature BEFORE the real call (the real call may donate its
        buffers).  Diagnostics only — any failure is swallowed."""
        try:
            sig = _signature(args, kwargs)
            if sig in self._rec.cost_seen:
                return
            self._rec.cost_seen.add(sig)
            compiled = self._jitted.lower(*args, **kwargs).compile()
            cost = compiled.cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            if cost:
                self._rec.cost = {
                    k: (round(float(v), 3)
                        if isinstance(v, (int, float)) else str(v))
                    for k, v in sorted(dict(cost).items())[:24]}
        except Exception:      # noqa: BLE001 — never the hot path's problem
            pass

    def lower(self, *args, **kwargs):
        return self._jitted.lower(*args, **kwargs)

    def clear_cache(self) -> None:
        self._jitted.clear_cache()


def jit(fn: Optional[Callable] = None, *, site: str,
        **jit_kwargs) -> Any:
    """Ledgered ``jax.jit``.  Usable as a direct call
    (``xla_obs.jit(f, site="x")``) or through functools.partial as a
    decorator (``@functools.partial(xla_obs.jit, site="x",
    static_argnames=(...))``)."""
    if not site:
        raise ValueError("xla_obs.jit needs a non-empty site= name")
    if fn is None:
        return functools.partial(jit, site=site, **jit_kwargs)
    return LedgeredJit(fn, site, jit_kwargs)


# -- module-level conveniences (the names tests and callers use) ------------

def cache_event(site: str, event: str, n: int = 1) -> None:
    LEDGER.cache_event(site, event, n)


def mark_steady(on: bool = True) -> None:
    LEDGER.mark_steady(on)


def set_cost_capture(on: bool) -> bool:
    return LEDGER.set_cost_capture(on)


def snapshot() -> Dict[str, int]:
    return LEDGER.snapshot()


def delta(before: Dict[str, int]) -> Dict[str, int]:
    return LEDGER.delta(before)


def total_compiles() -> int:
    return LEDGER.total_compiles()


def calls_snapshot() -> Dict[str, int]:
    return LEDGER.calls_snapshot()


def calls_delta(before: Dict[str, int]) -> Dict[str, int]:
    return LEDGER.calls_delta(before)


def total_calls() -> int:
    return LEDGER.total_calls()


def reset() -> None:
    LEDGER.reset()


def set_compile_observer(fn: Optional[Callable[[str, float], None]]
                         ) -> None:
    LEDGER.set_compile_observer(fn)
