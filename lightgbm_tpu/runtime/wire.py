"""Binary zero-copy serving data plane (ISSUE 16 tentpole).

The JSON-lines front end (`serving.ServingServer`) parses one JSON
object per request — fine for chaos soaks, hopeless at the 10k-100k
req/s the ROADMAP's north star implies: at serving rates the JSON
decode, the per-request `np.asarray(..., float64)` copy and the
response re-encode dominate the wall clock, not the predict.  This
module is the wire-speed path beside it:

* **Length-prefixed binary frames.**  A fixed 40-byte little-endian
  header (magic, version, msg type, dtype, flags, NUL-padded model id,
  row/col counts, payload length, CRC32) followed by a float32
  row-major feature payload.  The layout is mirrored field-for-field by
  ``cpp/lightgbm_tpu_c_api.h`` (``WIRE_FRAME_FIELDS`` /
  ``LGBMWireFrameHeader``) and ``helper/check_wire_abi.py`` lints the
  two against each other token-for-token, so a compiled C caller and
  this module can never silently disagree.
* **Zero-copy request path.**  Each connection owns a small pool of
  preallocated per-bucket receive buffers; the payload is read with
  ``readinto`` straight into the bucket buffer and submitted as a
  NUMPY VIEW of those bytes (`ServingRuntime.submit_view`) — no
  per-request allocation, no float64 conversion, no JSON on the hot
  path.  The serving batcher gathers views into its own preallocated
  per-bucket batch buffer, so steady-state serving allocates nothing
  per request.  (One frame is in flight per connection — the
  request/response protocol is serial per socket — so the buffer the
  view aliases is never reused before the response is written.)
* **Response/rejection frames with JSON parity.**  Responses carry the
  generation, served_by, compiled flag and the full ISSUE 14 ``stages``
  partition (queue_wait/batch_gather/device/drain) as a fixed meta
  block before the float32 values, so tracing and byte-verification
  against the offline predictor work exactly as on the JSON path.
  Rejections are machine-readable frames carrying the reason string,
  the retryable bit and a Retry-After-style backoff hint in seconds.
* **Torn-frame robustness.**  Truncated header, short payload, bad
  magic/version/dtype, bad CRC and oversized row counts each produce a
  machine-readable retryable rejection frame — never a hung connection,
  never an unbounded read (the declared payload length is bounded
  BEFORE any payload byte is read).  Only a CRC failure keeps the
  connection open (the frame boundary is still trustworthy); every
  other torn class closes it after the rejection is written, because a
  byte stream that lied about its framing cannot be resynchronized.

Served over both TCP (`WireTCPServer`) and a Unix-domain socket
(`WireUnixServer`, `task=serve serve_wire_uds=...`) — same frames, same
runtime, same bounded admission queue as the JSON path.
"""
from __future__ import annotations

import os
import socket
import socketserver
import struct
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import telemetry

__all__ = ["HEADER_FIELDS", "HEADER_FMT", "HEADER_SIZE", "MAGIC",
           "VERSION", "MSG_REQUEST", "MSG_RESPONSE", "MSG_REJECT",
           "MSG_SHM_SETUP", "MSG_SHM_OK", "DTYPE_F32", "pack_request",
           "pack_response", "pack_reject", "read_frame",
           "WireFrameError", "WireTCPServer", "WireUnixServer",
           "WireClient"]

#: the canonical header layout — ``helper/check_wire_abi.py`` pins this
#: tuple token-for-token against the ``WIRE_FRAME_FIELDS`` comment in
#: ``cpp/lightgbm_tpu_c_api.h``; edit both together or the lint fails
HEADER_FIELDS: Tuple[Tuple[str, str], ...] = (
    ("magic", "4s"),
    ("version", "B"),
    ("msg_type", "B"),
    ("dtype", "B"),
    ("flags", "B"),
    ("model_id", "16s"),
    ("n_rows", "I"),
    ("n_cols", "I"),
    ("payload_len", "I"),
    ("crc32", "I"),
)
HEADER_FMT = "<" + "".join(fmt for _name, fmt in HEADER_FIELDS)
HEADER_SIZE = struct.calcsize(HEADER_FMT)          # 40 bytes
_HEADER = struct.Struct(HEADER_FMT)

MAGIC = b"LGBW"
VERSION = 1
MSG_REQUEST, MSG_RESPONSE, MSG_REJECT = 1, 2, 3
#: shared-memory ring negotiation (ISSUE 20): a client on the UDS plane
#: sends MSG_SHM_SETUP carrying the packed ring config; the server acks
#: with MSG_SHM_OK (twice: config accepted, then segment mapped) and the
#: socket becomes the session's control channel — see runtime/shm_ring.py
MSG_SHM_SETUP, MSG_SHM_OK = 4, 5
DTYPE_F32 = 0                                      # the only wire dtype

#: response meta block, written BEFORE the float32 values payload:
#: generation (i64), latency_s, queue_wait_s, batch_gather_s, device_s,
#: drain_s (f32 — the ISSUE 14 stage partition, same clock as latency),
#: served_by (0=host 1=device), compiled (0/1), 2 pad bytes
RESP_META_FMT = "<qfffffBBxx"
RESP_META_SIZE = struct.calcsize(RESP_META_FMT)    # 32 bytes
_RESP_META = struct.Struct(RESP_META_FMT)

#: rejection meta block: retry_after_s (f32 backoff hint, 0 = none),
#: retryable (0/1), reserved, reason_len (u16), then reason utf-8 bytes
REJ_META_FMT = "<fBBH"
REJ_META_SIZE = struct.calcsize(REJ_META_FMT)      # 8 bytes
_REJ_META = struct.Struct(REJ_META_FMT)

#: hard bound on a frame's DECLARED payload before any payload byte is
#: read — the "never an unbounded read" contract.  Row counts are
#: additionally bounded by the server's max_rows_per_frame.
MAX_PAYLOAD = 1 << 26                              # 64 MiB
MAX_COLS = 1 << 16


class WireFrameError(RuntimeError):
    """A frame the server (or client) refused to parse.  `reason` is the
    machine-readable torn-frame class; `fatal` frames desynchronize the
    byte stream and close the connection after the rejection frame."""

    def __init__(self, reason: str, detail: str = "", fatal: bool = True,
                 retry_after_s: float = 0.0):
        super().__init__("%s%s" % (reason, ": " + detail if detail else ""))
        self.reason = reason
        self.fatal = fatal
        self.retry_after_s = retry_after_s


def _pad_model_id(model_id: str) -> bytes:
    raw = model_id.encode("utf-8")[:16]
    return raw + b"\x00" * (16 - len(raw))


def _unpad_model_id(raw: bytes) -> str:
    return raw.rstrip(b"\x00").decode("utf-8", "replace") or "default"


def pack_header(msg_type: int, model_id: str, n_rows: int, n_cols: int,
                payload: bytes, flags: int = 0) -> bytes:
    return _HEADER.pack(MAGIC, VERSION, msg_type, DTYPE_F32, flags,
                        _pad_model_id(model_id), n_rows, n_cols,
                        len(payload), zlib.crc32(payload) & 0xFFFFFFFF)


def pack_request(X: np.ndarray, model_id: str = "default",
                 priority: int = 0) -> bytes:
    """One request frame from a [B, F] float32 matrix (cast if needed).

    `priority` rides the header's flags byte (low nibble, 0 = highest):
    the server feeds it to the same per-class admission reservations as
    the JSON path (ISSUE 17 — the fleet loadgen's classed traffic uses
    the binary plane).  The wire ABI is unchanged: flags was always in
    the header, and 0 keeps the legacy highest-class behavior."""
    X = np.ascontiguousarray(np.atleast_2d(X), np.float32)
    payload = X.tobytes()
    return pack_header(MSG_REQUEST, model_id, X.shape[0], X.shape[1],
                       payload, flags=int(priority) & 0x0F) + payload


def pack_response(values: np.ndarray, generation: int, model_id: str,
                  served_by: str, latency_s: float,
                  stages: Dict[str, float], compiled: bool) -> bytes:
    """One response frame: RESP_META + float32 values.  n_rows/n_cols
    describe the VALUES matrix; payload_len additionally covers the meta
    block, so framing never depends on interpreting the payload."""
    vals = np.ascontiguousarray(np.atleast_2d(values), np.float32)
    meta = _RESP_META.pack(
        int(generation), float(latency_s),
        float(stages.get("queue_wait_s", 0.0)),
        float(stages.get("batch_gather_s", 0.0)),
        float(stages.get("device_s", 0.0)),
        float(stages.get("drain_s", 0.0)),
        1 if served_by == "device" else 0, 1 if compiled else 0)
    payload = meta + vals.tobytes()
    return pack_header(MSG_RESPONSE, model_id, vals.shape[0],
                       vals.shape[1], payload) + payload


def pack_reject(reason: str, retryable: bool = True,
                retry_after_s: float = 0.0,
                model_id: str = "default") -> bytes:
    """One machine-readable rejection frame (the binary twin of
    `ServeRejected.to_dict()`), carrying the Retry-After backoff hint."""
    rb = reason.encode("utf-8")[:1024]
    payload = _REJ_META.pack(max(float(retry_after_s), 0.0),
                             1 if retryable else 0, 0, len(rb)) + rb
    return pack_header(MSG_REJECT, model_id, 0, 0, payload) + payload


def unpack_response(header: Tuple, payload: bytes) -> Dict[str, Any]:
    """Decode a response/reject payload into the JSON-path dict shape —
    the parity surface the verification harnesses compare on."""
    (_magic, _ver, msg_type, _dtype, _flags, model_raw, n_rows, n_cols,
     _plen, _crc) = header
    if msg_type == MSG_REJECT:
        retry_after, retryable, _resv, rlen = _REJ_META.unpack_from(payload)
        reason = payload[REJ_META_SIZE:REJ_META_SIZE + rlen].decode(
            "utf-8", "replace")
        return {"error": "rejected", "reason": reason,
                "retryable": bool(retryable),
                "retry_after_s": round(float(retry_after), 6)}
    if msg_type != MSG_RESPONSE:
        raise WireFrameError("unexpected_msg_type", str(msg_type))
    (gen, latency, qw, bg, dv, dr, served_dev, compiled) = \
        _RESP_META.unpack_from(payload)
    vals = np.frombuffer(payload, np.float32, count=n_rows * n_cols,
                         offset=RESP_META_SIZE).reshape(n_rows, n_cols)
    return {"values": vals, "generation": int(gen),
            "model": _unpad_model_id(model_raw),
            "served_by": "device" if served_dev else "host",
            "latency_s": float(latency), "compiled": bool(compiled),
            "stages": {"queue_wait_s": float(qw),
                       "batch_gather_s": float(bg),
                       "device_s": float(dv), "drain_s": float(dr)}}


# ---------------------------------------------------------------------------
# frame reader (shared by server handler and client)
# ---------------------------------------------------------------------------

def _read_exact_into(rfile, view: memoryview) -> int:
    """Fill `view` from the buffered reader; returns bytes actually read
    (short only at EOF).  Bounded by len(view) — never an unbounded
    read."""
    got = 0
    while got < len(view):
        n = rfile.readinto(view[got:])
        if not n:
            break
        got += n
    return got


def read_frame(rfile, buffers: Optional["_BucketBuffers"] = None,
               max_rows: int = 1 << 20,
               expect=None):
    """Read one frame: (header tuple, payload).  With `buffers`, the
    payload lands in a preallocated per-bucket buffer and `payload` is a
    memoryview of it (zero-copy); otherwise a fresh bytes object.

    Returns None at clean EOF (no bytes).  Raises `WireFrameError` for
    every torn-frame class; the DECLARED payload length is validated
    against the header's own row/col counts and the hard bounds BEFORE
    any payload byte is read."""
    head = bytearray(HEADER_SIZE)
    got = _read_exact_into(rfile, memoryview(head))
    if got == 0:
        return None
    if got < HEADER_SIZE:
        raise WireFrameError("truncated_header",
                             "%d of %d header bytes" % (got, HEADER_SIZE))
    hdr = _HEADER.unpack(bytes(head))
    (magic, version, msg_type, dtype, _flags, _model, n_rows, n_cols,
     payload_len, crc) = hdr
    if magic != MAGIC:
        raise WireFrameError("bad_magic", repr(bytes(magic)))
    if version != VERSION:
        raise WireFrameError("bad_version", str(version))
    if dtype != DTYPE_F32:
        raise WireFrameError("bad_dtype", str(dtype))
    if expect is not None and msg_type != expect and not (
            isinstance(expect, tuple) and msg_type in expect):
        raise WireFrameError("unexpected_msg_type", str(msg_type))
    if payload_len > MAX_PAYLOAD or n_cols > MAX_COLS:
        raise WireFrameError("oversized",
                             "payload_len=%d n_cols=%d" % (payload_len,
                                                           n_cols))
    if msg_type == MSG_REQUEST:
        if n_rows > max_rows:
            raise WireFrameError(
                "oversized", "n_rows=%d > max_rows_per_frame=%d"
                % (n_rows, max_rows), retry_after_s=0.0)
        if n_rows < 1 or n_cols < 1 or payload_len != n_rows * n_cols * 4:
            raise WireFrameError(
                "bad_frame", "payload_len=%d does not match %dx%d float32"
                % (payload_len, n_rows, n_cols))
    if buffers is not None:
        buf = buffers.get(payload_len)
        view = memoryview(buf)[:payload_len]
        got = _read_exact_into(rfile, view)
    else:
        raw = bytearray(payload_len)
        view = memoryview(raw)
        got = _read_exact_into(rfile, view)
    if got < payload_len:
        raise WireFrameError("short_payload",
                             "%d of %d payload bytes" % (got, payload_len))
    if zlib.crc32(view) & 0xFFFFFFFF != crc:
        # the frame BOUNDARY is intact (payload_len was honored), so the
        # stream can keep going — the only retry-in-place torn class
        raise WireFrameError("bad_crc", fatal=False)
    return hdr, view if buffers is not None else bytes(raw)


class _BucketBuffers:
    """Per-connection pool of preallocated receive buffers, keyed by the
    power-of-two byte bucket — repeated frames of similar size reuse ONE
    allocation, and the numpy view handed to the runtime aliases it."""

    __slots__ = ("_bufs",)

    _MIN = 1 << 10

    def __init__(self):
        self._bufs: Dict[int, bytearray] = {}

    def get(self, nbytes: int) -> bytearray:
        bucket = max(self._MIN, 1 << max(int(nbytes) - 1, 1).bit_length())
        buf = self._bufs.get(bucket)
        if buf is None:
            buf = self._bufs[bucket] = bytearray(bucket)
        return buf


class _ResponseScratch:
    """Per-connection reusable RESPONSE buffer (ISSUE 17 perf fix).

    `pack_response` built three fresh bytes objects per response on the
    hot path (meta block, meta+values payload, header+payload frame) —
    measurable allocator traffic at wire rates.  This scratch packs the
    header and meta block INTO one preallocated bytearray with
    `Struct.pack_into`, copies the float32 values right behind them, and
    hands the socket a memoryview of the result: zero per-response
    buffer allocations (asserted in tests/test_serving.py).  The buffer
    grows by power-of-two bucket when a response outgrows it (amortized,
    never per-response); values that arrive as float64 (the legacy
    response surface) cast into a reusable per-bucket float32 arena.
    Model-id padding is memoized per id.

    Single-threaded by construction: one scratch per connection handler,
    one frame in flight per socket."""

    __slots__ = ("_buf", "_mids", "_f32")

    def __init__(self):
        self._buf = bytearray(HEADER_SIZE + RESP_META_SIZE + (1 << 12))
        self._mids: Dict[str, bytes] = {}
        self._f32: Dict[int, np.ndarray] = {}

    def _model(self, model_id: str) -> bytes:
        mid = self._mids.get(model_id)
        if mid is None:
            if len(self._mids) > 256:      # hostile id churn: stay bounded
                self._mids.clear()
            mid = self._mids[model_id] = _pad_model_id(model_id)
        return mid

    def _as_f32(self, values: np.ndarray) -> np.ndarray:
        """`values` as a C-contiguous float32 matrix — returned AS IS
        when it already is one (the response_dtype="float32" runtime),
        else cast into a reusable per-bucket conversion arena."""
        if values.dtype == np.float32 and values.flags["C_CONTIGUOUS"]:
            return values
        n = int(values.size)
        bucket = max(1 << max(n - 1, 1).bit_length(), 1 << 8)
        arena = self._f32.get(bucket)
        if arena is None:
            arena = self._f32[bucket] = np.empty(bucket, np.float32)
        dst = arena[:n].reshape(values.shape)
        np.copyto(dst, values, casting="same_kind")
        return dst

    def pack_response_into(self, buf, off: int, values: np.ndarray,
                           generation: int, model_id: str,
                           served_by: str, latency_s: float,
                           stages: Dict[str, float],
                           compiled: bool) -> int:
        """Pack one response frame at `buf[off:]` (any writable buffer —
        the SHM response ring hands its mmap here so the frame lands
        directly in the shared segment, no intermediate copy).  Returns
        the frame's total bytes.  The caller guarantees the room."""
        vals = self._as_f32(np.atleast_2d(values))
        nbytes = vals.size * 4
        total = HEADER_SIZE + RESP_META_SIZE + nbytes
        _RESP_META.pack_into(
            buf, off + HEADER_SIZE, int(generation), float(latency_s),
            float(stages.get("queue_wait_s", 0.0)),
            float(stages.get("batch_gather_s", 0.0)),
            float(stages.get("device_s", 0.0)),
            float(stages.get("drain_s", 0.0)),
            1 if served_by == "device" else 0, 1 if compiled else 0)
        mv = memoryview(buf)
        try:
            mv[off + HEADER_SIZE + RESP_META_SIZE:off + total] = \
                memoryview(vals).cast("B")
            crc = zlib.crc32(mv[off + HEADER_SIZE:off + total]) \
                & 0xFFFFFFFF
        finally:
            mv.release()
        _HEADER.pack_into(buf, off, MAGIC, VERSION, MSG_RESPONSE,
                          DTYPE_F32, 0, self._model(model_id),
                          vals.shape[0], vals.shape[1],
                          RESP_META_SIZE + nbytes, crc)
        return total

    def pack_response(self, values: np.ndarray, generation: int,
                      model_id: str, served_by: str, latency_s: float,
                      stages: Dict[str, float],
                      compiled: bool) -> memoryview:
        """Same frame bytes as module-level `pack_response` (parity is
        test-pinned), valid until the next call on this scratch."""
        vals = self._as_f32(np.atleast_2d(values))
        total = HEADER_SIZE + RESP_META_SIZE + vals.size * 4
        if len(self._buf) < total:
            self._buf = bytearray(1 << max(total - 1, 1).bit_length())
        total = self.pack_response_into(self._buf, 0, vals, generation,
                                        model_id, served_by, latency_s,
                                        stages, compiled)
        return memoryview(self._buf)[:total]


# ---------------------------------------------------------------------------
# servers
# ---------------------------------------------------------------------------

class _WireHandler(socketserver.StreamRequestHandler):
    """One thread per connection, strict request/response (one frame in
    flight per socket): the zero-copy receive buffer is safe to reuse
    once the response is on the wire."""

    def handle(self) -> None:
        server = self.server                      # type: ignore[assignment]
        rt = server.runtime
        path = server.wire_path_label
        bytes_total = telemetry.counter("lgbm_serve_bytes_total")
        frames_total = telemetry.counter("lgbm_serve_frames_total")
        buffers = _BucketBuffers()
        scratch = _ResponseScratch()
        from .serving import ServeRejected
        while True:
            try:
                frame = read_frame(self.rfile, buffers,
                                   max_rows=server.max_rows_per_frame,
                                   expect=(MSG_REQUEST, MSG_SHM_SETUP))
            except WireFrameError as e:
                frames_total.inc(outcome=e.reason)
                out = pack_reject(e.reason, retryable=True,
                                  retry_after_s=e.retry_after_s)
                self._send(out, bytes_total, path)
                if e.fatal:
                    return                        # stream desynchronized
                continue
            except OSError:
                return
            if frame is None:
                return                            # clean EOF
            hdr, payload = frame
            (_m, _v, msg_type, _d, flags, model_raw, n_rows, n_cols,
             plen, _crc) = hdr
            bytes_total.inc(HEADER_SIZE + plen, path=path, dir="rx")
            if msg_type == MSG_SHM_SETUP:
                # the shared-memory upgrade: fd passing needs AF_UNIX,
                # so the TCP plane refuses (non-retryable — the client
                # should fall back, not retry)
                if not getattr(server, "supports_shm", False):
                    frames_total.inc(outcome="shm_requires_uds")
                    self._send(pack_reject("shm_requires_uds",
                                           retryable=False),
                               bytes_total, path)
                    return
                from . import shm_ring
                shm_ring.serve_handler(self, bytes(payload))
                return                # the socket was the control channel
            model_id = _unpad_model_id(model_raw)
            # the zero-copy hand-off: a float32 VIEW of the receive
            # buffer rides the queue; no per-request numpy allocation
            X = np.frombuffer(payload, np.float32,
                              count=n_rows * n_cols).reshape(n_rows,
                                                             n_cols)
            try:
                rec = rt.submit_view(X, model_id=model_id,
                                     priority=flags & 0x0F).wait(
                    timeout=rt.wire_wait_timeout_s)
                # response values are always [n_rows, n_outputs] on the
                # wire (a squeezed 1-class vector reshapes, multiclass
                # passes through); the frame packs into the connection's
                # reusable scratch — zero per-response allocations
                vals = np.asarray(rec.values)
                out = scratch.pack_response(vals.reshape(n_rows, -1),
                                            rec.generation, model_id,
                                            rec.served_by, rec.latency_s,
                                            rec.stages, rec.compiled)
                frames_total.inc(outcome="completed")
            except ServeRejected as e:
                out = pack_reject(e.reason, retryable=e.retryable,
                                  retry_after_s=e.retry_after_s or 0.0,
                                  model_id=model_id)
                frames_total.inc(outcome="rejected")
            except Exception as e:                # noqa: BLE001 — wire error
                out = pack_reject("bad_request", retryable=False,
                                  model_id=model_id)
                rt.log.warning("wire: request failed: %s: %s",
                               type(e).__name__, e)
                frames_total.inc(outcome="rejected")
            if not self._send(out, bytes_total, path):
                return

    def _send(self, out, bytes_total, path: str) -> bool:
        try:
            self.wfile.write(out)
            self.wfile.flush()
        except OSError:
            return False                          # client went away
        bytes_total.inc(len(out), path=path, dir="tx")
        return True


class WireTCPServer(socketserver.ThreadingTCPServer):
    """Binary-frame TCP front end over a `ServingRuntime` — the same
    bounded admission queue as the JSON `ServingServer`, so admission
    control stays global across both planes."""

    daemon_threads = True
    allow_reuse_address = True
    wire_path_label = "tcp"
    supports_shm = False          # SCM_RIGHTS fd passing needs AF_UNIX

    def __init__(self, runtime, host: str = "127.0.0.1", port: int = 0,
                 max_rows_per_frame: Optional[int] = None):
        self.runtime = runtime
        self.max_rows_per_frame = int(max_rows_per_frame
                                      or runtime.max_batch_rows)
        super().__init__((host, port), _WireHandler)

    @property
    def port(self) -> int:
        return self.server_address[1]


class WireUnixServer(socketserver.ThreadingUnixStreamServer):
    """Binary-frame Unix-domain-socket front end: same frames as TCP,
    minus the TCP/loopback stack — the lowest-latency local data plane
    (the BENCH_WIRE headline path).  Also the SHM ring transport's
    handshake plane: a MSG_SHM_SETUP frame on any connection upgrades
    it to a shared-memory session (`enable_shm=False` turns that off)."""

    daemon_threads = True
    allow_reuse_address = True
    wire_path_label = "uds"

    def __init__(self, runtime, path: str,
                 max_rows_per_frame: Optional[int] = None,
                 enable_shm: bool = True):
        self.runtime = runtime
        self.uds_path = path
        self.supports_shm = bool(enable_shm)
        self.max_rows_per_frame = int(max_rows_per_frame
                                      or runtime.max_batch_rows)
        self._reap_stale_path(path)
        super().__init__(path, _WireHandler)

    @staticmethod
    def _reap_stale_path(path: str) -> None:
        """A replica SIGKILLed mid-serve leaves its socket FILE behind,
        and the relaunch's bind() hits EADDRINUSE.  Probe-connect first:
        refused means nobody is listening (stale inode — unlink it),
        success means a LIVE server owns the path (bind and fail loudly
        rather than yank a working server's socket out from under it)."""
        if not os.path.exists(path):
            return
        probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        probe.settimeout(0.5)
        try:
            probe.connect(path)
        except (ConnectionRefusedError, socket.timeout, OSError):
            try:
                os.unlink(path)       # stale: no listener behind it
            except FileNotFoundError:
                pass                  # raced another relaunch — fine
        else:
            raise OSError(
                "wire UDS path %r is owned by a LIVE server "
                "(probe-connect succeeded); refusing to unlink" % path)
        finally:
            probe.close()

    def server_close(self) -> None:
        super().server_close()
        try:
            os.unlink(self.uds_path)
        except OSError:
            pass


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

class WireClient:
    """Blocking binary-protocol client (one frame in flight).  `predict`
    retries retryable rejections with the server's Retry-After hint —
    the binary twin of `ServingRuntime.predict`'s backoff contract."""

    def __init__(self, address, timeout: float = 30.0):
        """`address`: ("host", port) for TCP or "/path/to.sock" for a
        Unix-domain socket."""
        if isinstance(address, (tuple, list)):
            self._sock = socket.create_connection(tuple(address),
                                                  timeout=timeout)
            self._sock.setsockopt(socket.IPPROTO_TCP,
                                  socket.TCP_NODELAY, 1)
        else:
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._sock.settimeout(timeout)
            self._sock.connect(address)
        self._rfile = self._sock.makefile("rb")
        self._buffers = _BucketBuffers()

    def close(self) -> None:
        try:
            self._rfile.close()
        finally:
            self._sock.close()

    def __enter__(self) -> "WireClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def request_once(self, X: np.ndarray, model_id: str = "default",
                     priority: int = 0) -> Dict[str, Any]:
        """One round trip; returns the decoded response dict (values as
        a float32 view valid until the NEXT call on this client)."""
        self._sock.sendall(pack_request(X, model_id, priority=priority))
        frame = read_frame(self._rfile, self._buffers)
        if frame is None:
            raise WireFrameError("connection_closed")
        hdr, payload = frame
        return unpack_response(hdr, bytes(payload))

    def predict(self, X: np.ndarray, model_id: str = "default",
                attempts: int = 3, priority: int = 0) -> Dict[str, Any]:
        last: Optional[Dict[str, Any]] = None
        for a in range(max(attempts, 1)):
            out = self.request_once(X, model_id, priority=priority)
            if "error" not in out:
                return out
            last = out
            if not out.get("retryable"):
                break
            if a + 1 < max(attempts, 1):
                # honor the server's Retry-After hint, floor 10 ms
                time.sleep(max(float(out.get("retry_after_s") or 0.0),
                               0.01))
        assert last is not None
        raise WireFrameError("rejected", last.get("reason", ""),
                             fatal=False)
