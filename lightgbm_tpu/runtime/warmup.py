"""Warm-start subsystem: persistent program cache + shape manifests.

Every process in the fleet used to pay cold XLA compilation on startup:
serving replicas compiled before their first real batch, `train_online`
relaunches recompiled the whole fused-step family after SIGTERM, and
each step of `exp/on_tpu_return.sh` re-lowered the same ~45 `xla_obs`
sites inside a scarce hardware window.  This module makes startup a
measured, optimized quantity — LightGBM's own "bin once, reuse the
binary cache" design (PAPER.md §L2) applied to compiled programs:

* **Persistent compilation cache seam** — `enable_compile_cache(base)`
  (CLI ``compile_cache_dir=`` / ``$LGBM_TPU_COMPILE_CACHE``) wires
  ``jax_compilation_cache_dir`` to a FINGERPRINTED subdirectory of the
  requested base: the fingerprint keys the requested backend, the jax
  version, the staged-kernel flag set, and the host CPU feature flags
  (XLA:CPU entries embed AOT machine code; loading one compiled on a
  different host can die of SIGILL — the same argument
  ``__graft_entry__._hermetic_cpu_env`` makes for the dryrun cache,
  which stays self-contained because it runs before this package is
  importable).  A stale or cross-version cache can therefore never
  poison results: a different stack simply lands in a different
  subdirectory and runs cold.  The cache is size-budgeted
  (``$LGBM_TPU_COMPILE_CACHE_MB``, default 512): an LRU sweep by mtime
  evicts the oldest entries past the budget.  Per-compile hit/miss
  classification (did this compile load from disk or write a fresh
  entry?) rides the `xla_obs` compile observer into
  ``lgbm_compile_cache_events_total{event}`` AND the compile ledger
  (site ``warmup.persistent_cache``), so doctor bundles and BENCH
  records carry the cache's behavior.

* **Shape manifests** — serving and the continuous trainer export the
  shape buckets and jit sites they actually compiled (straight from the
  `xla_obs` ledger) as a checksummed ``warmup.json`` published
  atomically ALONGSIDE model generations in the publish directory
  (`ModelPublisher.publish_manifest` / `ModelSubscriber.read_warmup`
  are the publish.py seam).  The file holds one section per kind
  (``serving`` / ``train_online``) merged read-modify-atomic-write, so
  the trainer and N serving replicas all land without clobbering each
  other; it is not a ``gen_`` file, so retention pruning never touches
  it and concurrent readers can never observe a torn manifest (atomic
  rename — test-pinned under publish/prune churn).

* **Prewarm classification** — `classify_serving_section` /
  `classify_train_section` decide whether a manifest is trustworthy for
  THIS process (torn / stale-generation / shape-mismatched manifests
  degrade to the legacy smallest-bucket prewarm — never block serving),
  and `record_prewarm` counts every prewarm attempt in
  ``lgbm_warmup_total{kind,outcome}`` + ``lgbm_warmup_seconds{kind}``.

No jax at module scope — the CLI entry and platform-free subscribers
import this; jax loads only when a cache dir is actually being enabled.
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from . import telemetry, xla_obs
from .resilience import atomic_write, wallclock

__all__ = [
    "CACHE_ENV", "CACHE_BUDGET_ENV", "MANIFEST_NAME",
    "MANIFEST_SCHEMA_VERSION",
    "cache_fingerprint", "enable_compile_cache", "maybe_enable_from_env",
    "sweep_cache", "cache_status",
    "write_manifest", "read_manifest", "manifest_path",
    "build_serving_section", "build_train_section", "params_sig",
    "classify_serving_section", "classify_train_section",
    "serving_row_buckets", "record_prewarm",
]

#: base directory of the persistent compilation cache (the fingerprinted
#: subdir is created under it); CLI spelling: ``compile_cache_dir=``
CACHE_ENV = "LGBM_TPU_COMPILE_CACHE"

#: size budget of ONE fingerprinted subdirectory, in MB (LRU sweep by
#: mtime past it; 0 disables the sweep)
CACHE_BUDGET_ENV = "LGBM_TPU_COMPILE_CACHE_MB"
DEFAULT_BUDGET_MB = 512

#: the shape manifest published alongside model generations.  Not a
#: ``gen_`` file: `publish.generation_paths` never lists it and
#: `ModelPublisher._prune` never unlinks it.
MANIFEST_NAME = "warmup.json"
MANIFEST_SCHEMA_VERSION = 1

#: serving prewarm never compiles more than this many manifest buckets
#: (a runaway manifest must not stall readiness indefinitely)
MAX_PREWARM_BUCKETS = 8

#: sanity bound on a manifest row bucket (2^22 rows is far past any
#: serving batch); anything outside [1, this] marks the manifest invalid
MAX_BUCKET_ROWS = 1 << 22

_lock = threading.Lock()
_STATE: Dict[str, Any] = {
    "enabled": False, "dir": None, "fingerprint": None,
    "hits": 0, "misses": 0, "evictions": 0, "budget_mb": None,
    "dir_sig": None,
}


# ---------------------------------------------------------------------------
# fingerprint
# ---------------------------------------------------------------------------

def _host_fingerprint() -> str:
    """Short stable hash of this host's CPU feature flags (XLA:CPU cache
    entries embed AOT machine code — a different host gets a cold cache
    instead of a SIGILL)."""
    try:
        with open("/proc/cpuinfo") as fh:
            flags = next((ln for ln in fh if ln.startswith("flags")), "")
    except OSError:
        flags = ""
    import platform
    blob = (flags + "|" + platform.machine()).encode()
    return hashlib.sha256(blob).hexdigest()[:8]


def _staged_flags_sig() -> str:
    """Hash of the staged-kernel flag set AND its current values: a flag
    flip (exp/flip_validated.py) compiles different programs, so it gets
    its own cache subdirectory instead of poisoning the old one."""
    try:
        from ..ops import pallas_segment as pseg
        pairs = sorted((name, bool(getattr(pseg, flag, False)))
                       for name, flag in pseg.STAGED_FLAGS.items())
    except Exception:    # noqa: BLE001 — a broken kernel import stays cold
        pairs = [("nostaged", False)]
    return hashlib.sha256(repr(pairs).encode()).hexdigest()[:8]


def _requested_backend() -> str:
    """The REQUESTED platform string, without initializing a backend:
    jax.config's jax_platforms when jax is already imported, else the
    JAX_PLATFORMS env var, else "default"."""
    import sys
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            p = jax.config.jax_platforms
            if p:
                return str(p)
        except Exception:    # noqa: BLE001 — config attr moved
            pass
    return os.environ.get("JAX_PLATFORMS") or "default"


def cache_fingerprint() -> str:
    """Identity of the compiled-program universe this process inhabits:
    ``<backend>-jax<version>-<staged8>-<host8>``.  Two processes share a
    cache subdirectory iff every component matches."""
    import jax
    backend = _requested_backend().replace(os.sep, "_").replace(",", "+")
    return "%s-jax%s-%s-%s" % (backend, jax.__version__,
                               _staged_flags_sig(), _host_fingerprint())


# ---------------------------------------------------------------------------
# persistent compilation cache
# ---------------------------------------------------------------------------

def enable_compile_cache(base_dir: Optional[str] = None,
                         budget_mb: Optional[int] = None,
                         min_compile_s: float = 0.0) -> Optional[str]:
    """Wire jax's persistent compilation cache to the fingerprinted
    subdirectory of `base_dir` (default: ``$LGBM_TPU_COMPILE_CACHE``;
    returns None — and touches nothing — when neither is set).

    Threshold 0 persists even sub-second programs so a warm start
    recompiles NOTHING; the size budget keeps the subdirectory bounded
    (oldest-mtime eviction).  Idempotent per (process, dir).  Returns
    the fingerprinted cache directory."""
    base = base_dir if base_dir else os.environ.get(CACHE_ENV)
    if not base:
        return None
    fp = cache_fingerprint()
    cdir = os.path.join(os.path.expanduser(base), fp)
    with _lock:
        if _STATE["enabled"] and _STATE["dir"] == cdir:
            return cdir
    os.makedirs(cdir, exist_ok=True)
    import jax
    jax.config.update("jax_compilation_cache_dir", cdir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(min_compile_s))
    if budget_mb is None:
        budget_mb = int(os.environ.get(CACHE_BUDGET_ENV, DEFAULT_BUDGET_MB))
    with _lock:
        _STATE.update(enabled=True, dir=cdir, fingerprint=fp,
                      budget_mb=int(budget_mb),
                      dir_sig=_dir_sig(cdir))
    # per-compile hit/miss classification rides the compile ledger's
    # observer seam (xla_obs must not import warmup — the observer is
    # registered, not imported)
    xla_obs.set_compile_observer(_compile_observer)
    sweep_cache()
    return cdir


def maybe_enable_from_env() -> Optional[str]:
    """`enable_compile_cache()` iff ``$LGBM_TPU_COMPILE_CACHE`` is set —
    zero-cost (no jax import) when it is not.  Every service entry point
    (CLI tasks, ServingRuntime.start, ContinuousTrainer.run, bench)
    calls this once."""
    if not os.environ.get(CACHE_ENV):
        return None
    return enable_compile_cache()


def _dir_sig(cdir: str) -> Optional[Tuple[int, int]]:
    """O(1) change signature of the cache directory: (mtime_ns, nlink)
    of the dir itself — a new cache entry bumps the dir mtime.  Stat of
    ONE inode, never a listing: the observer runs on every compile and
    the suite-wide cache holds thousands of entries."""
    try:
        st = os.stat(cdir)
        return (st.st_mtime_ns, st.st_nlink)
    except OSError:
        return None


def _compile_observer(site: str, wall_s: float) -> None:
    """Runs after every ledgered compile: a compile that wrote a NEW
    cache entry (the dir signature moved) ran cold (miss); one that did
    not load its executable from disk (hit).  Exact at the service
    default persist-threshold 0, where every fresh compile writes an
    entry; with a higher threshold (the test suite) sub-threshold
    compiles classify as hits — stats, never correctness."""
    with _lock:
        cdir = _STATE["dir"] if _STATE["enabled"] else None
        prev = _STATE["dir_sig"]
    if cdir is None:
        return
    sig = _dir_sig(cdir)
    with _lock:
        event = "miss" if sig != prev else "hit"
        _STATE["dir_sig"] = sig
        _STATE["hits" if event == "hit" else "misses"] += 1
    telemetry.counter("lgbm_compile_cache_events_total").inc(event=event)
    xla_obs.cache_event("warmup.persistent_cache", event)


def sweep_cache(budget_mb: Optional[int] = None) -> int:
    """LRU sweep of the active cache directory: evict oldest-mtime
    entries until the directory fits the budget.  Returns the number of
    entries evicted (0 when disabled or under budget)."""
    with _lock:
        cdir = _STATE["dir"] if _STATE["enabled"] else None
        if budget_mb is None:
            budget_mb = _STATE["budget_mb"] or DEFAULT_BUDGET_MB
    if cdir is None or budget_mb <= 0:
        return 0
    entries: List[Tuple[float, int, str]] = []
    try:
        for name in os.listdir(cdir):
            p = os.path.join(cdir, name)
            try:
                st = os.stat(p)
            except OSError:
                continue
            entries.append((st.st_mtime, st.st_size, p))
    except OSError:
        return 0
    total = sum(e[1] for e in entries)
    budget = int(budget_mb) << 20
    evicted = 0
    for mtime, size, p in sorted(entries):
        if total <= budget:
            break
        try:
            os.unlink(p)
        except OSError:
            continue
        total -= size
        evicted += 1
        telemetry.counter("lgbm_compile_cache_events_total").inc(
            event="evict")
    if evicted:
        with _lock:
            _STATE["evictions"] += evicted
            _STATE["dir_sig"] = _dir_sig(cdir)   # re-baseline after unlinks
    return evicted


def cache_status() -> Dict[str, Any]:
    """Machine-readable cache state (the doctor-bundle member)."""
    with _lock:
        st = {k: _STATE[k] for k in ("enabled", "dir", "fingerprint",
                                     "hits", "misses", "evictions",
                                     "budget_mb")}
    files, total = 0, 0
    if st["dir"]:
        try:
            for name in os.listdir(st["dir"]):
                try:
                    total += os.path.getsize(os.path.join(st["dir"], name))
                    files += 1
                except OSError:
                    continue
        except OSError:
            pass
    st["files"] = files
    st["bytes"] = total
    return st


def _reset_for_tests() -> None:
    """Test seam: forget the enable state (jax config is left as-is)."""
    with _lock:
        _STATE.update(enabled=False, dir=None, fingerprint=None,
                      hits=0, misses=0, evictions=0, budget_mb=None,
                      dir_sig=None)


# ---------------------------------------------------------------------------
# shape manifests (warmup.json in the publish dir)
# ---------------------------------------------------------------------------

def manifest_path(pub_dir: str) -> str:
    return os.path.join(pub_dir, MANIFEST_NAME)


def _doc_checksum(doc: Dict[str, Any]) -> str:
    payload = json.dumps({"schema_version": doc.get("schema_version"),
                          "sections": doc.get("sections")},
                         sort_keys=True)
    return hashlib.sha256(payload.encode()).hexdigest()


def _read_doc(pub_dir: str) -> Tuple[Optional[Dict[str, Any]], str]:
    """(manifest document, reason): reason is "ok", "missing" (no file)
    or "torn" (unparseable / checksum-invalid / wrong schema).  The
    atomic write discipline means "torn" only ever describes a file
    written by something that is not this seam."""
    try:
        with open(manifest_path(pub_dir), "rb") as fh:
            text = fh.read().decode("utf-8", "replace")
    except OSError:
        return None, "missing"
    try:
        doc = json.loads(text)
    except ValueError:
        return None, "torn"
    if not isinstance(doc, dict) \
            or not isinstance(doc.get("sections"), dict) \
            or doc.get("schema_version") != MANIFEST_SCHEMA_VERSION \
            or doc.get("checksum") != _doc_checksum(doc):
        return None, "torn"
    return doc, "ok"


def write_manifest(pub_dir: str, kind: str,
                   section: Dict[str, Any]) -> str:
    """Merge one kind's section into the publish dir's manifest
    (read-merge-atomic-write, the `mark_rollback` pattern: the trainer
    and N serving replicas can all publish their sections concurrently
    and every writer's section lands).  Returns the path."""
    doc, _ = _read_doc(pub_dir)
    sections = dict((doc or {}).get("sections", {}))
    sections[str(kind)] = dict(section)
    out = {"schema_version": MANIFEST_SCHEMA_VERSION, "sections": sections}
    out["checksum"] = _doc_checksum(out)
    path = manifest_path(pub_dir)
    os.makedirs(pub_dir, exist_ok=True)
    atomic_write(path, json.dumps(out, indent=1) + "\n")
    return path


def read_manifest(pub_dir: str, kind: str
                  ) -> Tuple[Optional[Dict[str, Any]], str]:
    """(section, reason) for one kind: reason "ok", "missing" (no file
    or no such section) or "torn"."""
    doc, reason = _read_doc(pub_dir)
    if doc is None:
        return None, reason
    sec = doc["sections"].get(str(kind))
    if not isinstance(sec, dict):
        return None, "missing"
    return sec, "ok"


def _ledger_sites(limit: int = 32) -> List[str]:
    """Site names the compile ledger saw compile in THIS process — the
    manifest's provenance trail ("what did this role actually build")."""
    snap = xla_obs.snapshot()
    return sorted(name for name, n in snap.items() if n > 0)[:limit]


def build_serving_section(num_features: int, row_buckets: List[int],
                          generation: Optional[int] = None
                          ) -> Dict[str, Any]:
    return {
        "kind": "serving",
        "num_features": int(num_features),
        "row_buckets": sorted({int(b) for b in row_buckets}),
        "generation": int(generation) if generation is not None else None,
        "fingerprint": _safe_fingerprint(),
        "created": wallclock(),
        "sites": _ledger_sites(),
    }


def params_sig(params: Dict[str, Any], n_features: int) -> Dict[str, Any]:
    """The program-shape-determining parameter subset: two training
    processes with equal signatures compile the same fused-step family
    on a same-width window."""
    p = params or {}
    return {
        "objective": str(p.get("objective", "regression")),
        "num_class": int(p.get("num_class", 1)),
        "num_leaves": int(p.get("num_leaves", 31)),
        "max_bin": int(p.get("max_bin", 255)),
        "boost_window": int(p.get("boost_window", 1)),
        "n_features": int(n_features),
    }


def build_train_section(params: Dict[str, Any], n_features: int,
                        generation: Optional[int] = None
                        ) -> Dict[str, Any]:
    return {
        "kind": "train_online",
        "params_sig": params_sig(params, n_features),
        "generation": int(generation) if generation is not None else None,
        "fingerprint": _safe_fingerprint(),
        "created": wallclock(),
        "sites": _ledger_sites(),
    }


def _safe_fingerprint() -> Optional[str]:
    try:
        return cache_fingerprint()
    except Exception:    # noqa: BLE001 — provenance only, never a blocker
        return None


def classify_serving_section(sec: Dict[str, Any],
                             num_features: Optional[int],
                             newest_generation: Optional[int]) -> str:
    """"ok" when the manifest's buckets can be trusted for this model;
    otherwise the degradation outcome the metrics count:

    * ``manifest_invalid`` — buckets missing/malformed/absurd;
    * ``manifest_stale`` — written for a DIFFERENT generation whose
      shape no longer matches (the lineage moved on; its buckets
      describe a model this replica is not serving);
    * ``shape_mismatch`` — written for this very generation yet the
      feature width disagrees (a corrupt or foreign manifest).

    Buckets are shape-keyed, not generation-keyed, so an old-generation
    manifest whose feature width still matches stays "ok" — that is the
    common steady-state case."""
    buckets = sec.get("row_buckets")
    if not isinstance(buckets, list) or not buckets \
            or not all(isinstance(b, int) and 0 < b <= MAX_BUCKET_ROWS
                       for b in buckets):
        return "manifest_invalid"
    nf = sec.get("num_features")
    if num_features is not None and nf != num_features:
        gen = sec.get("generation")
        if isinstance(gen, int) and newest_generation is not None \
                and gen != newest_generation:
            return "manifest_stale"
        return "shape_mismatch"
    return "ok"


def classify_train_section(sec: Dict[str, Any],
                           params: Dict[str, Any],
                           n_features: int) -> str:
    """"ok" when the manifest was written by a training process whose
    program-shape signature matches THIS one (same fused-step family —
    prewarming pays off); "shape_mismatch" otherwise."""
    sig = sec.get("params_sig")
    if not isinstance(sig, dict):
        return "manifest_invalid"
    return "ok" if sig == params_sig(params, n_features) \
        else "shape_mismatch"


def serving_row_buckets(num_features: Optional[int] = None) -> List[int]:
    """Row buckets the tree-parallel predictor ACTUALLY compiled in this
    process, read straight from the xla_obs ledger (the compile history
    of site ``predictor.tree_parallel`` records each trace's abstract
    shapes — the X argument is ``f32[rows,features]``)."""
    import re
    rec = xla_obs.LEDGER.register("predictor.tree_parallel")
    sigs: List[List[str]] = [list(h.get("signature", []))
                             for h in rec.history]
    if rec.last_sig:
        sigs.append(list(rec.last_sig))
    pat = re.compile(r"^f32\[(\d+),(\d+)\]$")
    buckets = set()
    for sig in sigs:
        for entry in sig:
            m = pat.match(entry)
            if not m:
                continue
            rows, feats = int(m.group(1)), int(m.group(2))
            if num_features is not None and feats != num_features:
                continue
            buckets.add(rows)
    return sorted(buckets)


def record_prewarm(kind: str, outcome: str, seconds: float) -> None:
    """Count one prewarm attempt: every path — manifest-driven, degraded
    to legacy, or errored — lands in ``lgbm_warmup_total{kind,outcome}``
    so the fleet's warm-start behavior is scrapeable."""
    telemetry.counter("lgbm_warmup_total").inc(kind=kind, outcome=outcome)
    telemetry.histogram("lgbm_warmup_seconds").observe(
        max(float(seconds), 0.0), kind=kind)
