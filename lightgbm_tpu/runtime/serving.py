"""Fault-tolerant model-serving runtime (`task=serve` / `ServingRuntime`).

ROADMAP item 3: the reference's serving story (`Predictor`/`c_api`,
SURVEY §2.5/§2.9) is strictly request-per-call — no lifecycle, no
backpressure, no model lifecycle.  This module is the long-lived server
those layers never had, built on seams earlier PRs proved out: PR 3's
tree-parallel device predictor (shape-bucketed program cache,
micro-batched streaming), PR 4's stage watchdog + degradation chain,
and PR 6's atomic publish/subscribe contract.  Robustness is the
headline, not an afterthought:

* **Admission control + backpressure.**  A bounded request queue with
  per-request deadlines.  Overload sheds with an explicit
  machine-readable retryable rejection (`ServeRejected.to_dict()`), at
  admission time — never an unbounded queue, never a silent hang.  A
  request whose deadline expires before its batch forms is shed the
  same way.
* **Priority, quotas, and a control loop (ISSUE 11).**  Requests carry a
  priority class; per-class queue reservations shed the lowest class
  first under pressure.  Per-model `quotas` bound any one tenant's
  share of the queue (`quota_exceeded`).  An optional
  `runtime.policy.AutoscaleShedPolicy` closes the loop on the
  queue-depth gauge: sustained pressure widens the micro-batch gather
  window and flips load-shed mode for the lowest class (`load_shed`),
  with every decision recorded as a metric and a trail event.
* **Micro-batching.**  Concurrent requests are coalesced (bounded rows,
  bounded gathering window) into ONE device predict through the
  shape-bucketed program cache, so p99 latency buys throughput instead
  of a compile per ragged batch.
* **Device-failure degradation.**  Every batch runs under the PR 4
  watchdog in thread mode (serving stage trail, bounded flight
  recorder).  A failed or hung device batch — `LGBM_TPU_FAULT=
  die_at_predict|slow_predict` are the injected stand-ins — trips a
  circuit breaker: the batch is RE-SERVED from the exact f64 host
  predictor (a `serving_degradation` event lands in the trail), later
  batches stay on the host path until a probe-based recovery predict
  succeeds after a cooldown.  The server answers; it does not error out.
* **Zero-drop hot swap.**  A background `ModelSubscriber` poller picks
  up new generations from the PR 6 publish directory and swaps the
  active model atomically BETWEEN batches: in-flight batches finish on
  the generation they started with, no request is ever dropped or
  served a torn/mixed model, and every response names the generation
  that produced it.  Multiple models (multi-tenancy) ride the same
  queue; compiled programs are shared across generations through the
  jit cache's shape bucketing.

Adversarial proof: `exp/chaos_serve.py` (CHAOS_SERVE_r07.json) hammers
this runtime with concurrent clients under randomized kill/stall/
publish-churn faults — zero torn or wrong-generation responses, every
completed response byte-identical to offline `Booster.predict` for the
generation it reports.  Quick pins live in tests/test_serving.py.

`Booster` (and therefore jax) is imported lazily — constructing a
runtime binds no platform until a model actually loads.
"""
from __future__ import annotations

import collections
import json
import os
import socketserver
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from . import policy as policy_mod
from . import publish, resilience, telemetry, tracing, warmup, xla_obs
from ..utils.log import Log

__all__ = ["ServingRuntime", "ServingServer", "ServeRejected",
           "ServeResult"]


class ServeRejected(RuntimeError):
    """A request the server explicitly refused (admission control,
    deadline, shutdown).  Machine-readable via `to_dict()`; `retryable`
    tells the client whether backing off and retrying can succeed."""

    def __init__(self, reason: str, retryable: bool = True,
                 detail: str = "", queue_depth: Optional[int] = None,
                 priority: Optional[int] = None,
                 retry_after_s: Optional[float] = None):
        super().__init__("request rejected (%s%s)%s"
                         % (reason, ", retryable" if retryable else "",
                            ": " + detail if detail else ""))
        self.reason = reason
        self.retryable = bool(retryable)
        self.detail = detail
        self.queue_depth = queue_depth
        # the priority class the shed applies to (ISSUE 11): every shed
        # is machine-readable WITH its class, so a client and the sim's
        # per-class shed-rate ledger never have to guess
        self.priority = priority
        # Retry-After-style backoff hint in seconds (ISSUE 16): rides
        # both the JSON rejection dict and the binary rejection frame;
        # `predict()` and the wire client raise their jittered delay to
        # it, so the server can slow a thundering herd without a new
        # round trip.  None = no hint.
        self.retry_after_s = retry_after_s

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"error": "rejected", "reason": self.reason,
                             "retryable": self.retryable,
                             "wallclock": resilience.wallclock()}
        if self.detail:
            d["detail"] = self.detail
        if self.queue_depth is not None:
            d["queue_depth"] = self.queue_depth
        if self.priority is not None:
            d["priority"] = self.priority
        if self.retry_after_s is not None:
            d["retry_after_s"] = self.retry_after_s
        return d


def retry_delay(base_delay: float, hint: Optional[float]) -> float:
    """The client-side sleep for one retryable rejection: the jittered
    backoff schedule's delay, raised to the server's Retry-After hint
    when the rejection carries a larger one (never lowered — the jitter
    is what breaks retry synchronization)."""
    return max(float(base_delay), float(hint or 0.0))


class ServeResult:
    """One completed prediction: the values, the generation that
    produced them, and how they were served."""

    __slots__ = ("values", "generation", "model_id", "served_by",
                 "latency_s", "compiled", "stages", "model_trace")

    def __init__(self, values: np.ndarray, generation: int, model_id: str,
                 served_by: str, latency_s: float, compiled: bool = False,
                 stages: Optional[Dict[str, float]] = None,
                 model_trace: Optional[str] = None):
        self.values = values
        self.generation = generation
        self.model_id = model_id
        self.served_by = served_by          # "device" | "host"
        self.latency_s = latency_s
        # True when THIS request's batch triggered an XLA compile (the
        # xla_obs ledger moved during the dispatch) — first-batch latency
        # outliers become attributable instead of mysterious
        self.compiled = compiled
        # per-request latency decomposition (ISSUE 14): queue_wait /
        # batch_gather / device / drain seconds, measured on the SAME
        # clock as latency_s so the stage sum is pinned against the
        # client-observed number (tests + the sim artifact gate on it)
        self.stages = stages or {}
        # traceparent of the training cycle that produced the serving
        # generation (from the publish meta footer) — the response's
        # backlink into the trainer's timeline
        self.model_trace = model_trace


class _Request:
    """Queued unit of work; doubles as the caller's future."""

    __slots__ = ("model_id", "X", "n_rows", "deadline", "enqueued",
                 "done", "result", "rejection", "error", "priority",
                 "label", "trace", "t_batched")

    def __init__(self, model_id: str, X: np.ndarray, deadline: float,
                 priority: int = 0, label: Optional[np.ndarray] = None,
                 trace: Optional[Tuple[str, str]] = None):
        self.model_id = model_id
        self.X = X
        self.n_rows = int(X.shape[0])
        self.deadline = deadline            # absolute time.monotonic()
        self.priority = int(priority)
        # optional ground-truth outcome the client already knows (the
        # online feedback loop): per-row labels feed the canary policy's
        # live error signal — never the prediction itself
        self.label = label
        # parsed client traceparent (ISSUE 14): requests that carry one
        # get their queue/gather/device/drain stages recorded as trace
        # events under the CLIENT's trace id
        self.trace = trace
        self.t_batched: Optional[float] = None
        self.enqueued = time.monotonic()
        self.done = threading.Event()
        self.result: Optional[ServeResult] = None
        self.rejection: Optional[ServeRejected] = None
        self.error: Optional[BaseException] = None

    def wait(self, timeout: Optional[float] = None) -> ServeResult:
        """Block for the outcome.  Raises the rejection/error the server
        recorded; a wait past `timeout` raises a retryable rejection
        (the server itself bounds every path, so this is belt-and-
        braces for a stopped runtime)."""
        if not self.done.wait(timeout):
            raise ServeRejected("result_timeout", retryable=True,
                                detail="no outcome within %.1fs"
                                % (timeout or -1.0))
        if self.rejection is not None:
            raise self.rejection
        if self.error is not None:
            raise self.error
        assert self.result is not None
        return self.result


class _ModelEntry:
    """One loaded generation of one model lineage.  Immutable after
    construction — the swap replaces the whole entry, so an in-flight
    batch holding the old reference finishes on a consistent model."""

    __slots__ = ("model_id", "generation", "booster", "meta", "loaded_at")

    def __init__(self, model_id: str, generation: int, booster, meta):
        self.model_id = model_id
        self.generation = generation
        self.booster = booster
        self.meta = dict(meta or {})
        self.loaded_at = time.monotonic()

    @property
    def num_features(self) -> int:
        return self.booster.num_feature()


class _Job:
    """One device-predict dispatch handed to the executor thread."""

    __slots__ = ("fn", "done", "values", "error", "abandoned")

    def __init__(self, fn: Callable[[], np.ndarray]):
        self.fn = fn
        self.done = threading.Event()
        self.values: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.abandoned = False


class _DeviceExecutor(threading.Thread):
    """Single dedicated thread that owns device predict dispatches.  The
    batcher waits on each job with a deadline; a job that blows it is
    marked abandoned and a FRESH executor takes over — this thread may
    be wedged inside a hung dispatch, and a wedged thread can only be
    left behind, never joined."""

    def __init__(self, index: int):
        super().__init__(name="serve-device-%d" % index, daemon=True)
        self.jobs: "collections.deque[Optional[_Job]]" = collections.deque()
        self._ready = threading.Event()
        self._stop = False

    def submit(self, job: Optional[_Job]) -> None:
        self.jobs.append(job)
        self._ready.set()

    def retire(self) -> None:
        """Ask the thread to exit after its current job (it may be
        wedged inside that job forever — that is fine, it is daemon)."""
        self._stop = True
        self._ready.set()

    def run(self) -> None:
        while True:
            if not self.jobs:
                if self._stop:
                    return
                self._ready.wait(0.1)
                self._ready.clear()
                continue
            job = self.jobs.popleft()
            if job is None:
                return
            try:
                job.values = job.fn()
            except BaseException as e:      # noqa: BLE001 — ferried out
                job.error = e
            job.done.set()
            # drop the reference before waiting: the job closure captures
            # the batch matrix, which may be a zero-copy view of a wire
            # receive buffer or a mapped SHM segment awaiting unmap
            job = None
            if self._stop:
                return


class ServingRuntime:
    """The long-lived serving loop.  Use as a context manager or call
    `start()` / `stop()` explicitly; `submit()` / `predict()` are the
    request surface (thread-safe, any number of client threads)."""

    def __init__(self,
                 publish_dir: Optional[str] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None,
                 models: Optional[Dict[str, str]] = None,
                 params: Optional[Dict[str, Any]] = None,
                 raw_score: bool = False,
                 response_dtype: Optional[str] = None,
                 max_queue: int = 256,
                 max_batch_rows: int = 4096,
                 batch_window_s: float = 0.002,
                 default_deadline_s: float = 10.0,
                 predict_deadline_s: float = 30.0,
                 poll_interval_s: float = 0.2,
                 breaker_cooldown_s: float = 2.0,
                 probe_platform_on_start: bool = False,
                 report_path: Optional[str] = None,
                 metrics_port: Optional[int] = None,
                 priority_levels: int = 3,
                 quotas: Optional[Dict[str, float]] = None,
                 max_resident: int = 0,
                 policy=None,
                 canary_fraction: float = 0.0,
                 canary_policy=None,
                 prewarm_manifest: bool = True,
                 export_manifest: bool = True,
                 log=Log):
        """`publish_dir` subscribes the default model to a PR 6 publish
        directory; `models` maps model_id -> publish_dir for
        multi-tenancy; `model_file`/`model_str` pin a static default
        model (no poller).  At least one source is required.

        ISSUE 11 admission knobs: `priority_levels` sets the number of
        priority classes (0 = highest); under queue pressure lower
        classes shed first through per-class queue reservations (class p
        may only occupy ``max_queue * (P - p) / P`` slots).  `quotas`
        maps model_id -> max fraction of the queue that tenant's
        requests may hold (rejection `quota_exceeded`, retryable) so one
        hot tenant cannot starve the rest.  `policy` is an
        `runtime.policy.AutoscaleShedPolicy`: a background thread feeds
        it the queue-depth fraction; its decisions retune
        `batch_window_s` and flip load-shed mode for the lowest class
        (rejection `load_shed`, retryable).  A ``"*"`` key in `quotas`
        is the default per-tenant share for every model id without an
        explicit entry — the knob that makes quota-fair admission
        tractable across hundreds of registered tenants (ISSUE 17).

        ISSUE 17 model-zoo residency: `max_resident` > 0 bounds how many
        registered models hold a LOADED entry at once.  Admission for a
        registered-but-paged-out tenant marks it *wanted*; its requests
        answer with the retryable ``no_model`` rejection until the
        poller pages it in, evicting the least-recently-used resident
        model first.  A model with queued or in-flight requests is NEVER
        evicted (pinned in tests); when every resident model is busy the
        page-in defers to the next poll instead of overshooting the
        bound.  Evicting exports the victim's per-tenant warm manifest
        (best effort), so the next page-in — here or on any replica —
        prewarms from the manifest instead of compiling cold.  The
        default 0 keeps every registered model resident (legacy).

        ISSUE 12 canary knobs: `canary_fraction` > 0 turns newly
        published generations into CANARIES — the poller loads them
        beside the incumbent instead of swapping, the batcher routes
        that fraction of batches to them (deterministic interleave at
        the existing swap seam), and a `runtime.policy.CanaryPolicy`
        (`canary_policy`, default-constructed when omitted) judges
        canary vs incumbent error/latency with hysteresis.  Sustained
        degradation ROLLS BACK: the canary is dropped, the publish dir
        gets a durable ROLLBACK marker condemning the generation
        fleet-wide, and the subscriber pins the incumbent until a fresh
        candidate lands.  Sustained health PROMOTES the canary to
        incumbent.  At the default `canary_fraction=0` every new
        generation swaps in directly — byte-identical to the pre-canary
        behavior.

        ISSUE 15 warm-start knobs: with `prewarm_manifest` (default on)
        a fresh runtime reads the newest ``warmup.json`` shape manifest
        from each publish dir and precompiles the row buckets it names
        BEFORE ``/healthz`` reports ready and before admission opens; a
        torn/stale/absent/shape-mismatched manifest degrades to the
        legacy smallest-bucket prewarm (counted in
        ``lgbm_warmup_total{outcome}``) — it never blocks serving.
        `export_manifest` (default on) publishes the buckets THIS
        process actually compiled back to the publish dir at stop, so
        the next replica starts warm."""
        self.log = log
        self._params = dict(params or {})
        self._raw_score = bool(raw_score)
        # ISSUE 16: response_dtype="float32" serves f32 values — the
        # device fetch moves half the bytes (D2H shrinks 2×) and the
        # result equals the f64 answer .astype(float32) exactly (the
        # device computes in f32; the fetch dtype only changes the
        # upcast).  Default None keeps the legacy f64 surface.
        if response_dtype not in (None, "float32", "float64"):
            raise ValueError("response_dtype must be None, 'float32' or "
                             "'float64', got %r" % (response_dtype,))
        self._out_dtype = (np.float32 if response_dtype == "float32"
                           else None)
        self.max_queue = int(max_queue)
        self.max_batch_rows = int(max_batch_rows)
        self.batch_window_s = float(batch_window_s)
        self.default_deadline_s = float(default_deadline_s)
        self.predict_deadline_s = float(predict_deadline_s)
        self.poll_interval_s = float(poll_interval_s)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self.probe_platform_on_start = bool(probe_platform_on_start)
        self.priority_levels = max(int(priority_levels), 1)
        self.quotas: Dict[str, float] = dict(quotas or {})
        self.policy = policy
        self.canary_fraction = float(canary_fraction)
        if not 0.0 <= self.canary_fraction <= 1.0:
            raise ValueError("canary_fraction must be in [0, 1], got %r"
                             % canary_fraction)
        self._canary_policy_proto = canary_policy
        self._canary_policies: Dict[str, policy_mod.CanaryPolicy] = {}
        self._canary: Dict[str, _ModelEntry] = {}
        self._canary_seq: "collections.Counter[str]" = collections.Counter()
        self.rollback_events: List[Dict[str, Any]] = []

        self._dirs: Dict[str, str] = dict(models or {})
        if publish_dir:
            self._dirs.setdefault("default", publish_dir)
        self._static: Optional[str] = None
        if model_str is not None:
            self._static = model_str
        elif model_file is not None:
            with open(model_file) as fh:
                self._static = fh.read()
        if not self._dirs and self._static is None:
            raise ValueError("ServingRuntime needs publish_dir=, models= "
                             "or a model_file/model_str")

        self._subs = {mid: publish.ModelSubscriber(d, attempts=1)
                      for mid, d in self._dirs.items()}
        self._entries: Dict[str, _ModelEntry] = {}
        self._entries_lock = threading.Lock()

        self.prewarm_manifest = bool(prewarm_manifest)
        self.export_manifest = bool(export_manifest)
        self.prewarm_events: List[Dict[str, Any]] = []
        #: readiness gate (ISSUE 15): set once start() has finished the
        #: prewarm pass — /healthz reports 503 and submit() sheds with
        #: reason "warming" until then, so a replica never admits a
        #: request it would answer with a cold compile
        self._ready = threading.Event()

        self._queue: "collections.deque[_Request]" = collections.deque()
        # batch-gather arena (ISSUE 16): preallocated per-bucket request
        # buffers keyed (row-bucket, cols, dtype) that multi-request
        # batches are gathered into instead of np.concatenate.  Only the
        # single batcher thread writes it, and a batch is fully consumed
        # (dispatched + drained) before the next one is gathered, so one
        # buffer per bucket serves the runtime's whole lifetime — zero
        # steady-state gather allocation.
        self._arena: Dict[Tuple[int, int, str], np.ndarray] = {}
        self._cond = threading.Condition()
        self._stopped = False
        self._started = False
        # per-tenant queued-request counts (the quota denominator) and
        # the policy-driven load-shed latch; both live under self._cond
        self._queued_by_model: "collections.Counter[str]" = \
            collections.Counter()
        self._shed_low = False
        # ISSUE 17 bounded model-zoo residency (0 = unbounded/legacy):
        # LRU stamps per tenant (touched at admission), demand marks for
        # paged-out tenants, and in-flight counts (the never-evict pin's
        # second leg — queued is the first)
        self.max_resident = max(int(max_resident or 0), 0)
        self._lru: Dict[str, float] = {}
        self._wanted: Dict[str, float] = {}
        self._inflight_by_model: "collections.Counter[str]" = \
            collections.Counter()
        self.residency_events: List[Dict[str, Any]] = []

        # serving stage trail: PR 4 watchdog in thread mode with a
        # bounded flight recorder (one stage per batch — unbounded
        # growth would be its own reliability bug)
        self.wd = resilience.Watchdog(
            0, hard=False, label="serve stage", use_alarm=False,
            keep_last=256, stream=sys.stderr,
            report_path=report_path
            or os.environ.get("LGBM_TPU_SERVE_REPORT"))
        self._wd_lock = threading.Lock()

        self._breaker = {"state": "closed", "open_until": 0.0}
        self.degradation_events: List[Dict[str, Any]] = []
        self.recovery_events: List[Dict[str, Any]] = []
        self.start_degradation: Optional[Dict[str, Any]] = None

        self._stats_lock = threading.Lock()
        self._stats: Dict[str, Any] = {
            "admitted": 0, "completed": 0,
            "rejected": collections.Counter(),
            "rows_served": 0, "batches_device": 0, "batches_host": 0,
            "swaps": 0, "degradations": 0, "recoveries": 0,
            "canary_batches": 0, "rollbacks": 0, "promotes": 0,
        }

        self._executor_idx = 0
        self._executor: Optional[_DeviceExecutor] = None
        self._batcher: Optional[threading.Thread] = None
        self._poller: Optional[threading.Thread] = None
        self._policy_thread: Optional[threading.Thread] = None

        # live Prometheus endpoint (ISSUE 9): metrics_port=0 picks an
        # ephemeral port, exposed via `metrics_port` after start()
        self._metrics_port_req = metrics_port
        self.metrics_server: Optional[telemetry.MetricsServer] = None

    # -- lifecycle -----------------------------------------------------------
    def __enter__(self) -> "ServingRuntime":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def start(self) -> "ServingRuntime":
        if self._started:
            return self
        self._started = True
        # persistent-compile-cache seam (ISSUE 15): honor
        # $LGBM_TPU_COMPILE_CACHE before the first model load compiles
        warmup.maybe_enable_from_env()
        if self._metrics_port_req is not None:
            # /healthz answers 503 "warming" until the prewarm pass
            # below finishes — prewarm-before-admit, visible to LBs
            self.metrics_server = telemetry.start_http_server(
                self._metrics_port_req,
                health_provider=self._ready.is_set)
            self.log.info("serve: /metrics on port %d",
                          self.metrics_server.port)
        with self._wd_lock:
            self.wd("start")
        if self.probe_platform_on_start:
            # PR 4 degradation chain at bring-up: a dead accelerator
            # tunnel degrades the PROCESS to cpu loudly instead of
            # hanging the first batch (the device path then runs the
            # jitted engine on the cpu backend — still the batched path)
            backend, event, _ = resilience.resolve_backend()
            if event is not None:
                self.start_degradation = event
                with self._wd_lock:
                    self.wd.annotate("degradation_event", event)
                self.log.warning("serve: platform degraded at start: %s",
                                 event["reason"])
            os.environ.setdefault("JAX_PLATFORMS", backend)
        if self._static is not None:
            self._swap_in("default", self._static, generation=0, meta={})
        # default first: under bounded residency the lineage model must
        # win a residency slot before any zoo tenant claims one
        for mid in sorted(self._dirs, key=lambda m: (m != "default", m)):
            self._poll_model(mid)       # best effort; poller keeps trying
        # prewarm-before-admit (ISSUE 15): precompile the shape buckets
        # the lineage's manifest names BEFORE readiness opens.  Bounded
        # and guarded — a bad manifest degrades to the smallest-bucket
        # prewarm _swap_in already did, never blocks serving.
        self._prewarm_start()
        # fleet fault seam (ISSUE 17): `die_at_spawn:K` kills the K-th
        # spawned replica exactly here — prewarm paid, /healthz never
        # ready — so a FleetController's relaunch path is exercised on
        # the most expensive death window
        resilience.maybe_die_at_spawn()
        self._ready.set()
        self._executor = self._spawn_executor()
        self._batcher = threading.Thread(target=self._batcher_loop,
                                         name="serve-batcher", daemon=True)
        self._batcher.start()
        if self._subs:
            self._poller = threading.Thread(target=self._poller_loop,
                                            name="serve-poller", daemon=True)
            self._poller.start()
        if self.policy is not None:
            self._policy_thread = threading.Thread(
                target=self._policy_loop, name="serve-policy", daemon=True)
            self._policy_thread.start()
        with self._wd_lock:
            self.wd("serving", seconds=0)
        return self

    def stop(self) -> None:
        """Clean shutdown: queued requests are rejected explicitly
        (reason `shutdown`, non-retryable against THIS endpoint), never
        silently dropped."""
        with self._cond:
            if self._stopped:
                return
            self._stopped = True
            pending = list(self._queue)
            self._queue.clear()
            self._cond.notify_all()
        self._queued_by_model.clear()
        for req in pending:
            req.rejection = ServeRejected("shutdown", retryable=False,
                                          priority=req.priority)
            req.done.set()
            self._count_rejection("shutdown", priority=req.priority)
        # publish this process's observed shape buckets so the NEXT
        # replica of the lineage starts warm (ISSUE 15); best effort —
        # shutdown must never fail on a read-only publish dir
        if self.export_manifest:
            for mid in list(self._dirs):
                try:
                    self.export_warmup_manifest(mid)
                except Exception as e:    # noqa: BLE001 — best effort
                    self.log.warning("serve: warmup-manifest export for "
                                     "%s failed: %s", mid, e)
        if self._executor is not None:
            self._executor.submit(None)
        for t in (self._batcher, self._poller, self._policy_thread):
            if t is not None:
                t.join(timeout=5)
        with self._wd_lock:
            self.wd.done()
        if self.metrics_server is not None:
            self.metrics_server.stop()
            self.metrics_server = None

    # -- model lifecycle -----------------------------------------------------
    def _swap_in(self, model_id: str, model_text: str, generation: int,
                 meta: Dict[str, Any]) -> None:
        """Load + prewarm a generation, then swap it in atomically.  The
        swap is a dict assignment under a lock taken only for the
        assignment: batches capture their entry BEFORE predicting, so an
        in-flight batch finishes on the generation it started with."""
        from ..basic import Booster
        t0 = time.monotonic()
        c0 = xla_obs.total_compiles()
        bst = Booster(params=dict(self._params), model_str=model_text)
        entry = _ModelEntry(model_id, generation, bst, meta)
        try:
            # prewarm the device program for the smallest shape bucket so
            # the first live batch does not pay the compile; an injected
            # device fault here must not block the swap (the host path
            # still serves)
            bst.predict(np.zeros((1, entry.num_features)),
                        raw_score=self._raw_score, device=True)
        except BaseException as e:          # noqa: BLE001 — degraded path
            self.log.warning("serve: prewarm of %s gen %d failed (%s); "
                             "swapping anyway (host path serves)",
                             model_id, generation, e)
        # prewarm compiles were invisible before ISSUE 10: tag them
        # through the ledger so a slow swap names its cause (a reused
        # shape bucket prewarms as a pure cache hit)
        prewarm_compiles = xla_obs.total_compiles() - c0
        xla_obs.cache_event("serving.prewarm",
                            "compile" if prewarm_compiles else "hit",
                            max(prewarm_compiles, 1))
        with self._entries_lock:
            fresh = model_id not in self._entries
            self._entries[model_id] = entry
            resident = len(self._entries)
        with self._stats_lock:
            self._stats["swaps"] += 1
        telemetry.counter("lgbm_serve_swaps_total").inc()
        telemetry.gauge("lgbm_serve_resident_models").set(resident)
        if self.max_resident > 0 and fresh:
            # a zoo tenant just paged in: clear its demand mark, stamp
            # its LRU slot, and prewarm from its per-tenant manifest so
            # the first live request doesn't pay the bucket compiles
            self._wanted.pop(model_id, None)
            self._lru.setdefault(model_id, time.monotonic())
            telemetry.counter("lgbm_serve_residency_events_total").inc(
                event="page_in")
            self.residency_events.append({
                "event": "page_in", "model": model_id,
                "generation": generation, "resident": resident,
                "wallclock": resilience.wallclock()})
            if self.prewarm_manifest and self._ready.is_set():
                pub_dir = self._dirs.get(model_id)
                try:
                    sec, _ = (warmup.read_manifest(pub_dir, "serving")
                              if pub_dir else (None, "static"))
                    if sec is not None and warmup.classify_serving_section(
                            sec, num_features=entry.num_features,
                            newest_generation=generation) == "ok":
                        self._prewarm_buckets(entry, sec["row_buckets"])
                except Exception as e:  # noqa: BLE001 — never block page-in
                    self.log.warning("serve: page-in prewarm of %s failed:"
                                     " %s", model_id, e)
        # sink end of the publish→subscriber flow arrow (ISSUE 14): the
        # flow id re-derives from the SAME meta fields the publisher
        # used, so the merged timeline links this swap back to the
        # training cycle that produced the generation
        tracing.flow_end(
            "model swap gen=%d" % generation,
            tracing.flow_id(meta.get("trace") or "no-trace", generation),
            model=model_id, generation=generation,
            producer_trace=meta.get("trace"))
        with self._wd_lock:
            self.wd.annotate("last_swap", {
                "model": model_id, "generation": generation,
                "load_s": round(time.monotonic() - t0, 4),
                "prewarm_compiles": prewarm_compiles,
                "wallclock": resilience.wallclock()})
        self.log.info("serve: %s now at generation %d (loaded in %.3fs)",
                      model_id, generation, time.monotonic() - t0)

    def _poll_model(self, model_id: str) -> None:
        sub = self._subs.get(model_id)
        if sub is None:
            return
        if not self._residency_admit(model_id):
            return
        rec = sub.resolve_once()
        if rec is None:
            return
        cur = self._entries.get(model_id)
        if cur is not None and cur.generation == rec.generation:
            return
        if self.canary_fraction <= 0 or cur is None:
            # canary disabled (or nothing to compare against yet): the
            # pre-ISSUE-12 direct swap, unchanged
            self._swap_in(model_id, rec.model_text, rec.generation,
                          rec.meta)
            return
        can = self._canary.get(model_id)
        if can is not None and can.generation == rec.generation:
            return
        self._canary_in(model_id, rec)

    # -- bounded model-zoo residency (ISSUE 17) ------------------------------
    def _residency_admit(self, model_id: str) -> bool:
        """Gate a (re)load of `model_id` against the residency bound.
        Resident models always pass (generation swaps replace in place,
        no net growth).  A paged-out tenant passes only when it is
        WANTED (a request touched it since the last poll — the default
        lineage model is always wanted) AND a slot is free or an idle
        LRU victim can give one up."""
        if self.max_resident <= 0:
            return True
        with self._entries_lock:
            if model_id in self._entries:
                return True
            room = len(self._entries) < self.max_resident
        if model_id != "default" and model_id not in self._wanted:
            return False
        if room:
            return True
        return self._evict_lru(model_id)

    def _evict_lru(self, incoming: str) -> bool:
        """Evict the least-recently-used resident model to make room for
        `incoming`.  The never-evict invariant: a model with queued OR
        in-flight requests is not a candidate — its clients have been
        admitted and must complete on a loaded entry.  When every
        resident model is busy, the page-in DEFERS (returns False)
        rather than overshooting the bound; the poller retries next
        cycle.  The victim's per-tenant warm manifest exports first
        (best effort) so its next page-in starts warm."""
        with self._cond:
            busy = {m for m, n in self._queued_by_model.items() if n > 0}
            busy |= {m for m, n in self._inflight_by_model.items()
                     if n > 0}
        with self._entries_lock:
            candidates = [m for m in self._entries
                          if m != incoming and m not in busy]
        if not candidates:
            telemetry.counter("lgbm_serve_residency_events_total").inc(
                event="defer")
            self.residency_events.append({
                "event": "defer", "model": incoming,
                "wallclock": resilience.wallclock()})
            return False
        victim = min(candidates, key=lambda m: self._lru.get(m, 0.0))
        if self.export_manifest:
            try:
                self.export_warmup_manifest(victim)
            except Exception as e:          # noqa: BLE001 — best effort
                self.log.warning("serve: eviction manifest export for %s "
                                 "failed: %s", victim, e)
        with self._entries_lock:
            self._entries.pop(victim, None)
            resident = len(self._entries)
        self._canary.pop(victim, None)
        self._lru.pop(victim, None)
        telemetry.counter("lgbm_serve_residency_events_total").inc(
            event="evict")
        telemetry.gauge("lgbm_serve_resident_models").set(resident)
        event = {"event": "evict", "model": victim, "for": incoming,
                 "resident": resident,
                 "wallclock": resilience.wallclock()}
        self.residency_events.append(event)
        with self._wd_lock:
            self.wd.annotate("residency_evict", event)
        self.log.info("serve: evicted %s (LRU) to page in %s (%d/%d "
                      "resident)", victim, incoming, resident,
                      self.max_resident)
        return True

    # -- warm start (ISSUE 15): manifest prewarm + manifest export ----------
    def _prewarm_start(self) -> None:
        """Read each publish dir's ``warmup.json`` and precompile the
        row buckets it names, BEFORE `_ready` opens.  Every attempt —
        manifest-driven or degraded — is counted in
        ``lgbm_warmup_total{kind="serving",outcome}``; a degradation
        means the legacy smallest-bucket prewarm from `_swap_in` is all
        this replica starts with, exactly the pre-ISSUE-15 behavior."""
        if not self.prewarm_manifest:
            return
        for mid, pub_dir in self._dirs.items():
            if self.max_resident > 0 and mid not in self._entries:
                # paged-out zoo tenant: its page-in prewarms from its
                # own per-tenant manifest when demand arrives
                continue
            t0 = time.monotonic()
            entry = self._entries.get(mid)
            outcome, buckets = "legacy", []
            try:
                sec, reason = warmup.read_manifest(pub_dir, "serving")
                if sec is None:
                    outcome = "manifest_" + reason
                elif entry is None:
                    # nothing resolved yet (racing the very first
                    # publish): the poller's later swap-in prewarms
                    outcome = "no_model"
                else:
                    outcome = warmup.classify_serving_section(
                        sec, num_features=entry.num_features,
                        newest_generation=entry.generation)
                    if outcome == "ok":
                        buckets = self._prewarm_buckets(
                            entry, sec["row_buckets"])
                        outcome = "manifest_ok"
            except Exception as e:      # noqa: BLE001 — never block serving
                outcome = "error"
                self.log.warning("serve: manifest prewarm of %s failed "
                                 "(%s); legacy prewarm serves", mid, e)
            dt = time.monotonic() - t0
            warmup.record_prewarm("serving", outcome, dt)
            event = {"model": mid, "outcome": outcome,
                     "buckets": buckets, "seconds": round(dt, 4),
                     "wallclock": resilience.wallclock()}
            self.prewarm_events.append(event)
            with self._wd_lock:
                self.wd.annotate("prewarm", event)
            if outcome == "manifest_ok":
                self.log.info("serve: %s prewarmed %d manifest bucket(s) "
                              "in %.3fs before admission", mid,
                              len(buckets), dt)

    def _prewarm_buckets(self, entry: _ModelEntry,
                         buckets: List[int]) -> List[int]:
        """Dispatch one zero batch per manifest row bucket through the
        device path, so the bucketed programs compile (or load from the
        persistent cache) before the first real request.  Bounded: at
        most MAX_PREWARM_BUCKETS, each clamped to the micro-batch bucket
        ceiling; a failing bucket is skipped (the host path still
        serves), never fatal."""
        cap = max(self.max_batch_rows, 16)
        todo = sorted({min(int(b), cap) for b in buckets
                       if isinstance(b, int) and b > 0})
        done: List[int] = []
        for b in todo[:warmup.MAX_PREWARM_BUCKETS]:
            c0 = xla_obs.total_compiles()
            try:
                entry.booster.predict(
                    np.zeros((b, entry.num_features)),
                    raw_score=self._raw_score, device=True)
            except BaseException as e:   # noqa: BLE001 — degraded path
                self.log.warning("serve: prewarm of bucket %d failed "
                                 "(%s); skipping", b, e)
                continue
            compiles = xla_obs.total_compiles() - c0
            xla_obs.cache_event("serving.prewarm",
                                "compile" if compiles else "hit",
                                max(compiles, 1))
            done.append(b)
        return done

    def export_warmup_manifest(self, model_id: str = "default"
                               ) -> Optional[str]:
        """Publish the row buckets THIS process actually compiled (from
        the xla_obs ledger) as the publish dir's ``serving`` manifest
        section.  No-op (returns None) when the model has no publish dir
        or no bucket ever compiled — an empty export must not clobber a
        useful manifest."""
        pub_dir = self._dirs.get(model_id)
        entry = self._entries.get(model_id)
        if not pub_dir or entry is None:
            return None
        buckets = warmup.serving_row_buckets(
            num_features=entry.num_features)
        if not buckets:
            return None
        return publish.ModelPublisher(pub_dir).publish_manifest(
            "serving", warmup.build_serving_section(
                num_features=entry.num_features, row_buckets=buckets,
                generation=entry.generation))

    # -- canary + automatic rollback (ISSUE 12 stage three) -----------------
    def _policy_for(self, model_id: str) -> policy_mod.CanaryPolicy:
        pol = self._canary_policies.get(model_id)
        if pol is None:
            pol = (self._canary_policy_proto
                   if self._canary_policy_proto is not None
                   and not self._canary_policies
                   else policy_mod.CanaryPolicy())
            self._canary_policies[model_id] = pol
        return pol

    def _canary_in(self, model_id: str, rec) -> None:
        """Load a freshly published generation as the CANARY: it serves
        only `canary_fraction` of batches until the policy promotes or
        rolls it back.  The incumbent keeps full ownership of the rest —
        a regressed publish can never touch more than the canary share
        of traffic."""
        from ..basic import Booster
        t0 = time.monotonic()
        bst = Booster(params=dict(self._params), model_str=rec.model_text)
        entry = _ModelEntry(model_id, rec.generation, bst, rec.meta)
        try:
            bst.predict(np.zeros((1, entry.num_features)),
                        raw_score=self._raw_score, device=True)
        except BaseException as e:          # noqa: BLE001 — degraded path
            self.log.warning("serve: canary prewarm of %s gen %d failed "
                             "(%s); host path serves it", model_id,
                             rec.generation, e)
        self._canary[model_id] = entry
        tracing.flow_end(
            "canary load gen=%d" % rec.generation,
            tracing.flow_id(rec.meta.get("trace") or "no-trace",
                            rec.generation),
            model=model_id, generation=rec.generation,
            producer_trace=rec.meta.get("trace"))
        start = self._policy_for(model_id).note_start(rec.generation)
        with self._wd_lock:
            self.wd.annotate("canary_start", dict(
                start, model=model_id,
                load_s=round(time.monotonic() - t0, 4)))
        self.log.warning("serve: generation %d of %s entered CANARY "
                         "(%.0f%% of batches); incumbent stays %d",
                         rec.generation, model_id,
                         self.canary_fraction * 100,
                         self._entries[model_id].generation)

    def _batch_error(self, values: np.ndarray,
                     batch: List[_Request]) -> Optional[float]:
        """Mean observed prediction error over the requests that carried
        a label (None when nobody did) — the canary policy's live
        quality signal.  Classification matrices score top-1 error;
        everything else scores mean absolute error on the transformed
        output."""
        errs: List[float] = []
        s = 0
        vals = np.asarray(values)
        for req in batch:
            e = s + req.n_rows
            if req.label is not None:
                lab = np.asarray(req.label, dtype=np.float64).reshape(-1)
                v = vals[s:e]
                if v.ndim == 2 and v.shape[1] > 1:
                    errs.append(float(np.mean(
                        np.argmax(v, axis=1) != lab[: v.shape[0]])))
                else:
                    errs.append(float(np.mean(np.abs(
                        v.reshape(-1) - lab[: v.size]))))
            s = e
        return float(np.mean(errs)) if errs else None

    def _apply_canary_decision(self, model_id: str,
                               rec: Dict[str, Any]) -> None:
        can = self._canary.pop(model_id, None)
        if can is None:
            return
        incumbent = self._entries.get(model_id)
        if rec["event"] == "canary_promote":
            with self._entries_lock:
                self._entries[model_id] = can
            with self._stats_lock:
                self._stats["promotes"] += 1
                self._stats["swaps"] += 1
            telemetry.counter("lgbm_serve_swaps_total").inc()
            with self._wd_lock:
                self.wd.annotate("canary_promote", dict(rec,
                                                        model=model_id))
            self.log.warning("serve: canary generation %d of %s PROMOTED "
                             "to incumbent", can.generation, model_id)
            return
        # rollback: condemn the generation fleet-wide and pin the
        # subscriber to the incumbent until a NEWER candidate lands.
        # The marker is durable (atomic file in the publish dir): it
        # survives pruning, relaunch, and is seen by every concurrent
        # reader — a condemned generation can never be resolved again.
        pinned = incumbent.generation if incumbent is not None else None
        pub_dir = self._dirs.get(model_id)
        marker = None
        if pub_dir:
            marker = publish.mark_rollback(
                pub_dir, can.generation, pinned_generation=pinned,
                reason="canary degradation", evidence=rec.get("evidence"))
            sub = self._subs.get(model_id)
            if sub is not None and pinned is not None:
                sub.pin_generation(pinned, release_above=can.generation)
        event = dict(rec, model=model_id, bad_generation=can.generation,
                     pinned_generation=pinned,
                     marker=bool(marker))
        self.rollback_events.append(event)
        with self._stats_lock:
            self._stats["rollbacks"] += 1
        with self._wd_lock:
            self.wd.annotate("canary_rollback", event)
        self.log.warning(
            "serve: canary generation %d of %s ROLLED BACK after %s "
            "batches (%s); fleet pinned to generation %s",
            can.generation, model_id, rec.get("canary_batches"),
            rec.get("evidence"), pinned)

    def _poller_loop(self) -> None:
        while not self._stopped:
            for mid in list(self._subs):
                try:
                    self._poll_model(mid)
                except BaseException as e:   # noqa: BLE001 — keep polling
                    self.log.warning("serve: poll of %s failed: %s", mid, e)
            time.sleep(self.poll_interval_s)

    def _policy_loop(self) -> None:
        """Feed the autoscale/shed policy the queue-depth fraction and
        APPLY its decisions: the gather window retunes live (the batcher
        reads `batch_window_s` per batch) and load-shed mode latches
        under the admission lock.  Every decision lands in the stage
        trail next to degradations and swaps."""
        pol = self.policy
        while not self._stopped:
            time.sleep(pol.interval_s)
            decisions = pol.observe(len(self._queue)
                                    / max(self.max_queue, 1))
            if not decisions:
                continue
            self.batch_window_s = pol.window_s
            with self._cond:
                self._shed_low = pol.shed_active
            for rec in decisions:
                with self._wd_lock:
                    self.wd.annotate("policy_decision", rec)
                self.log.warning(
                    "serve: policy %s (window=%.4fs shed=%s depth=%.0f%%)",
                    rec["action"], rec["window_s"], rec["shed_active"],
                    rec["depth_frac"] * 100)

    def set_shed_allowed(self, allowed: bool) -> None:
        """Grant/revoke the autoscale policy's shed permission (ISSUE 17:
        a fleet controller grants it only once the fleet is at max
        replicas — shedding is the LAST resort, after scale-up).  A
        revoke while shed is latched releases it immediately under the
        admission lock.  No-op without a policy."""
        pol = self.policy
        if pol is None or not hasattr(pol, "allow_shed"):
            return
        decisions = pol.allow_shed(allowed)
        with self._cond:
            self._shed_low = bool(pol.shed_active)
        for rec in decisions:
            with self._wd_lock:
                self.wd.annotate("policy_decision", rec)
            self.log.warning("serve: fleet %s shed permission (shed=%s)",
                             "granted" if allowed else "revoked",
                             pol.shed_active)

    def generation(self, model_id: str = "default") -> Optional[int]:
        entry = self._entries.get(model_id)
        return entry.generation if entry is not None else None

    def canary_generation(self, model_id: str = "default") -> Optional[int]:
        """Generation currently under canary judgment (None when no
        canary window is open for this model)."""
        entry = self._canary.get(model_id)
        return entry.generation if entry is not None else None

    @property
    def metrics_port(self) -> Optional[int]:
        """The live /metrics port (None unless metrics_port= was given)."""
        return self.metrics_server.port if self.metrics_server else None

    @property
    def ready(self) -> bool:
        """True once the prewarm pass finished and admission opened
        (what /healthz reports)."""
        return self._ready.is_set()

    @property
    def wire_wait_timeout_s(self) -> float:
        """How long a wire-plane handler (socket or SHM ring) waits on
        an admitted request's future before giving up — generous enough
        that the runtime's own deadline machinery always fires first."""
        return self.default_deadline_s + self.predict_deadline_s + 10.0

    # -- request surface -----------------------------------------------------
    def submit(self, data, deadline_s: Optional[float] = None,
               model_id: str = "default", priority: int = 0,
               label=None, traceparent: Optional[str] = None) -> _Request:
        """Admit one request (a feature row [F] or small matrix [B, F]).
        Raises `ServeRejected` IMMEDIATELY when the queue is full or the
        server is stopped — shedding at admission is the backpressure
        contract; blocking the caller would just move the unbounded
        queue into the clients.

        `priority` (0 = highest, clamped to `priority_levels`) selects
        the admission class: class p only admits while the queue holds
        fewer than ``max_queue * (P - p) / P`` requests, so under
        pressure the lowest class sheds FIRST and the highest keeps the
        full queue.  A policy-flipped load-shed mode rejects the lowest
        class outright (`load_shed`); a tenant past its `quotas` share
        is rejected `quota_exceeded`.  All three rejections are
        machine-readable, carry the request's class, and are retryable.

        `label` optionally carries the request's ground-truth outcome
        (per row): it never influences the prediction — it feeds the
        canary policy's live error signal (ISSUE 12).

        `traceparent` (ISSUE 14) attaches the client's trace context:
        the server records this request's queue_wait / batch_gather /
        device / drain stages as trace events under the client's trace
        id, and the response's stage decomposition rides `ServeResult.
        stages`.  A malformed value is dropped, never rejected."""
        X = np.atleast_2d(np.asarray(data, dtype=np.float64))
        return self._submit_array(X, deadline_s, model_id, priority,
                                  label, traceparent)

    def submit_view(self, X: np.ndarray,
                    deadline_s: Optional[float] = None,
                    model_id: str = "default",
                    priority: int = 0) -> _Request:
        """Zero-copy admission for the binary data plane (ISSUE 16):
        `X` must already be a 2-D float matrix — typically a float32
        VIEW of a wire receive buffer — and is queued AS IS: no dtype
        conversion, no copy, no per-request allocation.  The caller owns
        the aliased buffer and must not reuse it until the request
        completes (the wire handler's one-frame-in-flight protocol
        guarantees this).  Same admission contract as `submit`."""
        if X.ndim != 2:
            X = np.atleast_2d(X)
        return self._submit_array(X, deadline_s, model_id, priority,
                                  None, None)

    def _submit_array(self, X: np.ndarray, deadline_s: Optional[float],
                      model_id: str, priority: int, label,
                      traceparent: Optional[str]) -> _Request:
        deadline = time.monotonic() + (self.default_deadline_s
                                       if deadline_s is None
                                       else float(deadline_s))
        P = self.priority_levels
        prio = min(max(int(priority), 0), P - 1)
        req = _Request(model_id, X, deadline, priority=prio,
                       label=None if label is None
                       else np.asarray(label, dtype=np.float64),
                       trace=tracing.parse_traceparent(traceparent)
                       if traceparent else tracing.thread_context())
        with self._cond:
            if self._stopped or not self._started:
                raise ServeRejected("shutdown", retryable=False,
                                    detail="runtime not serving",
                                    priority=prio)
            if not self._ready.is_set():
                # admission opens only after the prewarm pass (ISSUE
                # 15): retryable — the client's bounded backoff lands
                # after readiness instead of paying the cold compile
                self._count_rejection("warming", priority=prio)
                raise ServeRejected(
                    "warming", retryable=True, priority=prio,
                    retry_after_s=0.1,
                    detail="prewarm in progress; retry shortly")
            if self._shed_low and prio == P - 1:
                self._count_rejection("load_shed", priority=prio)
                raise ServeRejected(
                    "load_shed", retryable=True, priority=prio,
                    queue_depth=len(self._queue), retry_after_s=0.1,
                    detail="policy shed mode active for the lowest class")
            # per-tenant quota, with "*" as the default share for every
            # registered tenant without an explicit entry (ISSUE 17:
            # quota-fair admission across hundreds of tenants without
            # hundreds of config lines)
            quota = self.quotas.get(model_id, self.quotas.get("*"))
            if quota is not None and self._queued_by_model[model_id] >= \
                    max(int(quota * self.max_queue), 1):
                self._count_rejection("quota_exceeded", priority=prio)
                raise ServeRejected(
                    "quota_exceeded", retryable=True, priority=prio,
                    queue_depth=len(self._queue), retry_after_s=0.05,
                    detail="model %r is at its quota (%d queued >= %.0f%% "
                           "of the queue)" % (model_id,
                                              self._queued_by_model[model_id],
                                              quota * 100))
            limit = (self.max_queue * (P - prio)) // P
            if len(self._queue) >= limit:
                self._count_rejection("queue_full", priority=prio)
                raise ServeRejected(
                    "queue_full", retryable=True, priority=prio,
                    queue_depth=len(self._queue), retry_after_s=0.05,
                    detail="class p%d reservation is %d slots" % (prio,
                                                                  limit))
            self._queue.append(req)
            self._queued_by_model[model_id] += 1
            depth = len(self._queue)
            if self.max_resident > 0:
                # residency bookkeeping (ISSUE 17): every admission
                # touches the tenant's LRU stamp; a registered-but-
                # paged-out tenant is marked wanted so the poller pages
                # it in (this request retries through retryable
                # no_model rejections until the entry lands)
                self._lru[model_id] = req.enqueued
                if model_id in self._dirs \
                        and model_id not in self._entries:
                    self._wanted[model_id] = req.enqueued
            self._cond.notify()
        with self._stats_lock:
            self._stats["admitted"] += 1
        telemetry.gauge("lgbm_serve_queue_depth").set(depth)
        return req

    def predict(self, data, deadline_s: Optional[float] = None,
                model_id: str = "default", attempts: int = 3,
                seed: int = 0, priority: int = 0,
                label=None) -> ServeResult:
        """Blocking client helper: submit + wait, with bounded jittered
        retry on RETRYABLE rejections (queue_full under a load spike,
        no_model while the first generation lands).  A rejection that
        carries a `retry_after_s` hint raises the jittered delay to it
        (ISSUE 16) — same contract as the binary `wire.WireClient`."""
        delays = resilience.backoff_delays(max(attempts, 1), base=0.05,
                                           cap=0.5, seed=seed)
        deadline = (self.default_deadline_s if deadline_s is None
                    else float(deadline_s))
        last: Optional[ServeRejected] = None
        for a in range(max(attempts, 1)):
            try:
                req = self.submit(data, deadline_s=deadline,
                                  model_id=model_id, priority=priority,
                                  label=label)
                return req.wait(timeout=deadline
                                + self.predict_deadline_s + 10.0)
            except ServeRejected as e:
                last = e
                if not e.retryable:
                    raise
                if a < len(delays):
                    time.sleep(retry_delay(delays[a], e.retry_after_s))
        assert last is not None
        raise last

    # -- the batcher ---------------------------------------------------------
    def _gather_batch(self, batch: List[_Request]) -> np.ndarray:
        """Rows of a multi-request batch, gathered into the preallocated
        per-bucket arena (no np.concatenate allocation).  A mixed
        float32/float64 batch — wire and JSON requests for the same
        model — gathers as float64 (the f32→f64 upcast is exact, and the
        device path casts to f32 anyway)."""
        if len(batch) == 1:
            return batch[0].X
        rows = sum(r.n_rows for r in batch)
        cols = int(batch[0].X.shape[1])
        dtype = batch[0].X.dtype
        for r in batch[1:]:
            if r.X.dtype != dtype:
                dtype = np.dtype(np.float64)
                break
        bucket = max(1 << max(rows - 1, 1).bit_length(), 16)
        key = (bucket, cols, dtype.str)
        arena = self._arena.get(key)
        if arena is None:
            arena = self._arena[key] = np.empty((bucket, cols), dtype)
        out = arena[:rows]
        s = 0
        for r in batch:
            out[s:s + r.n_rows] = r.X
            s += r.n_rows
        return out

    def _reject(self, req: _Request, reason: str, retryable: bool = True,
                detail: str = "") -> None:
        req.rejection = ServeRejected(reason, retryable=retryable,
                                      detail=detail, priority=req.priority)
        req.done.set()
        self._count_rejection(reason, priority=req.priority)

    def _count_rejection(self, reason: str,
                         priority: Optional[int] = None) -> None:
        with self._stats_lock:
            self._stats["rejected"][reason] += 1
        telemetry.counter("lgbm_serve_requests_total").inc(outcome=reason)
        if priority is not None:
            telemetry.counter("lgbm_serve_class_requests_total").inc(
                cls="p%d" % priority, outcome=reason)

    def _next_batch(self) -> Optional[List[_Request]]:
        """Pop a batch of same-model requests: head-of-line model wins,
        up to `max_batch_rows`, gathering follow-ups for at most
        `batch_window_s`.  Expired requests are shed here (deadline
        rejection) — work is never spent on an answer nobody is waiting
        for."""
        while True:
            with self._cond:
                while not self._queue and not self._stopped:
                    self._cond.wait(0.1)
                if self._stopped:
                    return None
                batch: List[_Request] = []
                rows = 0
                window_end = time.monotonic() + self.batch_window_s

                def take() -> None:
                    nonlocal rows
                    keep: List[_Request] = []
                    now = time.monotonic()
                    while self._queue and rows < self.max_batch_rows:
                        req = self._queue.popleft()
                        if req.deadline < now:
                            self._queued_by_model[req.model_id] -= 1
                            self._reject(req, "deadline_exceeded",
                                         detail="expired before batching")
                            continue
                        if batch and req.model_id != batch[0].model_id:
                            keep.append(req)
                            continue
                        self._queued_by_model[req.model_id] -= 1
                        req.t_batched = now      # queue_wait ends here
                        batch.append(req)
                        rows += req.n_rows
                    self._queue.extendleft(reversed(keep))

                take()
                while (batch and rows < self.max_batch_rows
                       and not self._stopped):
                    remaining = window_end - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
                    take()
                if batch:
                    return batch
                # everything popped this round was shed as expired:
                # go back to waiting for live work

    def _batcher_loop(self) -> None:
        while True:
            batch = self._next_batch()
            if batch is None:
                return
            mid = batch[0].model_id
            # in-flight mark (ISSUE 17): between batch pop and response
            # drain the model is pinned against LRU eviction exactly
            # like a queued request would pin it
            with self._cond:
                self._inflight_by_model[mid] += len(batch)
            try:
                self._serve_batch(batch)
            except BaseException as e:       # noqa: BLE001 — must not die
                for req in batch:
                    if not req.done.is_set():
                        req.error = e
                        req.done.set()
                self.log.warning("serve: batch failed terminally: %s", e)
            finally:
                with self._cond:
                    self._inflight_by_model[mid] -= len(batch)
                # drop the reference BEFORE blocking for the next batch:
                # wire-plane requests are zero-copy views of a receive
                # buffer or a mapped SHM segment, and a stale `batch`
                # local would pin those bytes (and the segment's unmap)
                # for as long as the queue stays idle
                batch = None

    def _serve_batch(self, batch: List[_Request]) -> None:
        model_id = batch[0].model_id
        entry = self._entries.get(model_id)
        if entry is None:
            for req in batch:
                self._reject(req, "no_model", retryable=True,
                             detail="no generation loaded for %r"
                             % model_id)
            return
        # canary routing (ISSUE 12): while a canary window is open,
        # a deterministic interleave hands it exactly canary_fraction of
        # batches — the per-batch generation routing at the swap seam,
        # so in-flight batches still finish on the entry they captured
        canary = self._canary.get(model_id)
        kind = "incumbent"
        if canary is not None:
            self._canary_seq[model_id] += 1
            n, f = self._canary_seq[model_id], self.canary_fraction
            if int(n * f) > int((n - 1) * f):
                entry, kind = canary, "canary"
            telemetry.counter("lgbm_canary_batches_total").inc(kind=kind)
            if kind == "canary":
                with self._stats_lock:
                    self._stats["canary_batches"] += 1
        X = self._gather_batch(batch)
        with self._wd_lock:
            self.wd("batch model=%s gen=%d rows=%d"
                    % (model_id, entry.generation, X.shape[0]),
                    seconds=0)
        c0 = xla_obs.total_compiles()
        t_dispatch = time.monotonic()
        with tracing.span("serve batch", model=model_id,
                          generation=entry.generation,
                          rows=int(X.shape[0]), requests=len(batch)):
            values, served_by = self._serve_path(entry, X)
        t_values = time.monotonic()
        if canary is not None:
            pol = self._policy_for(model_id)
            decisions = pol.observe(
                kind, error=self._batch_error(values, batch),
                latency_s=time.monotonic() - t_dispatch)
            for d in decisions:
                self._apply_canary_decision(model_id, d)
        # a batch that moved the compile ledger pays trace+compile wall
        # time — stamp it on the batch span and every response in it
        compiled = xla_obs.total_compiles() > c0
        if compiled:
            with self._wd_lock:
                self.wd.annotate("compiled", True)
        now = time.monotonic()
        with self._stats_lock:
            self._stats["rows_served"] += int(X.shape[0])
            self._stats["completed"] += len(batch)
            self._stats["batches_device" if served_by == "device"
                        else "batches_host"] += 1
        telemetry.counter("lgbm_serve_rows_total").inc(int(X.shape[0]))
        telemetry.counter("lgbm_serve_batches_total").inc(path=served_by)
        telemetry.gauge("lgbm_serve_queue_depth").set(len(self._queue))
        if served_by == "device":
            # LGBM_TPU_PROFILE serving hook: the first M DEVICE batches
            # land in one jax.profiler trace
            telemetry.profile_hook("serve").tick()
        # model staleness at completion: age of the serving generation —
        # measured against its publish stamp when the publish meta
        # carries one (ISSUE 11), else against the local swap-in time.
        # The registry histogram is what the sim artifact scrapes.
        published_unix = entry.meta.get("published_unix")
        staleness = (time.time() - float(published_unix)
                     if published_unix is not None
                     else now - entry.loaded_at)
        telemetry.histogram("lgbm_serve_staleness_seconds").observe(
            max(staleness, 0.0), model=model_id)
        lat_hist = telemetry.histogram("lgbm_serve_latency_seconds")
        completed = telemetry.counter("lgbm_serve_requests_total")
        by_class = telemetry.counter("lgbm_serve_class_requests_total")
        model_trace = entry.meta.get("trace")
        s = 0
        for req in batch:
            e = s + req.n_rows
            latency = round(now - req.enqueued, 6)
            # per-request decomposition on the SAME clock as latency_s:
            # queue_wait ends at the batcher pop, batch_gather at the
            # dispatch, device at the values, drain at completion — the
            # four stages PARTITION [enqueued, now], so their sum equals
            # the latency to rounding (the acceptance pin)
            t_b = req.t_batched if req.t_batched is not None else t_dispatch
            stages = {
                "queue_wait_s": round(max(t_b - req.enqueued, 0.0), 6),
                "batch_gather_s": round(max(t_dispatch - t_b, 0.0), 6),
                "device_s": round(max(t_values - t_dispatch, 0.0), 6),
                "drain_s": round(max(now - t_values, 0.0), 6),
            }
            req.result = ServeResult(values[s:e], entry.generation,
                                     model_id, served_by, latency,
                                     compiled=compiled, stages=stages,
                                     model_trace=model_trace)
            if req.trace is not None:
                # the request's stages as slices under the CLIENT's trace
                # id — the cross-thread/cross-process half of the causal
                # timeline (only requests that carry a context pay this)
                marks = ((req.enqueued, t_b, "req/queue_wait"),
                         (t_b, t_dispatch, "req/batch_gather"),
                         (t_dispatch, t_values, "req/device"),
                         (t_values, now, "req/drain"))
                for a, b, nm in marks:
                    tracing.record(nm, int(a * 1e9),
                                   int(max(b - a, 0.0) * 1e9),
                                   trace=req.trace[0], parent=req.trace[1],
                                   served_by=served_by,
                                   generation=entry.generation)
            req.done.set()
            s = e
            # the registry histogram IS the serving latency ledger: the
            # /metrics quantiles and BENCH_SERVE's p50/p99 both read it
            lat_hist.observe(latency, model=model_id)
            completed.inc(outcome="completed")
            by_class.inc(cls="p%d" % req.priority, outcome="completed")

    # -- device path + circuit breaker ---------------------------------------
    def _spawn_executor(self) -> _DeviceExecutor:
        self._executor_idx += 1
        ex = _DeviceExecutor(self._executor_idx)
        ex.start()
        return ex

    def _device_predict(self, entry: _ModelEntry, X: np.ndarray
                        ) -> np.ndarray:
        """One device dispatch under a deadline.  A dispatch that blows
        it is abandoned (the executor thread may be wedged; a fresh one
        takes over) and surfaces as `StageTimeout` for the breaker."""
        kw = ({"out_dtype": self._out_dtype}
              if self._out_dtype is not None else {})
        job = _Job(lambda: entry.booster.predict(
            X, raw_score=self._raw_score, device=True, **kw))
        self._executor.submit(job)
        if not job.done.wait(self.predict_deadline_s):
            job.abandoned = True
            self._executor.retire()
            self._executor = self._spawn_executor()
            raise resilience.StageTimeout("device predict",
                                          self.predict_deadline_s)
        if job.error is not None:
            raise job.error
        assert job.values is not None
        return np.asarray(job.values)

    def _serve_path(self, entry: _ModelEntry, X: np.ndarray):
        """(values, served_by): device when the breaker allows it, with
        host fallback — degraded, the server still answers."""
        if self._device_allowed(entry):
            try:
                return self._device_predict(entry, X), "device"
            except BaseException as e:       # noqa: BLE001 — degrade
                self._trip_breaker(entry, e)
        values = entry.booster.predict(X, raw_score=self._raw_score,
                                       device=False)
        if self._out_dtype is not None:
            # the host fallback serves the same surface dtype as the
            # device path, so a breaker flip never changes the response
            # schema mid-stream
            values = np.asarray(values, self._out_dtype)
        return values, "host"

    def _device_allowed(self, entry: _ModelEntry) -> bool:
        b = self._breaker
        if b["state"] == "closed":
            return True
        now = time.monotonic()
        if now < b["open_until"]:
            return False
        # cooldown elapsed: PROBE-based recovery (a tiny dispatch pays
        # the gamble, not a client batch)
        try:
            self._device_predict(
                entry, np.zeros((1, entry.num_features), np.float64))
        except BaseException as e:           # noqa: BLE001 — stay open
            b["open_until"] = time.monotonic() + self.breaker_cooldown_s
            with self._wd_lock:
                self.wd.annotate("recovery_probe_failed",
                                 "%s: %s" % (type(e).__name__, e))
            return False
        b["state"] = "closed"
        event = {"event": "serving_recovery", "from": "host",
                 "to": "device", "model": entry.model_id,
                 "generation": entry.generation,
                 "wallclock": resilience.wallclock()}
        self.recovery_events.append(event)
        with self._stats_lock:
            self._stats["recoveries"] += 1
        telemetry.counter("lgbm_serve_recoveries_total").inc()
        with self._wd_lock:
            self.wd.annotate("recovery_event", event)
        self.log.warning("serve: device path recovered (probe ok); "
                         "circuit closed")
        return True

    def _trip_breaker(self, entry: _ModelEntry, err: BaseException) -> None:
        timed_out = isinstance(err, resilience.StageTimeout)
        reason = "%s: %s" % (type(err).__name__, err)
        self._breaker["state"] = "open"
        self._breaker["open_until"] = (time.monotonic()
                                       + self.breaker_cooldown_s)
        event = {"event": "serving_degradation", "from": "device",
                 "to": "host", "reason": reason,
                 "model": entry.model_id, "generation": entry.generation,
                 "cooldown_s": self.breaker_cooldown_s,
                 "wallclock": resilience.wallclock()}
        self.degradation_events.append(event)
        with self._stats_lock:
            self._stats["degradations"] += 1
        telemetry.counter("lgbm_serve_degradations_total").inc()
        with self._wd_lock:
            if timed_out:
                # hung dispatch: the trail gets the timeout status AND
                # all-thread tracebacks naming the wedged executor
                self.wd.record_timeout(note=reason)
            self.wd.annotate("degradation_event", event)
        self.log.warning("serve: device batch failed (%s); circuit OPEN "
                         "for %.1fs, serving from the host predictor",
                         reason, self.breaker_cooldown_s)

    # -- observability -------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._stats_lock:
            st = {k: (dict(v) if isinstance(v, collections.Counter) else v)
                  for k, v in self._stats.items()}
        st["queue_depth"] = len(self._queue)
        st["breaker"] = dict(self._breaker)
        st["priority_levels"] = self.priority_levels
        st["shed_active"] = self._shed_low
        if self.quotas:
            st["quotas"] = dict(self.quotas)
            st["queued_by_model"] = {m: c for m, c
                                     in self._queued_by_model.items() if c}
        if self.policy is not None:
            st["policy"] = dict(self.policy.state(),
                                decisions_tail=self.policy.decisions[-16:])
        st["generations"] = {mid: e.generation
                             for mid, e in self._entries.items()}
        if self.max_resident > 0:
            st["residency"] = {
                "max_resident": self.max_resident,
                "resident": len(self._entries),
                "registered": len(self._dirs),
                "wanted": sorted(self._wanted),
                "events_tail": self.residency_events[-16:],
                "page_ins": sum(1 for e in self.residency_events
                                if e["event"] == "page_in"),
                "evictions": sum(1 for e in self.residency_events
                                 if e["event"] == "evict"),
            }
        if self.canary_fraction > 0:
            st["canary_fraction"] = self.canary_fraction
            st["canary_generations"] = {mid: e.generation
                                        for mid, e in self._canary.items()}
            st["canary_policy"] = {mid: p.state() for mid, p
                                   in self._canary_policies.items()}
            st["rollback_events"] = list(self.rollback_events)
        st["ready"] = self._ready.is_set()
        st["prewarm_events"] = list(self.prewarm_events)
        st["degradation_events"] = list(self.degradation_events)
        st["recovery_events"] = list(self.recovery_events)
        if self.start_degradation is not None:
            st["start_degradation"] = self.start_degradation
        # the registry histogram is the latency ledger: the same numbers
        # a /metrics scrape (and BENCH_SERVE) reads
        hist = telemetry.histogram("lgbm_serve_latency_seconds")
        hstate = hist.state()
        st["latency_quantiles_s"] = {
            "p50": hist.quantile(0.5, state=hstate),
            "p95": hist.quantile(0.95, state=hstate),
            "p99": hist.quantile(0.99, state=hstate),
            "count": hstate["count"],
        }
        return st


# ---------------------------------------------------------------------------
# TCP front end (task=serve)
# ---------------------------------------------------------------------------

#: one encoder for every response — `json.dumps` builds a fresh
#: JSONEncoder per call, measurable at serving rates (ISSUE 16 fix)
_JSON_ENCODER = json.JSONEncoder(separators=(",", ":"))


class _Handler(socketserver.StreamRequestHandler):
    """JSON-lines protocol: one request object per line, one response
    object per line.  Requests: ``{"features": [...], "model": "id",
    "deadline_s": 2.0, "traceparent": "00-..-..-01"}`` or
    ``{"cmd": "stats"}``.  Responses: ``{"values": [...],
    "generation": N, "served_by": ..., "latency_s": ..., "stages":
    {queue_wait_s, batch_gather_s, device_s, drain_s}, "model_trace":
    ...}`` or a `ServeRejected.to_dict()` rejection."""

    def handle(self) -> None:
        rt: ServingRuntime = self.server.runtime    # type: ignore[attr-defined]
        for raw in self.rfile:
            line = raw.strip()
            if not line:
                continue
            try:
                msg = json.loads(line.decode("utf-8"))
                if msg.get("cmd") == "stats":
                    out = rt.stats()
                else:
                    rec = rt.submit(
                        np.asarray(msg["features"], np.float64),
                        deadline_s=msg.get("deadline_s"),
                        model_id=msg.get("model", "default"),
                        priority=int(msg.get("priority", 0)),
                        label=msg.get("label"),
                        # cross-process context propagation (ISSUE 14):
                        # the wire carries the client's traceparent
                        traceparent=msg.get("traceparent"),
                    ).wait(timeout=rt.wire_wait_timeout_s)
                    out = {"values": np.asarray(rec.values).tolist(),
                           "generation": rec.generation,
                           "served_by": rec.served_by,
                           "latency_s": rec.latency_s,
                           "compiled": rec.compiled,
                           "stages": rec.stages}
                    if rec.model_trace:
                        out["model_trace"] = rec.model_trace
            except ServeRejected as e:
                out = e.to_dict()
            except Exception as e:           # noqa: BLE001 — wire error
                out = {"error": "bad_request",
                       "detail": "%s: %s" % (type(e).__name__, e)}
            try:
                self.wfile.write((_JSON_ENCODER.encode(out)
                                  + "\n").encode("utf-8"))
                self.wfile.flush()
            except OSError:
                return                       # client went away


class ServingServer(socketserver.ThreadingTCPServer):
    """Thin TCP wrapper over a `ServingRuntime` (the CLI `task=serve`
    front end).  One thread per connection; all connections share the
    runtime's bounded queue, so admission control is global."""

    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, runtime: ServingRuntime, host: str = "127.0.0.1",
                 port: int = 0):
        self.runtime = runtime
        super().__init__((host, port), _Handler)

    @property
    def port(self) -> int:
        return self.server_address[1]
