"""Blocking host-sync audit seam (ISSUE 5 sync audit).

Every *blocking* device->host observation the training stack performs —
`jax.device_get` of tree outputs, score fetches for metrics/snapshots,
explicit `block_until_ready` barriers — goes through this module so one
instrument can answer "how many times per iteration does the host stall
the device pipeline, and where?".

Two orthogonal dimensions are recorded per event:

* a **label** naming the call site family (``tree_fetch``, ``eval_fetch``,
  ``pipeline_drain``, ...), and
* whether the calling thread currently sits on the **tree->tree critical
  path** (the dispatch loop of ``GBDT._train_one_iter_fast``, marked with
  :func:`critical_path`).  The async pipeline's host halves run on the
  assembler thread, which never carries the marker — so the tier-1 pin
  "0 blocking fetches on the critical path at ``pipeline_depth=1``" is a
  direct counter assertion, not an inference from timings.

The counters are process-global and monotonically increasing; consumers
take a :func:`snapshot` before a region and diff with :func:`delta`
after it (bench reports ``host_syncs_per_iter`` this way).

Implicit syncs (``np.asarray`` on a live jax array, printing a device
array) are outside the seam by construction; the training/boosting code
paths use the explicit helpers only, and the tests pin that property for
the fused fast path.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from . import telemetry

_lock = threading.Lock()
_counts: Dict[str, int] = {}
_critical_counts: Dict[str, int] = {}
_total = 0
_critical_total = 0

_tls = threading.local()


def _on_critical_path() -> bool:
    return getattr(_tls, "depth", 0) > 0


class critical_path:
    """Context manager marking the current thread as the device critical
    path: blocking syncs recorded while inside count as critical.  The
    marker is thread-local, so work handed to the pipeline assembler
    thread is off-path by construction."""

    def __enter__(self) -> "critical_path":
        _tls.depth = getattr(_tls, "depth", 0) + 1
        return self

    def __exit__(self, *exc) -> None:
        _tls.depth = getattr(_tls, "depth", 1) - 1


def record(label: str) -> None:
    """Count one blocking host sync under `label` (seam-internal; call
    sites should prefer the device_get/block_until_ready wrappers)."""
    global _total, _critical_total
    crit = _on_critical_path()
    with _lock:
        _counts[label] = _counts.get(label, 0) + 1
        _total += 1
        if crit:
            _critical_counts[label] = _critical_counts.get(label, 0) + 1
            _critical_total += 1
    # the same event feeds the process-wide metrics registry (ISSUE 9),
    # so a live /metrics scrape sees the sync profile the bench pins
    telemetry.count_sync(label, crit)


def device_get(x: Any, label: str = "host_fetch") -> Any:
    """Audited `jax.device_get`: ONE recorded blocking fetch, whatever
    the pytree width (jax starts every leaf's D2H copy asynchronously
    before blocking, so a pytree is one round of transfers)."""
    import jax
    record(label)
    return jax.device_get(x)


def block_until_ready(x: Any, label: str = "barrier") -> Any:
    """Audited `jax.block_until_ready`."""
    import jax
    record(label)
    return jax.block_until_ready(x)


def snapshot() -> Dict[str, Any]:
    """A copyable view of the monotone counters."""
    with _lock:
        return {
            "total": _total,
            "critical_path": _critical_total,
            "by_label": dict(_counts),
            "critical_by_label": dict(_critical_counts),
        }


def delta(before: Dict[str, Any],
          after: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Counter movement since `before` (to `after`, default: now)."""
    if after is None:
        after = snapshot()
    by_label = {k: v - before["by_label"].get(k, 0)
                for k, v in after["by_label"].items()
                if v - before["by_label"].get(k, 0)}
    crit = {k: v - before["critical_by_label"].get(k, 0)
            for k, v in after["critical_by_label"].items()
            if v - before["critical_by_label"].get(k, 0)}
    return {
        "total": after["total"] - before["total"],
        "critical_path": after["critical_path"] - before["critical_path"],
        "by_label": by_label,
        "critical_by_label": crit,
    }


def reset() -> None:
    """Zero the counters (tests and bench sections)."""
    global _total, _critical_total
    with _lock:
        _counts.clear()
        _critical_counts.clear()
        _total = 0
        _critical_total = 0
