"""Continuous-training service: rolling-window trainer + atomic publish.

The reference's Boosting drivers are strictly batch-only (SURVEY §2.4);
this module is the scenario it never had — a long-running service that
keeps a model fresh against a moving data window and publishes every
cycle through the atomic publish/subscribe seam (runtime/publish.py),
composed entirely from runtime features PRs 4–5 already proved out:

* **ingest** — a background producer thread re-parses the data file
  through the existing parse pipeline (io/parser.py's chunked
  producer/consumer path) whenever the file changes, keeping the newest
  `online_window_rows` rows staged for the next cycle; an optional
  binary cache (`online_save_binary=true`) makes relaunch ingest a
  single binary load.
* **train** — each cycle boosts `online_rounds` iterations (continued
  training on the live engine) or `refit`s the current model to the new
  window, on an **absolute-clock schedule**: cycle slots are
  ``t0 + k*interval`` with ``t0`` persisted in the service state file,
  so a relaunch (after preemption or an injected death) rejoins the
  same schedule instead of drifting.
* **recover** — warm start from the newest VALID snapshot (scanning past
  corrupt ones), finish a mid-cycle preemption's partial cycle to the
  exact iteration target, and REPUBLISH a cycle whose publish was torn
  or never landed — from the snapshot's own model text, so the
  republished generation is byte-identical to what an uninterrupted run
  would have published.
* **observe** — every cycle stage runs under the PR 4 stage watchdog
  (named deadlines, persisted JSON stage trail) and the train stage's
  blocking-sync profile is recorded through the PR 5 sync-audit seam
  into the same trail.

Correctness under churn is adversarial: `exp/chaos.py` runs this loop
under randomized `LGBM_TPU_FAULT` kill/tear/stall churn with a
high-frequency subscriber polling throughout; the pins are **zero
corrupt observations ever** and **byte-identical published generations**
vs an uninterrupted run (tests/test_continuous.py).
"""
from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from . import publish, quality, resilience, syncs, telemetry, tracing, \
    warmup, xla_obs
from ..utils.log import LightGBMError, Log

__all__ = ["ContinuousTrainer", "OnlineParams"]


class OnlineParams:
    """Config surface of `task=train_online` (all `k=v` CLI params).

    Everything not consumed here flows through as ordinary training
    parameters (objective, num_leaves, bagging, pipeline_depth, ...).
    """

    def __init__(self, params: Dict[str, Any]):
        p = dict(params)
        self.data = p.pop("data", p.pop("train_data", None))
        self.output_model = p.pop("output_model", "LightGBM_online.txt")
        self.input_model = p.pop("input_model", None)
        self.publish_dir = p.pop("publish_dir",
                                 self.output_model + ".pub")
        self.interval_s = float(p.pop("online_interval", 10.0))
        self.cycles = int(p.pop("online_cycles", 0))          # 0 = forever
        self.rounds = max(int(p.pop("online_rounds", 5)), 1)
        self.mode = str(p.pop("online_mode", "boost")).lower()
        self.window_rows = int(p.pop("online_window_rows", 0))
        self.save_binary = str(p.pop("online_save_binary", "")
                               ).lower() in ("true", "1")
        self.publish_retention = int(p.pop("publish_retention", 8))
        self.publish_grace_s = float(p.pop("publish_grace", 30.0))
        self.snapshot_retention = int(p.pop("snapshot_retention", 4))
        self.snapshot_grace_s = float(p.pop("snapshot_grace", 30.0))
        self.stage_timeout = int(p.pop("online_stage_timeout", 600))
        # metrics_port=N serves GET /metrics (Prometheus text) from the
        # live trainer; 0 picks an ephemeral port (logged at start)
        mp = p.pop("metrics_port", None)
        self.metrics_port = int(mp) if mp is not None else None
        self.label_column = int(p.pop("label_column", p.pop("label", 0) or 0))
        self.has_header = str(p.pop("has_header", p.pop("header", ""))
                              ).lower() in ("true", "1") or None
        # ranking online path (ISSUE 11): `query_column=<i>` names the
        # parsed FEATURE column (post label extraction) carrying the
        # query id; consecutive equal ids form one query group.  The
        # column is stripped from the features, the rolling window trims
        # only on group boundaries, and each cycle's dataset carries the
        # window's group sizes — lambdarank streams like any objective.
        qc = p.pop("query_column", None)
        self.query_column = int(qc) if qc is not None else None
        # -- model-quality firewall (ISSUE 12) -------------------------------
        # stage one: quarantine threshold — an ingest pass whose
        # quarantined fraction exceeds this fails the CYCLE loudly
        # (status=quarantine) instead of training on the remainder.
        self.quarantine_limit = float(p.pop("online_quarantine_limit", 0.5))
        # stage two: pre-publish eval gate.  tolerance=inf (the default)
        # DISABLES the gate entirely: no holdout is carved out of the
        # window and the training path is byte-identical to a gate-less
        # build (the default-off contract).  A finite tolerance holds out
        # `publish_gate_holdout` of each window, evaluates candidate vs
        # incumbent with the configured metric stack, and refuses to
        # publish a regression.
        self.gate_tolerance = float(p.pop("publish_gate_tolerance",
                                          math.inf))
        self.gate_holdout = float(p.pop("publish_gate_holdout", 0.2))
        gm = p.pop("publish_gate_metric", None)
        self.gate_metric = str(gm) if gm else None
        # warm start (ISSUE 15): a relaunch whose publish dir carries a
        # matching shape manifest precompiles the fused-step family
        # BEFORE the first cycle slot (online_prewarm=false opts out)
        self.prewarm = str(p.pop("online_prewarm", "true")
                           ).lower() not in ("false", "0")
        self.train_params = p
        if not self.data:
            raise LightGBMError("train_online needs data=<file>")
        if self.mode not in ("boost", "refit"):
            raise LightGBMError("online_mode must be boost or refit, got %r"
                                % self.mode)
        if self.query_column is not None and self.mode == "refit":
            raise LightGBMError("query_column (ranking) requires "
                                "online_mode=boost; refit re-fits leaf "
                                "values without query structure")
        if self.gate_enabled and not 0.0 < self.gate_holdout < 1.0:
            raise LightGBMError("publish_gate_holdout must be in (0, 1), "
                                "got %r" % self.gate_holdout)
        if not 0.0 <= self.quarantine_limit <= 1.0:
            raise LightGBMError("online_quarantine_limit must be in "
                                "[0, 1], got %r" % self.quarantine_limit)

    @property
    def gate_enabled(self) -> bool:
        return math.isfinite(self.gate_tolerance)


class _IngestProducer(threading.Thread):
    """Background ingest: incremental tail-append parser + rolling window.

    The first pass parses `path` fully through io/parser.py and records
    the sniffed format (separator, header, feature count), the consumed
    byte offset and a signature of the bytes just before it.  When the
    file GROWS and that signature still matches, only the appended tail
    is read and parsed — rows outside the new tail are never re-read,
    re-parsed or re-binned (ISSUE 8).  Any other change (rewrite,
    truncation, signature mismatch, no trailing newline) falls back to a
    full re-parse.  The newest `online_window_rows` rows stay staged; the
    training loop never blocks on an unchanged file (the parse of a
    growing file overlaps the previous cycle's training)."""

    #: bytes hashed immediately before the consumed offset; a rewrite that
    #: happens to grow the file is caught by this prefix check
    _SIG_BYTES = 64

    def __init__(self, cfg: OnlineParams, log=Log):
        super().__init__(name="online-ingest", daemon=True)
        self.cfg = cfg
        self.log = log
        self._ready = threading.Event()
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._latest: Optional[Tuple] = None   # (stamp, X, y, q)
        self._error: Optional[BaseException] = None
        self._stamp: Optional[Tuple] = None
        # incremental-parse state
        self._fmt: Optional[Tuple] = None   # (fmt, sep, n_features)
        self._offset: Optional[int] = None  # bytes consumed (None = no tail)
        self._sig: bytes = b""
        self._chunks: list = []             # [(X, y, q)] rolling window
        # ingest telemetry (read by the cycle stage trail and the pins)
        self.last_ingest: Optional[Dict[str, Any]] = None
        self.rows_parsed_total = 0
        # ingest quarantine (ISSUE 12 stage one): schema-invalid rows are
        # routed here instead of the window; the cycle reads the ledger
        # for its stage trail and the quarantine-fraction threshold
        self.quarantine = quality.QuarantineLedger()

    def _file_stamp(self) -> Optional[Tuple]:
        try:
            st = os.stat(self.cfg.data)
            return (st.st_mtime_ns, st.st_size)
        except OSError:
            return None

    # -- incremental parsing -------------------------------------------------
    def _sig_ok(self) -> bool:
        if self._offset is None:
            return False
        lo = max(0, self._offset - self._SIG_BYTES)
        try:
            with open(self.cfg.data, "rb") as fh:
                fh.seek(lo)
                return fh.read(self._offset - lo) == self._sig
        except OSError:
            return False

    def _record_offset(self, size: int) -> None:
        """Arm tail mode at `size` if the consumed region ends on a line
        boundary; otherwise disable it until the next full parse."""
        try:
            with open(self.cfg.data, "rb") as fh:
                if size <= 0:
                    self._offset = None
                    return
                fh.seek(size - 1)
                if fh.read(1) != b"\n":
                    self._offset = None
                    return
                lo = max(0, size - self._SIG_BYTES)
                fh.seek(lo)
                self._sig = fh.read(size - lo)
                self._offset = size
        except OSError:
            self._offset = None

    def _parse_tail(self, size: int) -> Tuple[np.ndarray, np.ndarray]:
        """Parse ONLY the appended bytes [offset, last complete line)."""
        from ..io.parser import _parse_delimited, _parse_libsvm
        fmt, sep, n_feat = self._fmt
        with open(self.cfg.data, "rb") as fh:
            fh.seek(self._offset)
            blob = fh.read(size - self._offset)
        cut = blob.rfind(b"\n")
        if cut < 0:          # no complete appended line yet
            return (np.empty((0, n_feat)), np.empty(0))
        consumed = blob[:cut + 1]
        lines = [l for l in consumed.decode("utf-8", "replace").splitlines()
                 if l.strip()]
        if lines:
            if fmt == "libsvm":
                X, y = _parse_libsvm(lines, n_feat)
            else:
                X, y = _parse_delimited(lines, sep, self.cfg.label_column,
                                        n_feat)
        else:
            X, y = np.empty((0, n_feat)), np.empty(0)
        self._offset += len(consumed)
        lo = max(0, self._offset - self._SIG_BYTES)
        self._sig = (self._sig + consumed)[-(self._offset - lo):]
        return X, y

    def _split_query(self, X: np.ndarray):
        """Strip the query-id column (ranking mode): (features, qid)."""
        qc = self.cfg.query_column
        if qc is None:
            return X, None
        if X.shape[0] == 0:
            return X[:, : max(X.shape[1] - 1, 0)], np.empty(0, np.int64)
        if not 0 <= qc < X.shape[1]:
            raise LightGBMError("query_column %d out of range for %d "
                                "parsed columns" % (qc, X.shape[1]))
        q = X[:, qc].astype(np.int64)
        return np.delete(X, qc, axis=1), q

    def _append_window(self, X: np.ndarray, y: np.ndarray,
                       q: Optional[np.ndarray]) -> None:
        if X.shape[0]:
            self._chunks.append((X, y, q))
        w = self.cfg.window_rows
        if w <= 0:
            return
        total = sum(c[0].shape[0] for c in self._chunks)
        while len(self._chunks) > 1 and \
                total - self._chunks[0][0].shape[0] >= w:
            total -= self._chunks[0][0].shape[0]
            self._chunks.pop(0)
        if total > w:
            X0, y0, q0 = self._chunks[0]
            cut = total - w
            if q0 is not None and cut < q0.size:
                # ranking window: never split a query group — advance the
                # cut to the next group boundary (the window may come up
                # slightly short of `window_rows`, never torn mid-query)
                boundaries = np.flatnonzero(np.diff(q0)) + 1
                later = boundaries[boundaries >= cut]
                cut = int(later[0]) if later.size else q0.size
            if cut >= X0.shape[0] and len(self._chunks) > 1:
                self._chunks.pop(0)
            else:
                self._chunks[0] = (
                    X0[cut:], y0[cut:],
                    q0[cut:] if q0 is not None else None)

    def _window(self):
        Xs = [c[0] for c in self._chunks]
        ys = [c[1] for c in self._chunks]
        qs = [c[2] for c in self._chunks]
        q = None
        if qs and qs[0] is not None:
            q = np.concatenate(qs) if len(qs) > 1 else qs[0]
        return (np.concatenate(Xs) if len(Xs) > 1 else Xs[0],
                np.concatenate(ys) if len(ys) > 1 else ys[0], q)

    def _parse_once(self) -> None:
        t0 = time.perf_counter()
        size = os.path.getsize(self.cfg.data)
        mode = "full_parse"
        if self._fmt is not None and self._offset is not None \
                and size > self._offset and self._sig_ok():
            X, y = self._parse_tail(size)
            mode = "tail_append"
        else:
            from ..io.parser import parse_file, sniff
            X, y = parse_file(self.cfg.data,
                              label_column=self.cfg.label_column,
                              has_header=self.cfg.has_header)
            fmt, sep, _, _ = sniff(self.cfg.data, self.cfg.has_header)
            self._fmt = (fmt, sep, X.shape[1])
            self._chunks = []
            self._record_offset(size)
        parsed = int(X.shape[0])
        # fault seam: an upstream logging outage poisoning a fraction of
        # every chunk's labels — the quarantine below must catch it
        y, _ = resilience.maybe_poison_rows(X, y)
        X, q = self._split_query(X)
        # firewall stage one: schema validation — offenders go to the
        # bounded ledger, never the window.  The clean-path fast case
        # (keep.all()) adds zero copies, so a healthy stream's windows
        # (and therefore its models) are byte-identical to a
        # quarantine-less build.
        keep, _ = quality.validate_rows(X, y, query=q,
                                        ledger=self.quarantine)
        quarantined = parsed - int(keep.sum())
        if quarantined:
            X, y = X[keep], np.asarray(y)[keep]
            q = q[keep] if q is not None else None
        self._append_window(X, y, q)
        Xw, yw, qw = self._window()
        dt = time.perf_counter() - t0
        with self._lock:
            self._latest = (self._stamp, Xw, yw, qw)
        self.rows_parsed_total += parsed
        self.last_ingest = {
            "mode": mode, "rows_parsed": parsed,
            "seconds": round(dt, 4),
            "rows_per_sec": round(parsed / dt, 1) if dt > 0 else None,
            "window_rows": int(Xw.shape[0]),
            "quarantined": quarantined,
            "quarantine_frac": round(quarantined / parsed, 4)
            if parsed else 0.0,
        }
        # the same ingest record feeds the live registry (ISSUE 9):
        # rows/sec is the counter+histogram pair, the window a gauge
        telemetry.counter("lgbm_ingest_rows_total").inc(parsed, mode=mode)
        telemetry.histogram("lgbm_ingest_seconds").observe(dt)
        telemetry.gauge("lgbm_ingest_window_rows").set(Xw.shape[0])
        self._ready.set()

    def run(self) -> None:
        while not self._stop.is_set():
            stamp = self._file_stamp()
            if stamp is not None and stamp != self._stamp:
                self._stamp = stamp
                try:
                    self._parse_once()
                except BaseException as e:   # surfaced at the next ingest
                    if self._latest is None:
                        self._error = e
                        self._ready.set()
                    else:
                        self.log.warning("online ingest: re-parse of %s "
                                         "failed (%s); keeping the previous "
                                         "window", self.cfg.data, e)
            self._stop.wait(0.2)

    def stop(self) -> None:
        self._stop.set()

    def current(self, timeout: float) -> Tuple:
        """(stamp, X, y, query_ids) of the freshest staged window; the
        query ids are None outside ranking mode."""
        if not self._ready.wait(timeout):
            raise LightGBMError("online ingest: no parsed window of %s "
                                "within %.0fs" % (self.cfg.data, timeout))
        if self._error is not None:
            raise LightGBMError("online ingest: cannot parse %s: %s"
                                % (self.cfg.data, self._error))
        with self._lock:
            return self._latest  # type: ignore[return-value]


class ContinuousTrainer:
    """The service loop.  `run()` returns a process exit code: 0 when the
    target cycle count is reached or the run is preempted cleanly."""

    def __init__(self, params: Dict[str, Any], log=Log):
        self.cfg = OnlineParams(params)
        self.log = log
        self.publisher = publish.ModelPublisher(
            self.cfg.publish_dir, keep_last=self.cfg.publish_retention,
            grace_s=self.cfg.publish_grace_s)
        self.wd = resilience.Watchdog(
            self.cfg.stage_timeout, hard=False, label="online stage",
            report_path=os.environ.get("LGBM_TPU_STAGE_REPORT",
                                       self.cfg.output_model
                                       + ".stage_trail.json"))
        self._booster = None
        self._window_stamp: Optional[Tuple] = None
        self._base_iter = 0              # iterations in the pre-service model
        self.timeouts = 0
        # pre-publish eval gate state (ISSUE 12 stage two): the holdout
        # slice of the CURRENT window, refreshed whenever a window is
        # adopted; None while the gate is disabled
        self._holdout: Optional[Tuple] = None
        self.gate_rejections = 0
        self.quarantine_failures = 0

    # -- service state file (the schedule clock) ----------------------------
    @property
    def _state_path(self) -> str:
        return self.cfg.output_model + ".service.json"

    def _load_or_create_state(self) -> Dict[str, Any]:
        try:
            with open(self._state_path) as fh:
                st = json.load(fh)
            if float(st.get("interval", -1)) != self.cfg.interval_s:
                self.log.warning(
                    "online_interval changed (%.3fs -> %.3fs); the schedule "
                    "clock keeps its original t0", st.get("interval"),
                    self.cfg.interval_s)
            return st
        except (OSError, ValueError):
            st = {"t0": time.time(), "interval": self.cfg.interval_s,
                  "base_iter": self._base_iter, "mode": self.cfg.mode,
                  "created": resilience.wallclock()}
            resilience.atomic_write(self._state_path, json.dumps(st, indent=1))
            return st

    # -- stage plumbing ------------------------------------------------------
    def _stage(self, cycle: int, name: str,
               seconds: Optional[int] = None) -> None:
        label = "cycle %d: %s" % (cycle, name)
        self.wd(label, seconds)
        stalled = resilience.maybe_slow_stage(label, defer=True)
        if stalled:
            # annotate BEFORE sleeping: the watchdog alarm lands mid-sleep
            # and the trail must already name the injected stall
            self.wd.annotate("injected_stall_s", stalled)
            time.sleep(stalled)

    # -- data / booster construction ----------------------------------------
    def _binary_cache_path(self) -> str:
        return self.cfg.output_model + ".window.bin"

    def _cache_fresh(self) -> bool:
        cache = self._binary_cache_path()
        try:
            return os.path.getmtime(cache) >= os.path.getmtime(self.cfg.data)
        except OSError:
            return False

    @staticmethod
    def _group_sizes(q: Optional[np.ndarray]) -> Optional[np.ndarray]:
        """Run lengths of consecutive equal query ids (ranking mode)."""
        if q is None or q.size == 0:
            return None
        starts = np.flatnonzero(np.diff(q)) + 1
        return np.diff(np.concatenate([[0], starts, [q.size]]))

    def _make_dataset(self, X, y, q=None):
        from ..basic import Dataset
        from ..config import Config
        from ..io.dataset import BinnedDataset
        params = dict(self.cfg.train_params)
        if BinnedDataset.is_binary_file(self.cfg.data):
            ds = Dataset(self.cfg.data, params=params)
            ds.construct(Config(params))
            return ds
        if self.cfg.save_binary and self._cache_fresh():
            try:
                ds = Dataset(self._binary_cache_path(), params=params)
                ds.construct(Config(params))
                return ds
            except LightGBMError as e:
                # e.g. a stale format_version from an older build: the
                # service rebuilds the cache instead of wedging the cycle
                self.log.warning("online: binary window cache unusable "
                                 "(%s); rebuilding it", e)
        ds = Dataset(X, label=y, group=self._group_sizes(q), params=params)
        if self.cfg.save_binary:
            ds.construct(Config(params))
            ds.save_binary(self._binary_cache_path())
        return ds

    def _build_booster(self, X, y, q=None, init_model=None, snap_state=None):
        from ..basic import Booster
        ds = self._make_dataset(X, y, q)
        bst = Booster(params=dict(self.cfg.train_params), train_set=ds,
                      init_model=init_model)
        if snap_state is not None:
            resilience.restore_training_state(bst, snap_state, log=self.log)
        return bst

    def _model_text(self, booster) -> str:
        booster._drain()
        return booster._model.save_model_to_string()

    def _total_iter(self) -> int:
        return int(self._booster.current_iteration())

    # -- schedule ------------------------------------------------------------
    def _wait_for_slot(self, t0: float, guard) -> None:
        """Sleep until the next absolute slot boundary ``t0 + m*interval``
        strictly in the future, waking early on a preemption signal.  A
        relaunch lands in whatever slot is next on the SAME clock — the
        schedule does not drift with downtime."""
        if self.cfg.interval_s <= 0:
            return
        now = time.time()
        m = max(int(math.ceil((now - t0) / self.cfg.interval_s)), 0)
        deadline = t0 + m * self.cfg.interval_s
        if deadline - now < 1e-4:        # exactly on the boundary: take it
            return
        while True:
            if guard.signum is not None:
                return
            remaining = deadline - time.time()
            if remaining <= 0:
                return
            time.sleep(min(remaining, 0.05))

    # -- recovery ------------------------------------------------------------
    def _recover_boost(self, X, y, q=None) -> int:
        """Boost-mode recovery: warm start from the newest valid snapshot
        and reconcile snapshots against published generations.  Returns
        the number of COMPLETED cycles."""
        from ..models.gbdt_model import GBDTModel
        snap_path, snap_state = resilience.find_resume_snapshot(
            self.cfg.output_model, log=self.log)
        init = None
        if self.cfg.input_model:
            init = GBDTModel.load_model(self.cfg.input_model)
            self._base_iter = int(init.current_iteration)
        if snap_path is None:
            self._booster = self._build_booster(X, y, q, init_model=init)
            return 0
        svc = snap_state.get("service", {})
        self._base_iter = int(svc.get("base_iter", self._base_iter))
        total = int(snap_state["total_iter"])
        done_cycles = (total - self._base_iter) // self.cfg.rounds
        self.log.info("online: warm start from %s (iteration %d, "
                      "%d completed cycles)", snap_path, total, done_cycles)
        self.wd("recover: warm start")
        self._booster = self._build_booster(
            X, y, q, init_model=GBDTModel.load_model(snap_path),
            snap_state=snap_state)
        # republish a cycle whose publish was torn away with the dead
        # process: the snapshot's own model text IS what that publish
        # would have carried
        latest = self.publisher.latest_valid()
        latest_gen = latest.generation if latest else 0
        mid = (total - self._base_iter) % self.cfg.rounds
        if mid == 0 and done_cycles > latest_gen:
            self.wd("recover: republish generation %d" % done_cycles)
            text = resilience.snapshot_model_text(snap_path)
            if text is not None:
                # the republish runs before any cycle span exists: open
                # one so this generation's meta carries THIS process's
                # fresh trace context like every other publish
                with tracing.span("recover republish %d" % done_cycles):
                    self.publisher.publish(text, meta=self._gen_meta(
                        done_cycles, total), generation=done_cycles)
                self.log.info("online: republished generation %d from the "
                              "snapshot", done_cycles)
        return done_cycles

    def _recover_refit(self) -> int:
        """Refit-mode recovery: the published lineage IS the state."""
        from ..basic import Booster
        latest = self.publisher.latest_valid()
        if latest is None:
            return 0
        self._booster = Booster(params=dict(self.cfg.train_params),
                                model_str=latest.model_text)
        self.log.info("online: refit mode resumed from published "
                      "generation %d", latest.generation)
        return int(latest.meta.get("cycle", latest.generation))

    def _gen_meta(self, cycle: int, total_iter: int) -> Dict[str, Any]:
        meta = {"cycle": cycle, "total_iter": int(total_iter),
                "mode": self.cfg.mode, "rounds_per_cycle": self.cfg.rounds,
                "window_rows": self.cfg.window_rows}
        # the producing cycle's trace context rides the publish meta
        # (ISSUE 14): a served response links back to the training cycle
        # that made its model, across the process boundary.  A relaunch
        # opens a FRESH trace, but every pre-kill generation keeps the
        # dead process's context in its footer — the lineage stays
        # linkable across preemptions.
        tp = tracing.current_traceparent()
        if tp is not None:
            meta["trace"] = tp
        return meta

    # -- pre-publish eval gate (ISSUE 12 stage two) --------------------------
    def _gate_split(self, X, y, q=None) -> Tuple:
        """Carve the deterministic holdout out of a freshly adopted
        window (gate enabled) and stage it for this window's gate
        evaluations; with the gate disabled the window passes through
        UNTOUCHED (same arrays, no copy — the byte-identity contract)."""
        if not self.cfg.gate_enabled:
            self._holdout = None
            return X, y, q
        hold = quality.holdout_mask(X.shape[0], self.cfg.gate_holdout, q)
        self._holdout = (X[hold], np.asarray(y)[hold],
                         q[hold] if q is not None else None)
        keep = ~hold
        return (X[keep], np.asarray(y)[keep],
                q[keep] if q is not None else None)

    def _gate_decide(self, cycle: int) -> Dict[str, Any]:
        """Evaluate the candidate (the live model) and the incumbent (the
        newest published generation) on the SAME holdout slice with the
        configured metric stack; returns the auditable gate record."""
        from ..models.gbdt_model import GBDTModel
        Xh, yh, qh = self._holdout
        self._booster._drain()
        params = dict(self.cfg.train_params)
        cand = quality.evaluate_model(self._booster._model, Xh, yh, params,
                                      query=qh)
        inc_rec = self.publisher.latest_valid()
        inc = None
        if inc_rec is not None:
            inc = quality.evaluate_model(
                GBDTModel.load_model_from_string(inc_rec.model_text),
                Xh, yh, params, query=qh)
        rec = quality.gate_verdict(cand, inc, self.cfg.gate_tolerance,
                                   self.cfg.gate_metric)
        rec["cycle"] = cycle
        rec["holdout_rows"] = int(len(yh))
        rec["incumbent_generation"] = \
            inc_rec.generation if inc_rec is not None else None
        telemetry.counter("lgbm_publish_gate_total").inc(
            verdict=rec["verdict"])
        self.wd.annotate("publish_gate", rec)
        return rec

    # -- the loop ------------------------------------------------------------
    def run(self) -> int:
        cfg = self.cfg
        # persistent-compile-cache seam (ISSUE 15): honor
        # $LGBM_TPU_COMPILE_CACHE before the first cycle compiles
        warmup.maybe_enable_from_env()
        guard = resilience.PreemptionGuard(cfg.output_model,
                                           retention=cfg.snapshot_retention,
                                           log=self.log)
        producer = _IngestProducer(cfg, log=self.log)
        producer.start()
        metrics_server = None
        if cfg.metrics_port is not None:
            metrics_server = telemetry.start_http_server(cfg.metrics_port)
            self.log.info("online: serving /metrics on port %d",
                          metrics_server.port)
        telemetry.maybe_start_file_export("train_online")
        try:
            with guard:
                return self._run_inner(guard, producer)
        finally:
            producer.stop()
            self.wd.done()
            telemetry.write_snapshot_now("train_online")
            if metrics_server is not None:
                metrics_server.stop()

    def _run_inner(self, guard, producer) -> int:
        cfg = self.cfg
        state = self._load_or_create_state()
        t0 = float(state["t0"])

        self.wd("ingest: first window")
        stamp, X, y, q = producer.current(timeout=max(cfg.stage_timeout, 60))
        self._window_stamp = stamp
        X, y, q = self._gate_split(X, y, q)

        if cfg.mode == "boost":
            done = self._recover_boost(X, y, q)
        else:
            done = self._recover_refit()
        if self._booster is None:
            self.wd("bootstrap: initial booster")
            from ..models.gbdt_model import GBDTModel
            init = GBDTModel.load_model(cfg.input_model) \
                if cfg.input_model else None
            if init is not None:
                self._base_iter = int(init.current_iteration)
            self._booster = self._build_booster(X, y, q, init_model=init)
        # keep base_iter on disk so every relaunch derives the same cycle
        # arithmetic even before its first snapshot
        if int(state.get("base_iter", -1)) != self._base_iter:
            state["base_iter"] = self._base_iter
            resilience.atomic_write(self._state_path,
                                    json.dumps(state, indent=1))

        # warm start (ISSUE 15): a relaunch compiles the fused-step
        # family NOW — during the dead time before the first slot —
        # instead of inside cycle 1's budget
        self._maybe_prewarm(X, y, q)

        cycle = done + 1
        while cfg.cycles <= 0 or cycle <= cfg.cycles:
            self._stage(cycle, "wait for slot", seconds=0)
            self._wait_for_slot(t0, guard)
            if guard.signum is not None:
                return self._preempt(guard, cycle)
            try:
                self._run_cycle(cycle, producer, guard)
            except resilience.StageTimeout as e:
                self.timeouts += 1
                telemetry.counter("lgbm_online_cycles_total").inc(
                    status="timeout")
                self.log.warning("online: %s — cycle %d will be retried at "
                                 "the next slot", e, cycle)
                self.wd.annotate("retry", True)
                continue
            except quality.QuarantineExceeded as e:
                # firewall stage one tripping its threshold: the window
                # is mostly garbage — refuse the cycle LOUDLY and retry
                # at the next slot (fresh data may arrive; training on
                # the remainder would launder the outage into a model)
                self.quarantine_failures += 1
                telemetry.counter("lgbm_online_cycles_total").inc(
                    status="quarantine")
                self.log.warning("online: %s", e)
                self.wd.annotate("quarantine_failed", str(e))
                continue
            except resilience.TrainingPreempted:
                return self._preempt(guard, cycle, snapshot_written=True)
            if guard.signum is not None:
                return self._preempt(guard, cycle + 1)
            cycle += 1

        self.wd("save final model (%s)" % cfg.output_model)
        self._booster._drain()
        self._booster.save_model(cfg.output_model)
        self.wd.done(final=False)
        self.log.info("online: target of %d cycles reached; final model "
                      "saved to %s", cfg.cycles, cfg.output_model)
        return 0

    # -- warm start (ISSUE 15): manifest prewarm + manifest export ----------
    def _maybe_prewarm(self, X, y, q) -> None:
        """Relaunch prewarm: when the publish dir's ``warmup.json``
        carries a ``train_online`` section whose program-shape signature
        matches THIS configuration, train ONE iteration on a THROWAWAY
        booster over the same window — every fused-step program the real
        loop needs compiles (or loads from the persistent cache) before
        the first cycle slot, and the live booster's state is untouched,
        so published generations stay byte-identical (the test_continuous
        schedule-rejoin pins now run over this path).  Any mismatch or
        failure degrades to a cold first cycle, counted in
        ``lgbm_warmup_total{kind="train_online",outcome}``."""
        if not self.cfg.prewarm or self.cfg.interval_s <= 0:
            # interval 0 = no slot wait to hide the prewarm in: the
            # first cycle starts immediately, so prewarming would only
            # delay it (schedule-free bench/test runs keep today's cost)
            return
        t0 = time.monotonic()
        outcome = "legacy"
        try:
            sec, reason = warmup.read_manifest(self.cfg.publish_dir,
                                               "train_online")
            if sec is None:
                outcome = "manifest_" + reason
            else:
                outcome = warmup.classify_train_section(
                    sec, params=self.cfg.train_params,
                    n_features=int(X.shape[1]))
                if outcome == "ok":
                    self.wd("prewarm: compile from manifest")
                    throwaway = self._build_booster(X, y, q)
                    throwaway.update()
                    throwaway._drain()
                    outcome = "manifest_ok"
        except BaseException as e:   # noqa: BLE001 — never block the loop
            outcome = "error"
            self.log.warning("online: manifest prewarm failed (%s); "
                             "first cycle runs cold", e)
        dt = time.monotonic() - t0
        warmup.record_prewarm("train_online", outcome, dt)
        self.wd.annotate("prewarm", {"outcome": outcome,
                                     "seconds": round(dt, 4)})
        if outcome == "manifest_ok":
            self.log.info("online: fused-step family prewarmed from the "
                          "manifest in %.2fs (before the first slot)", dt)

    def _export_manifest(self, cycle: int) -> None:
        """Publish this trainer's shape manifest alongside the cycle's
        generation: the program-shape signature + the jit sites the
        ledger saw compile.  Best effort — a manifest failure must never
        fail a published cycle."""
        try:
            n_feat = int(self._booster._model.max_feature_idx) + 1
            self.publisher.publish_manifest(
                "train_online", warmup.build_train_section(
                    self.cfg.train_params, n_feat, generation=cycle))
        except Exception as e:       # noqa: BLE001 — best effort
            self.log.warning("online: warmup-manifest export failed: %s", e)

    def _run_cycle(self, cycle: int, producer, guard) -> None:
        # one trace per cycle (ISSUE 14): the root span every watchdog
        # stage close, dispatch mark and assembler drain of this cycle
        # records under; its traceparent rides the published meta so the
        # serving side can link responses back to this exact cycle
        with tracing.span("cycle %d" % cycle, cycle=cycle):
            self._run_cycle_traced(cycle, producer, guard)

    def _run_cycle_traced(self, cycle: int, producer, guard) -> None:
        cfg = self.cfg

        # -- ingest: adopt a fresh window if the producer staged one ---------
        self._stage(cycle, "ingest")
        stamp, X, y, q = producer.current(timeout=max(cfg.stage_timeout, 60))
        info = getattr(producer, "last_ingest", None)
        if info:
            # ingest telemetry (mode + rows/sec) rides the cycle's stage
            # trail next to the sync audit and publish latency
            self.wd.annotate("ingest", dict(info))
            if info.get("quarantined"):
                self.wd.annotate("quarantine",
                                 producer.quarantine.summary())
            frac = float(info.get("quarantine_frac", 0.0) or 0.0)
            if frac > cfg.quarantine_limit:
                raise quality.QuarantineExceeded(
                    "cycle %d: ingest quarantined %.0f%% of the last "
                    "parse (online_quarantine_limit=%.0f%%) — refusing "
                    "to train on the remainder" % (
                        cycle, frac * 100, cfg.quarantine_limit * 100))
        # fault seam: valid-looking but WRONG labels for this cycle's
        # TRAINING slice (the flip lands after the gate split, so the
        # holdout stays trustworthy — the eval gate below is the
        # defense, not the quarantine)
        flip_armed = (resilience.fault_active("label_flip") and
                      int(resilience.fault_arg("label_flip", "-1") or -1)
                      == cycle)
        if (stamp != self._window_stamp or flip_armed) \
                and cfg.mode == "boost":
            # continued training onto the new window: the live engine's
            # trees carry over as the init model (scores are replayed onto
            # the new data — reference continued-training semantics)
            self.log.info("online: data window changed; rebuilding the "
                          "engine on %d rows", X.shape[0])
            Xtr, ytr, qtr = self._gate_split(X, y, q)
            ytr, _ = resilience.maybe_flip_labels(ytr, cycle)
            self._booster = self._build_booster(
                Xtr, ytr, qtr, init_model=self._booster._model)
            self._window_stamp = stamp
        elif stamp != self._window_stamp:
            self._window_stamp = stamp
        if cfg.mode == "refit":
            Xtr, ytr, _ = self._gate_split(X, y, None)
            ytr, _ = resilience.maybe_flip_labels(ytr, cycle)
            self._refit_window = (Xtr, ytr)
        else:
            self._refit_window = (X, y)

        # -- train: to the cycle's absolute iteration target -----------------
        self._stage(cycle, "train")
        s0 = syncs.snapshot()
        c0 = xla_obs.snapshot()
        it0 = self._total_iter()
        pre_refit = None
        refitting = (cfg.mode == "refit"
                     and self._booster._model.current_iteration > 0)
        if not refitting:
            # boost mode every cycle; refit mode's FIRST cycle bootstraps
            # an initial model the later refit cycles keep re-fitting
            target = self._base_iter + cfg.rounds * (
                cycle if cfg.mode == "boost" else 1)
            while self._total_iter() < target:
                self._booster.update()
                if guard.signum is not None:
                    raise resilience.TrainingPreempted(
                        guard.signum, self._total_iter(),
                        self._snapshot(cycle, mid_cycle=True))
        else:
            X, y = self._refit_window
            pre_refit = self._booster
            self._booster = self._booster.refit(X, y)
        self.wd.annotate("syncs", syncs.delta(s0)["by_label"])
        # per-cycle compile ledger delta (ISSUE 10): steady-state cycles
        # on an unchanged window annotate {} — a rebuild (window reshape)
        # names exactly which sites recompiled and why the cycle was slow
        self.wd.annotate("xla_compiles", xla_obs.delta(c0))

        # -- eval gate: judge the candidate BEFORE it can become state -------
        gate_rec = None
        if cfg.gate_enabled and self._holdout is not None:
            self._stage(cycle, "gate")
            gate_rec = self._gate_decide(cycle)
            if gate_rec["verdict"] == "reject":
                self._reject_cycle(cycle, gate_rec, it0, pre_refit)
                return

        # -- snapshot (boost mode: full resume state at the boundary) --------
        if self._booster._engine is not None:
            self._stage(cycle, "snapshot")
            self._snapshot(cycle)

        # -- publish ---------------------------------------------------------
        self._stage(cycle, "publish")
        t_pub = time.monotonic()
        meta = self._gen_meta(cycle, self._total_iter())
        if gate_rec is not None:
            meta["gate"] = gate_rec
        # fault seam: a regression the offline gate cannot see (injected
        # AFTER the verdict) — the serving canary is the defense
        rec = self.publisher.publish(
            resilience.maybe_regress_model(
                self._model_text(self._booster), cycle),
            meta=meta, generation=cycle)
        telemetry.histogram("lgbm_online_publish_seconds").observe(
            time.monotonic() - t_pub)
        telemetry.counter("lgbm_online_cycles_total").inc(status="ok")
        self.wd.annotate("publish_latency_s",
                         round(time.monotonic() - t_pub, 4))
        # the warm-start shape manifest rides every publish (ISSUE 15):
        # a relaunch — or a fresh serving replica — reads it to compile
        # before its first real work
        self._export_manifest(cycle)
        self.log.info("online: cycle %d published generation %d (%s)",
                      cycle, rec.generation, os.path.basename(rec.path))

    def _reject_cycle(self, cycle: int, gate_rec: Dict[str, Any],
                      it0: int, pre_refit) -> None:
        """Gate rejection: persist the rejected candidate for the audit
        trail, then UNDO the cycle so the regressed trees cannot leak
        into the next cycle's lineage — boost mode rolls the cycle's
        iterations back (scores restored per iteration), refit mode
        restores the pre-refit booster.  The incumbent generation keeps
        serving; the trainer retries toward the same absolute targets on
        the next window."""
        self.gate_rejections += 1
        rej_path = self.publisher.record_rejection(
            self._model_text(self._booster), gate_rec, cycle)
        if pre_refit is not None:
            self._booster = pre_refit
        else:
            while self._total_iter() > it0:
                self._booster.rollback_one_iter()
            # the rejected cycle's TRAINING DATA may be what was wrong
            # (label_flip models exactly this): force the next cycle to
            # rebuild from the freshest window instead of continuing on
            # the suspect dataset
            self._window_stamp = None
        telemetry.counter("lgbm_online_cycles_total").inc(
            status="gate_reject")
        self.wd.annotate("gate_rejected", {
            "cycle": cycle, "rejected_model": os.path.basename(rej_path),
            "metric": gate_rec.get("metric"),
            "regression": gate_rec.get("regression")})
        self.log.warning(
            "online: cycle %d REJECTED by the publish gate (%s regressed "
            "%.4f > tolerance %s); rejected model persisted at %s, "
            "incumbent generation %s keeps serving",
            cycle, gate_rec.get("metric"),
            gate_rec.get("regression") or float("nan"),
            gate_rec.get("tolerance"), rej_path,
            gate_rec.get("incumbent_generation"))

    def _snapshot(self, cycle: int, mid_cycle: bool = False) -> Optional[str]:
        extra = {"cycle": cycle - 1 if mid_cycle else cycle,
                 "base_iter": self._base_iter,
                 "mid_cycle": bool(mid_cycle)}
        return resilience.write_snapshot(
            self._booster, self.cfg.output_model,
            retention=self.cfg.snapshot_retention, log=self.log,
            extra_state=extra,
            retention_grace_s=self.cfg.snapshot_grace_s)

    def _preempt(self, guard, cycle: int,
                 snapshot_written: bool = False) -> int:
        """Clean preemption exit: the snapshot (written at the iteration
        boundary) plus the service state file carry everything the next
        launch needs to finish this cycle and rejoin the slot schedule."""
        if not snapshot_written and self._booster is not None \
                and self._booster._engine is not None:
            self.wd("preempt: final snapshot")
            self._snapshot(cycle, mid_cycle=True)
        self.log.warning("online: preempted by signal %s during cycle %d; "
                         "relaunch with the same parameters to continue the "
                         "schedule", guard.signum, cycle)
        return 0
